"""Device-resident query units: filter + partially aggregate in HBM.

POST /v1/query with an attached device backend (ServeConfig(device=...))
routes each unit (one row group of one file) through the reader's device
delivery instead of to_arrow: columns decode straight into device memory,
the residual predicate evaluates as a resident boolean mask
(core/filter_device — host vec engine fallback, typed and counted), and
each aggregate reduces to ONE masked jnp reduction
(kernels/device_ops.masked_agg_device) whose scalar result is the only
byte that crosses back to the host. The partial feeds the exact
pyarrow-pinned merge in serve/aggregate.py unchanged — device and host
units mix freely within one request because both produce the same
((groups, types), scanned, matched) shape with the same value semantics.

The ENGAGEMENT ENVELOPE is deliberately narrow and typed: global (no
group_by) count/sum/min/max over flat integer leaves (signed and unsigned,
compared and summed in their bit-pattern view domain), count over anything
flat. Everything else — group_by (pyarrow's hash-groupby semantics),
float sum (reduction order), decimal/temporal logicals (arrow type
domains) — raises DeviceQueryError and the executor reruns the unit on
the host vec engine, counted per query_device_units_total{engine=...}.
Exactness always wins over residency: int sums wrap in two's complement
exactly like pyarrow's unchecked int64/uint64 kernels, min/max of zero
matching rows is null, count skips nulls — the differential suite pins
device == host byte-for-byte.
"""

from __future__ import annotations

import numpy as np

from ..core.filter_vec import VecFilterError
from ..meta.parquet_types import Type

__all__ = ["DeviceQueryError", "device_unit_partial"]


class DeviceQueryError(Exception):
    """This unit's query shape cannot run device-resident (group_by,
    non-integer aggregate domain, undeliverable column, filter the whole
    engine ladder declined). The executor falls back to the host path —
    same answer, counted."""


def _require(cond: bool, why: str) -> None:
    if not cond:
        raise DeviceQueryError(f"query_device: {why}")


def _agg_leaf(schema, name: str):
    try:
        leaf = schema.column(tuple(name.split(".")))
    except Exception as e:
        raise DeviceQueryError(f"query_device: column {name!r}: {e}") from None
    _require(leaf.is_leaf, f"column {name!r} is not a leaf")
    _require(leaf.max_rep == 0, f"column {name!r} is repeated")
    return leaf


def _int_domain(leaf):
    """(unsigned,) engagement check for sum/min/max: plain signed or
    unsigned integers only — every other logical domain (decimal, temporal,
    float NaN skipping, int96) keeps pyarrow's kernels authoritative."""
    from ..core.assembly import logical_kind
    from ..core.stats import column_is_unsigned

    _require(
        leaf.type in (Type.INT32, Type.INT64),
        f"column {leaf.path_str}: non-integer physical type",
    )
    unsigned = column_is_unsigned(leaf)
    if not unsigned:
        _require(
            logical_kind(leaf) is None,
            f"column {leaf.path_str}: logical domain needs pyarrow semantics",
        )
    return unsigned


def _dense_values(dc, leaf):
    """The chunk's dense values as a resident jax array (dictionary-encoded
    numeric chunks expand with one small upload + gather)."""
    import jax.numpy as jnp

    if dc.values is not None:
        _require(
            getattr(dc.values, "ndim", 1) == 1,
            f"column {leaf.path_str}: no 1-D device value form",
        )
        return dc.values
    if dc.indices is not None and dc.dictionary is not None:
        d = dc.dictionary
        if isinstance(d, np.ndarray) and d.ndim == 1:
            return jnp.asarray(d)[dc.indices]
    raise DeviceQueryError(
        f"query_device: column {leaf.path_str}: no device value form"
    )


def _validity(dc, leaf):
    """Host bool[num_rows] validity (None = all valid)."""
    if leaf.max_def > 0 and dc.def_levels is not None:
        v = np.asarray(dc.def_levels) == leaf.max_def
        if not v.all():
            return v
    return None


def device_unit_partial(reader, row_group: int, query, filters, device=None):
    """One unit's ((groups, types), scanned, matched) partial, computed
    device-resident. Raises DeviceQueryError when the query shape is
    outside the device envelope — the caller falls back to the host path
    (and counts it)."""
    try:
        import jax.numpy as jnp

        from ..core.filter_device import _device_numeric_view
        from ..kernels.device_ops import masked_agg_device
    except ImportError as e:  # pragma: no cover - jax-less deployment
        raise DeviceQueryError(f"query_device: jax unavailable: {e}") from None

    _require(not query.group_by, "group_by needs pyarrow's hash groupby")
    schema = reader.schema
    aggs = query.aggregates
    plans = []  # (op, leaf|None, unsigned)
    paths = []
    for a in aggs:
        if a.column is None:
            plans.append(("count*", None, False))
            continue
        leaf = _agg_leaf(schema, a.column)
        _require(
            a.op in ("count", "sum", "min", "max"), f"unsupported op {a.op!r}"
        )
        unsigned = False
        if a.op != "count":
            unsigned = _int_domain(leaf)
        plans.append((a.op, leaf, unsigned))
        if leaf.path not in paths:
            paths.append(leaf.path)

    normalized = None
    if filters is not None:
        from ..core.filter import normalize_dnf

        normalized = normalize_dnf(schema, filters)
        for conj in normalized:
            for e in conj:
                if e[0] not in paths:
                    paths.append(e[0])

    n = int(reader.row_group(row_group).num_rows or 0)
    group = reader.read_row_group_device(
        row_group, paths or None, device=device
    )

    mask = None
    matched = n
    if normalized is not None:
        # the to_arrow host path filters with pyarrow null conventions, so
        # the resident mask uses the SAME "arrow" mode; the engine ladder
        # inside _device_group_mask counts its own declines, and a shape
        # even the host vec engine refuses declines the whole unit
        try:
            with reader._devctx(device):
                mask = reader._device_group_mask(
                    row_group, group, normalized, n, null_mode="arrow"
                )
                matched = int(jnp.sum(mask))
        except VecFilterError as e:
            raise DeviceQueryError(f"query_device: {e}") from None

    vals: list = []
    types: list = [None] * len(aggs)
    import pyarrow as pa

    from ..utils.trace import span

    with reader._devctx(device), span(
        "query.aggregate", {"group": row_group, "aggs": len(aggs)}
    ):
        for j, (op, leaf, unsigned) in enumerate(plans):
            if op == "count*":
                vals.append(matched)
                continue
            dc = group.get(leaf.path)
            _require(dc is not None, f"column {leaf.path_str} not delivered")
            valid = _validity(dc, leaf)
            if op == "count":
                # count skips nulls: |mask & valid| with no value math at all
                if valid is None:
                    cnt = (
                        matched
                        if mask is not None
                        else int(dc.num_values)
                    )
                elif mask is None:
                    cnt = int(valid.sum())
                else:
                    cnt = int(jnp.sum(mask & jnp.asarray(valid)))
                vals.append(cnt)
                continue
            dense = _dense_values(dc, leaf)
            nd = int(valid.sum()) if valid is not None else n
            _require(
                dense.shape[0] == nd,
                f"column {leaf.path_str}: dense length mismatch",
            )
            # the aggregate runs in the column's COMPARISON domain (unsigned
            # bit-pattern views), widened to the 64-bit merge domain pyarrow
            # uses (sum promotes; min/max values embed exactly)
            view = _device_numeric_view(dense, leaf)
            c64 = view.astype(jnp.uint64 if unsigned else jnp.int64)
            if mask is None:
                dm = jnp.ones(nd, dtype=bool)
                live = nd
            elif valid is None:
                dm = mask
                live = matched
            else:
                dm = mask[jnp.asarray(np.flatnonzero(valid))]
                live = None
            if live is None:
                live = int(masked_agg_device(c64, dm, "count"))
            if live == 0:
                # pyarrow sum/min/max over zero (non-null, matching) values
                # is null
                vals.append(None)
                continue
            r = masked_agg_device(c64, dm, op)
            vals.append(int(r))
            types[j] = pa.uint64() if unsigned else pa.int64()
    return ({(): vals}, types), n, matched

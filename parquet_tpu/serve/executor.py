"""Push-down execution of planned scans on the dedicated pqt-serve pool.

The executor turns a PlannedScan into an incremental byte stream:

  * every unit (one row group of one file) decodes as an independent task
    on the process-wide bounded `pqt-serve` pool (PQT_SERVE_THREADS) —
    separate from the chunk-prepare / pqt-data / pqt-io pools, so serve
    traffic can never deadlock a dataset loader (and vice versa);
  * results stream back IN PLAN ORDER with a bounded lookahead `window`:
    at most `window` units are in flight or buffered per request, and the
    generator only advances when the consumer (the chunked HTTP write)
    drains — backpressure is the pull itself, nothing buffers the whole
    result;
  * predicate push-down continues below the plan's group pruning: each
    unit reads through the reader's page-index pruning + exact residual
    filtering (core/filter.py), with the projection applied at the source
    (only selected chunks' byte ranges are fetched, through the shared
    BlockCache);
  * cancellation is cooperative: the deadline and the abort flag are
    checked between units and every few thousand rows inside one, and
    result waits are bounded by the deadline — an expired or disconnected
    request frees its slot promptly instead of scanning to the end.

Output formats: "jsonl" (rows exactly as `parquet-tool cat` prints them,
one chunk per unit) and "arrow-ipc" (one Arrow IPC stream; each unit's
table appended as record batches, EOS on completion).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout

from ..core.reader import PARQUET_ERRORS, FileReader
from ..io.source import SourceError
from ..obs.cost import unit_clock
from ..obs.pool import instrumented_submit
from ..utils import metrics as _metrics
from ..utils.trace import stage
from .protocol import ServeError, json_default

__all__ = ["serve_pool", "execute_stream", "execute_query"]

_ROW_CHECK_EVERY = 4096  # rows between cooperative cancellation checks
_WAIT_SLICE_S = 0.1  # result-wait poll granularity (bounds deadline latency)

_pool = None
_pool_size = 0
_pool_lock = threading.Lock()


def serve_pool() -> ThreadPoolExecutor:
    """The process-wide scan-execution pool. Sized by PQT_SERVE_THREADS
    (default: min(8, cpus)); dedicated so nested pools (chunk prepare,
    pqt-io readahead) can never self-deadlock against serve traffic."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None:
            n = int(
                os.environ.get("PQT_SERVE_THREADS", min(8, os.cpu_count() or 4))
            )
            _pool_size = max(1, n)
            _pool = ThreadPoolExecutor(
                max_workers=_pool_size, thread_name_prefix="pqt-serve"
            )
        return _pool


def pool_size() -> int:
    """The serve pool's worker count (creating the pool if needed)."""
    serve_pool()
    return _pool_size


class _Check:
    """The cooperative cancellation point: deadline + abort flag in one
    callable, shared by the request generator and its unit tasks."""

    __slots__ = ("deadline", "abort")

    def __init__(self, deadline=None):
        self.deadline = deadline
        self.abort = threading.Event()

    def __call__(self) -> None:
        if self.abort.is_set():
            raise ServeError(
                499, "cancelled", "request cancelled (client gone or drained)"
            )
        if self.deadline is not None:
            self.deadline.check()

    def wait_slice(self) -> float:
        if self.deadline is None:
            return _WAIT_SLICE_S
        rem = self.deadline.remaining()
        if rem is None:
            return _WAIT_SLICE_S
        return max(0.0, min(_WAIT_SLICE_S, rem))


def _open_reader(session, planned, unit) -> FileReader:
    meta = planned.plan.metas[unit.file_index]
    return FileReader(
        session.open_source(unit.path),
        columns=planned.request.columns,
        metadata=meta,
        block_cache=session.block_cache,
        coalesce_gap=getattr(session, "coalesce_gap", None),
    )


def _close_unit_reader(session, reader) -> None:
    # factory-built sources (chaos/remote seam) are caller-owned per the
    # ByteSource contract: the reader won't close them, so we must
    reader.close()
    if session.source_factory is not None:
        reader._source.close()


def _run_jsonl_unit(session, planned, unit, max_rows, check):
    """Decode + serialize one unit; returns (payload bytes, rows).
    unit_clock bills the unit's thread-time (exact per-thread CPU) to the
    request's tenant through the cost contextvar the submit carried."""
    check()
    with unit_clock(), stage("serve.execute"):
        reader = _open_reader(session, planned, unit)
        try:
            lines = []
            n = 0
            for row in reader.iter_rows(
                row_groups=[unit.row_group], filters=planned.request.filters
            ):
                lines.append(json.dumps(row, default=json_default))
                n += 1
                if n % _ROW_CHECK_EVERY == 0:
                    check()
                if max_rows is not None and n >= max_rows:
                    break
            payload = ("\n".join(lines) + "\n").encode() if lines else b""
            return payload, n
        finally:
            _close_unit_reader(session, reader)


def _run_arrow_unit(session, planned, unit, max_rows, check):
    """Decode one unit to a pyarrow Table (serialized by the stream side,
    which owns the single IPC writer). unit_clock: see _run_jsonl_unit."""
    check()
    with unit_clock(), stage("serve.execute"):
        reader = _open_reader(session, planned, unit)
        try:
            t = reader.to_arrow(
                row_groups=[unit.row_group], filters=planned.request.filters
            )
            if max_rows is not None and t.num_rows > max_rows:
                t = t.slice(0, max_rows)
            return t
        finally:
            _close_unit_reader(session, reader)


def _pipelined(units, run_one, window: int, check: "_Check"):
    """Bounded in-order unit pipeline: submit up to `window` ahead, yield
    results in plan order. Result waits poll in deadline-bounded slices so
    an expired request raises its typed 504 even while a unit is stuck."""
    pending: deque = deque()
    idx = 0
    try:
        while pending or idx < len(units):
            while idx < len(units) and len(pending) < window:
                u = units[idx]
                pending.append(
                    instrumented_submit(serve_pool(), run_one, u, pool="pqt-serve")
                )
                idx += 1
            fut = pending.popleft()
            while True:
                check()
                try:
                    result = fut.result(timeout=check.wait_slice())
                    break
                except _FutTimeout:
                    continue
            yield result
    finally:
        # abort first so already-running tasks exit at their next check,
        # then drop anything still queued
        check.abort.set()
        for f in pending:
            f.cancel()


def _wrap_decode_errors(gen):
    """Typed-error discipline at the execution boundary: a corrupt file
    surfaces as a ServeError (422) the server renders structurally, never
    a raw decode exception unwinding the handler. A circuit breaker's
    fast-fail (SourceError code="breaker_open" — the source is KNOWN dark)
    becomes a 503 with Retry-After instead: the file is fine, the
    transport is down, and the client should back off rather than re-ask —
    and the unit fails in microseconds instead of burning its deadline on
    a retry ladder that cannot succeed. Counted
    serve_shed_total{reason="breaker_open"}."""
    try:
        yield from gen
    except ServeError:
        raise
    except SourceError as e:
        code = getattr(e, "code", None)
        if code == "breaker_open":
            _metrics.inc("serve_shed_total", reason="breaker_open")
            raise ServeError(
                503, "source_unavailable",
                f"source circuit breaker open: {e}", retry_after_s=1,
            ) from None
        if code == "retry_exhausted":
            # the ladder gave up on a TRANSIENT fault storm: the file is
            # not wrong, the transport is — same 503 + Retry-After shape
            # the raw OSError below gets, not a permanent-looking 422
            raise ServeError(
                503, "source_error", f"{type(e).__name__}: {e}",
                retry_after_s=1,
            ) from None
        raise ServeError(
            422, "unreadable_file", f"{type(e).__name__}: {e}"
        ) from None
    except PARQUET_ERRORS as e:
        raise ServeError(
            422, "unreadable_file", f"{type(e).__name__}: {e}"
        ) from None
    except OSError as e:
        # a raw transport fault (EIO from a flaky store, a vanished mount)
        # is the DAEMON's environment failing, not the request: 503 +
        # Retry-After, not a 500 that reads as a server bug
        raise ServeError(
            503, "source_error", f"{type(e).__name__}: {e}", retry_after_s=1
        ) from None


def _count_bytes(payload: bytes) -> None:
    _metrics.inc("serve_scan_bytes_total", len(payload))


def _stream_jsonl(planned, session, check, window):
    remaining = planned.request.limit
    units = planned.units

    def run(u, cap=None):
        return _run_jsonl_unit(session, planned, u, cap, check)

    if remaining is None:
        for payload, _n in _pipelined(units, run, window, check):
            if payload:
                _count_bytes(payload)
                yield payload
        return
    # limited scans run sequentially: each unit's cap is what's left, and
    # lookahead past a satisfied limit would be wasted decode work
    for u in units:
        if remaining <= 0:
            break
        check()
        fut = instrumented_submit(
            serve_pool(), run, u, remaining, pool="pqt-serve"
        )
        while True:
            check()
            try:
                payload, n = fut.result(timeout=check.wait_slice())
                break
            except _FutTimeout:
                continue
        remaining -= n
        if payload:
            _count_bytes(payload)
            yield payload


class _ChunkSink:
    """A file-like the single Arrow IPC writer writes into; `take()` hands
    the bytes accumulated since the last take to the HTTP stream."""

    closed = False  # pyarrow's IPC writer checks the file-like protocol

    def __init__(self):
        self._parts: list[bytes] = []

    def write(self, data) -> int:
        b = bytes(data)
        self._parts.append(b)
        return len(b)

    def flush(self) -> None:
        pass

    def take(self) -> bytes:
        out = b"".join(self._parts)
        self._parts.clear()
        return out


def _empty_table(planned, session):
    """A zero-row table carrying the scan's schema (so an empty result is
    still a VALID Arrow IPC stream: schema header + EOS)."""
    for fi, meta in enumerate(planned.plan.metas):
        if meta is None:
            continue
        reader = FileReader(
            session.open_source(planned.plan.files[fi]),
            columns=planned.request.columns,
            metadata=meta,
        )
        try:
            return reader.to_arrow(row_groups=[])
        finally:
            _close_unit_reader(session, reader)
    raise ServeError(422, "unreadable_file", "no readable file to derive a schema")


def _stream_arrow(planned, session, check, window):
    import pyarrow as pa

    sink = _ChunkSink()
    writer = None
    remaining = planned.request.limit
    units = planned.units

    def run(u):
        return _run_arrow_unit(session, planned, u, None, check)

    def limited():
        # limited scans run sequentially, each unit capped at what the
        # limit STILL needs (`remaining` shrinks as the loop consumes) —
        # lookahead past a satisfied limit would be wasted decode work
        for u in units:
            if remaining <= 0:
                return
            check()
            fut = instrumented_submit(
                serve_pool(), _run_arrow_unit, session, planned, u,
                remaining, check, pool="pqt-serve",
            )
            while True:
                check()
                try:
                    yield fut.result(timeout=check.wait_slice())
                    break
                except _FutTimeout:
                    continue

    try:
        source = (
            _pipelined(units, run, window, check)
            if remaining is None
            else limited()
        )
        for table in source:
            if remaining is not None:
                table = table.slice(0, remaining)
                remaining -= table.num_rows
            if writer is None:
                writer = pa.ipc.new_stream(sink, table.schema)
            try:
                writer.write_table(table)
            except pa.ArrowInvalid as e:
                raise ServeError(
                    422, "schema_mismatch",
                    f"files in one scan must share a schema: {e}",
                ) from None
            payload = sink.take()
            if payload:
                _count_bytes(payload)
                yield payload
            if remaining is not None and remaining <= 0:
                break
        if writer is None:
            writer = pa.ipc.new_stream(sink, _empty_table(planned, session).schema)
        writer.close()
        payload = sink.take()
        if payload:
            _count_bytes(payload)
            yield payload
    finally:
        check.abort.set()


def execute_query(
    planned, query, session, *, deadline=None, window: int = 2, device=None
):
    """Aggregation push-down over the planned units (POST /v1/query).

    Each unit decodes + filters + partially aggregates as one pqt-serve
    pool task (the residual filter runs the vectorized mask pipeline via
    to_arrow's buffer-level take); partials merge on the caller's thread
    with exact pyarrow semantics (serve/aggregate.py), bounded by the
    request's max_groups. Pure count(*) with no filters never opens a
    file — the footer-promised unit row counts ARE the answer. Returns the
    response body dict; every failure mode is a typed ServeError, and the
    deadline/abort checks run between units exactly like streamed scans.

    `device` (ServeConfig(device=...)) attaches an accelerator backend:
    each unit first tries the device-resident path (serve/query_device —
    decode into HBM, resident residual mask, one masked reduction per
    aggregate) and falls back, typed and counted
    (query_device_units_total{engine=...}), to the host vec engine for any
    shape outside the device envelope. True means the process-default jax
    device; a jax.Device pins one."""
    from .aggregate import (
        QueryState,
        query_columns,
        result_dict,
        unit_count_partial,
        unit_partial,
    )

    check = _Check(deadline)
    if window < 1:
        raise ValueError("executor: window must be >= 1")
    cols = query_columns(query)
    decode = bool(cols) or query.filters is not None
    state = QueryState(query)
    units = planned.units
    device_unit = None
    if device is not None and decode:
        try:
            from .query_device import DeviceQueryError, device_unit_partial

            device_unit = device_unit_partial
        except ImportError:
            # jax-less deployment with device= set: every unit is a host
            # unit; the counter makes the misconfiguration visible
            _metrics.inc("query_device_unavailable_total")
            device_unit = None
    # a streamed scan's window bounds BUFFERED payload; a query's unit
    # results are kilobyte partials, so the lookahead widens to the pool —
    # merge order doesn't matter and idle workers are pure waste
    window = max(window, min(pool_size(), len(units) or 1))

    def run(u):
        check()
        if not decode:
            return (
                unit_count_partial(query, u.num_rows), u.num_rows, u.num_rows
            )
        with unit_clock(), stage("serve.aggregate"):
            reader = _open_reader(session, planned, u)
            try:
                if device_unit is not None:
                    try:
                        part = device_unit(
                            reader,
                            u.row_group,
                            query,
                            planned.request.filters,
                            None if device is True else device,
                        )
                        _metrics.inc(
                            "query_device_units_total", engine="device"
                        )
                        return part
                    except DeviceQueryError:
                        _metrics.inc(
                            "query_device_units_total", engine="host_fallback"
                        )
                t = reader.to_arrow(
                    row_groups=[u.row_group], filters=planned.request.filters
                )
            finally:
                _close_unit_reader(session, reader)
            return (unit_partial(t, query), u.num_rows, t.num_rows)

    gen = _pipelined(units, run, window, check)
    try:
        for part in _wrap_decode_errors(gen):
            with stage("serve.merge"):
                state.absorb(part)
    finally:
        gen.close()
    _metrics.inc("serve_aggregate_requests_total")
    return result_dict(query, state, units=len(units))


def execute_stream(planned, session, *, deadline=None, window: int = 2):
    """The request's payload-chunk generator. Pull-driven: nothing decodes
    beyond `window` units ahead of what the consumer has taken, and closing
    the generator (client disconnect) aborts in-flight unit tasks at their
    next cooperative check. Raises ServeError (typed) for every failure
    mode — deadline, cancellation, corrupt data, schema drift."""
    check = _Check(deadline)
    if window < 1:
        raise ValueError("executor: window must be >= 1")
    if planned.request.format == "arrow-ipc":
        gen = _stream_arrow(planned, session, check, window)
    else:
        gen = _stream_jsonl(planned, session, check, window)

    def outer():
        try:
            for payload in _wrap_decode_errors(gen):
                # the stage brackets the YIELD: its wall time is how long
                # the consumer (the chunked HTTP write) took to drain this
                # chunk — the backpressure/writeback measurement
                with stage("serve.stream", nbytes=len(payload)):
                    yield payload
        finally:
            check.abort.set()
            gen.close()

    return outer()

"""The `parquet-tool serve` daemon: a concurrent scan/query HTTP service.

stdlib-only (ThreadingHTTPServer — one thread per connection, scan work on
the bounded pqt-serve pool), four endpoints:

  POST /v1/scan     {"paths": ..., "columns": ..., "filters": ..., "limit":
                    ..., "format": "jsonl"|"arrow-ipc", "shard": [i, n]}
                    → chunked-transfer stream of results. Headers:
                    `X-Tenant` (budget accounting key), `X-Timeout-Ms`
                    (deadline override).
  POST /v1/query    {"paths": ..., "filters": ..., "aggregates":
                    [["count"], ["sum", "v"], ...], "group_by": [...],
                    "max_groups": N} → ONE small JSON body: aggregation
                    push-down executed per row-group unit on the pqt-serve
                    pool and merged exactly (serve/aggregate.py). Same
                    admission/budget/deadline discipline as /v1/scan.
  GET  /v1/plan     dry-run of the same request (query params or POSTed
                    body): pruned vs total row groups, estimated bytes —
                    zero source reads when the footer cache is warm.
  GET  /metrics     Prometheus text exposition of the process registry
                    (`Accept: application/openmetrics-text` negotiates the
                    OpenMetrics variant whose serve_request_seconds
                    buckets carry request-id EXEMPLARS).
  GET  /healthz     {"status": "ok"|"draining", "in_flight": n}; 503 while
                    draining so load balancers stop routing here.
  GET  /v1/debug/requests[/<id>[/trace]]  the flight recorder (PR 9).
  GET  /v1/debug/profile?seconds=N  live sampling profile of the process
                    (collapsed flamegraph text / top table / json),
                    lane-attributed to the pqt-* pools.
  GET  /v1/debug/tenants  per-tenant cost table (CPU seconds, decoded/
                    source bytes, cache outcomes) + cross-tenant totals.
  GET  /v1/debug/vars  process snapshot: uptime, pid, version, pool
                    sizes, resilience policy, cache/admission budgets,
                    process self-stats (rss/fds/threads).
  GET  /v1/debug/slo  the burn-rate engine's verdict (ok/warn/burning)
                    + per-window math (obs/slo.py); the same verdict
                    folds into /healthz as "degraded" at 200.
  GET  /v1/debug/fleet?peers=host:port,...  scrape the named replicas'
                    /metrics and answer the exactly-merged exposition
                    (obs/fleet.py: counters sum, histogram buckets add,
                    gauges keep a replica= label).

Every request resolves an inbound `traceparent` header (malformed ones
are replaced, never echoed) into a propagation context that is injected
into EVERY outbound HTTP call the request makes (remote range GETs,
multipart PUTs), echoed on responses, and carried on error bodies,
flight-recorder records and structured log lines as `trace_id` — the
cross-process join key `parquet-tool trace-merge` stitches on.

Error discipline: EVERY failure renders as a structured JSON body
({"error": {code, message, status}}) — never a traceback. Failures after
the 200 header is sent (the stream already started) emit a terminal
`{"error": ...}` line (jsonl) and abort the chunked encoding WITHOUT the
terminating 0-chunk, so clients always detect the torn transfer instead
of mistaking a prefix for the full result.

Shutdown: SIGTERM/SIGINT (install_signal_handlers, the `parquet-tool
serve` path) or drain() begin a graceful drain — new requests get typed
503s while in-flight ones run to completion — then the listener stops.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..core.reader import PARQUET_ERRORS
from ..io.cache import BlockCache
from ..obs import cost as _cost
from ..obs import log as _obslog
from ..obs import prof as _prof
from ..obs import propagate as _propagate
from ..obs.recorder import ObsConfig as _ObsConfig
from ..obs.recorder import configure as _obs_configure
from ..obs.recorder import sanitize_request_id as _sanitize_request_id
from ..obs.slo import BurnRateEngine as _BurnRateEngine
from ..obs.slo import SLOObjective as _SLOObjective
from ..utils import metrics as _metrics
from ..utils.trace import decode_trace
from .admission import AdmissionController
from .executor import execute_query, execute_stream
from .protocol import (
    ScanRequest,
    ServeError,
    parse_query_request,
    parse_scan_request,
    scan_request_from_query,
)
from .session import ScanSession

__all__ = ["ServeConfig", "ScanService", "ScanServer"]

# ObsConfig owns the observability knob defaults; ServeConfig mirrors them
_OBS_DEFAULTS = _ObsConfig()


@dataclass
class ServeConfig:
    """Everything a daemon instance is allowed to do, in one place."""

    host: str = "127.0.0.1"
    port: int = 8080
    root: str | None = None  # confine requested paths to this directory
    cache_mb: int = 64  # shared block cache (0 disables)
    # tiered cache: cache_disk_mb > 0 grows the block cache into a RAM ->
    # local-disk TieredCache (io/tiercache.py) spilling to cache_dir (a
    # private temp dir when None; a given dir is REUSED across restarts —
    # intact spilled blocks re-serve after a crash). The RAM tier is
    # cache_mb (its default applies when 0 but a disk tier is asked for).
    cache_disk_mb: int = 0
    cache_dir: str | None = None
    # resolve the read coalesce gap (and readahead depth) per fetch from
    # the observed per-transport latency profile (io/autotune.py): local
    # corpora keep the 64 KiB default, remote-backed source factories
    # coalesce MiB-scale
    io_autotune: bool = False
    max_inflight: int = 32
    tenant_concurrent: int = 8
    tenant_budget_mb: int | None = None  # scanned-byte budget per window
    budget_window_s: float = 60.0
    default_timeout_s: float | None = 30.0
    max_timeout_s: float = 300.0
    # brownout: shed NEW scans with typed 503s + Retry-After once the
    # pqt-serve pool's windowed mean queue wait crosses brownout_wait_ms
    # (or its queue depth crosses brownout_depth) — degrade loudly and
    # early instead of admitting work that will only 504 later. None
    # disables (the default: an explicitly sized deployment opts in).
    brownout_wait_ms: float | None = None
    brownout_depth: int | None = None
    brownout_window_s: float = 2.0
    window: int = 2  # per-request unit lookahead (backpressure bound)
    # request bodies are small JSON specs; a client-declared Content-Length
    # is rejected with a typed 413 past this, BEFORE any bytes are buffered
    max_body_bytes: int = 1 << 20
    # the write path: a lake-table directory (lake/manifest.py) arms
    # POST /v1/append on this replica — batches buffer in the ingest
    # writer and commit one manifest generation per flush. None keeps the
    # daemon read-only (/v1/append answers a typed 503 ingest_disabled).
    # The table is created on demand with lake_schema (DSL text) when the
    # directory is not yet a table; lake_sort_key orders flushed files'
    # row groups (and drives compaction's sort stage).
    lake_root: str | None = None
    lake_schema: str | None = None
    lake_sort_key: str | None = None
    lake_flush_mb: int = 4  # ingest buffer bound; a flush commits a generation
    # append bodies are DATA, not specs: they get their own, larger cap
    max_append_bytes: int = 32 << 20
    # per-socket-op timeout: a client that stalls (stops sending its body,
    # or accepts the 200 and stops reading) would otherwise pin its handler
    # thread AND its admission ticket forever — the cooperative deadline
    # can't fire while the thread is blocked in a socket call
    socket_timeout_s: float = 60.0
    shard: tuple | None = None  # this daemon's (index, count) corpus stripe
    source_factory: object = None  # chaos/remote seam: path -> ByteSource
    # {path prefix -> object-store base URL}: requested paths under a
    # mapped prefix resolve to URLs and read through the shared block/
    # footer caches; everything else stays root-confined (escapes 403)
    remote_map: dict | None = None
    # attached accelerator backend for POST /v1/query: True runs query
    # units device-resident on the process-default jax device, a
    # jax.Device pins one — decode into HBM, resident residual mask, one
    # masked reduction per aggregate (serve/query_device). Units outside
    # the device envelope fall back, typed and counted, to the host vec
    # engine; None (default) keeps every unit on the host.
    device: object = None
    # a PRE-BUILT BlockCache/TieredCache (caller-owned, survives close()):
    # how a daemon and co-resident dataset workers pool ONE tier budget.
    # Overrides cache_mb/cache_disk_mb.
    block_cache: object = None
    # observability (parquet_tpu.obs): every request runs under a
    # request-scoped DecodeTrace whose stage rollup is ALWAYS retained in
    # the flight-recorder ring; the full span tree is kept for a
    # trace_sample_rate share of ok-and-fast requests and for EVERY
    # request that errors or runs >= slow_ms. Defaults come from
    # ObsConfig, the one place that owns the knobs — restated numbers
    # here would silently drift.
    trace_sample_rate: float = _OBS_DEFAULTS.trace_sample_rate
    slow_ms: float = _OBS_DEFAULTS.slow_ms  # serve_slow_requests_total bar
    debug_ring_size: int = _OBS_DEFAULTS.ring_size  # /v1/debug retention
    debug_max_traces: int = _OBS_DEFAULTS.max_traces  # trees kept (~MBs each)
    # the SLO this replica promises (obs/slo.py burn-rate engine): the
    # availability objective over server-side failures (5xx), and an
    # optional latency bar — None disables the latency SLI. The verdict
    # serves /v1/debug/slo and folds into /healthz as "degraded".
    slo_availability: float = 0.999
    slo_p99_ms: float | None = None
    # test/chaos seam (like source_factory): a pre-built BurnRateEngine —
    # how fake-clock tests replay a fault schedule deterministically
    slo_engine: object = None

    def __post_init__(self):
        if self.window < 1:
            raise ValueError("serve: window must be >= 1")
        if self.cache_mb < 0:
            raise ValueError("serve: cache_mb must be >= 0")
        if self.cache_disk_mb < 0:
            raise ValueError("serve: cache_disk_mb must be >= 0")
        if self.socket_timeout_s is not None and self.socket_timeout_s <= 0:
            raise ValueError("serve: socket_timeout_s must be positive")
        if self.max_body_bytes < 1:
            raise ValueError("serve: max_body_bytes must be >= 1")
        if self.max_append_bytes < 1:
            raise ValueError("serve: max_append_bytes must be >= 1")
        if self.lake_flush_mb < 1:
            raise ValueError("serve: lake_flush_mb must be >= 1")
        if self.default_timeout_s is not None and self.default_timeout_s <= 0:
            raise ValueError(
                "serve: default_timeout_s must be positive (None disables)"
            )
        if self.max_timeout_s <= 0:
            raise ValueError("serve: max_timeout_s must be positive")
        if self.brownout_wait_ms is not None and self.brownout_wait_ms <= 0:
            raise ValueError(
                "serve: brownout_wait_ms must be positive (None disables)"
            )
        if self.brownout_depth is not None and self.brownout_depth <= 0:
            raise ValueError(
                "serve: brownout_depth must be positive (None disables)"
            )
        if self.brownout_window_s <= 0:
            raise ValueError("serve: brownout_window_s must be positive")
        # validate the shard assignment AT STARTUP: a daemon silently
        # serving the wrong stripe (shard index past the count) would
        # answer every request with a plausible-looking empty subset —
        # the one misconfiguration a mesh cannot detect from outside
        if self.shard is not None:
            try:
                i, n = (int(x) for x in tuple(self.shard))
            except (TypeError, ValueError):
                raise ValueError(
                    f"serve: shard must be (index, count), got {self.shard!r}"
                ) from None
            if n < 1 or not 0 <= i < n:
                raise ValueError(
                    f"serve: shard index {i} out of range for count {n} "
                    "(need n >= 1 and 0 <= index < count)"
                )
            self.shard = (i, n)
        # delegate the obs-knob validation to the one place that owns it
        _ObsConfig(
            ring_size=self.debug_ring_size,
            trace_sample_rate=self.trace_sample_rate,
            slow_ms=self.slow_ms,
            max_traces=self.debug_max_traces,
        )
        # likewise the SLO knobs: SLOObjective owns their invariants
        if self.slo_engine is None:
            _SLOObjective(
                availability=self.slo_availability, p99_ms=self.slo_p99_ms
            )


class ScanService:
    """The daemon's request brain, HTTP-free so tests and embedders drive
    it directly: session (shared caches + confinement) + admission."""

    def __init__(self, config: ServeConfig):
        self.config = config
        if config.block_cache is not None:
            block_cache = config.block_cache
            self._owns_cache = False
        elif config.cache_disk_mb:
            from ..io.tiercache import TieredCache

            block_cache = TieredCache(
                ram_bytes=(config.cache_mb or 64) << 20,
                disk_bytes=config.cache_disk_mb << 20,
                cache_dir=config.cache_dir,
            )
            self._owns_cache = True
        elif config.cache_mb:
            block_cache = BlockCache(config.cache_mb << 20)
            self._owns_cache = True
        else:
            block_cache = None
            self._owns_cache = True
        self.session = ScanSession(
            root=config.root,
            block_cache=block_cache,
            source_factory=config.source_factory,
            shard=config.shard,
            coalesce_gap="auto" if config.io_autotune else None,
            remote_map=config.remote_map,
        )
        self.admission = AdmissionController(
            max_inflight=config.max_inflight,
            tenant_concurrent=config.tenant_concurrent,
            tenant_budget_bytes=(
                config.tenant_budget_mb << 20
                if config.tenant_budget_mb is not None
                else None
            ),
            budget_window_s=config.budget_window_s,
            default_timeout_s=config.default_timeout_s,
            max_timeout_s=config.max_timeout_s,
            brownout_wait_s=(
                config.brownout_wait_ms / 1e3
                if config.brownout_wait_ms is not None
                else None
            ),
            brownout_depth=config.brownout_depth,
            brownout_window_s=config.brownout_window_s,
        )
        # the PROCESS-wide flight recorder, configured with this daemon's
        # knobs: library records (dataset units, encode groups) land in
        # the same recorder the debug endpoints serve (a sibling ring, so
        # pipeline churn can't evict request evidence)
        self.recorder = _obs_configure(
            _ObsConfig(
                ring_size=config.debug_ring_size,
                trace_sample_rate=config.trace_sample_rate,
                slow_ms=config.slow_ms,
                max_traces=config.debug_max_traces,
            )
        )
        # the process-wide tenant cost ledger (same lifetime discipline as
        # the recorder) and the daemon's start instant for /v1/debug/vars
        self.ledger = _cost.LEDGER
        self.started_at = time.time()
        # the burn-rate health engine: fed one sample per finished
        # recorded request (_Handler._finish), read by /v1/debug/slo and
        # /healthz. A config-passed engine (fake clock) wins.
        if config.slo_engine is not None:
            self.slo = config.slo_engine
        else:
            self.slo = _BurnRateEngine(
                _SLOObjective(
                    availability=config.slo_availability,
                    p99_ms=config.slo_p99_ms,
                )
            )
        # the write path (lake/): /v1/append buffers into this writer and
        # commits one manifest generation per flush. Built at startup so
        # a misconfigured lake root fails the daemon, not the first append.
        self.lake = None
        self.ingest = None
        if config.lake_root is not None:
            from ..lake.ingest import IngestWriter
            from ..lake.manifest import LakeError, LakeTable

            try:
                self.lake = LakeTable.open(config.lake_root)
            except LakeError:
                if config.lake_schema is None:
                    raise
                self.lake = LakeTable.create(
                    config.lake_root, config.lake_schema,
                    sort_key=config.lake_sort_key,
                )
            self.ingest = IngestWriter(
                self.lake, flush_bytes=config.lake_flush_mb << 20
            )

    # -- request entry points (raise ServeError; HTTP layer renders) -----------

    def plan(self, request) -> dict:
        """The /v1/plan dry-run body (no admission: planning is cheap and
        cached; hammering /v1/plan cannot starve scans of pool threads)."""
        return self.session.plan(request).summary()

    def scan(self, request, tenant: str, timeout_ms=None, record=None):
        """Admit, plan, charge, and open the result stream. Returns
        (ticket, content_type, chunk iterator); the caller MUST close the
        iterator and release the ticket (both context-manage safely).
        `record` (a flight-recorder RequestRecord) receives the plan's
        pruning summary as soon as planning finishes."""
        deadline = self.admission.deadline_for(
            timeout_ms if timeout_ms is not None else request.timeout_ms
        )
        ticket = self.admission.admit(tenant)
        try:
            planned = self.session.plan(request)
            if record is not None:
                record.plan = planned.summary()
            # ticket.tenant is the RESOLVED accounting key (it may have
            # collapsed to the overflow bucket under tenant-table pressure)
            self.admission.charge(ticket.tenant, planned.estimated_bytes)
            deadline.check()
            chunks = execute_stream(
                planned,
                self.session,
                deadline=deadline,
                window=self.config.window,
            )
        except BaseException:
            ticket.release()
            raise
        content_type = (
            "application/vnd.apache.arrow.stream"
            if request.format == "arrow-ipc"
            else "application/x-ndjson"
        )
        return ticket, content_type, chunks

    def query(self, request, tenant: str, timeout_ms=None, record=None):
        """POST /v1/query: aggregation push-down. Admission is EXACTLY the
        scan discipline — same ticket, same deadline clamp, and the tenant
        byte budget is charged with the same plan estimate (aggregation
        must not become a budget bypass: the daemon still decodes those
        bytes, it just doesn't ship them). Returns (ticket, body dict); the
        caller renders and must release the ticket."""
        from .aggregate import query_columns

        deadline = self.admission.deadline_for(
            timeout_ms if timeout_ms is not None else request.timeout_ms
        )
        ticket = self.admission.admit(tenant)
        try:
            cols = query_columns(request)
            planned = self.session.plan(
                ScanRequest(
                    paths=request.paths,
                    # [] is meaningful: a pure count(*) decodes nothing and
                    # its plan estimate is zero bytes
                    columns=cols,
                    filters=request.filters,
                    limit=None,
                    format="jsonl",
                    shard=request.shard,
                    timeout_ms=request.timeout_ms,
                )
            )
            if record is not None:
                record.plan = planned.summary()
            self.admission.charge(ticket.tenant, planned.estimated_bytes)
            deadline.check()
            body = execute_query(
                planned,
                request,
                self.session,
                deadline=deadline,
                window=self.config.window,
                device=self.config.device,
            )
        except BaseException:
            ticket.release()
            raise
        if record is not None and isinstance(record.plan, dict):
            # mask selectivity rides NEXT TO the pruning summary: the two
            # numbers together say how much each rung (stats/bloom vs the
            # residual mask) actually cut
            scanned = body.get("rows_scanned", 0)
            matched = body.get("rows_matched", 0)
            record.plan = {
                **record.plan,
                "residual": {
                    "rows_scanned": scanned,
                    "rows_matched": matched,
                    "selectivity": (
                        round(matched / scanned, 6) if scanned else None
                    ),
                },
            }
        return ticket, body

    def append(self, body: bytes, content_type, tenant: str, *,
               flush: bool = False, record=None):
        """POST /v1/append: one row batch into the lake table's ingest
        buffer. Admission is the scan discipline — same ticket, and the
        tenant byte budget is charged the BODY size up front (ingest work
        scales with payload exactly the way scans scale with plan bytes).
        Returns (ticket, ack dict); the caller releases the ticket."""
        if self.ingest is None:
            raise ServeError(
                503, "ingest_disabled",
                "this replica serves no lake table (start it with a "
                "--lake root to accept appends)",
            )
        from ..lake.ingest import rows_from_payload
        from ..lake.manifest import LakeError

        ticket = self.admission.admit(tenant)
        try:
            self.admission.charge(ticket.tenant, len(body))
            try:
                rows = rows_from_payload(body, content_type)
                if not rows:
                    raise ServeError(
                        400, "bad_request", "append body holds no rows"
                    )
                ack = self.ingest.append(rows, flush=flush)
            except LakeError as e:
                raise _lake_serve_error(e) from None
            except ServeError:
                # ServeError subclasses ValueError: already typed, keep it
                raise
            except PARQUET_ERRORS + (ValueError,) as e:
                # schema-shaped failures (a row that doesn't shred:
                # ShredError/WriterError are ValueErrors) are the
                # CLIENT's rows being wrong, not the daemon
                raise ServeError(
                    422, "bad_rows", f"{type(e).__name__}: {e}"
                ) from None
            if record is not None:
                record.plan = {
                    "rows": ack["rows"],
                    "flushed": ack["flushed"],
                    "generation": ack["generation"],
                }
        except BaseException:
            ticket.release()
            raise
        return ticket, ack

    def healthz(self) -> tuple[int, dict]:
        draining = self.admission.draining
        verdict = self.slo.evaluate()["verdict"]
        # draining wins (the replica must not be routed to AT ALL, 503);
        # burning degrades at 200 — still serving, a router may merely
        # deprioritize it. "warn" stays "ok": /healthz is a routing
        # signal, not a pager (the full math lives at /v1/debug/slo).
        if draining:
            status_str = "draining"
        elif verdict == "burning":
            status_str = "degraded"
        else:
            status_str = "ok"
        in_flight = self.admission.in_flight
        body = {
            "status": status_str,
            "in_flight": in_flight,
            "slo": verdict,
        }
        if draining:
            # the mesh client's failover reads this to tell "drains in a
            # couple seconds, come back" from "gone" — the remaining
            # in-flight count above says how much work is still leaving
            body["retry_after_s"] = min(30, 1 + in_flight)
        return (503 if draining else 200), body

    # -- the /v1/debug bodies (HTTP-free, like plan/scan) ----------------------

    def debug_requests(
        self, *, limit: int = 100, slow_only: bool = False, endpoint=None
    ) -> dict:
        """The /v1/debug/requests listing: newest-first record summaries."""
        return {
            "requests": self.recorder.list(
                limit=limit, slow_only=slow_only, endpoint=endpoint
            )
        }

    def debug_request(self, request_id) -> dict:
        """One record in full (plan summary, stage rollup, queue-wait).
        The id is sanitized before lookup — a hostile value can only miss."""
        rec = self.recorder.get(request_id)
        if rec is None:
            raise ServeError(
                404, "no_such_request",
                f"request {str(request_id)[:64]!r} is not in the flight "
                "recorder (never seen, or evicted from the ring)",
            )
        return rec.to_dict()

    def debug_trace(self, request_id) -> dict:
        """One record's Chrome-trace document (Perfetto-loadable)."""
        rec = self.recorder.get(request_id)
        if rec is None:
            raise ServeError(
                404, "no_such_request",
                f"request {str(request_id)[:64]!r} is not in the flight "
                "recorder (never seen, or evicted from the ring)",
            )
        doc = rec._trace
        if doc is None:
            if rec.trace_kind is not None:
                # it QUALIFIED (error/slow/sampled) but newer qualifying
                # requests pushed it past the trace budget — the knob to
                # turn is max_traces, not the sampler
                raise ServeError(
                    404, "trace_evicted",
                    f"request {rec.id!r} kept a span tree "
                    f"({rec.trace_kind}) but it was evicted by newer "
                    "traces (raise --debug-max-traces to retain more)",
                )
            raise ServeError(
                404, "no_trace",
                f"request {rec.id!r} kept no span tree (not sampled, not "
                "slow, not errored — raise trace_sample_rate or lower "
                "slow_ms to keep more)",
            )
        return doc

    def debug_slo(self) -> dict:
        """GET /v1/debug/slo: the burn-rate engine's full verdict + window
        math (and, as a side effect, a refresh of the slo_* gauges)."""
        return self.slo.evaluate()

    def debug_fleet(self, urls, *, timeout_s: float = 5.0) -> dict:
        """GET /v1/debug/fleet: scrape `urls` and merge their expositions
        (obs/fleet.py). Raises ValueError when no peer answers — the HTTP
        layer renders that as a typed 502."""
        from ..obs import fleet as _fleet

        return _fleet.federate(urls, timeout_s=timeout_s)

    def debug_tenants(self) -> dict:
        """The /v1/debug/tenants usage table: per-tenant CPU seconds,
        decoded/source/payload bytes, cache outcomes, request and unit
        counts — hottest CPU first, plus the cross-tenant totals. This is
        how a hot tenant is identified BEFORE its byte-budget 429s fire."""
        return {
            "tenants": self.ledger.table(),
            "totals": self.ledger.totals(),
        }

    def debug_vars(self) -> dict:
        """The /v1/debug/vars process snapshot: uptime, pid, version, the
        effective pool sizes, resilience policy, cache/admission budgets
        and obs knobs — everything `parquet-tool debug` needs to know
        about a daemon's configuration without scraping its flags."""
        import os

        from .. import __version__ as _version
        from ..io.autotune import io_tuner as _io_tuner
        from ..io.hedge import resilience_config
        from ..obs.pool import pool_depths

        cfg = self.config
        res = resilience_config()
        # service-relative uptime in the BODY only: the
        # process_uptime_seconds gauge is owned by the exposition render
        # (one writer, one epoch — process start)
        uptime = round(time.time() - self.started_at, 3)
        return {
            "pid": os.getpid(),
            "version": _version,
            "uptime_s": uptime,
            "started_at": self.started_at,
            "pools": {
                "env": {
                    k: os.environ[k]
                    for k in (
                        "PQT_SERVE_THREADS",
                        "PQT_IO_THREADS",
                        "PQT_DATA_THREADS",
                        "PQT_ENCODE_THREADS",
                    )
                    if k in os.environ
                },
                "depths": pool_depths(),
            },
            "serve": {
                "root": cfg.root,
                "cache_mb": cfg.cache_mb,
                "cache_disk_mb": cfg.cache_disk_mb,
                "cache_dir": cfg.cache_dir,
                "io_autotune": cfg.io_autotune,
                "max_inflight": cfg.max_inflight,
                "tenant_concurrent": cfg.tenant_concurrent,
                "tenant_budget_mb": cfg.tenant_budget_mb,
                "budget_window_s": cfg.budget_window_s,
                "default_timeout_s": cfg.default_timeout_s,
                "max_timeout_s": cfg.max_timeout_s,
                "brownout_wait_ms": cfg.brownout_wait_ms,
                "brownout_depth": cfg.brownout_depth,
                "window": cfg.window,
                "max_body_bytes": cfg.max_body_bytes,
                "socket_timeout_s": cfg.socket_timeout_s,
                "shard": list(cfg.shard) if cfg.shard else None,
            },
            "lake": (
                {
                    "root": self.lake.root,
                    "sort_key": self.lake.sort_key,
                    "generation": self.lake.manifest.current_generation(),
                    "flush_mb": cfg.lake_flush_mb,
                    "max_append_bytes": cfg.max_append_bytes,
                    "buffered_rows": (
                        self.ingest.buffered_rows
                        if self.ingest is not None
                        else 0
                    ),
                }
                if self.lake is not None
                else None
            ),
            "obs": {
                "trace_sample_rate": cfg.trace_sample_rate,
                "slow_ms": cfg.slow_ms,
                "debug_ring_size": cfg.debug_ring_size,
                "debug_max_traces": cfg.debug_max_traces,
            },
            "slo": {
                "availability": self.slo.objective.availability,
                "p99_ms": self.slo.objective.p99_ms,
            },
            # process self-stats (same /proc read the exposition gauges
            # refresh from; empty on platforms without procfs)
            "process": _metrics.process_stats(),
            "resilience": {
                "breaker": res.breaker,
                "retry": res.retry,
                "hedge": res.hedge,
            },
            # the shared cache's live occupancy (tier-split for a
            # TieredCache) and the IO tuner's per-transport profiles —
            # what `parquet-tool debug --vars` shows an operator asking
            # "is the tier actually absorbing the hot set?"
            "cache": (
                self.session.block_cache.stats()
                if self.session.block_cache is not None
                else None
            ),
            "io_autotune": _io_tuner().stats(),
        }

    def debug_profile(
        self, seconds: float, interval_ms: float = 10.0
    ) -> _prof.SamplingProfiler:
        """Run one live capture window (the /v1/debug/profile body; the
        HTTP layer renders collapsed/top/json). Bounded: at most 60 s and
        at least 1 ms interval; a concurrent window is a typed 409."""
        if not 0 < seconds <= 60:
            raise ServeError(
                400, "bad_request", "'seconds' must be in (0, 60]"
            )
        if not 1.0 <= interval_ms <= 1000.0:
            raise ServeError(
                400, "bad_request", "'interval_ms' must be in [1, 1000]"
            )
        try:
            return _prof.capture(seconds, interval_ms / 1e3)
        except _prof.ProfilerBusy as e:
            raise ServeError(
                409, "profile_in_progress", str(e), retry_after_s=1
            ) from None


def _count_request(tenant: str, status: int) -> None:
    _metrics.inc("serve_requests_total", status=str(status), tenant=tenant)


# the LakeError -> ServeError taxonomy map: lake codes stay the error
# currency end to end, the HTTP layer only picks the status
_LAKE_STATUS = {
    "unsupported_format": 415,
    "bad_payload": 400,
    "bad_manifest": 500,
    "no_such_generation": 404,
    "no_such_table": 503,
    "commit_conflict": 409,
    "closed": 503,
}


def _lake_serve_error(e) -> "ServeError":
    code = getattr(e, "code", "lake_error")
    return ServeError(_LAKE_STATUS.get(code, 500), code, str(e))


def _normalize_peer(peer: str) -> str:
    """A fleet peer spec as a scrape URL — shared with the CLI's --fleet
    so `?peers=127.0.0.1:8081` and a full URL both work either way."""
    from ..obs.fleet import normalize_peer

    return normalize_peer(peer)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "parquet-tpu-serve"

    # -- plumbing --------------------------------------------------------------

    def setup(self):
        # StreamRequestHandler applies self.timeout to the connection; a
        # stalled read/write then raises TimeoutError (handled as a gone
        # client) instead of pinning the thread + admission slot forever
        self.timeout = getattr(self.server, "socket_timeout", 60.0)
        super().setup()

    def log_message(self, fmt, *args):  # noqa: A003 - BaseHTTPRequestHandler API
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    @property
    def service(self) -> ScanService:
        return self.server.service

    def _tenant(self) -> str:
        # resolved through admission so a flood of distinct X-Tenant values
        # cannot grow per-tenant state or the metrics label set unbounded
        return self.service.admission.resolve_tenant(
            self.headers.get("X-Tenant")
        )

    def _timeout_ms(self):
        return self.headers.get("X-Timeout-Ms")

    def _read_body(self, cap: int | None = None) -> bytes:
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            raise ServeError(400, "bad_request", "bad Content-Length") from None
        if cap is None:
            cap = getattr(self.server, "max_body_bytes", 1 << 20)
        if n > cap:
            # reject on the DECLARED length, before buffering a byte — one
            # request must not be able to exhaust daemon memory ahead of
            # admission (the unread body closes the connection in _drain_body)
            raise ServeError(
                413, "body_too_large",
                f"request body {n} bytes exceeds the {cap}-byte limit",
            )
        body = self.rfile.read(n) if n > 0 else b""
        self._body_read = True
        return body

    def _drain_body(self) -> None:
        """Consume a request body the route never read, so the next
        keep-alive request isn't parsed out of leftover body bytes; bodies
        too large (or unreadable) to drain close the connection instead."""
        if getattr(self, "_body_read", False):
            return
        self._body_read = True
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True
            return
        if n <= 0:
            return
        if n > getattr(self.server, "max_body_bytes", 1 << 20):
            self.close_connection = True
            return
        try:
            self.rfile.read(n)
        except OSError:
            self.close_connection = True

    def _send_json(self, status: int, body: dict, *, retry_after=None) -> None:
        self._send_payload(
            status, (json.dumps(body) + "\n").encode(), retry_after=retry_after
        )

    def _send_payload(
        self, status: int, payload: bytes, *,
        content_type: str = "application/json", retry_after=None,
    ) -> None:
        self._drain_body()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        if getattr(self, "_rid", None):
            self.send_header("X-Request-Id", self._rid)
        tp = getattr(self, "_tp", None)
        if tp is not None:
            # echo the RESOLVED context (daemon's own span-id under the
            # adopted trace-id) — never the client's raw header
            self.send_header("traceparent", tp.header())
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.end_headers()
        self.wfile.write(payload)

    def _send_error_body(self, e: ServeError) -> None:
        # absorb a client that hung up before reading its error: an escape
        # from THIS send would bubble past the route's except clauses into
        # socketserver's traceback dump (TimeoutError is an OSError)
        body = e.to_body()
        if getattr(self, "_rid", None):
            # the correlation key rides the error body too, so a client
            # that logs only bodies can still quote the id to an operator
            body["error"]["request_id"] = self._rid
        tp = getattr(self, "_tp", None)
        if tp is not None:
            # and the cross-process key: a failed request is exactly the
            # one an operator wants to trace-merge across the fleet
            body["error"]["trace_id"] = tp.trace_id
        try:
            self._send_json(e.status, body, retry_after=e.retry_after_s)
        except OSError:
            self.close_connection = True

    # -- chunked streaming -----------------------------------------------------

    def _write_chunk(self, payload: bytes) -> None:
        self.wfile.write(b"%x\r\n" % len(payload) + payload + b"\r\n")

    def _stream(self, chunks, content_type: str):
        """Send a 200 + chunked body. The FIRST chunk is pulled before the
        status line goes out, so planning/admission/decode errors that
        surface lazily still produce a clean typed error response.
        Returns (status, payload bytes sent, error-or-None) for the route
        wrapper to finish metrics + the flight record with."""
        started = False
        status = 200
        nbytes = 0
        err = None
        try:
            it = iter(chunks)
            try:
                first = next(it)
            except StopIteration:
                first = None
            self.send_response(200)
            self.send_header("Content-Type", content_type)
            self.send_header("Transfer-Encoding", "chunked")
            if getattr(self, "_rid", None):
                self.send_header("X-Request-Id", self._rid)
            tp = getattr(self, "_tp", None)
            if tp is not None:
                self.send_header("traceparent", tp.header())
            self.end_headers()
            started = True
            if first:
                self._write_chunk(first)
                nbytes += len(first)
            for payload in it:
                if payload:
                    self._write_chunk(payload)
                    nbytes += len(payload)
            self._write_chunk(b"")  # terminating 0-chunk: complete transfer
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            status = 499  # client gone or stalled; executor aborts via gen.close()
            self.close_connection = True
        except Exception as exc:  # noqa: BLE001 - the no-traceback contract
            # EVERY failure is absorbed here (a stray one escaping would be
            # double-counted by the route handler — and, once the 200 went
            # out, its 500 response line would corrupt the open chunked
            # stream). Non-ServeError = a bug, rendered as the typed 500.
            e = (
                exc
                if isinstance(exc, ServeError)
                else ServeError(500, "internal", f"{type(exc).__name__}: {exc}")
            )
            status, err = e.status, e
            if not started:
                self._send_error_body(e)
            else:
                # mid-stream failure: typed terminal record, then ABORT the
                # chunked encoding (no 0-chunk) so the client cannot
                # mistake the prefix for a complete result
                _metrics.event("serve_stream_aborted")
                if content_type == "application/x-ndjson":
                    try:
                        self._write_chunk(
                            (json.dumps(e.to_body()) + "\n").encode()
                        )
                    except OSError:
                        pass
                self.close_connection = True
        finally:
            chunks.close()
        return status, nbytes, err

    # -- request finishing (metrics + flight record, one place) ----------------

    def _finish(
        self, *, endpoint, tenant, status, t0, rec=None, trace=None,
        nbytes=0, error=None,
    ) -> None:
        dt = time.perf_counter() - t0
        _count_request(tenant, status)
        # one SLI sample per finished recorded request: the burn-rate
        # engine sees exactly what serve_request_seconds sees
        self.service.slo.record(status, dt)
        # endpoint labels are the matched-route constants, never the raw
        # client path — a 404 probe flood cannot grow the label set. The
        # request id rides the histogram bucket as an OpenMetrics exemplar
        # (visible only to scrapers that negotiate that format): a latency
        # spike on a dashboard names the exact /v1/debug/requests record.
        _metrics.observe(
            "serve_request_seconds",
            dt,
            exemplar=({"request_id": rec.id} if rec is not None else None),
            endpoint=endpoint,
        )
        if rec is None:
            return
        svc = self.service
        svc.recorder.finish(
            rec, status, nbytes=nbytes, error=error, trace=trace,
            duration_s=dt,
        )
        # the request's byte/cache usage, charged to its tenant out of the
        # same trace rollup the flight record stores (CPU was already
        # charged per unit by the executor's thread-time clock)
        _cost.charge_request_from_trace(
            tenant, trace, nbytes=nbytes, ledger=svc.ledger
        )
        if dt * 1e3 >= svc.config.slow_ms:
            _metrics.inc("serve_slow_requests_total", endpoint=endpoint)
            _obslog.log_event(
                "slow_request", level="warning",
                endpoint=endpoint, status=status,
                duration_ms=round(dt * 1e3, 3), bytes=nbytes,
            )

    # -- routes ----------------------------------------------------------------

    def _recorded_request(self, endpoint: str, tenant: str, t0, run) -> None:
        """One copy of the request discipline every recorded endpoint runs
        under: open a flight record, bind the log context, run a
        request-scoped trace, render failures through the typed-error
        ladder, and finish metrics + record in one place. `run(rec)` does
        the endpoint work and returns (status, payload bytes, error)."""
        svc = self.service
        rec = svc.recorder.begin(endpoint, tenant, request_id=self._rid)
        self._rid = rec.id
        ctx = getattr(self, "_tp", None)
        if ctx is not None:
            rec.trace_id = ctx.trace_id
        status, nbytes, err, trace = 500, 0, None, None
        with _obslog.log_context(
            request_id=rec.id,
            tenant=tenant,
            trace_id=ctx.trace_id if ctx is not None else None,
        ), _cost.cost_context(tenant), _propagate.propagation_scope(ctx):
            try:
                with decode_trace() as trace:
                    if ctx is not None:
                        trace.trace_id = ctx.trace_id
                    try:
                        status, nbytes, err = run(rec)
                    except ServeError as e:
                        self._send_error_body(e)
                        status, err = e.status, e
                    except (
                        BrokenPipeError, ConnectionResetError, TimeoutError,
                    ):
                        self.close_connection = True
                        status = 499
                    except Exception as e:  # noqa: BLE001 - no-traceback contract
                        self._send_internal_error(e)
                        status, err = 500, e
            finally:
                self._finish(
                    endpoint=endpoint, tenant=tenant, status=status, t0=t0,
                    rec=rec, trace=trace, nbytes=nbytes, error=err,
                )

    def _scan_request(self, tenant: str, t0: float) -> None:
        """POST /v1/scan under the record discipline."""

        def run(rec):
            request = parse_scan_request(self._read_body())
            ticket, content_type, chunks = self.service.scan(
                request, tenant, timeout_ms=self._timeout_ms(), record=rec
            )
            with ticket:
                return self._stream(chunks, content_type)

        self._recorded_request("/v1/scan", tenant, t0, run)

    def _query_request(self, tenant: str, t0: float) -> None:
        """POST /v1/query under the record discipline: aggregation
        push-down. The response is ONE small JSON body (Content-Length,
        not chunked) rendered through the canonical serializer, so daemon
        bytes match `parquet-tool scan --aggregate` bytes."""
        from .aggregate import render_query_body

        def run(rec):
            request = parse_query_request(self._read_body())
            ticket, body = self.service.query(
                request, tenant, timeout_ms=self._timeout_ms(), record=rec
            )
            with ticket:
                payload = render_query_body(body)
                self._send_payload(200, payload)
                return 200, len(payload), None

        self._recorded_request("/v1/query", tenant, t0, run)

    def _append_request(self, tenant: str, t0: float) -> None:
        """POST /v1/append under the record discipline: one row batch
        into the lake ingest buffer. `?flush=1` forces the buffer to
        commit a generation before the ack (the durability handshake)."""

        def run(rec):
            flush = (
                parse_qs(urlsplit(self.path).query).get("flush", ["0"])[0]
                in ("1", "true")
            )
            body = self._read_body(
                cap=getattr(self.server, "max_append_bytes", 32 << 20)
            )
            ticket, ack = self.service.append(
                body,
                self.headers.get("Content-Type"),
                tenant,
                flush=flush,
                record=rec,
            )
            with ticket:
                self._send_json(200, ack)
                return 200, 0, None

        self._recorded_request("/v1/append", tenant, t0, run)

    def _plan_request(self, tenant: str, t0: float, request_fn) -> None:
        """GET/POST /v1/plan under the same record discipline."""

        def run(rec):
            body = self.service.plan(request_fn())
            rec.plan = body
            self._send_json(200, body)
            return 200, 0, None

        self._recorded_request("/v1/plan", tenant, t0, run)

    _DEBUG_PREFIX = "/v1/debug/requests"

    def _debug_request(self, route: str, qs: dict) -> None:
        """GET /v1/debug/requests[/<id>[/trace]] — read-only views of the
        flight recorder. No admission (cheap, in-memory), no record (the
        debugger must not evict the evidence it is reading)."""
        svc = self.service
        if route == self._DEBUG_PREFIX:
            raw = qs.get("limit", ["100"])[-1]
            try:
                limit = int(raw)
            except ValueError:
                raise ServeError(
                    400, "bad_request", f"'limit' must be an integer, got {raw!r}"
                ) from None
            if not 1 <= limit <= 1000:
                raise ServeError(400, "bad_request", "'limit' must be in [1, 1000]")
            slow_only = qs.get("slow", ["0"])[-1] in ("1", "true", "yes")
            endpoint = qs.get("endpoint", [None])[-1]
            self._send_json(
                200,
                svc.debug_requests(
                    limit=limit, slow_only=slow_only, endpoint=endpoint
                ),
            )
            return
        rest = route[len(self._DEBUG_PREFIX) + 1 :]
        if rest.endswith("/trace"):
            self._send_json(200, svc.debug_trace(rest[: -len("/trace")]))
        elif "/" not in rest and rest:
            self._send_json(200, svc.debug_request(rest))
        else:
            raise ServeError(404, "no_such_route", f"unknown path {route!r}")

    def _profile_request(self, qs: dict) -> None:
        """GET /v1/debug/profile?seconds=N[&interval_ms=M][&format=F] —
        run one live capture window on THIS handler thread (connection
        threads are cheap; scan work never runs on them) and return it as
        `collapsed` flamegraph text (default), a `top` self-time table,
        or the full `json` snapshot. No admission: the window is bounded
        at 60 s and a concurrent capture is a typed 409."""

        def num(name, default):
            raw = qs.get(name, [None])[-1]
            if raw is None:
                return default
            try:
                return float(raw)
            except ValueError:
                raise ServeError(
                    400, "bad_request",
                    f"{name!r} must be a number, got {raw!r}",
                ) from None

        seconds = num("seconds", 2.0)
        interval_ms = num("interval_ms", 10.0)
        fmt = qs.get("format", ["collapsed"])[-1]
        if fmt not in ("collapsed", "top", "json"):
            raise ServeError(
                400, "bad_request",
                "'format' must be collapsed, top or json",
            )
        prof = self.service.debug_profile(seconds, interval_ms)
        if fmt == "json":
            self._send_json(200, prof.snapshot())
            return
        text = prof.collapsed() if fmt == "collapsed" else prof.render_top(30)
        payload = text.encode()
        self._drain_body()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        if self._rid:
            self.send_header("X-Request-Id", self._rid)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        split = urlsplit(self.path)
        route = split.path
        t0 = time.perf_counter()
        self._body_read = False  # per-request: the handler serves many
        self._rid = self._request_id()
        self._tp = self._trace_context()
        tenant = self._tenant()
        try:
            if route == "/healthz":
                status, body = self.service.healthz()
                self._send_json(
                    status, body, retry_after=body.get("retry_after_s")
                )
                return
            if route == "/metrics":
                self._drain_body()
                # content negotiation: a scraper asking for OpenMetrics
                # gets the exemplar-carrying variant (+ the # EOF
                # terminator); everyone else sees the classic text format
                # byte-for-byte unchanged
                accept = self.headers.get("Accept") or ""
                if "application/openmetrics-text" in accept:
                    payload = _metrics.render_openmetrics().encode()
                    ctype = (
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8"
                    )
                else:
                    payload = _metrics.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                if self._rid:
                    self.send_header("X-Request-Id", self._rid)
                self.end_headers()
                self.wfile.write(payload)
                return
            if route == "/v1/plan":
                self._plan_request(
                    tenant, t0,
                    lambda: scan_request_from_query(parse_qs(split.query)),
                )
                return
            if route == self._DEBUG_PREFIX or route.startswith(
                self._DEBUG_PREFIX + "/"
            ):
                self._debug_request(route, parse_qs(split.query))
                return
            if route == "/v1/debug/tenants":
                self._send_json(200, self.service.debug_tenants())
                return
            if route == "/v1/debug/vars":
                self._send_json(200, self.service.debug_vars())
                return
            if route == "/v1/debug/profile":
                self._profile_request(parse_qs(split.query))
                return
            if route == "/v1/debug/slo":
                self._send_json(200, self.service.debug_slo())
                return
            if route == "/v1/debug/fleet":
                self._fleet_request(parse_qs(split.query))
                return
            raise ServeError(404, "no_such_route", f"unknown path {route!r}")
        except ServeError as e:
            self._send_error_body(e)
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            self.close_connection = True  # scraper/LB hung up or stalled
        except Exception as e:  # noqa: BLE001 - the no-traceback contract
            self._send_internal_error(e)

    def _request_id(self) -> str | None:
        """The sanitized client-supplied X-Request-Id (None generates one
        at record-begin time). Bounded exactly like tenant keys: a hostile
        header cannot grow the ring, the index, or the debug JSON."""
        return _sanitize_request_id(self.headers.get("X-Request-Id"))

    def _trace_context(self):
        """Resolve the inbound traceparent header into this request's
        propagation context (the X-Request-Id discipline applied to trace
        context: a malformed header is counted and REPLACED by a mint,
        never echoed). Every request — including /metrics scrapes — gets
        a context, so every outbound call a request makes is traceable."""
        ctx, _ = _propagate.resolve_inbound(self.headers.get("traceparent"))
        return ctx

    _MAX_FLEET_PEERS = 32

    def _fleet_request(self, qs: dict) -> None:
        """GET /v1/debug/fleet?peers=host:port[,host:port...] — scrape the
        named replicas' /metrics and answer the MERGED exposition (plus
        `# fleet:` comment lines naming merged/failed replicas — comments
        are legal exposition content). Bounded peer count: a hostile query
        cannot fan this daemon out unboundedly."""
        raw = qs.get("peers", [None])[-1]
        if not raw:
            raise ServeError(
                400, "bad_request",
                "'peers' query parameter required: "
                "peers=host:port[,host:port...]",
            )
        peers = [p.strip() for p in raw.split(",") if p.strip()]
        if not peers:
            raise ServeError(400, "bad_request", "'peers' names no replica")
        if len(peers) > self._MAX_FLEET_PEERS:
            raise ServeError(
                400, "bad_request",
                f"at most {self._MAX_FLEET_PEERS} peers per fleet scrape "
                f"(got {len(peers)})",
            )
        urls = [_normalize_peer(p) for p in peers]
        try:
            view = self.service.debug_fleet(urls)
        except ValueError as e:
            raise ServeError(502, "fleet_unreachable", str(e)) from None
        lines = [
            "# fleet: merged "
            + f"{len(view['replicas'])} replica(s): "
            + ", ".join(view["replicas"])
        ]
        for replica, err in view["errors"].items():
            lines.append(f"# fleet: {replica} failed: {err}")
        payload = ("\n".join(lines) + "\n" + view["text"]).encode()
        self._send_payload(
            200, payload,
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )

    def _send_internal_error(self, e) -> None:
        """Best-effort typed 500: never let a dead socket turn a handler
        bug into a socketserver traceback dump."""
        try:
            self._send_error_body(
                ServeError(500, "internal", f"{type(e).__name__}: {e}")
            )
        except OSError:
            self.close_connection = True

    def do_POST(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        route = urlsplit(self.path).path
        t0 = time.perf_counter()
        self._body_read = False  # per-request: the handler serves many
        self._rid = self._request_id()
        self._tp = self._trace_context()
        tenant = self._tenant()
        try:
            if route == "/v1/scan":
                self._scan_request(tenant, t0)
                return
            if route == "/v1/query":
                self._query_request(tenant, t0)
                return
            if route == "/v1/append":
                self._append_request(tenant, t0)
                return
            if route == "/v1/plan":
                self._plan_request(
                    tenant, t0, lambda: parse_scan_request(self._read_body())
                )
                return
            raise ServeError(404, "no_such_route", f"unknown path {route!r}")
        except ServeError as e:
            self._send_error_body(e)
            _count_request(tenant, e.status)
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            self.close_connection = True
            _count_request(tenant, 499)
        except Exception as e:  # noqa: BLE001 - the no-traceback contract
            self._send_internal_error(e)
            _count_request(tenant, 500)


class ScanServer:
    """Lifecycle wrapper: bind, serve (foreground or background thread),
    drain, stop. `port=0` binds an ephemeral port (tests/bench).

    Subclass seams (the mesh router rides the whole lifecycle — bind,
    background serve, drain, signal handlers — with its own brain):
    `service_cls` builds the request brain from the config, `handler_cls`
    is the per-connection handler, `thread_name` names the accept loop."""

    service_cls = ScanService
    handler_cls = _Handler
    thread_name = "pqt-serve-http"

    def __init__(self, config: ServeConfig, *, verbose: bool = False):
        self.config = config
        self.service = type(self).service_cls(config)
        self._httpd = ThreadingHTTPServer(
            (config.host, config.port), type(self).handler_cls
        )
        self._httpd.daemon_threads = True
        self._httpd.service = self.service
        self._httpd.verbose = verbose
        self._httpd.socket_timeout = config.socket_timeout_s
        self._httpd.max_body_bytes = config.max_body_bytes
        self._httpd.max_append_bytes = config.max_append_bytes
        self._thread: threading.Thread | None = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- run -------------------------------------------------------------------

    def serve_forever(self) -> None:
        self._httpd.serve_forever(poll_interval=0.1)

    def start_background(self) -> "ScanServer":
        self._thread = threading.Thread(
            target=self.serve_forever, name=type(self).thread_name,
            daemon=True,
        )
        self._thread.start()
        return self

    # -- stop ------------------------------------------------------------------

    def drain(self, timeout: float | None = 30.0) -> bool:
        """Graceful shutdown, the SIGTERM semantics: stop admitting (new
        scans get typed 503s), let in-flight requests complete (bounded by
        `timeout`), then stop the listener. True iff fully drained."""
        _obslog.log_event(
            "drain_begin", in_flight=self.service.admission.in_flight
        )
        self.service.admission.begin_drain()
        drained = self.service.admission.wait_drained(timeout=timeout)
        _obslog.log_event(
            "drain_complete",
            level="info" if drained else "warning",
            drained=drained,
        )
        self.shutdown()
        return drained

    def shutdown(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        try:
            self.shutdown()
        finally:
            # the ingest buffer's tail commits one last generation (rows
            # a client appended without ?flush=1 survive a clean stop)
            ingest = getattr(self.service, "ingest", None)
            if ingest is not None:
                try:
                    ingest.close()
                except Exception:  # noqa: BLE001 — close() must not raise
                    pass
            self._httpd.server_close()
            # a tiered cache the SERVICE built owns spill files/fds; a
            # config-passed block_cache belongs to the caller (it may be
            # shared with live dataset workers). BlockCache has no close;
            # a sessionless service (the mesh router) has no cache at all.
            session = getattr(self.service, "session", None)
            cache = getattr(session, "block_cache", None)
            if getattr(self.service, "_owns_cache", True) and hasattr(
                cache, "close"
            ):
                cache.close()

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain then stop (main thread only —
        the `parquet-tool serve` foreground path)."""
        import signal

        def _on_term(signum, frame):
            # the handler must not block the main loop: drain on a thread,
            # which shuts the listener down when the last request leaves
            threading.Thread(
                target=self.drain, name="pqt-serve-drain", daemon=True
            ).start()

        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""Vectorized bit-packing kernels (host path).

The reference generates 4,574 lines of width-specialized Go (reference:
bitpack_gen.go, bitbacking32.go:10-44, bitpacking64.go:10) to pack/unpack groups
of 8 values at bit widths 0..64. Here the same operation is a single vectorized
formulation, parameterized by width:

    unpack:  bytes --np.unpackbits(LSB-first)--> bitstream --reshape (N, W)-->
             bit-matrix @ [1, 2, 4, ...]  (per-value little-endian bit weights)
    pack:    values -> bit-matrix ((v >> j) & 1) -> flatten -> np.packbits

Parquet's RLE/bit-packed hybrid packs values LSB-first back to back, so bit j of
value i is bit (i*W + j) of the byte stream — exactly NumPy's little bitorder.
This same bit-matrix ⊗ weight-vector shape is what the Pallas kernel uses on TPU
(kernels/bitpack_tpu.py), where the contraction maps onto the MXU for large
batches.
"""

from __future__ import annotations

import numpy as np

__all__ = ["unpack_bits", "pack_bits", "bit_width", "bytes_for"]


def bit_width(v: int) -> int:
    """Number of bits needed to represent v (0 -> 0)."""
    return int(v).bit_length()


def bytes_for(num_values: int, width: int) -> int:
    """Bytes occupied by num_values bit-packed values (caller pads to groups of 8)."""
    return (num_values * width + 7) // 8


def unpack_bits(
    data, num_values: int, width: int, dtype=np.uint64, bit_offset: int = 0
) -> np.ndarray:
    """Unpack `num_values` little-endian bit-packed values of `width` bits.

    `data` is a bytes-like; values start `bit_offset` bits into it (windowed
    consumers like PackedLevels.widen pass unaligned starts) and only the
    covering bytes are consumed. Returns an array of `dtype`.
    """
    if width == 0:
        return np.zeros(num_values, dtype=dtype)
    if width > 64:
        raise ValueError(f"bitpack: unsupported width {width}")
    byte0 = bit_offset >> 3
    off = bit_offset - (byte0 << 3)
    nbytes = (off + num_values * width + 7) >> 3
    raw = np.frombuffer(data, dtype=np.uint8, offset=byte0, count=nbytes)
    bits = np.unpackbits(raw, bitorder="little")
    needed = num_values * width
    if bits.size - off < needed:
        raise ValueError("bitpack: input too short")
    bits = bits[off : off + needed].reshape(num_values, width)
    weights = (np.uint64(1) << np.arange(width, dtype=np.uint64))
    out = bits.astype(np.uint64) @ weights
    return out.astype(dtype, copy=False)


def pack_bits(values: np.ndarray, width: int) -> bytes:
    """Pack values (non-negative, < 2**width) LSB-first at `width` bits each.

    The caller is responsible for padding to a multiple of 8 values where the
    format requires it (hybrid bit-packed runs always cover groups of 8).
    """
    if width == 0 or len(values) == 0:
        return b""
    if width > 64:
        raise ValueError(f"bitpack: unsupported width {width}")
    v = np.asarray(values).astype(np.uint64, copy=False)
    if width < 64 and v.size and int(v.max()) >= (1 << width):
        raise ValueError(
            f"bitpack: value {int(v.max())} does not fit in {width} bits"
        )
    shifts = np.arange(width, dtype=np.uint64)
    bits = ((v[:, None] >> shifts[None, :]) & np.uint64(1)).astype(np.uint8)
    return np.packbits(bits.reshape(-1), bitorder="little").tobytes()

"""Repetition/definition level codec (host path).

Levels are RLE-encoded (hybrid) at width bit_length(max_level). V1 data pages
prefix the level stream with a 4-byte LE length (reference:
hybrid_decoder.go:56-66); V2 pages store levels raw, sizes in the page header
(reference: page_v2.go:79-131). max_level == 0 means the stream is absent and
all levels are 0 (reference: helpers.go:210-231 constDecoder).
"""

from __future__ import annotations

import struct

import numpy as np

from .bitpack import bit_width
from .rle_hybrid import decode_hybrid, encode_hybrid

__all__ = [
    "decode_levels_v1",
    "decode_levels_v2",
    "encode_levels_v1",
    "encode_levels_v2",
    "LevelError",
]


class LevelError(ValueError):
    pass


def decode_levels_v1(data, num_values: int, max_level: int) -> tuple[np.ndarray, int]:
    """Returns (levels, total bytes consumed incl. the 4-byte size prefix)."""
    if max_level == 0:
        return np.zeros(num_values, dtype=np.uint16), 0
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    if len(buf) < 4:
        raise LevelError("levels: truncated v1 size prefix")
    (size,) = struct.unpack_from("<I", buf, 0)
    if 4 + size > len(buf):
        raise LevelError(f"levels: v1 stream size {size} exceeds page")
    levels = decode_hybrid(buf[4 : 4 + size], num_values, bit_width(max_level), dtype=np.uint16)
    _check(levels, max_level)
    return levels, 4 + size


def decode_levels_v2(data, num_values: int, max_level: int) -> np.ndarray:
    """V2: `data` is exactly the level stream (length from the page header)."""
    if max_level == 0:
        return np.zeros(num_values, dtype=np.uint16)
    levels = decode_hybrid(data, num_values, bit_width(max_level), dtype=np.uint16)
    _check(levels, max_level)
    return levels


def encode_levels_v1(levels, max_level: int) -> bytes:
    if max_level == 0:
        return b""
    stream = encode_hybrid(np.asarray(levels), bit_width(max_level))
    return struct.pack("<I", len(stream)) + stream


def encode_levels_v2(levels, max_level: int) -> bytes:
    if max_level == 0:
        return b""
    return encode_hybrid(np.asarray(levels), bit_width(max_level))


def _check(levels: np.ndarray, max_level: int) -> None:
    if levels.size and int(levels.max()) > max_level:
        raise LevelError(
            f"levels: value {int(levels.max())} exceeds max level {max_level}"
        )

"""Repetition/definition level codec (host path).

Levels are RLE-encoded (hybrid) at width bit_length(max_level). V1 data pages
prefix the level stream with a 4-byte LE length (reference:
hybrid_decoder.go:56-66); V2 pages store levels raw, sizes in the page header
(reference: page_v2.go:79-131). max_level == 0 means the stream is absent and
all levels are 0 (reference: helpers.go:210-231 constDecoder).
"""

from __future__ import annotations

import struct

import numpy as np

from .bitpack import bit_width
from .rle_hybrid import decode_hybrid, encode_hybrid

__all__ = [
    "decode_levels_v1",
    "decode_levels_v2",
    "encode_levels_v1",
    "encode_levels_v2",
    "LevelError",
    "rows_from_rep",
    "slot_ids",
    "list_layout",
    "validity_from_def",
]


class LevelError(ValueError):
    pass


def _single_rle_run(buf, num_values: int, width: int):
    """Value of the stream's first RLE run if it alone covers num_values,
    else None. The all-one-value level stream (no nulls / flat data) is the
    overwhelmingly common case; recognizing it from the run header skips the
    full hybrid decode AND the O(n) range check / non-null count."""
    pos = 0
    header = 0
    shift = 0
    while True:
        if pos >= len(buf) or shift > 35:
            return None
        b = buf[pos]
        pos += 1
        header |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if header & 1 or (header >> 1) < num_values:
        return None
    nbytes = (width + 7) // 8
    if pos + nbytes > len(buf):
        return None
    return int.from_bytes(buf[pos : pos + nbytes], "little")


def decode_levels_v1(
    data, num_values: int, max_level: int, want_const: bool = False
):
    """Returns (levels, total bytes consumed incl. the 4-byte size prefix);
    with want_const=True, (levels, consumed, const_value_or_None)."""
    if max_level == 0:
        z = np.zeros(num_values, dtype=np.uint16)
        return (z, 0, 0) if want_const else (z, 0)
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    if len(buf) < 4:
        raise LevelError("levels: truncated v1 size prefix")
    (size,) = struct.unpack_from("<I", buf, 0)
    if 4 + size > len(buf):
        raise LevelError(f"levels: v1 stream size {size} exceeds page")
    width = bit_width(max_level)
    cv = _single_rle_run(buf[4 : 4 + size], num_values, width) if num_values else None
    if cv is not None:
        if cv > max_level:
            raise LevelError(f"levels: value {cv} exceeds max level {max_level}")
        levels = np.full(num_values, cv, dtype=np.uint16)
        return (levels, 4 + size, cv) if want_const else (levels, 4 + size)
    levels = decode_hybrid(buf[4 : 4 + size], num_values, width, dtype=np.uint16)
    _check(levels, max_level)
    return (levels, 4 + size, None) if want_const else (levels, 4 + size)


def decode_levels_v2(data, num_values: int, max_level: int, want_const: bool = False):
    """V2: `data` is exactly the level stream (length from the page header).
    With want_const=True returns (levels, const_value_or_None)."""
    if max_level == 0:
        z = np.zeros(num_values, dtype=np.uint16)
        return (z, 0) if want_const else z
    width = bit_width(max_level)
    cv = _single_rle_run(data, num_values, width) if num_values else None
    if cv is not None:
        if cv > max_level:
            raise LevelError(f"levels: value {cv} exceeds max level {max_level}")
        levels = np.full(num_values, cv, dtype=np.uint16)
        return (levels, cv) if want_const else levels
    levels = decode_hybrid(data, num_values, width, dtype=np.uint16)
    _check(levels, max_level)
    return (levels, None) if want_const else levels


def encode_levels_v1(levels, max_level: int) -> bytes:
    if max_level == 0:
        return b""
    stream = encode_hybrid(np.asarray(levels), bit_width(max_level))
    return struct.pack("<I", len(stream)) + stream


def encode_levels_v2(levels, max_level: int) -> bytes:
    if max_level == 0:
        return b""
    return encode_hybrid(np.asarray(levels), bit_width(max_level))


def _check(levels: np.ndarray, max_level: int) -> None:
    if levels.size and int(levels.max()) > max_level:
        raise LevelError(
            f"levels: value {int(levels.max())} exceeds max level {max_level}"
        )


# -- assembly prefix scans ------------------------------------------------------
#
# The data-parallel formulation of Dremel record assembly (PAPER.md; reference
# schema.go:216-312 walks these streams entry by entry): every structural fact
# a cursor walk discovers one `int(levels[pos])` at a time is a whole-column
# scan over the rep/def arrays. These four primitives are the complete set —
# core/assembly_vec.py composes them per nesting depth, and
# kernels/device_ops.list_layout_device is the same math in jittable JAX so
# device-resident level streams never round-trip to the host.


def rows_from_rep(rep, n: int | None = None) -> np.ndarray:
    """Positions where a record starts (rep == 0), as int64 indices.

    `rep is None` means the column has no repetition dimension: every entry
    starts a record, so the starts are 0..n-1 (`n` required then)."""
    if rep is None:
        if n is None:
            raise ValueError("rows_from_rep: n required when rep is None")
        return np.arange(n, dtype=np.int64)
    return np.flatnonzero(np.asarray(rep) == 0)


def slot_ids(rep, parent_rep: int) -> np.ndarray:
    """Which slot (instance at nesting depth `parent_rep`) each level entry
    belongs to: the inclusive prefix count of boundary entries, minus one.
    An entry opens a new slot iff its rep level <= parent_rep (reference
    data_store.go:294-308: the loop-until-rep-drops cursor walk, as one
    cumsum)."""
    return np.cumsum(np.asarray(rep) <= parent_rep, dtype=np.int64) - 1


def list_layout(rep, dfl, slot_of, n_slots: int, elem_rep: int, elem_def: int):
    """One repeated node's Arrow-style layout over the current entry stream.

    rep/dfl are the stream's level arrays, slot_of the slot each entry
    belongs to at the PARENT's granularity (from slot_ids, int64,
    non-decreasing over n_slots slots). An entry STARTS an element of this
    depth iff its rep level <= elem_rep AND its def level >= elem_def (below
    elem_def the entry is the placeholder of an empty/null list and
    contributes no element); entries with rep > elem_rep extend the open
    element's subtree.

    Returns (offsets, elem_start, exists):
      offsets     int64[n_slots+1]  element-count prefix sums — slot i's
                                    elements sit at [offsets[i], offsets[i+1])
      elem_start  bool[n]           entry opens an element of this depth
      exists      bool[n]           entry belongs to SOME element of this
                                    depth (the child stream's keep mask)
    """
    rep = np.asarray(rep)
    dfl = np.asarray(dfl)
    exists = dfl >= elem_def
    elem_start = (rep <= elem_rep) & exists
    counts = np.bincount(slot_of[elem_start], minlength=n_slots)
    offsets = np.zeros(n_slots + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return offsets, elem_start, exists


def validity_from_def(first_def, null_def: int):
    """Null mask (uint8[n_slots], 1 = null) from each slot's first def
    level: the slot's node is absent where that level sits below `null_def`.
    None when every slot is present (callers skip mask work entirely then —
    the overwhelmingly common all-present case stays one vectorized
    compare)."""
    if null_def <= 0:
        return None
    first_def = np.asarray(first_def)
    if bool((first_def >= null_def).all()):
        return None
    return (first_def < null_def).astype(np.uint8)

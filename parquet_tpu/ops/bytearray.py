"""DELTA_LENGTH_BYTE_ARRAY and DELTA_BYTE_ARRAY codecs (host path).

Format (parquet-format Encodings.md; reference: type_bytearray.go:98-292):
  DELTA_LENGTH_BYTE_ARRAY = delta-bp int32 lengths stream, then all value bytes
  concatenated. DELTA_BYTE_ARRAY = delta-bp int32 shared-prefix lengths, then a
  DELTA_LENGTH_BYTE_ARRAY stream of suffixes; value[i] = value[i-1][:prefix[i]]
  + suffix[i].

Lengths/offsets decode vectorizes via the delta codec; only the prefix
reconstruction of DELTA_BYTE_ARRAY is inherently sequential (each value depends
on the previous), and stays a host loop.
"""

from __future__ import annotations

import numpy as np

from ..core.arrays import ByteArrayData
from .delta import decode_delta, encode_delta

__all__ = [
    "decode_delta_length_byte_array",
    "encode_delta_length_byte_array",
    "decode_delta_byte_array",
    "encode_delta_byte_array",
    "ByteArrayError",
]


class ByteArrayError(ValueError):
    pass


def decode_delta_length_byte_array(data, num_values: int) -> tuple[ByteArrayData, int]:
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    lengths, consumed = decode_delta(buf, 32, max_total=num_values)
    if len(lengths) < num_values:
        raise ByteArrayError(
            f"delta-length: stream has {len(lengths)} lengths, need {num_values}"
        )
    lengths = lengths[:num_values].astype(np.int64)
    if num_values and lengths.min() < 0:
        raise ByteArrayError("delta-length: negative length")
    offsets = np.zeros(num_values + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    total = int(offsets[-1])
    if consumed + total > len(buf):
        raise ByteArrayError("delta-length: value bytes exceed page")
    blob = bytes(buf[consumed : consumed + total])
    return ByteArrayData(offsets=offsets, data=blob), consumed + total


def encode_delta_length_byte_array(values: ByteArrayData) -> bytes:
    lengths = (values.offsets[1:] - values.offsets[:-1]).astype(np.int32)
    return encode_delta(lengths, 32) + values.data


def decode_delta_byte_array(data, num_values: int) -> tuple[ByteArrayData, int]:
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    prefixes, consumed = decode_delta(buf, 32, max_total=num_values)
    if len(prefixes) < num_values:
        raise ByteArrayError("delta-byte-array: prefix stream too short")
    prefixes = prefixes[:num_values].astype(np.int64)
    suffixes, consumed2 = decode_delta_length_byte_array(buf[consumed:], num_values)
    # Sequential prefix reconstruction with bounds checks
    # (reference: type_bytearray.go:227-230).
    out_parts: list[bytes] = []
    prev = b""
    soff = suffixes.offsets
    sdata = suffixes.data
    for i in range(num_values):
        p = int(prefixes[i])
        if p < 0 or p > len(prev):
            raise ByteArrayError(
                f"delta-byte-array: prefix {p} exceeds previous value length {len(prev)}"
            )
        v = prev[:p] + sdata[soff[i] : soff[i + 1]]
        out_parts.append(v)
        prev = v
    return ByteArrayData.from_list(out_parts), consumed + consumed2


def encode_delta_byte_array(values: ByteArrayData) -> bytes:
    n = len(values)
    prefixes = np.zeros(n, dtype=np.int32)
    suffix_parts: list[bytes] = []
    prev = b""
    for i in range(n):
        v = values[i]
        p = _shared_prefix(prev, v)
        prefixes[i] = p
        suffix_parts.append(v[p:])
        prev = v
    return encode_delta(prefixes, 32) + encode_delta_length_byte_array(
        ByteArrayData.from_list(suffix_parts)
    )


def _shared_prefix(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i

"""RLE_DICTIONARY index codec (host path).

Data pages of dictionary-encoded columns carry: 1 byte bit-width, then a hybrid
RLE/bit-packed stream of indices into the dictionary page (reference:
type_dict.go:22-60, :135-159). Index bounds are validated against the
dictionary size before any gather (reference: type_dict.go:52-54).
"""

from __future__ import annotations

import numpy as np

from .bitpack import bit_width
from .rle_hybrid import decode_hybrid, encode_hybrid

__all__ = ["decode_dict_indices", "encode_dict_indices", "DictError"]


class DictError(ValueError):
    pass


def decode_dict_indices(data, num_values: int, dict_size: int) -> np.ndarray:
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    if num_values == 0:
        return np.empty(0, dtype=np.uint32)
    if len(buf) < 1:
        raise DictError("dict: missing bit-width byte")
    width = buf[0]
    if width > 32:
        raise DictError(f"dict: invalid index bit width {width}")
    indices = decode_hybrid(buf[1:], num_values, width, dtype=np.uint32)
    if indices.size and int(indices.max()) >= dict_size:
        raise DictError(
            f"dict: index {int(indices.max())} out of range (dictionary has {dict_size})"
        )
    return indices


def encode_dict_indices(indices, dict_size: int) -> bytes:
    width = bit_width(max(dict_size - 1, 0))
    return bytes([width]) + encode_hybrid(np.asarray(indices), width)

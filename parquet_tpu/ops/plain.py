"""PLAIN encoding per physical type (host path).

Semantics match the reference's per-type codecs (reference: type_boolean.go,
type_int32.go, type_int64.go, type_int96.go, type_float.go, type_double.go,
type_bytearray.go) but decode whole pages as array views instead of one boxed
value per call. Numeric decode is a dtype view of the wire bytes — bit-exact by
construction, including NaN payloads (SURVEY §7.3 hard-part #2).
"""

from __future__ import annotations

import numpy as np

from ..meta.parquet_types import Type
from ..core.arrays import ByteArrayData

__all__ = ["decode_plain", "encode_plain", "PlainError"]


class PlainError(ValueError):
    pass


_NUMERIC_DTYPES = {
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
}


def decode_plain(data, num_values: int, ptype: Type, type_length: int | None = None):
    """Decode `num_values` PLAIN values. Returns (values, bytes_consumed)."""
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    if ptype in _NUMERIC_DTYPES:
        dt = _NUMERIC_DTYPES[ptype]
        need = num_values * dt.itemsize
        if len(buf) < need:
            raise PlainError(
                f"plain: need {need} bytes for {num_values} {ptype.name}, have {len(buf)}"
            )
        return np.frombuffer(buf, dtype=dt, count=num_values), need
    if ptype == Type.BOOLEAN:
        need = (num_values + 7) // 8
        if len(buf) < need:
            raise PlainError("plain: boolean payload too short")
        bits = np.unpackbits(
            np.frombuffer(buf, dtype=np.uint8, count=need), bitorder="little"
        )
        return bits[:num_values].astype(bool), need
    if ptype == Type.INT96:
        need = num_values * 12
        if len(buf) < need:
            raise PlainError("plain: int96 payload too short")
        return (
            np.frombuffer(buf, dtype=np.uint8, count=need).reshape(num_values, 12),
            need,
        )
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        if not type_length or type_length < 0:
            raise PlainError("plain: fixed_len_byte_array requires type_length")
        need = num_values * type_length
        if len(buf) < need:
            raise PlainError("plain: fixed payload too short")
        return (
            np.frombuffer(buf, dtype=np.uint8, count=need).reshape(
                num_values, type_length
            ),
            need,
        )
    if ptype == Type.BYTE_ARRAY:
        return _decode_plain_byte_array(buf, num_values)
    raise PlainError(f"plain: unsupported type {ptype}")


def _decode_plain_byte_array(buf: memoryview, num_values: int):
    # Inline 4-byte LE length before each value (reference: type_bytearray.go:24-45).
    # The offset chain is data-dependent; the native C++ helper does the walk at
    # memcpy speed, with a pure-Python fallback.
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_byte_array_scan and num_values > 0:
        try:
            offsets, flat, consumed = lib.byte_array_gather(buf, num_values)
        except ValueError as e:
            raise PlainError(str(e)) from e
        return ByteArrayData(offsets=offsets, data=flat), consumed
    end = len(buf)
    offsets = np.empty(num_values + 1, dtype=np.int64)
    offsets[0] = 0
    parts = []
    pos = 0
    total = 0
    b = buf
    for i in range(num_values):
        if pos + 4 > end:
            raise PlainError("plain: truncated byte_array length")
        ln = int.from_bytes(b[pos : pos + 4], "little")
        pos += 4
        if ln < 0 or pos + ln > end:
            raise PlainError(f"plain: byte_array length {ln} exceeds page")
        parts.append(bytes(b[pos : pos + ln]))
        pos += ln
        total += ln
        offsets[i + 1] = total
    return ByteArrayData(offsets=offsets, data=b"".join(parts)), pos


def encode_plain(values, ptype: Type, type_length: int | None = None) -> bytes:
    """Encode values (in the array representations of decode_plain) as PLAIN."""
    if ptype in _NUMERIC_DTYPES:
        dt = _NUMERIC_DTYPES[ptype]
        return np.ascontiguousarray(np.asarray(values, dtype=dt)).tobytes()
    if ptype == Type.BOOLEAN:
        v = np.asarray(values, dtype=bool)
        return np.packbits(v.astype(np.uint8), bitorder="little").tobytes()
    if ptype in (Type.INT96, Type.FIXED_LEN_BYTE_ARRAY):
        v = np.asarray(values, dtype=np.uint8)
        if v.ndim != 2:
            raise PlainError(f"plain: {ptype.name} expects a (n, width) uint8 array")
        if ptype == Type.INT96 and v.shape[1] != 12:
            raise PlainError("plain: int96 rows must be 12 bytes")
        if ptype == Type.FIXED_LEN_BYTE_ARRAY and type_length and v.shape[1] != type_length:
            raise PlainError("plain: fixed-len width mismatch")
        return v.tobytes()
    if ptype == Type.BYTE_ARRAY:
        if isinstance(values, ByteArrayData):
            from ..utils.native import get_native

            lib = get_native()
            if lib is not None and lib.has_plain_encode_ba:
                # one C pass over (offsets, data) — the write path's hot
                # loop for string chunks; the Python loop below is the
                # no-native oracle
                return lib.plain_encode_bytearray(values.data, values.offsets)
            items = values.to_list(cache=True)
        else:
            items = [bytes(x) for x in values]
        out = bytearray()
        for item in items:
            out += len(item).to_bytes(4, "little")
            out += item
        return bytes(out)
    raise PlainError(f"plain: unsupported type {ptype}")

"""Shared ULEB128 varint helpers for the byte-stream codecs."""

from __future__ import annotations

__all__ = ["read_uvarint", "read_zigzag", "emit_uvarint", "emit_zigzag"]


def read_uvarint(buf, pos: int, end: int, err=ValueError) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= end:
            raise err("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            if result >= 1 << 64:
                # overflow — same rejection as Go's binary.ReadUvarint (the
                # native C path accumulates in uint64 and must agree)
                raise err("varint overflows uint64")
            return result, pos
        shift += 7
        if shift > 63:
            raise err("varint too long")


def read_zigzag(buf, pos: int, end: int, err=ValueError) -> tuple[int, int]:
    n, pos = read_uvarint(buf, pos, end, err)
    return (n >> 1) ^ -(n & 1), pos


def emit_uvarint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def emit_zigzag(out: bytearray, v: int) -> None:
    emit_uvarint(out, (v << 1) ^ (v >> 63))

"""DELTA_BINARY_PACKED codec (host path) + block-table prescan for the TPU path.

Wire format (parquet-format Encodings.md; same semantics as the reference's
deltabp_decoder.go/deltabp_encoder.go): ULEB128 header <block size> <miniblocks
per block> <total value count> <first value: zigzag>, then per block: <min
delta: zigzag> <one width byte per miniblock> <bit-packed miniblock payloads>.

Semantics reproduced from the reference (SURVEY §7.3 hard-part #2):
  - all delta arithmetic wraps at the type width — min-delta subtraction can
    overflow by design (reference: deltabp_encoder.go:58-61), so decode runs in
    unsigned modular arithmetic and bit-casts at the end;
  - a miniblock that holds >=1 value always carries its full payload,
    (miniblock_len/8)*width bytes, zero-padded (reference: deltabp_decoder.go
    buf construction in flush());
  - unused trailing miniblocks carry a width byte but NO payload; writers
    should set those widths to 0 but readers must accept arbitrary values
    (parquet-format Encodings.md; the reference writes 0-width there,
    deltabp_encoder.go flush()).

The reference decodes one value per call through a virtual unpacker table
(deltabp_decoder.go:113-174); here the whole stream becomes one concatenated
(delta + min_delta) vector and a single wrapping cumulative sum — an associative
scan, which is exactly what the TPU kernel parallelizes (kernels/delta_tpu.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .bitpack import pack_bits, unpack_bits
from .varint import emit_uvarint as _emit_uvarint_impl, emit_zigzag as _emit_zigzag_impl, read_uvarint, read_zigzag

__all__ = [
    "DeltaError",
    "decode_delta",
    "encode_delta",
    "prescan_delta",
    "prescan_delta_packed",
    "DeltaTable",
    "DeltaPackedTable",
]

# Defaults carried over from the reference (chunk_writer.go:53-57,69-73).
DEFAULT_BLOCK_SIZE = 128
DEFAULT_MINIBLOCKS = 4


class DeltaError(ValueError):
    pass




@dataclass
class DeltaTable:
    """Prescanned delta stream, ready for parallel expansion.

    deltas_plus_min  uint64 array of length total-1: (raw delta + block min_delta)
                     mod 2**nbits, in order
    first_value      unsigned first value (mod 2**nbits)
    total            total value count from the header
    consumed         bytes consumed from the input
    """

    deltas_plus_min: np.ndarray
    first_value: int
    total: int
    consumed: int


def prescan_delta(data, nbits: int, max_total: int | None = None) -> DeltaTable:
    """Parse headers + unpack miniblocks into a flat modular-delta vector.

    The header walk is sequential but touches only varints and width bytes; the
    miniblock unpacking is vectorized per miniblock. `max_total` bounds the
    header's value count before any allocation (validation-before-allocation,
    reference: SURVEY §5) — callers pass the page/chunk value count.
    """
    if nbits not in (32, 64):
        raise DeltaError(f"delta: unsupported type width {nbits}")
    mask = (1 << nbits) - 1
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    end = len(buf)
    pos = 0
    block_size, pos = read_uvarint(buf, pos, end, DeltaError)
    mini_count, pos = read_uvarint(buf, pos, end, DeltaError)
    total, pos = read_uvarint(buf, pos, end, DeltaError)
    first, pos = read_zigzag(buf, pos, end, DeltaError)
    if block_size <= 0 or block_size % 128 != 0 or block_size > (1 << 20):
        raise DeltaError(f"delta: invalid block size {block_size}")
    if mini_count <= 0 or mini_count > 512 or block_size % mini_count != 0:
        raise DeltaError(f"delta: invalid miniblock count {mini_count}")
    mini_len = block_size // mini_count
    if mini_len % 8 != 0:
        raise DeltaError(f"delta: miniblock length {mini_len} not a multiple of 8")
    if max_total is not None and total > max(max_total, 0):
        raise DeltaError(
            f"delta: stream claims {total} values, caller expects at most {max_total}"
        )
    # Absolute backstop: a tiny stream must not drive a huge allocation. Every
    # block needs at least 1 min-delta byte + mini_count width bytes, and
    # covers block_size values, so `end` bytes cannot encode more than:
    plausible = 1 + (end // (1 + mini_count) + 1) * block_size
    if total > plausible:
        raise DeltaError(
            f"delta: implausible value count {total} for {end}-byte stream"
        )

    n_deltas = max(total - 1, 0)
    parts: list[np.ndarray] = []
    produced = 0
    while produced < n_deltas:
        min_delta, pos = read_zigzag(buf, pos, end, DeltaError)
        if pos + mini_count > end:
            raise DeltaError("delta: truncated miniblock widths")
        widths = bytes(buf[pos : pos + mini_count])
        pos += mini_count
        md = np.uint64(min_delta & mask)
        for w in widths:
            remaining = n_deltas - produced
            if remaining <= 0:
                # Unused trailing miniblock: no payload on the wire; the width
                # byte may hold any value (Encodings.md).
                continue
            if w > nbits:
                raise DeltaError(f"delta: miniblock width {w} exceeds type width")
            payload = (mini_len // 8) * w
            if pos + payload > end:
                raise DeltaError("delta: miniblock payload exceeds buffer")
            take = min(mini_len, remaining)
            if w == 0:
                vals = np.zeros(take, dtype=np.uint64)
            else:
                vals = unpack_bits(buf[pos : pos + payload], take, w, dtype=np.uint64)
            if nbits == 32:
                vals = (vals + md) & np.uint64(0xFFFFFFFF)
            else:
                vals = vals + md  # uint64 wraps naturally
            parts.append(vals)
            pos += payload
            produced += take
    deltas = (
        np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)
    )
    return DeltaTable(
        deltas_plus_min=deltas,
        first_value=first & mask,
        total=total,
        consumed=pos,
    )


@dataclass
class DeltaPackedTable:
    """Header-only prescan of a delta stream: payload bytes stay *packed*.

    The TPU path uploads the wire bytes plus these tiny tables and unpacks on
    device (kernels/device_ops.py delta_packed_decode_device) — the upload is
    the encoded size, not 8 bytes/value. One entry per miniblock that covers
    >=1 real delta (zero-width miniblocks included: they still carry the
    block's min_delta).
    """

    widths: np.ndarray  # uint32[M]
    byte_starts: np.ndarray  # int64[M], payload offset in the stream
    out_starts: np.ndarray  # int32[M], delta index (0-based) at miniblock start
    mins: np.ndarray  # uint64[M], block min_delta mod 2**nbits
    first_value: int  # unsigned first value (mod 2**nbits)
    total: int  # value count from the header
    consumed: int  # bytes consumed from the input


def prescan_delta_packed(data, nbits: int, max_total: int | None = None) -> DeltaPackedTable:
    """Walk delta block/miniblock *headers* only; never unpack payloads.

    Same validation discipline as prescan_delta (reference:
    deltabp_decoder.go:51-111 header sanity); the payload bytes are left in
    place for the device kernel.
    """
    if nbits not in (32, 64):
        raise DeltaError(f"delta: unsupported type width {nbits}")
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_prescan_delta and max_total is not None:
        try:
            widths, byte_starts, out_starts, mins, first, total, consumed = (
                lib.prescan_delta_packed(data, nbits, max_total)
            )
        except (OverflowError, ValueError) as e:
            raise DeltaError(f"delta: {e}") from e
        return DeltaPackedTable(
            widths=widths,
            byte_starts=byte_starts,
            out_starts=out_starts,
            mins=mins,
            first_value=int(first),
            total=int(total),
            consumed=int(consumed),
        )
    mask = (1 << nbits) - 1
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    end = len(buf)
    pos = 0
    block_size, pos = read_uvarint(buf, pos, end, DeltaError)
    mini_count, pos = read_uvarint(buf, pos, end, DeltaError)
    total, pos = read_uvarint(buf, pos, end, DeltaError)
    first, pos = read_zigzag(buf, pos, end, DeltaError)
    if block_size <= 0 or block_size % 128 != 0 or block_size > (1 << 20):
        raise DeltaError(f"delta: invalid block size {block_size}")
    if mini_count <= 0 or mini_count > 512 or block_size % mini_count != 0:
        raise DeltaError(f"delta: invalid miniblock count {mini_count}")
    mini_len = block_size // mini_count
    if mini_len % 8 != 0:
        raise DeltaError(f"delta: miniblock length {mini_len} not a multiple of 8")
    if max_total is not None and total > max(max_total, 0):
        raise DeltaError(
            f"delta: stream claims {total} values, caller expects at most {max_total}"
        )
    plausible = 1 + (end // (1 + mini_count) + 1) * block_size
    if total > plausible:
        raise DeltaError(
            f"delta: implausible value count {total} for {end}-byte stream"
        )

    n_deltas = max(total - 1, 0)
    widths: list[int] = []
    byte_starts: list[int] = []
    out_starts: list[int] = []
    mins: list[int] = []
    produced = 0
    while produced < n_deltas:
        min_delta, pos = read_zigzag(buf, pos, end, DeltaError)
        if pos + mini_count > end:
            raise DeltaError("delta: truncated miniblock widths")
        wbytes = bytes(buf[pos : pos + mini_count])
        pos += mini_count
        md = min_delta & mask
        for w in wbytes:
            remaining = n_deltas - produced
            if remaining <= 0:
                continue  # unused trailing miniblock: width byte, no payload
            if w > nbits:
                raise DeltaError(f"delta: miniblock width {w} exceeds type width")
            payload = (mini_len // 8) * w
            if pos + payload > end:
                raise DeltaError("delta: miniblock payload exceeds buffer")
            widths.append(w)
            byte_starts.append(pos)
            out_starts.append(produced)
            mins.append(md)
            pos += payload
            produced += min(mini_len, remaining)
    return DeltaPackedTable(
        widths=np.array(widths, dtype=np.uint32),
        byte_starts=np.array(byte_starts, dtype=np.int64),
        out_starts=np.array(out_starts, dtype=np.int32),
        mins=np.array(mins, dtype=np.uint64),
        first_value=first & mask,
        total=total,
        consumed=pos,
    )


def decode_delta(data, nbits: int, max_total: int | None = None) -> tuple[np.ndarray, int]:
    """Decode a full DELTA_BINARY_PACKED stream.

    Returns (values as int32/int64 ndarray, bytes consumed). The count comes
    from the stream header; `max_total` (the page/chunk value count) bounds it
    before allocation.
    """
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_delta_decode and nbits in (32, 64):
        try:
            return lib.delta_decode(data, nbits, max_total)
        except OverflowError as e:
            raise DeltaError(f"delta: {e}") from e
        except ValueError as e:
            raise DeltaError(f"delta: {e}") from e
    t = prescan_delta(data, nbits, max_total)
    if nbits == 32:
        seq = np.empty(t.total, dtype=np.uint32)
        if t.total:
            seq[0] = t.first_value
            if t.total > 1:
                seq[1:] = np.cumsum(t.deltas_plus_min.astype(np.uint32), dtype=np.uint32)
                seq[1:] += np.uint32(t.first_value)
        return seq.view(np.int32), t.consumed
    seq = np.empty(t.total, dtype=np.uint64)
    if t.total:
        seq[0] = t.first_value
        if t.total > 1:
            seq[1:] = np.cumsum(t.deltas_plus_min, dtype=np.uint64)
            seq[1:] += np.uint64(t.first_value)
    return seq.view(np.int64), t.consumed


def encode_delta(
    values,
    nbits: int,
    block_size: int = DEFAULT_BLOCK_SIZE,
    mini_count: int = DEFAULT_MINIBLOCKS,
) -> bytes:
    """Encode int32/int64 values as DELTA_BINARY_PACKED."""
    if nbits not in (32, 64):
        raise DeltaError(f"delta: unsupported type width {nbits}")
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_delta_encode and 0 < mini_count <= 512:
        # byte-identical C encoder (pack_bits per miniblock dominated here);
        # mini_count > 512 is undecodable by every reader anyway and takes
        # the NumPy path
        return lib.delta_encode(values, nbits, block_size, mini_count)
    mask = (1 << nbits) - 1
    udtype = np.uint32 if nbits == 32 else np.uint64
    sdtype = np.int32 if nbits == 32 else np.int64
    v = np.asarray(values, dtype=sdtype).view(udtype)
    n = len(v)
    mini_len = block_size // mini_count

    out = bytearray()
    _emit_uvarint(out, block_size)
    _emit_uvarint(out, mini_count)
    _emit_uvarint(out, n)
    first = int(v[0]) if n else 0
    _emit_zigzag(out, _to_signed(first, nbits))
    if n <= 1:
        return bytes(out)

    # Wrapping deltas in unsigned arithmetic.
    deltas = (v[1:] - v[:-1]).astype(udtype)
    sdeltas = deltas.view(sdtype)
    for block_start in range(0, len(deltas), block_size):
        block = deltas[block_start : block_start + block_size]
        sblock = sdeltas[block_start : block_start + block_size]
        min_delta = int(sblock.min())
        _emit_zigzag(out, min_delta)
        adj = (block - udtype(min_delta & mask)).astype(udtype)
        widths = []
        payloads = []
        for m in range(mini_count):
            mini = adj[m * mini_len : (m + 1) * mini_len]
            if len(mini) == 0:
                widths.append(0)
                payloads.append(b"")
                continue
            w = int(mini.max()).bit_length()
            widths.append(w)
            if len(mini) < mini_len:
                mini = np.concatenate([mini, np.zeros(mini_len - len(mini), dtype=udtype)])
            payloads.append(pack_bits(mini, w) if w else b"")
        out += bytes(widths)
        for p in payloads:
            out += p
    return bytes(out)


_emit_uvarint = _emit_uvarint_impl
_emit_zigzag = _emit_zigzag_impl


def _to_signed(v: int, nbits: int) -> int:
    if v >= 1 << (nbits - 1):
        v -= 1 << nbits
    return v





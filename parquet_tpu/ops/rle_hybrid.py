"""RLE / bit-packing hybrid codec (host path) + run-table prescan for the TPU path.

Wire format (parquet-format Encodings.md, same semantics as the reference's
hybrid_decoder.go:81-165): a sequence of runs, each introduced by a ULEB128
header. Low bit 0 → RLE run of (header >> 1) copies of one value stored in
ceil(width/8) little-endian bytes. Low bit 1 → bit-packed run of (header >> 1)
groups of 8 values at `width` bits, LSB-first.

The reference decodes this one value per virtual call (hybrid_decoder.go:81-113,
the hottest loop in the library, SURVEY §3.1). Here decode is two phases:

  1. `prescan` — a cheap sequential byte-level walk of the run *headers* only,
     producing a run table (kind, count, value, payload offset). This touches a
     tiny fraction of the data and is the only inherently sequential part
     (SURVEY §7.3 hard-part #1).
  2. expansion — fully vectorized/parallel: RLE runs become broadcasts,
     bit-packed runs become one batched unpack. On host this is NumPy; on TPU
     the same run table drives the Pallas expansion kernel (kernels/rle_tpu.py).

Encoding: unlike the reference, which only ever emits bit-packed runs
(reference: hybrid_encoder.go:55-70, README.md:42), `encode_hybrid` emits RLE
runs for 8-aligned stretches of repeated values — strictly smaller output for
level streams and low-cardinality dictionaries, still spec-conformant.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .bitpack import pack_bits, unpack_bits
from .varint import emit_uvarint as _emit_uvarint, read_uvarint

__all__ = [
    "RunTable",
    "prescan_hybrid",
    "decode_hybrid",
    "expand_runs",
    "encode_hybrid",
]


class HybridError(ValueError):
    pass


@dataclass
class RunTable:
    """Prescanned hybrid stream: one row per run.

    is_rle[i]       True for RLE runs
    counts[i]       number of values produced by run i (bit-packed: groups*8)
    rle_values[i]   the repeated value (0 for bit-packed runs)
    bp_offsets[i]   byte offset of run i's packed payload within `packed` (RLE: 0)
    packed          all bit-packed payload bytes, concatenated
    consumed        bytes of the input stream consumed (headers + payloads)
    """

    is_rle: np.ndarray
    counts: np.ndarray
    rle_values: np.ndarray
    bp_offsets: np.ndarray
    packed: bytes
    consumed: int

    @property
    def total_values(self) -> int:
        return int(self.counts.sum())


def prescan_hybrid(data, num_values: int, width: int) -> RunTable:
    """Walk run headers until `num_values` values are covered.

    Validates every count and payload size before accepting it, per the
    reference's validation-before-allocation discipline (reference:
    hybrid_decoder.go:126-129, SURVEY §5 failure handling).
    """
    if width < 0 or width > 64:
        raise HybridError(f"hybrid: invalid bit width {width}")
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_prescan_hybrid and num_values > 0:
        raw = data
        try:
            is_rle, counts, values, offsets, consumed = lib.prescan_hybrid(
                raw, num_values, width
            )
        except ValueError as e:
            raise HybridError(f"hybrid: {e}") from e
        # Compact the packed buffer to just the bit-packed payloads so device
        # buffers sized by len(packed) don't scale with RLE-heavy streams.
        new_offsets = np.zeros(len(counts), dtype=np.int64)
        bp_idx = np.flatnonzero(~is_rle)
        if len(bp_idx) == 0:
            packed = b""
        else:
            nb = (counts[bp_idx] // 8) * width
            offs = offsets[bp_idx]
            if len(bp_idx) > 1:
                new_offsets[bp_idx[1:]] = np.cumsum(nb[:-1])
            if len(bp_idx) == 1 or bool(np.all(offs[1:] == offs[:-1] + nb[:-1])):
                # payload regions are back-to-back (the no-RLE common case):
                # one zero-copy slice of the input
                mv = memoryview(raw) if not isinstance(raw, memoryview) else raw
                packed = mv[int(offs[0]) : int(offs[0] + nb.sum())]
            else:
                packed = b"".join(
                    raw[o : o + n] for o, n in zip(offs.tolist(), nb.tolist())
                )
        return RunTable(
            is_rle=is_rle,
            counts=counts,
            rle_values=values,
            bp_offsets=new_offsets,
            packed=packed,
            consumed=consumed,
        )
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    end = len(buf)
    vbytes = (width + 7) // 8
    pos = 0
    produced = 0
    kinds: list[bool] = []
    counts: list[int] = []
    values: list[int] = []
    offsets: list[int] = []
    packed_parts: list[bytes] = []
    packed_len = 0
    while produced < num_values:
        header, pos = read_uvarint(buf, pos, end, HybridError)
        if header & 1:
            groups = header >> 1
            count = groups * 8
            nbytes = groups * width
            if count == 0:
                raise HybridError("hybrid: empty bit-packed run")
            if pos + nbytes > end:
                raise HybridError("hybrid: bit-packed payload exceeds buffer")
            kinds.append(False)
            counts.append(count)
            values.append(0)
            offsets.append(packed_len)
            packed_parts.append(bytes(buf[pos : pos + nbytes]))
            packed_len += nbytes
            pos += nbytes
        else:
            count = header >> 1
            if count == 0:
                raise HybridError("hybrid: empty RLE run")
            if pos + vbytes > end:
                raise HybridError("hybrid: RLE value exceeds buffer")
            v = int.from_bytes(buf[pos : pos + vbytes], "little")
            if width < 64 and v >= (1 << width):
                raise HybridError(
                    f"hybrid: RLE value {v} does not fit bit width {width}"
                )
            pos += vbytes
            kinds.append(True)
            counts.append(count)
            values.append(v)
            offsets.append(0)
        produced += count
    return RunTable(
        is_rle=np.array(kinds, dtype=bool),
        counts=np.array(counts, dtype=np.int64),
        rle_values=np.array(values, dtype=np.uint64),
        bp_offsets=np.array(offsets, dtype=np.int64),
        packed=b"".join(packed_parts),
        consumed=pos,
    )


def expand_runs(table: RunTable, num_values: int, width: int, dtype=np.uint32) -> np.ndarray:
    """Vectorized expansion of a prescanned run table into a value array.

    No per-run Python loop (adversarial streams can hold millions of
    one-value runs): RLE positions broadcast via np.repeat of the run
    table, bit-packed positions gather from one unpack of the whole packed
    buffer — both O(values) in C.
    """
    counts = table.counts.astype(np.int64)
    k = len(counts)
    if k == 0 or num_values == 0:
        if num_values > 0:
            raise HybridError(
                f"hybrid: stream produced 0 values, expected {num_values}"
            )
        return np.empty(0, dtype=dtype)
    ends = np.cumsum(counts)
    if int(ends[-1]) < num_values:
        raise HybridError(
            f"hybrid: stream produced {int(ends[-1])} values, expected {num_values}"
        )
    # clamp to the first k' runs covering num_values; partial last run
    kp = int(np.searchsorted(ends, num_values, side="left")) + 1
    takes = counts[:kp].copy()
    takes[kp - 1] = num_values - (int(ends[kp - 2]) if kp > 1 else 0)
    is_rle = np.asarray(table.is_rle[:kp], dtype=bool)
    out = np.empty(num_values, dtype=dtype)
    run_of = np.repeat(np.arange(kp), takes)  # run index at each position
    rle_pos = is_rle[run_of]
    if rle_pos.any():
        out[rle_pos] = table.rle_values[:kp].astype(dtype)[run_of[rle_pos]]
    if not rle_pos.all():
        # one unpack of every bit-packed payload (payloads are dense:
        # counts are multiples of 8), then a gather by global bp index
        bp_counts = np.where(is_rle, 0, counts[:kp])
        bp_total = int(bp_counts.sum())
        if width == 0:
            out[~rle_pos] = 0
        else:
            first_off = int(table.bp_offsets[:kp][~is_rle][0])
            bp_vals = unpack_bits(
                table.packed[first_off : first_off + (bp_total // 8) * width],
                bp_total,
                width,
                dtype=dtype,
            )
            bp_base = np.zeros(kp, dtype=np.int64)
            np.cumsum(bp_counts[:-1], out=bp_base[1:])
            starts = np.zeros(kp, dtype=np.int64)
            np.cumsum(takes[:-1], out=starts[1:])
            # index math only over the bit-packed positions: temporaries
            # scale with the bp count, not num_values (a stream that is one
            # huge RLE run plus 8 bp values should not allocate 16B/value)
            bp_pos = np.flatnonzero(~rle_pos)
            bp_runs = run_of[bp_pos]
            out[bp_pos] = bp_vals[bp_base[bp_runs] + (bp_pos - starts[bp_runs])]
    return out


def decode_hybrid(data, num_values: int, width: int, dtype=np.uint32) -> np.ndarray:
    """One-shot host decode: prescan + expand (C fast path when built)."""
    if num_values == 0:
        return np.empty(0, dtype=dtype)
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_hybrid_decode and 0 <= width <= 64:
        nbits = 32 if width <= 32 else 64
        try:
            out, _ = lib.hybrid_decode(data, num_values, width, nbits)
        except ValueError as e:
            raise HybridError(f"hybrid: {e}") from e
        want = np.dtype(dtype)
        if want == out.dtype:
            return out
        if want.itemsize == out.dtype.itemsize:  # e.g. int32 view of uint32
            return out.view(want)
        return out.astype(want)
    table = prescan_hybrid(data, num_values, width)
    return expand_runs(table, num_values, width, dtype=dtype)


def encode_hybrid(values, width: int) -> bytes:
    """Encode values as a hybrid stream.

    8-aligned stretches of ≥8 identical values become RLE runs; everything else
    is bit-packed in groups of 8 (the trailing partial group is zero-padded,
    which the decoder discards — padding only ever appears at stream end).
    """
    v = np.asarray(values)
    n = len(v)
    if n == 0:
        return b""
    if width == 0:
        # Single RLE run covering everything; value occupies 0 bytes.
        out = bytearray()
        _emit_uvarint(out, n << 1)
        return bytes(out)
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_hybrid_encode and 0 < width <= 64:
        # byte-identical C encoder (the write path's hottest loop)
        return lib.hybrid_encode(v.astype(np.uint64, copy=False), width)
    v64 = v.astype(np.uint64, copy=False)
    run_starts = np.nonzero(np.concatenate(([True], v64[1:] != v64[:-1])))[0]
    run_lengths = np.diff(np.append(run_starts, n))
    out = bytearray()
    vbytes = (width + 7) // 8
    pos = 0
    for start, length in zip(run_starts, run_lengths):
        if length < 8:
            continue
        # 8-align the RLE window so surrounding bit-packed segments stay
        # multiples of 8 values (mid-stream padding would shift the stream).
        rle_start = (int(start) + 7) & ~7
        rle_end = (int(start) + int(length)) & ~7
        if rle_end - rle_start < 8:
            continue
        if rle_start > pos:
            _emit_bitpacked(out, v64[pos:rle_start], width)
        _emit_uvarint(out, (rle_end - rle_start) << 1)
        out += int(v64[start]).to_bytes(vbytes, "little")
        pos = rle_end
    if pos < n:
        _emit_bitpacked(out, v64[pos:n], width, pad=True)
    return bytes(out)


def _emit_bitpacked(out: bytearray, vals: np.ndarray, width: int, pad: bool = False) -> None:
    n = len(vals)
    if n == 0:
        return
    if n % 8:
        if not pad:
            raise HybridError("hybrid: internal — unaligned bit-packed segment")
        vals = np.concatenate([vals, np.zeros(8 - n % 8, dtype=vals.dtype)])
    groups = len(vals) // 8
    _emit_uvarint(out, (groups << 1) | 1)
    out += pack_bits(vals, width)

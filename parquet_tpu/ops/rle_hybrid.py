"""RLE / bit-packing hybrid codec (host path) + run-table prescan for the TPU path.

Wire format (parquet-format Encodings.md, same semantics as the reference's
hybrid_decoder.go:81-165): a sequence of runs, each introduced by a ULEB128
header. Low bit 0 → RLE run of (header >> 1) copies of one value stored in
ceil(width/8) little-endian bytes. Low bit 1 → bit-packed run of (header >> 1)
groups of 8 values at `width` bits, LSB-first.

The reference decodes this one value per virtual call (hybrid_decoder.go:81-113,
the hottest loop in the library, SURVEY §3.1). Here decode is two phases:

  1. `prescan` — a cheap sequential byte-level walk of the run *headers* only,
     producing a run table (kind, count, value, payload offset). This touches a
     tiny fraction of the data and is the only inherently sequential part
     (SURVEY §7.3 hard-part #1).
  2. expansion — fully vectorized/parallel: RLE runs become broadcasts,
     bit-packed runs become one batched unpack. On host this is NumPy; on TPU
     the same run table drives the Pallas expansion kernel (kernels/rle_tpu.py).

Encoding: unlike the reference, which only ever emits bit-packed runs
(reference: hybrid_encoder.go:55-70, README.md:42), `encode_hybrid` emits RLE
runs for 8-aligned stretches of repeated values — strictly smaller output for
level streams and low-cardinality dictionaries, still spec-conformant.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .bitpack import pack_bits, unpack_bits
from .varint import emit_uvarint as _emit_uvarint, read_uvarint

__all__ = [
    "RunTable",
    "prescan_hybrid",
    "decode_hybrid",
    "expand_runs",
    "encode_hybrid",
]


class HybridError(ValueError):
    pass


@dataclass
class RunTable:
    """Prescanned hybrid stream: one row per run.

    is_rle[i]       True for RLE runs
    counts[i]       number of values produced by run i (bit-packed: groups*8)
    rle_values[i]   the repeated value (0 for bit-packed runs)
    bp_offsets[i]   byte offset of run i's packed payload within `packed` (RLE: 0)
    packed          all bit-packed payload bytes, concatenated
    consumed        bytes of the input stream consumed (headers + payloads)
    """

    is_rle: np.ndarray
    counts: np.ndarray
    rle_values: np.ndarray
    bp_offsets: np.ndarray
    packed: bytes
    consumed: int

    @property
    def total_values(self) -> int:
        return int(self.counts.sum())


def prescan_hybrid(data, num_values: int, width: int) -> RunTable:
    """Walk run headers until `num_values` values are covered.

    Validates every count and payload size before accepting it, per the
    reference's validation-before-allocation discipline (reference:
    hybrid_decoder.go:126-129, SURVEY §5 failure handling).
    """
    if width < 0 or width > 64:
        raise HybridError(f"hybrid: invalid bit width {width}")
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_prescan_hybrid and num_values > 0:
        raw = data
        try:
            is_rle, counts, values, offsets, consumed = lib.prescan_hybrid(
                raw, num_values, width
            )
        except ValueError as e:
            raise HybridError(f"hybrid: {e}") from e
        # Compact the packed buffer to just the bit-packed payloads so device
        # buffers sized by len(packed) don't scale with RLE-heavy streams.
        new_offsets = np.zeros(len(counts), dtype=np.int64)
        bp_idx = np.flatnonzero(~is_rle)
        if len(bp_idx) == 0:
            packed = b""
        else:
            nb = (counts[bp_idx] // 8) * width
            offs = offsets[bp_idx]
            if len(bp_idx) > 1:
                new_offsets[bp_idx[1:]] = np.cumsum(nb[:-1])
            if len(bp_idx) == 1 or bool(np.all(offs[1:] == offs[:-1] + nb[:-1])):
                # payload regions are back-to-back (the no-RLE common case):
                # one zero-copy slice of the input
                mv = memoryview(raw) if not isinstance(raw, memoryview) else raw
                packed = mv[int(offs[0]) : int(offs[0] + nb.sum())]
            else:
                packed = b"".join(
                    raw[o : o + n] for o, n in zip(offs.tolist(), nb.tolist())
                )
        return RunTable(
            is_rle=is_rle,
            counts=counts,
            rle_values=values,
            bp_offsets=new_offsets,
            packed=packed,
            consumed=consumed,
        )
    buf = memoryview(data) if not isinstance(data, memoryview) else data
    end = len(buf)
    vbytes = (width + 7) // 8
    pos = 0
    produced = 0
    kinds: list[bool] = []
    counts: list[int] = []
    values: list[int] = []
    offsets: list[int] = []
    packed_parts: list[bytes] = []
    packed_len = 0
    while produced < num_values:
        header, pos = read_uvarint(buf, pos, end, HybridError)
        if header & 1:
            groups = header >> 1
            count = groups * 8
            nbytes = groups * width
            if count == 0:
                raise HybridError("hybrid: empty bit-packed run")
            if pos + nbytes > end:
                raise HybridError("hybrid: bit-packed payload exceeds buffer")
            kinds.append(False)
            counts.append(count)
            values.append(0)
            offsets.append(packed_len)
            packed_parts.append(bytes(buf[pos : pos + nbytes]))
            packed_len += nbytes
            pos += nbytes
        else:
            count = header >> 1
            if count == 0:
                raise HybridError("hybrid: empty RLE run")
            if pos + vbytes > end:
                raise HybridError("hybrid: RLE value exceeds buffer")
            v = int.from_bytes(buf[pos : pos + vbytes], "little")
            if width < 64 and v >= (1 << width):
                raise HybridError(
                    f"hybrid: RLE value {v} does not fit bit width {width}"
                )
            pos += vbytes
            kinds.append(True)
            counts.append(count)
            values.append(v)
            offsets.append(0)
        produced += count
    return RunTable(
        is_rle=np.array(kinds, dtype=bool),
        counts=np.array(counts, dtype=np.int64),
        rle_values=np.array(values, dtype=np.uint64),
        bp_offsets=np.array(offsets, dtype=np.int64),
        packed=b"".join(packed_parts),
        consumed=pos,
    )


def expand_runs(table: RunTable, num_values: int, width: int, dtype=np.uint32) -> np.ndarray:
    """Vectorized expansion of a prescanned run table into a value array."""
    out = np.empty(num_values, dtype=dtype)
    pos = 0
    n_runs = len(table.counts)
    for i in range(n_runs):
        count = int(table.counts[i])
        take = min(count, num_values - pos)
        if take <= 0:
            break
        if table.is_rle[i]:
            out[pos : pos + take] = dtype(table.rle_values[i])
        else:
            off = int(table.bp_offsets[i])
            vals = unpack_bits(
                table.packed[off : off + (count // 8) * width], take, width, dtype=dtype
            )
            out[pos : pos + take] = vals
        pos += take
    if pos < num_values:
        raise HybridError(
            f"hybrid: stream produced {pos} values, expected {num_values}"
        )
    return out


def decode_hybrid(data, num_values: int, width: int, dtype=np.uint32) -> np.ndarray:
    """One-shot host decode: prescan + expand (C fast path when built)."""
    if num_values == 0:
        return np.empty(0, dtype=dtype)
    from ..utils.native import get_native

    lib = get_native()
    if lib is not None and lib.has_hybrid_decode and 0 <= width <= 64:
        nbits = 32 if width <= 32 else 64
        try:
            out, _ = lib.hybrid_decode(data, num_values, width, nbits)
        except ValueError as e:
            raise HybridError(f"hybrid: {e}") from e
        want = np.dtype(dtype)
        if want == out.dtype:
            return out
        if want.itemsize == out.dtype.itemsize:  # e.g. int32 view of uint32
            return out.view(want)
        return out.astype(want)
    table = prescan_hybrid(data, num_values, width)
    return expand_runs(table, num_values, width, dtype=dtype)


def encode_hybrid(values, width: int) -> bytes:
    """Encode values as a hybrid stream.

    8-aligned stretches of ≥8 identical values become RLE runs; everything else
    is bit-packed in groups of 8 (the trailing partial group is zero-padded,
    which the decoder discards — padding only ever appears at stream end).
    """
    v = np.asarray(values)
    n = len(v)
    if n == 0:
        return b""
    if width == 0:
        # Single RLE run covering everything; value occupies 0 bytes.
        out = bytearray()
        _emit_uvarint(out, n << 1)
        return bytes(out)
    v64 = v.astype(np.uint64, copy=False)
    run_starts = np.nonzero(np.concatenate(([True], v64[1:] != v64[:-1])))[0]
    run_lengths = np.diff(np.append(run_starts, n))
    out = bytearray()
    vbytes = (width + 7) // 8
    pos = 0
    for start, length in zip(run_starts, run_lengths):
        if length < 8:
            continue
        # 8-align the RLE window so surrounding bit-packed segments stay
        # multiples of 8 values (mid-stream padding would shift the stream).
        rle_start = (int(start) + 7) & ~7
        rle_end = (int(start) + int(length)) & ~7
        if rle_end - rle_start < 8:
            continue
        if rle_start > pos:
            _emit_bitpacked(out, v64[pos:rle_start], width)
        _emit_uvarint(out, (rle_end - rle_start) << 1)
        out += int(v64[start]).to_bytes(vbytes, "little")
        pos = rle_end
    if pos < n:
        _emit_bitpacked(out, v64[pos:n], width, pad=True)
    return bytes(out)


def _emit_bitpacked(out: bytearray, vals: np.ndarray, width: int, pad: bool = False) -> None:
    n = len(vals)
    if n == 0:
        return
    if n % 8:
        if not pad:
            raise HybridError("hybrid: internal — unaligned bit-packed segment")
        vals = np.concatenate([vals, np.zeros(8 - n % 8, dtype=vals.dtype)])
    groups = len(vals) // 8
    _emit_uvarint(out, (groups << 1) | 1)
    out += pack_bits(vals, width)

"""BYTE_STREAM_SPLIT codec (Parquet encoding 9).

Not supported by the reference at all (its encoding matrix stops at
DELTA_BYTE_ARRAY, reference: chunk_reader.go:41-159) — this exceeds parity.
The encoding stores the k-th byte of every value contiguously: for width-W
values, stream = all byte-0s, then all byte-1s, ... byte-(W-1)s. It carries
no compression itself; it groups similar bytes (exponents, high-order bytes)
so a general-purpose codec behind it compresses better — the layout transform
IS the whole codec, which makes it the most array-native encoding in the
format: decode/encode are a single (W, n) <-> (n, W) transpose, vectorized
here and a pure layout op for XLA on device (the native chunk walk performs
it in C so BSS pages ride the PLAIN device route).

Applies to fixed-width types: FLOAT/DOUBLE (classic), INT32/INT64/
FIXED_LEN_BYTE_ARRAY (format 2.11+).
"""

from __future__ import annotations

import numpy as np

from ..meta.parquet_types import Type

__all__ = ["decode_byte_stream_split", "encode_byte_stream_split", "bss_width"]


_WIDTHS = {
    Type.FLOAT: 4,
    Type.DOUBLE: 8,
    Type.INT32: 4,
    Type.INT64: 8,
}

# explicit little-endian wire dtypes (the repo-wide convention, ops/plain.py)
_DTYPES = {
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
}


def bss_width(ptype, type_length=None) -> int:
    """Element width in bytes, or 0 if the type cannot be byte-stream-split."""
    if ptype in _WIDTHS:
        return _WIDTHS[ptype]
    if ptype == Type.FIXED_LEN_BYTE_ARRAY and type_length:
        return int(type_length)
    return 0


def decode_byte_stream_split(data, n: int, ptype, type_length=None):
    """Decode n values; returns a typed 1-D array (or (n, W) uint8 for FLBA)."""
    w = bss_width(ptype, type_length)
    if w == 0:
        raise ValueError(f"byte_stream_split: unsupported type {ptype}")
    need = n * w
    if len(data) < need:
        raise ValueError(
            f"byte_stream_split: stream has {len(data)} bytes, needs {need}"
        )
    raw = (
        np.frombuffer(data, dtype=np.uint8, count=need)
        if need
        else np.empty(0, dtype=np.uint8)
    )
    # (W, n) streams -> (n, W) little-endian value rows: one transpose
    rows = np.ascontiguousarray(raw.reshape(w, n).T)
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        return rows
    return rows.view(_DTYPES[ptype]).reshape(n)


def encode_byte_stream_split(values, ptype, type_length=None) -> bytes:
    w = bss_width(ptype, type_length)
    if w == 0:
        raise ValueError(f"byte_stream_split: unsupported type {ptype}")
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        rows = np.asarray(values, dtype=np.uint8)
        if rows.ndim != 2 or rows.shape[1] != w:
            raise ValueError("byte_stream_split: FLBA values must be (n, width)")
    else:
        arr = np.ascontiguousarray(np.asarray(values, dtype=_DTYPES[ptype]))
        rows = arr.view(np.uint8).reshape(len(arr), w)
    return np.ascontiguousarray(rows.T).tobytes()

"""Bit-packed repetition/definition level storage with widen-on-demand.

The reference keeps R/D levels bit-packed at width = bit_length(max_level) in
`packedArray` (reference: packed_array.go:13-101) — ~1/8 the memory of widened
arrays. The round-2 design here stored levels as uint16 ndarrays: 16x the
packed footprint on billion-row nested scans. PackedLevels restores the
reference's advantage the array-native way: levels at rest are a contiguous
LSB-first bitstream; consumers get vectorized windows on demand (`widen`), or
use NumPy operators directly (`==`, `<`, `np.asarray`) which widen
transiently — peak memory is packed + one transient window, instead of a
permanently widened array per chunk.

Opt-in via FileReader(..., compact_levels=True): ChunkData.def_levels /
rep_levels (and DeviceColumn's level arrays) then hold PackedLevels instead of
ndarrays. Record assembly widens per chunk transiently; the device-batch
validity-mask path compares packed levels directly.
"""

from __future__ import annotations

import numpy as np

from .bitpack import bit_width, pack_bits, unpack_bits

__all__ = ["PackedLevels", "widen_levels"]


class PackedLevels:
    """Immutable bit-packed level array (LSB-first, like Parquet's hybrid
    bit-packed runs: bit j of value i is bit i*width+j of the stream)."""

    __slots__ = ("_packed", "width", "_n")

    def __init__(self, packed: np.ndarray, width: int, n: int):
        self._packed = packed  # uint8, >= ceil(n*width/8) bytes
        self.width = width
        self._n = n

    # -- construction ----------------------------------------------------------

    @classmethod
    def from_array(cls, levels, max_level: int) -> "PackedLevels":
        """Pack a widened level array at width bit_length(max_level)."""
        arr = np.asarray(levels)
        w = bit_width(max_level)
        n = arr.shape[0]
        if arr.size and int(arr.max()) > max_level:
            # checked against max_level, not the bit width: level 3 fits
            # width 2 but exceeds max_level 2 (and width 0 must stay empty)
            raise ValueError(
                f"levels: value {int(arr.max())} exceeds max level {max_level}"
            )
        if w == 0 or n == 0:
            return cls(np.empty(0, dtype=np.uint8), w, n)
        packed = np.frombuffer(pack_bits(arr, w), dtype=np.uint8)
        return cls(packed, w, n)

    # -- core access -----------------------------------------------------------

    def widen(self, start: int = 0, stop: int | None = None) -> np.ndarray:
        """Vectorized unpack of levels [start, stop) into a uint16 array.

        Windowed widening is the memory contract: callers that stream (row
        windows, per-chunk assembly) materialize only their window.
        """
        n = self._n
        if stop is None:
            stop = n
        start = max(0, min(start, n))
        stop = max(start, min(stop, n))
        w = self.width
        count = stop - start
        if w == 0 or count == 0:
            return np.zeros(count, dtype=np.uint16)
        return unpack_bits(
            self._packed, count, w, dtype=np.uint16, bit_offset=start * w
        )

    # -- ndarray interop -------------------------------------------------------

    def __array__(self, dtype=None, copy=None):
        if copy is False:
            # NumPy 2 protocol: widening always materializes a new array, so
            # a no-copy request cannot be honored
            raise ValueError("PackedLevels cannot be converted without a copy")
        out = self.widen()
        return out.astype(dtype) if dtype is not None else out

    def __len__(self) -> int:
        return self._n

    @property
    def shape(self) -> tuple:
        return (self._n,)

    @property
    def dtype(self):
        return np.dtype(np.uint16)

    @property
    def nbytes(self) -> int:
        return self._packed.nbytes

    @property
    def packed(self) -> np.ndarray:
        return self._packed

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, step = key.indices(self._n)
            if step < 0:
                # indices() yields start > stop here; widen the covering
                # window [stop+1, start+1) and stride it backwards
                win = self.widen(stop + 1, start + 1)
                return win[::step]
            win = self.widen(start, stop)
            return win[::step] if step != 1 else win
        if isinstance(key, (int, np.integer)):
            i = int(key)
            if i < 0:
                i += self._n
            if not 0 <= i < self._n:
                raise IndexError(f"level index {key} out of range ({self._n})")
            if self.width == 0:
                return np.uint16(0)
            return self.widen(i, i + 1)[0]
        return self.widen()[key]  # fancy indexing: widen once

    def max(self):
        if self._n == 0:
            raise ValueError("max of empty levels")
        if self.width == 0:
            return np.uint16(0)
        return self.widen().max()

    def tolist(self) -> list:
        return self.widen().tolist()

    # -- comparisons (transient widen) -----------------------------------------

    def __eq__(self, other):  # type: ignore[override]
        return self.widen() == other

    def __ne__(self, other):  # type: ignore[override]
        return self.widen() != other

    def __lt__(self, other):
        return self.widen() < other

    def __le__(self, other):
        return self.widen() <= other

    def __gt__(self, other):
        return self.widen() > other

    def __ge__(self, other):
        return self.widen() >= other

    __hash__ = None  # arrays are unhashable

    def __repr__(self) -> str:
        return f"PackedLevels(n={self._n}, width={self.width}, nbytes={self.nbytes})"


def widen_levels(levels):
    """ndarray view of a level array that may be packed (None passes through)."""
    if levels is None or isinstance(levels, np.ndarray):
        return levels
    return np.asarray(levels)

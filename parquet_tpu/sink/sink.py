"""Pluggable byte sinks: where a Parquet file's bytes actually go.

The write-side counterpart of parquet_tpu.io.source: the encode stack above
this layer (FileWriter, the parallel encoder, merge/split) never touches a
file handle directly — it speaks the small ByteSink contract:

    write(data)     append bytes at the current position
    tell()          bytes written so far
    flush()         push buffered bytes toward durability
    close()         COMMIT: make the written bytes the visible artifact
    abort()         DISCARD: tear down without committing (idempotent,
                    safe after close — never destroys committed output)
    sink_id         stable identity for logs/metrics

Concrete sinks:

  LocalFileSink    writes a same-directory temp file and atomically renames
                   it over the destination at close() — a crash, a flush
                   fault, or an abort can never leave a torn half-written
                   parquet file where readers look (the reference writer,
                   and the original FileWriter here, truncated the target
                   in the constructor and left garbage on any failure)
  MemorySink       an in-memory buffer (tests, size probes, network staging)
  FileObjectSink   adapter over an arbitrary writable file-like (BytesIO,
                   sockets wrapped in a buffer) — the compatibility lane
                   for FileWriter(file_obj); the CALLER keeps the lifetime
  BufferedSink     wrapper batching small writes (page headers are tens of
                   bytes) into spill_bytes-sized runs before they hit the
                   inner sink — the cheap win for syscall-priced or
                   request-priced inner sinks

Every CONCRETE sink feeds the always-on sink_bytes_written_total /
sink_write_calls_total counters (wrappers don't double-count). The seeded
write-fault injector lives in parquet_tpu.testing.flaky (FlakySink).
"""

from __future__ import annotations

import io as _io
import itertools
import os
from pathlib import Path

from ..utils import metrics as _metrics

__all__ = [
    "ByteSink",
    "SinkError",
    "LocalFileSink",
    "MemorySink",
    "FileObjectSink",
    "BufferedSink",
    "open_sink",
]


class SinkError(OSError):
    """Terminal IO failure of a byte sink: the write/flush/commit is not
    satisfiable (sink closed or aborted, rename failed, remote store
    refused). An OSError subclass so callers treating IO failures
    generically need no new clause; FileWriter re-raises sink failures as
    typed WriterError. `code` names the failure shape ("http_403",
    "put_retry_exhausted", "breaker_open", "sink_closed") for tests and
    error routing, mirroring SourceError."""

    def __init__(self, *args, code: str | None = None):
        super().__init__(*args)
        self.code = code


def _count_write(nbytes: int) -> None:
    # concrete sinks only — wrappers delegate and must not double-count
    _metrics.inc("sink_bytes_written_total", nbytes)
    _metrics.inc("sink_write_calls_total")


class ByteSink:
    """Base contract for byte sinks (see module docstring).

    Sinks are context managers: a clean `with` exit commits (close), an
    exception aborts — so `with LocalFileSink(p) as s: ...` can never leave
    a torn file at p. close() and abort() are idempotent; abort() after a
    successful close() is a no-op (committed output is never destroyed)."""

    def write(self, data) -> int:
        """Append `data` at the current position; returns len(data). A sink
        that cannot take all of it raises — short writes are a contract
        violation (real transports that commit them must be wrapped)."""
        raise NotImplementedError

    def tell(self) -> int:
        """Bytes written so far (the next write's offset)."""
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        """Commit. Idempotent; raising here means the artifact did NOT
        become visible (atomic sinks leave nothing behind)."""
        pass

    def abort(self) -> None:
        """Discard without committing. Idempotent; must be safe after
        close() (no-op) and after a failed write (best-effort cleanup).
        The default is a no-op, NOT close(): for a subclass whose close()
        is its commit (finalize a multipart upload, rename a temp file),
        an inherited abort-that-commits would publish exactly the
        half-written bytes abort exists to discard."""

    @property
    def sink_id(self) -> str:
        """Stable identity for logs and error messages."""
        return f"{type(self).__name__}:{id(self):#x}"

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        else:
            self.abort()
        return False


# unique-per-process suffix so concurrent writers to one destination never
# collide on the temp name (last committed rename wins, as with O_TRUNC)
_tmp_seq = itertools.count()


class LocalFileSink(ByteSink):
    """Atomic local-file sink: bytes accumulate in `<dir>/.<name>.<pid>.<n>.tmp`
    next to the destination (same filesystem, so the commit rename is atomic)
    and the destination appears only at close(), complete. abort() — or the
    process dying — leaves at most a stale temp file, never a torn parquet
    file where a reader (or a glob-driven dataset) would pick it up."""

    def __init__(self, path):
        # pin the destination NOW: a relative path + a cwd change before
        # close() must not commit the file into the wrong directory (the
        # old writer pinned it via open() at construction; rename must too)
        self._path = os.path.abspath(os.fspath(path))
        d, name = os.path.split(self._path)
        self._tmp = os.path.join(
            d, f".{name}.{os.getpid()}.{next(_tmp_seq)}.tmp"
        )
        self._f = open(self._tmp, "wb")
        self._pos = 0
        self._committed = False
        self._aborted = False

    @property
    def path(self) -> str:
        return self._path

    @property
    def sink_id(self) -> str:
        return f"file:{self._path}"

    def write(self, data) -> int:
        if self._committed or self._aborted:
            raise SinkError(f"sink closed: {self._path}")
        n = self._f.write(data)
        self._pos += n
        _count_write(n)
        return n

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        if not (self._committed or self._aborted):
            self._f.flush()

    def close(self) -> None:
        if self._committed or self._aborted:
            return
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            os.replace(self._tmp, self._path)
        except OSError:
            self.abort()
            raise
        self._committed = True

    def abort(self) -> None:
        if self._committed or self._aborted:
            return  # never unlink a committed file (or double-abort)
        self._aborted = True
        try:
            self._f.close()
        finally:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass


class MemorySink(ByteSink):
    """An in-memory byte buffer as a sink (tests, size probes, staging
    bytes for a network PUT)."""

    def __init__(self, sink_id: str | None = None):
        self._buf = bytearray()
        self._id = sink_id or f"mem:{id(self):#x}"
        self._closed = False

    @property
    def sink_id(self) -> str:
        return self._id

    def write(self, data) -> int:
        if self._closed:
            raise SinkError("sink closed: memory sink")
        self._buf += data
        n = len(data)
        _count_write(n)
        return n

    def tell(self) -> int:
        return len(self._buf)

    def getvalue(self) -> bytes:
        """The written bytes (valid before and after close)."""
        return bytes(self._buf)

    def close(self) -> None:
        self._closed = True

    def abort(self) -> None:
        self._closed = True


class FileObjectSink(ByteSink):
    """Adapter over an arbitrary writable binary file-like object (BytesIO,
    a pipe, an already-open handle). The CALLER owns the object's lifetime:
    close() flushes but never closes it, abort() leaves it untouched (the
    caller decides what a half-written stream means for them)."""

    def __init__(self, f):
        self._f = f
        self._pos = 0

    def write(self, data) -> int:
        written = self._f.write(data)
        if written is not None and written != len(data):
            # raw unbuffered streams may legally short-write; accepting it
            # would silently drift every footer offset from the real bytes
            raise SinkError(
                f"short write to file object: {written}/{len(data)} bytes"
            )
        n = len(data)
        self._pos += n
        _count_write(n)
        return n

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        flush = getattr(self._f, "flush", None)
        if flush is not None:
            flush()

    def close(self) -> None:
        self.flush()

    def abort(self) -> None:
        pass


class BufferedSink(ByteSink):
    """Write-combining wrapper: writes accumulate in memory and spill to the
    inner sink in runs of >= `spill_bytes` (default 1 MiB). Page headers are
    tens of bytes and a row group writes hundreds of them — batching them
    turns per-page syscalls (or per-page PUTs, for request-priced sinks)
    into a handful of large sequential writes. flush()/close() drain the
    buffer; abort() drops it and aborts the inner sink."""

    def __init__(self, inner: ByteSink, *, spill_bytes: int = 1 << 20):
        if spill_bytes < 1:
            raise ValueError("spill_bytes must be >= 1")
        self.inner = inner
        self.spill_bytes = int(spill_bytes)
        self._buf = bytearray()
        self._flushed = 0  # bytes already handed to the inner sink
        self._closed = False

    @property
    def sink_id(self) -> str:
        return self.inner.sink_id

    def buffered(self) -> int:
        """Bytes held in memory, not yet written through (tests/tuning)."""
        return len(self._buf)

    def write(self, data) -> int:
        if self._closed:
            # without this, a buffered write after close/abort would report
            # success and silently vanish until (never) the next spill
            raise SinkError("sink closed: buffered sink")
        self._buf += data
        if len(self._buf) >= self.spill_bytes:
            self._spill()
        return len(data)

    def _spill(self) -> None:
        if self._buf:
            # hand off state only AFTER the fallible inner write: a caller
            # retrying past a transient spill fault must not silently lose
            # this run of bytes while tell() still counts them
            self.inner.write(bytes(self._buf))
            self._flushed += len(self._buf)
            self._buf = bytearray()

    def tell(self) -> int:
        return self._flushed + len(self._buf)

    def flush(self) -> None:
        self._spill()
        self.inner.flush()

    def close(self) -> None:
        self._spill()
        self._closed = True
        self.inner.close()

    def abort(self) -> None:
        self._buf = bytearray()
        self._closed = True
        self.inner.abort()


def open_sink(obj) -> tuple[ByteSink, bool]:
    """Coerce `obj` into a (ByteSink, owns) pair — the FileWriter
    constructor's one entry point for every accepted destination shape.

      str / Path           -> LocalFileSink        (owned: writer commits
                                                    atomically at close)
      http(s):// URL       -> io.remote_sink.HttpSink  (owned: multipart
                                                    commit at close, same
                                                    atomicity contract)
      ByteSink             -> passed through       (caller keeps lifetime)
      writable file-like   -> FileObjectSink       (caller keeps lifetime)
    """
    if isinstance(obj, ByteSink):
        return obj, False
    if isinstance(obj, str) and obj.startswith(("http://", "https://")):
        # the write-side twin of open_source's URL coercion: remote
        # writes inherit signing (io.sign registry) and the resilience
        # policy's breaker with zero per-callsite wiring
        from ..io.remote_sink import HttpSink

        return HttpSink(obj), True
    if isinstance(obj, (str, Path)):
        return LocalFileSink(obj), True
    if (
        hasattr(obj, "write")
        and hasattr(obj, "tell")
        and hasattr(obj, "abort")
    ):
        return obj, False  # duck-typed sink (custom remote implementations)
    if hasattr(obj, "write"):
        if isinstance(obj, _io.TextIOBase):
            raise TypeError("cannot write parquet to a text-mode file object")
        return FileObjectSink(obj), False
    raise TypeError(
        f"cannot open {type(obj).__name__!r} as a byte sink (expected a "
        "path, a ByteSink, or a writable binary file object)"
    )

"""Parallel row-group encode pipeline: the write-side mirror of the read
architecture (fused prepare pool + io seam).

The original FileWriter encoded and wrote serially: one host loop converting
buffered values, building dictionaries, encoding pages and pushing bytes
straight at one file handle. But row groups are independent by construction
(that's what makes parallel READS work), and so are the column chunks inside
one group — the only serial obligation is the byte ORDER in the file. This
module splits the two concerns:

  encode_chunk()      one column chunk -> page bytes + metadata with offsets
                      RELATIVE to the chunk start. Pure function of
                      (config, builder snapshot): no writer state, no sink,
                      safe on any thread. Reuses the existing C fast paths
                      (ops.rle_hybrid.encode_hybrid, ops.delta.encode_delta,
                      the vectorized/native dictionary build in
                      core.column_store) — ctypes calls drop the GIL, which
                      is what makes the thread pool actually scale.
  assemble_group()    stitch encoded chunks into one row group, offsets
                      relative to the GROUP start
  commit_group()      rebase a group to its absolute file position and write
                      its bytes to the sink — the only stateful step, and
                      the same few lines for the serial and parallel paths,
                      so the two can never diverge on bytes
  EncodePipeline      the parallel orchestrator: chunk encodes fan out on
                      the dedicated "pqt-encode" pool while ONE in-order
                      flusher thread commits finished groups to the sink in
                      submission order — output bytes are identical to the
                      serial path. Backpressure bounds in-flight encoded
                      bytes; faults are captured and re-raised as typed
                      errors on the next writer call (deferred propagation).

Observability: every chunk encode bills the write.encode trace stage and the
encode_seconds histogram + pages_written_total{encoding}; every commit bills
write.flush and write_bytes_total{codec}.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from ..core.arrays import ByteArrayData
from ..core.column_store import PROBE_NA
from ..core.page import (
    encode_data_page_v1,
    encode_data_page_v2,
    encode_dict_page,
)
from ..core.stats import column_is_unsigned, compute_statistics
from ..ops import plain as plain_ops
from ..meta.parquet_types import (
    BoundaryOrder,
    ColumnChunk,
    ColumnIndex,
    ColumnMetaData,
    Encoding,
    KeyValue,
    OffsetIndex,
    PageEncodingStats,
    PageLocation,
    PageType,
    RowGroup,
    Type,
)
from ..obs.log import log_event as _log_event
from ..obs.pool import instrumented_submit
from ..obs.recorder import recorder as _recorder
from ..utils import metrics as _metrics
from ..utils.trace import bump as trace_bump
from ..utils.trace import stage, timed_stage

__all__ = [
    "EncoderConfig",
    "EncodedChunk",
    "EncodedRowGroup",
    "encode_chunk",
    "assemble_group",
    "commit_group",
    "EncodePipeline",
    "encode_pool",
]


@dataclass(frozen=True)
class EncoderConfig:
    """The immutable slice of FileWriter configuration a chunk encode needs —
    snapshotting it (instead of passing the writer) is what lets encodes run
    on pool threads while the writer keeps accepting rows."""

    codec: int
    data_page_version: int
    max_page_size: int
    with_crc: bool
    write_page_index: bool
    column_encodings: dict  # leaf path tuple -> fallback Encoding
    bloom_specs: dict  # leaf path tuple -> (ndv or None, fpp)
    sorting: tuple | None = None  # resolved SortingColumn list (or None)


@dataclass
class EncodedChunk:
    """One encoded column chunk: page bytes + footer structs with offsets
    relative to the CHUNK start (rebased twice: group stitch, then file)."""

    parts: list  # page byte strings, in file order
    nbytes: int
    chunk: ColumnChunk
    index: tuple | None  # (ColumnIndex, OffsetIndex) when the page index is on
    bloom: object | None  # (ColumnMetaData, BloomFilter) awaiting close()


@dataclass
class EncodedRowGroup:
    chunks: list  # list[EncodedChunk], leaf order
    row_group: RowGroup
    nbytes: int
    n_rows: int
    indexes: list = field(default_factory=list)  # [(cc, ci, oi)]
    blooms: list = field(default_factory=list)  # [(md, bf)]


class _PageIndexBuilder:
    """Accumulates one chunk's per-page locations + statistics into
    (ColumnIndex, OffsetIndex) — the Parquet page index (beyond the
    reference, which writes no page index)."""

    def __init__(self, column, dictionary):
        self.column = column
        self.unsigned = column_is_unsigned(column)
        self.dictionary = dictionary  # dict VALUES when pages carry indices
        self.locations: list[PageLocation] = []
        self.null_pages: list[bool] = []
        self.mins: list[bytes] = []
        self.maxs: list[bytes] = []
        self.null_counts: list[int] = []
        self.first_row = 0
        self.ok = True  # a page without computable stats voids the index

    def add_page(self, offset: int, size: int, v_slice, d_slice, r_slice) -> None:
        if not self.ok:
            return
        if r_slice is not None and len(r_slice):
            rows = int((np.asarray(r_slice) == 0).sum())
        elif d_slice is not None:
            rows = len(d_slice)
        else:
            rows = len(v_slice)
        self.locations.append(
            PageLocation(
                offset=offset, compressed_page_size=size, first_row_index=self.first_row
            )
        )
        self.first_row += rows
        nulls = (
            int((np.asarray(d_slice) != self.column.max_def).sum())
            if d_slice is not None
            else 0
        )
        self.null_counts.append(nulls)
        values = v_slice
        if self.dictionary is not None:
            idx = np.asarray(v_slice)
            values = (
                self.dictionary.take(idx.astype(np.int64))
                if isinstance(self.dictionary, ByteArrayData)
                else np.asarray(self.dictionary)[idx]
            )
        if len(values) == 0:
            self.null_pages.append(True)
            self.mins.append(b"")
            self.maxs.append(b"")
            return
        st = compute_statistics(self.column.type, values, nulls, self.unsigned)
        if st.min_value is None or st.max_value is None:
            # all-NaN page / oversized binary: a legal index can't represent
            # it, so write no index for this chunk at all
            self.ok = False
            return
        self.null_pages.append(False)
        self.mins.append(st.min_value)
        self.maxs.append(st.max_value)

    def _boundary_order(self) -> int:
        # the tables that packed these exact bytes
        from ..core.stats import _PACK, _PACK_UNSIGNED
        from ..meta.parquet_types import ConvertedType, Type

        unpack = (
            _PACK_UNSIGNED.get(self.column.type)
            if self.unsigned
            else _PACK.get(self.column.type)
        )
        if unpack is None:
            if self.column.type in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
                ct = self.column.converted_type
                lt = self.column.logical_type
                if ct in (ConvertedType.DECIMAL, ConvertedType.INTERVAL) or (
                    lt is not None
                    and (lt.DECIMAL is not None or lt.FLOAT16 is not None)
                ):
                    # signed / no defined order: lexicographic bytes would
                    # mislead a reader's binary search
                    return int(BoundaryOrder.UNORDERED)
                # unsigned lexicographic IS the defined order for binary
                # columns, and it's how these bounds were computed — sorted
                # string columns keep readers' binary search
                unpack = None
            else:
                return int(BoundaryOrder.UNORDERED)  # INT96 etc.: stay safe
        if unpack is None:
            pairs = [
                (mn, mx)
                for mn, mx, null in zip(self.mins, self.maxs, self.null_pages)
                if not null
            ]
        else:
            pairs = [
                (unpack.unpack(mn)[0], unpack.unpack(mx)[0])
                for mn, mx, null in zip(self.mins, self.maxs, self.null_pages)
                if not null
            ]
        if len(pairs) < 2:
            return int(BoundaryOrder.ASCENDING)
        if all(
            b[0] >= a[0] and b[1] >= a[1] for a, b in zip(pairs, pairs[1:])
        ):
            return int(BoundaryOrder.ASCENDING)
        if all(
            b[0] <= a[0] and b[1] <= a[1] for a, b in zip(pairs, pairs[1:])
        ):
            return int(BoundaryOrder.DESCENDING)
        return int(BoundaryOrder.UNORDERED)

    def build(self):
        if not self.ok:
            return ()
        ci = ColumnIndex(
            null_pages=self.null_pages,
            min_values=self.mins,
            max_values=self.maxs,
            boundary_order=self._boundary_order(),
            null_counts=self.null_counts,
        )
        oi = OffsetIndex(page_locations=self.locations)
        return (ci, oi)


def _slice_values(values, a: int, b: int):
    if isinstance(values, ByteArrayData):
        off = values.offsets
        sub = off[a : b + 1] - off[a]
        return ByteArrayData(offsets=sub, data=values.data[off[a] : off[b]])
    return values[a:b]


def _value_width(values) -> int:
    if isinstance(values, ByteArrayData):
        n = len(values)
        return max(int(len(values.data) / n) + 4, 5) if n else 8
    arr = np.asarray(values)
    if arr.ndim == 2:
        return arr.shape[1]
    return max(arr.itemsize, 1)


def _split_starts(n: int, per_page: int):
    """The flat-column page boundaries of _split_pages as (a, b) pairs —
    shared with the device batch-materialization path so its page split
    can never drift from the host's."""
    if n == 0:
        yield 0, 0
        return
    if n <= per_page:
        yield 0, n
        return
    starts = list(range(0, n, per_page)) + [n]
    yield from zip(starts[:-1], starts[1:])


def _split_pages(values, def_levels, rep_levels, column, max_page_size: int):
    """Split a chunk into page-sized slices (~max_page_size of value data),
    keeping repeated-value rows intact (page boundaries at rep==0)."""
    n = len(def_levels) if def_levels is not None else len(values)
    if n == 0:
        yield values, def_levels, rep_levels
        return
    per_value = _value_width(values)
    per_page = max(int(max_page_size // max(per_value, 1)), 1)
    if n <= per_page:
        yield values, def_levels, rep_levels
        return
    # candidate boundaries: rows (rep==0) if repeated, else any index
    starts = list(range(0, n, per_page)) + [n]
    if rep_levels is not None and len(rep_levels):
        # Page boundaries must fall on row starts (rep == 0) so a row's
        # repeated values never straddle pages.
        row_starts = np.nonzero(np.asarray(rep_levels) == 0)[0]
        fixed = [0]
        for s in starts[1:-1]:
            k = np.searchsorted(row_starts, s, side="left")
            b = int(row_starts[k]) if k < len(row_starts) else n
            if b > fixed[-1]:
                fixed.append(b)
        if fixed[-1] != n:
            fixed.append(n)
        starts = fixed
    vpos = 0
    for a, b in zip(starts[:-1], starts[1:]):
        if def_levels is not None:
            d_slice = def_levels[a:b]
            nn = int((d_slice == column.max_def).sum())
            v_slice = _slice_values(values, vpos, vpos + nn)
            vpos += nn
        else:
            d_slice = None
            v_slice = _slice_values(values, a, b)
        r_slice = rep_levels[a:b] if rep_levels is not None else None
        yield v_slice, d_slice, r_slice


@dataclass
class _ChunkEncodePlan:
    """Shared front half of the encode ladder: typed/level normalization and
    the dictionary decision, computed ONCE and consumed by whichever rung
    (fused native or staged Python) produces the bytes — the two rungs
    cannot diverge on inputs because they read the same plan."""

    nv: int  # non-null value count
    num_entries: int  # level entries (nulls/empty lists included)
    null_count: int
    def_levels: np.ndarray | None
    rep_levels: np.ndarray | None
    typed: object | None  # None iff the object-domain probe engaged a dict
    dict_result: tuple | None  # (dict_values, indices) | None
    value_encoding: object  # Encoding
    page_values: object  # indices when dict, typed otherwise
    dict_size: int | None
    stats_src: object  # dict_values when dict (same min/max, ~U values)


def _plan_chunk(cfg: EncoderConfig, builder) -> _ChunkEncodePlan:
    column = builder.column
    nv = builder._n_values()
    def_levels = (
        np.asarray(builder.def_levels, dtype=np.uint16)
        if column.max_def > 0
        else None
    )
    rep_levels = (
        np.asarray(builder.rep_levels, dtype=np.uint16)
        if column.max_rep > 0
        else None
    )
    if def_levels is None:
        num_entries = nv
    else:
        num_entries = len(def_levels)
        if builder._columnar_values is not None and len(def_levels) == 0:
            # columnar input for optional column without explicit levels:
            # treat as fully present
            def_levels = np.full(nv, column.max_def, dtype=np.uint16)
            num_entries = nv
    if rep_levels is not None and len(rep_levels) == 0:
        rep_levels = np.zeros(num_entries, dtype=np.uint16)
    null_count = (
        int((def_levels != column.max_def).sum()) if def_levels is not None else 0
    )
    # Dictionary decision. The object-domain probe dedups Python str values
    # BEFORE any UTF-8 materialization — when it engages, `typed` is never
    # built and only the (few) uniques are byte-encoded; when it rules
    # dictionary encoding out (None) the verdict is definitive and only the
    # typed conversion remains. PROBE_NA falls back to the byte/bit-pattern
    # probes over the typed array, exactly as before.
    typed = None
    with timed_stage("encode.dict", record_span=True):
        dict_result = builder.fast_dictionary()
        if dict_result is PROBE_NA:
            typed = builder.typed_values()
            dict_result = builder.build_dictionary(typed)
        elif dict_result is None:
            typed = builder.typed_values()
    if dict_result is not None:
        dict_values, indices = dict_result
        value_encoding = Encoding.RLE_DICTIONARY
        page_values = indices
        dict_size = len(dict_values)
        # the dictionary holds exactly the distinct value set: chunk min/max
        # over it equals min/max over the full column at ~U values scanned
        stats_src = dict_values
    else:
        value_encoding = cfg.column_encodings.get(column.path, Encoding.PLAIN)
        page_values = typed
        dict_size = None
        stats_src = typed
    return _ChunkEncodePlan(
        nv=nv,
        num_entries=num_entries,
        null_count=null_count,
        def_levels=def_levels,
        rep_levels=rep_levels,
        typed=typed,
        dict_result=dict_result,
        value_encoding=value_encoding,
        page_values=page_values,
        dict_size=dict_size,
        stats_src=stats_src,
    )


def _chunk_meta(cfg: EncoderConfig, builder, kv, plan, *,
                uncompressed_total, pos, data_offset, dict_offset,
                n_pages) -> tuple:
    """Footer structs shared by both rungs: ColumnMetaData + statistics +
    bloom, built from the plan and the (rung-produced) page accounting."""
    column = builder.column
    encodings = {int(Encoding.RLE)}
    enc_stats: list[PageEncodingStats] = []
    if plan.dict_result is not None:
        encodings.add(int(Encoding.PLAIN))
        encodings.add(int(Encoding.RLE_DICTIONARY))
        enc_stats.append(
            PageEncodingStats(
                page_type=int(PageType.DICTIONARY_PAGE),
                encoding=int(Encoding.PLAIN),
                count=1,
            )
        )
    page_type = (
        int(PageType.DATA_PAGE)
        if cfg.data_page_version == 1
        else int(PageType.DATA_PAGE_V2)
    )
    encodings.add(int(plan.value_encoding))
    enc_stats.append(
        PageEncodingStats(
            page_type=page_type, encoding=int(plan.value_encoding), count=n_pages
        )
    )
    stats = compute_statistics(
        column.type, plan.stats_src, plan.null_count, column_is_unsigned(column)
    )
    if plan.dict_result is not None:
        # the dictionary IS the distinct set: record the exact count
        stats.distinct_count = plan.dict_size
    md = ColumnMetaData(
        type=int(column.type),
        encodings=sorted(encodings),
        path_in_schema=list(column.path),
        codec=cfg.codec,
        num_values=plan.num_entries,
        total_uncompressed_size=uncompressed_total,
        total_compressed_size=pos,
        data_page_offset=data_offset,
        dictionary_page_offset=dict_offset,
        statistics=stats,
        encoding_stats=enc_stats,
        key_value_metadata=(
            [KeyValue(key=k, value=v) for k, v in kv.items()] if kv else None
        ),
    )
    bloom = None
    spec = cfg.bloom_specs.get(column.path)
    if spec is not None:
        hash_src = (
            plan.dict_result[0] if plan.dict_result is not None else plan.typed
        )
        if len(hash_src):
            from ..core.bloom import BloomFilter, bloom_hash_values

            ndv, fpp = spec
            bf = BloomFilter.sized_for(ndv or len(hash_src), fpp)
            bf.insert_hashes(bloom_hash_values(column.type, hash_src))
            bloom = (md, bf)
    # file_offset: where this chunk's pages begin (parquet-cpp's
    # convention; some readers sanity-check it against the page offsets)
    cc = ColumnChunk(
        file_offset=dict_offset if dict_offset is not None else data_offset,
        meta_data=md,
    )
    return cc, bloom


def encode_chunk(cfg: EncoderConfig, builder, kv: dict | None) -> EncodedChunk:
    """Encode one buffered column chunk into page bytes + footer structs,
    offsets relative to the chunk start. Pure w.r.t. the writer: the only
    inputs are the frozen config, the builder SNAPSHOT (the writer has
    already swapped in fresh builders), and this flush's KV metadata.

    Runs the fused -> staged encode ladder (the write-side mirror of the
    prepare ladder in kernels/pipeline.py): one GIL-free native call
    (ptq_chunk_encode) does page split + level pack + value encode +
    compress + Thrift framing for the common flat shapes, byte-identical
    to the staged per-page Python loop, which remains the fallback rung for
    everything else and the error-semantics oracle. PQT_FUSED_ENCODE=0
    forces the staged rung; the outcome is pinned by the
    encode_fused_engaged / encode_fused_declined / encode_fallback_recovered
    counters."""
    with timed_stage("write.encode", record_span=True) as clock:
        plan = _plan_chunk(cfg, builder)
        fault = None
        ec = _fused_encode_chunk(cfg, builder, kv, plan)
        if ec is not None and not isinstance(ec, EncodedChunk):
            fault, ec = ec, None  # EncodeFault: remember for the recovery pin
        if ec is None:
            ec = _staged_encode_chunk(cfg, builder, kv, plan)
            if fault is not None:
                # the staged rung salvaged a chunk the native walk refused
                trace_bump("encode_fallback_recovered")
                _log_event(
                    "encode_fallback_recovered",
                    level="warning",
                    column=builder.column.path_str,
                    stage=fault.stage,
                    code=fault.code,
                    page=fault.page,
                )
    _metrics.observe("encode_seconds", clock.seconds)
    return ec


# codecs the native encode walk inlines (must also still resolve to the
# stock implementation — core.compress.is_fused_encode_codec checks both)
_FUSED_ENCODE_CODECS = (0, 1, 2)

# stage_ns slot -> trace lane, mirroring the prepare.* sub-clock lanes
_ENCODE_STAGE_LANES = (
    "encode.levels",
    "encode.values",
    "encode.compress",
    "encode.frame",
    "encode.crc",
)


def _fused_encode_chunk(cfg: EncoderConfig, builder, kv, plan):
    """The native rung: returns an EncodedChunk, an EncodeFault (walk ran
    and aborted — the caller retries staged and counts the recovery), or
    None (declined: shape/codec/config outside the fused envelope, or the
    escape hatch is set)."""
    if os.environ.get("PQT_FUSED_ENCODE", "1") == "0":
        return None  # forced staged path: not a decline, no counter
    from ..utils.native import delta_encode_cap, get_native, hybrid_encode_cap

    lib = get_native()
    if lib is None or not lib.has_chunk_encode:
        return None
    column = builder.column
    if column.max_rep > 0 or cfg.write_page_index:
        # nested page splits / per-page index stats are staged-only shapes
        trace_bump("encode_fused_declined")
        return None
    from ..core.compress import is_fused_encode_codec

    if cfg.codec not in _FUSED_ENCODE_CODECS or not is_fused_encode_codec(
        cfg.codec
    ):
        trace_bump("encode_fused_declined")
        return None

    ba_offsets = None
    dict_raw = None
    dict_width = 0
    dict_num = 0
    if plan.dict_result is not None:
        from ..ops.bitpack import bit_width

        dict_values = plan.dict_result[0]
        values_buf = np.ascontiguousarray(plan.dict_result[1], dtype=np.uint32)
        route = 2
        type_size = 4
        per_value = 4
        dict_width = bit_width(max(plan.dict_size - 1, 0))
        dict_num = plan.dict_size
        try:
            dict_raw = plain_ops.encode_plain(
                dict_values, column.type, column.type_length
            )
        except Exception:
            trace_bump("encode_fused_declined")
            return None
        values_worst = 1 + hybrid_encode_cap(plan.nv, dict_width)
    else:
        typed = plan.typed
        enc = plan.value_encoding
        if enc == Encoding.PLAIN and isinstance(typed, ByteArrayData):
            route = 1
            type_size = 0
            values_buf = typed.data
            ba_offsets = np.ascontiguousarray(typed.offsets, dtype=np.int64)
            n = plan.nv
            per_value = max(int(len(typed.data) / n) + 4, 5) if n else 8
            values_worst = len(typed.data) + 4 * n + 16
        elif (
            enc == Encoding.PLAIN
            and isinstance(typed, np.ndarray)
            and typed.ndim == 1
            and typed.dtype.kind in "iuf"
            and typed.itemsize in (4, 8)
        ):
            route = 0
            values_buf = np.ascontiguousarray(typed)
            type_size = per_value = typed.itemsize
            values_worst = values_buf.nbytes + 16
        elif (
            enc == Encoding.PLAIN
            and isinstance(typed, np.ndarray)
            and typed.ndim == 2
            and typed.dtype == np.uint8
            and 1 <= typed.shape[1] <= 4096
        ):
            # FIXED_LEN_BYTE_ARRAY / INT96: PLAIN is a row-major memcpy
            route = 0
            values_buf = np.ascontiguousarray(typed)
            type_size = per_value = typed.shape[1]
            values_worst = values_buf.nbytes + 16
        elif (
            enc == Encoding.DELTA_BINARY_PACKED
            and isinstance(typed, np.ndarray)
            and typed.ndim == 1
            and typed.dtype in (np.dtype(np.int32), np.dtype(np.int64))
        ):
            route = 3
            values_buf = np.ascontiguousarray(typed)
            type_size = per_value = typed.itemsize
            values_worst = delta_encode_cap(plan.nv, type_size * 8)
        elif (
            enc == Encoding.RLE
            and column.type == Type.BOOLEAN
            and isinstance(typed, np.ndarray)
            and typed.ndim == 1
        ):
            # RLE-boolean: width-1 hybrid stream behind a 4-byte prefix.
            # per_value stays the STAGED path's _value_width (the 1-byte
            # bool element) so page splits cannot drift; the native walk
            # reads the values as uint16 like the level packer.
            route = 4
            values_buf = np.ascontiguousarray(typed, dtype=np.uint16)
            type_size = 2
            per_value = 1
            pages_est = plan.nv // max(int(cfg.max_page_size), 1) + 2
            values_worst = (
                hybrid_encode_cap(plan.nv, 1) + 96 * pages_est
            )
        else:
            # BOOLEAN bit-packing, BYTE_STREAM_SPLIT, DELTA_*_BYTE_ARRAY
            # and exotic inputs stay on the staged rung
            trace_bump("encode_fused_declined")
            return None

    per_page = max(int(cfg.max_page_size // max(per_value, 1)), 1)
    levels_worst = 0
    if column.max_def > 0:
        from ..ops.bitpack import bit_width

        levels_worst = 4 + hybrid_encode_cap(
            plan.num_entries, bit_width(column.max_def)
        )
    from ..utils import trace as _trace

    res = lib.chunk_encode(
        route,
        values_buf,
        ba_offsets,
        plan.nv,
        type_size,
        dict_width,
        dict_raw,
        dict_num,
        plan.def_levels if column.max_def > 0 else None,
        plan.num_entries,
        column.max_def,
        int(cfg.codec),
        cfg.data_page_version,
        cfg.with_crc,
        per_page,
        values_worst + levels_worst + 64,
        collect_stages=_trace.active(),
    )
    if not isinstance(res, dict):
        trace_bump("encode_fused_declined")
        trace_bump(f"encode_fused_fault_{res.stage}")
        return res  # EncodeFault: the caller runs the staged rung + counters
    trace_bump("encode_fused_engaged")
    stage_ns = res.get("stage_ns")
    if stage_ns is not None:
        _trace.add_seconds_batch(
            [
                (lane, int(stage_ns[slot]) / 1e9)
                for slot, lane in enumerate(_ENCODE_STAGE_LANES)
                if stage_ns[slot]
            ]
        )
    totals = res["totals"]
    n_pages = int(totals[2])
    if plan.dict_result is not None:
        _metrics.inc("pages_written_total", encoding="PLAIN")
    _metrics.inc(
        "pages_written_total",
        n_pages,
        encoding=_metrics.encoding_name(plan.value_encoding),
    )
    dict_offset = int(totals[3]) if int(totals[3]) >= 0 else None
    data_offset = int(totals[4])
    pos = int(totals[0])
    cc, bloom = _chunk_meta(
        cfg,
        builder,
        kv,
        plan,
        uncompressed_total=int(totals[1]),
        pos=pos,
        data_offset=data_offset,
        dict_offset=dict_offset,
        n_pages=n_pages,
    )
    # bytes-like part, not the ndarray itself: sinks concatenate parts into
    # bytearrays/files, and an ndarray would be swallowed by numpy's
    # arithmetic overloads instead. The slice VIEW pins the whole
    # worst-case-sized staging buffer until the group commits, so when
    # compression left significant slack (the common gzip/snappy case)
    # copy out exactly the encoded bytes — the parallel pipeline's
    # in-flight window then holds encoded sizes, not capacities.
    out = res["out"]
    if out.base is not None and out.base.nbytes > pos + pos // 4 + 4096:
        part = out.tobytes()
    else:
        part = memoryview(out)
    return EncodedChunk(
        parts=[part], nbytes=pos, chunk=cc, index=None, bloom=bloom
    )


def _staged_encode_chunk(
    cfg: EncoderConfig, builder, kv: dict | None, plan: _ChunkEncodePlan
) -> EncodedChunk:
    """The staged rung: the original per-page Python loop over the shared
    plan — the byte oracle of the differential matrix and the path every
    shape outside the fused envelope takes."""
    column = builder.column
    parts: list = []
    pos = 0
    uncompressed_total = 0

    def write_page(header, block) -> None:
        nonlocal pos, uncompressed_total
        hdr = header.dumps()
        parts.append(hdr)
        parts.append(block)
        pos += len(hdr) + len(block)
        uncompressed_total += len(hdr) + (header.uncompressed_page_size or 0)

    dict_offset = None
    if plan.dict_result is not None:
        dict_values = plan.dict_result[0]
        header, block = encode_dict_page(
            column, dict_values, cfg.codec, cfg.with_crc
        )
        dict_offset = pos
        write_page(header, block)
        _metrics.inc("pages_written_total", encoding="PLAIN")

    data_offset = pos
    n_pages = 0
    index = (
        _PageIndexBuilder(
            column, plan.dict_result[0] if plan.dict_result else None
        )
        if cfg.write_page_index
        else None
    )
    for v_slice, d_slice, r_slice in _split_pages(
        plan.page_values, plan.def_levels, plan.rep_levels, column,
        cfg.max_page_size,
    ):
        page_offset = pos
        if cfg.data_page_version == 1:
            header, block = encode_data_page_v1(
                column, v_slice, d_slice, r_slice, plan.value_encoding,
                cfg.codec, plan.dict_size, cfg.with_crc,
            )
        else:
            header, block = encode_data_page_v2(
                column, v_slice, d_slice, r_slice, plan.value_encoding,
                cfg.codec, plan.dict_size, cfg.with_crc,
            )
        write_page(header, block)
        if index is not None:
            index.add_page(
                page_offset, pos - page_offset, v_slice, d_slice, r_slice
            )
        n_pages += 1
    _metrics.inc(
        "pages_written_total", n_pages,
        encoding=_metrics.encoding_name(plan.value_encoding),
    )
    cc, bloom = _chunk_meta(
        cfg,
        builder,
        kv,
        plan,
        uncompressed_total=uncompressed_total,
        pos=pos,
        data_offset=data_offset,
        dict_offset=dict_offset,
        n_pages=n_pages,
    )
    built = index.build() if index is not None else None
    return EncodedChunk(
        parts=parts, nbytes=pos, chunk=cc, index=built or None, bloom=bloom
    )


def _shift_chunk(ec: EncodedChunk, delta: int) -> None:
    """Rebase one encoded chunk's offsets by `delta` (group stitch or final
    file placement — the same arithmetic both times)."""
    if delta == 0:
        return
    md = ec.chunk.meta_data
    for attr in ("data_page_offset", "dictionary_page_offset", "index_page_offset"):
        v = getattr(md, attr)
        if v is not None:
            setattr(md, attr, v + delta)
    if ec.chunk.file_offset is not None:
        ec.chunk.file_offset += delta
    if ec.index:
        for loc in ec.index[1].page_locations:
            loc.offset += delta


def assemble_group(
    cfg: EncoderConfig, chunks: list, n_rows: int
) -> EncodedRowGroup:
    """Stitch per-chunk encodes (leaf order) into one row group with offsets
    relative to the GROUP start."""
    base = 0
    total_bytes = 0
    total_compressed = 0
    ccs = []
    indexes = []
    blooms = []
    for ec in chunks:
        _shift_chunk(ec, base)
        base += ec.nbytes
        ccs.append(ec.chunk)
        md = ec.chunk.meta_data
        total_bytes += md.total_uncompressed_size
        total_compressed += md.total_compressed_size
        if cfg.write_page_index and ec.index:
            indexes.append((ec.chunk, *ec.index))
        if ec.bloom is not None:
            blooms.append(ec.bloom)
    first_md = ccs[0].meta_data if ccs else None
    first_page_offset = None
    if first_md is not None:
        # file_offset = first page of the group, dictionary page included.
        first_page_offset = (
            first_md.dictionary_page_offset
            if first_md.dictionary_page_offset is not None
            else first_md.data_page_offset
        )
    rg = RowGroup(
        columns=ccs,
        total_byte_size=total_bytes,
        total_compressed_size=total_compressed,
        num_rows=n_rows,
        file_offset=first_page_offset,
        sorting_columns=list(cfg.sorting) if cfg.sorting else None,
    )
    return EncodedRowGroup(
        chunks=chunks,
        row_group=rg,
        nbytes=base,
        n_rows=n_rows,
        indexes=indexes,
        blooms=blooms,
    )


def commit_group(erg: EncodedRowGroup, sink, pos: int, codec_label: str) -> int:
    """Rebase `erg` to absolute file position `pos` and write its bytes to
    the sink. Returns the new position. The ONE place group bytes meet the
    sink — serial and parallel writes are byte-identical because they both
    end here, in submission order."""
    for ec in erg.chunks:
        _shift_chunk(ec, pos)  # chunks are group-relative: one shift places all
    if erg.row_group.file_offset is not None:
        erg.row_group.file_offset += pos
    with stage("write.flush", erg.nbytes):
        for ec in erg.chunks:
            for part in ec.parts:
                sink.write(part)
    _metrics.inc("write_bytes_total", erg.nbytes, codec=codec_label)
    return pos + erg.nbytes


# -- the dedicated encode pool -------------------------------------------------

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def encode_pool() -> ThreadPoolExecutor:
    """The process-wide parallel-encode executor ("pqt-encode",
    PQT_ENCODE_THREADS or min(cpu, 8) workers). Deliberately its OWN pool:
    encode tasks are CPU-bound native/numpy work, and parking them in the
    prepare, io or dataset pools would let a heavy write starve reads (or
    deadlock a pool waiting on work it must itself run)."""
    global _pool
    with _pool_lock:
        if _pool is None:
            env = os.environ.get("PQT_ENCODE_THREADS")
            workers = int(env) if env else min(os.cpu_count() or 1, 8)
            _pool = ThreadPoolExecutor(
                max_workers=max(1, workers), thread_name_prefix="pqt-encode"
            )
        return _pool


class EncodePipeline:
    """Per-writer parallel encode + in-order flush orchestrator.

    submit() fans one row group's chunk encodes out on the pool (every chunk
    an independent task — intra-group AND inter-group parallelism with no
    nested submission, so the pool can never deadlock on itself) and hands
    the ordered future list to the single flusher thread, which assembles,
    rebases and commits finished groups to the sink STRICTLY in submission
    order — the file's bytes are identical to the serial path's.

    Backpressure: submit() blocks while the estimated in-flight encoded
    bytes exceed `max_inflight_bytes` (at least one group is always
    admitted, so a group larger than the budget still makes progress).

    Faults (encode or flush) are captured, the queue is drained without
    writing further groups, and the error re-raises from the next submit()/
    drain() — the writer surfaces it as a typed WriterError. After an error
    the pipeline never writes another byte (abort semantics are the sink's:
    an atomic sink leaves no torn file)."""

    def __init__(
        self,
        cfg: EncoderConfig,
        sink,
        start_pos: int,
        *,
        pool: ThreadPoolExecutor,
        max_inflight_bytes: int = 256 << 20,
    ):
        self.cfg = cfg
        self.sink = sink
        self.pos = start_pos
        self.pool = pool
        self.max_inflight_bytes = int(max_inflight_bytes)
        self.row_groups: list[RowGroup] = []
        self.page_indexes: list[list] = []  # per committed group, when enabled
        self.blooms: list = []  # (md, bf) in file order
        self.error: BaseException | None = None
        self._codec_label = _metrics.codec_name(cfg.codec)
        self._lock = threading.Lock()
        self._have_work = threading.Condition(self._lock)
        self._room = threading.Condition(self._lock)
        self._queue: deque = deque()  # (chunk_futures, n_rows, est_bytes)
        self._inflight_bytes = 0
        self._inflight_groups = 0
        self._stopping = False
        self._thread: threading.Thread | None = None

    # -- producer side (writer thread) -----------------------------------------

    def submit(self, builders: list, kvs: list, n_rows: int, est_bytes: int) -> None:
        """Fan out one row group's chunk encodes (builders in leaf order,
        kvs aligned) and queue it for in-order commit. Blocks for
        backpressure; raises the captured pipeline error if one is set."""
        from contextvars import copy_context

        with self._lock:
            self._raise_pending()
            while (
                self._inflight_groups > 0
                and self._inflight_bytes + est_bytes > self.max_inflight_bytes
            ):
                self._room.wait()
                self._raise_pending()
        # ONE context snapshot per group, shared as a template by every
        # chunk task — the group's chunks all carry the same trace/tenant
        # state, so there is nothing per-task left to capture
        group_ctx = copy_context()
        futs = [
            instrumented_submit(
                self.pool, encode_chunk, self.cfg, b, kv,
                pool="pqt-encode", ctx=group_ctx,
            )
            for b, kv in zip(builders, kvs)
        ]
        with self._lock:
            self._queue.append((futs, n_rows, est_bytes))
            self._inflight_bytes += est_bytes
            self._inflight_groups += 1
            if self._thread is None:
                # the flusher carries the submitting context (an active
                # decode_trace at first flush keeps collecting its spans)
                from contextvars import copy_context

                ctx = copy_context()
                self._thread = threading.Thread(
                    target=ctx.run, args=(self._run,), name="pqt-flush", daemon=True
                )
                self._thread.start()
            self._have_work.notify()

    def _raise_pending(self) -> None:
        # caller holds self._lock
        if self.error is not None:
            raise self.error

    # -- consumer side (the one flusher thread) --------------------------------

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stopping:
                    self._have_work.wait()
                if not self._queue:
                    return  # stopping and drained
                futs, n_rows, est = self._queue.popleft()
            try:
                if self.error is None:
                    t0 = time.perf_counter()
                    chunks = [f.result() for f in futs]
                    erg = assemble_group(self.cfg, chunks, n_rows)
                    ordinal = erg.row_group.ordinal = len(self.row_groups)
                    pos0 = self.pos
                    self.pos = commit_group(
                        erg, self.sink, self.pos, self._codec_label
                    )
                    self.row_groups.append(erg.row_group)
                    if self.cfg.write_page_index:
                        self.page_indexes.append(erg.indexes)
                    self.blooms.extend(erg.blooms)
                    # the library side of the flight recorder: encode groups
                    # land in the same ring the serve daemon's /v1/debug
                    # lists, so one listing interleaves serving + pipeline
                    _recorder().record(
                        "encode.group",
                        duration_s=time.perf_counter() - t0,
                        nbytes=self.pos - pos0,
                        detail={"group": ordinal, "rows": n_rows},
                    )
                else:
                    for f in futs:  # error set: drop, but don't leak workers
                        f.cancel()
            except BaseException as e:  # noqa: BLE001 — deferred to the writer
                _log_event(
                    "encode_group_failed", level="error",
                    group=len(self.row_groups), error=f"{type(e).__name__}: {e}",
                )
                _recorder().record(
                    "encode.group", status="error",
                    detail={"group": len(self.row_groups), "rows": n_rows},
                    error=e,
                )
                with self._lock:
                    if self.error is None:
                        self.error = e
            finally:
                with self._lock:
                    self._inflight_bytes -= est
                    self._inflight_groups -= 1
                    self._room.notify_all()

    # -- lifecycle -------------------------------------------------------------

    def drain(self) -> None:
        """Block until every submitted group is committed; re-raise the
        pipeline error if any group failed."""
        with self._lock:
            self._stopping = True
            self._have_work.notify_all()
            t = self._thread
        if t is not None:
            t.join()
        with self._lock:
            self._raise_pending()

    def abort(self) -> None:
        """Stop without committing queued groups (their encodes are dropped).
        Never raises — abort is the error path."""
        from .sink import SinkError

        with self._lock:
            if self.error is None:
                self.error = SinkError("write pipeline aborted")  # poison
            self._stopping = True
            self._have_work.notify_all()
            t = self._thread
        if t is not None:
            t.join()

"""parquet_tpu.sink — pluggable byte sinks and the parallel encode pipeline.

The write-side counterpart of parquet_tpu.io: ByteSink implementations
(atomic tmp+rename local files, in-memory, file-object adapters, a
write-combining buffer), and the row-group encode pipeline that fans chunk
encodes out on the dedicated pqt-encode pool while a single in-order
flusher commits groups to the sink — output bytes identical to the serial
path. See each module's docstring.
"""

from .encoder import (  # noqa: F401
    EncodePipeline,
    EncodedChunk,
    EncodedRowGroup,
    EncoderConfig,
    assemble_group,
    commit_group,
    encode_chunk,
    encode_pool,
)
from .sink import (  # noqa: F401
    BufferedSink,
    ByteSink,
    FileObjectSink,
    LocalFileSink,
    MemorySink,
    SinkError,
    open_sink,
)

__all__ = [
    "ByteSink",
    "SinkError",
    "LocalFileSink",
    "MemorySink",
    "FileObjectSink",
    "BufferedSink",
    "open_sink",
    "EncoderConfig",
    "EncodedChunk",
    "EncodedRowGroup",
    "encode_chunk",
    "assemble_group",
    "commit_group",
    "EncodePipeline",
    "encode_pool",
]

"""csv2parquet: CSV -> Parquet with optional per-column type hints.

Equivalent of the reference's cmd/csv2parquet (reference:
cmd/csv2parquet/main.go:25-32 flags, parseTypeHints/writeParquetData): derives
an all-optional-string schema from the header by default; -typehints overrides
per column with one of: string, byte_array, boolean, int8/16/32/64,
uint8/16/32/64, float, double, int, json.

    python -m parquet_tpu.tools.csv2parquet -o out.parquet \
        -typehints "age=int64,score=double" in.csv
"""

from __future__ import annotations

import argparse
import csv
import sys

from ..core.writer import FileWriter
from ..meta.parquet_types import Type
from ..schema.builder import _TypeSpec, int_type, message, optional, string
from ..meta.parquet_types import ConvertedType, JsonType, LogicalType

__all__ = ["main", "parse_type_hints"]

_HINTS = {
    "string": string,
    "byte_array": lambda: Type.BYTE_ARRAY,
    "boolean": lambda: Type.BOOLEAN,
    "int8": lambda: int_type(8),
    "int16": lambda: int_type(16),
    "int32": lambda: Type.INT32,
    "int64": lambda: Type.INT64,
    "int": lambda: Type.INT64,
    "uint8": lambda: int_type(8, signed=False),
    "uint16": lambda: int_type(16, signed=False),
    "uint32": lambda: int_type(32, signed=False),
    "uint64": lambda: int_type(64, signed=False),
    "float": lambda: Type.FLOAT,
    "double": lambda: Type.DOUBLE,
    "json": lambda: _TypeSpec(
        Type.BYTE_ARRAY,
        converted=ConvertedType.JSON,
        logical=LogicalType(JSON=JsonType()),
    ),
}

_BOOL_TRUE = {"true", "1", "t", "yes", "y"}
_BOOL_FALSE = {"false", "0", "f", "no", "n"}


def parse_type_hints(text: str) -> dict[str, str]:
    hints = {}
    if not text:
        return hints
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"csv2parquet: bad type hint {part!r} (want col=type)")
        col, typ = part.split("=", 1)
        typ = typ.strip().lower()
        if typ not in _HINTS:
            raise ValueError(
                f"csv2parquet: unknown type {typ!r} (valid: {', '.join(sorted(_HINTS))})"
            )
        hints[col.strip()] = typ
    return hints


def _convert(value: str, typ: str, col: str, line: int):
    if value == "":
        return None
    try:
        if typ in ("string", "json"):
            return value
        if typ == "byte_array":
            return value.encode("utf-8")
        if typ == "boolean":
            lv = value.lower()
            if lv in _BOOL_TRUE:
                return True
            if lv in _BOOL_FALSE:
                return False
            raise ValueError(f"not a boolean: {value!r}")
        if typ in ("float", "double"):
            return float(value)
        return int(value)
    except ValueError as e:
        raise ValueError(f"csv2parquet: line {line}, column {col!r}: {e}") from e


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="csv2parquet", description=__doc__)
    p.add_argument("-o", "--output", required=True, help="output parquet file")
    p.add_argument("-typehints", "--typehints", default="", help="col=type,...")
    p.add_argument("--codec", default="snappy")
    p.add_argument("--row-group-size", type=int, default=1_000_000, help="rows per row group")
    p.add_argument("--delimiter", default=",")
    p.add_argument(
        "--page-index", action="store_true",
        help="write the Parquet page index (per-page min/max for pruning)",
    )
    p.add_argument(
        "--bloom", default="",
        help="comma-separated columns to build bloom filters for",
    )
    p.add_argument(
        "--sort", default="",
        help="comma-separated columns recorded as the row ordering "
        "(metadata only; data is written as-is)",
    )
    p.add_argument(
        "--parallel", type=int, default=0, metavar="N",
        help="encode row groups on N pqt-encode workers (0 = serial; "
        "output bytes are identical either way, and the file commits "
        "atomically at close)",
    )
    p.add_argument("csv", help="input CSV file with header row")
    args = p.parse_args(argv)

    try:
        hints = parse_type_hints(args.typehints)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2

    with open(args.csv, newline="") as f:
        reader = csv.reader(f, delimiter=args.delimiter)
        try:
            header = next(reader)
        except StopIteration:
            print("csv2parquet: empty input", file=sys.stderr)
            return 1
        unknown = set(hints) - set(header)
        if unknown:
            print(f"csv2parquet: type hints for unknown columns {sorted(unknown)}", file=sys.stderr)
            return 2
        col_types = {c: hints.get(c, "string") for c in header}
        fields = [optional(c, _HINTS[col_types[c]]()) for c in header]
        schema = message(*fields, name="csv")
        n = 0
        wkw = {}
        if args.page_index:
            wkw["write_page_index"] = True
        if args.bloom:
            wkw["bloom_filters"] = [c.strip() for c in args.bloom.split(",") if c.strip()]
        if args.sort:
            wkw["sorting_columns"] = [c.strip() for c in args.sort.split(",") if c.strip()]
        if args.parallel:
            wkw["parallel"] = args.parallel
        try:
            with FileWriter(args.output, schema, codec=args.codec, **wkw) as w:
                for i, rec in enumerate(reader, start=2):
                    if len(rec) != len(header):
                        print(
                            f"csv2parquet: line {i}: {len(rec)} fields, expected {len(header)}",
                            file=sys.stderr,
                        )
                        return 1
                    row = {
                        c: _convert(v, col_types[c], c, i)
                        for c, v in zip(header, rec)
                    }
                    w.write_row(row)
                    n += 1
                    if n % args.row_group_size == 0:
                        w.flush_row_group()
        except ValueError as e:
            print(e, file=sys.stderr)
            return 1
    print(f"wrote {n} rows to {args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""parquet-tool: cat / head / meta / schema / rowcount / split / verify / salvage / profile / scan / serve / debug.

Equivalent of the reference's cobra CLI (reference: cmd/parquet-tool/cmds —
cat.go:14, head.go:17, meta.go:14, schema.go:16, rowcount.go:16, split.go:31),
plus corruption triage beyond the reference: `verify` walks every page of
every chunk and reports each corrupt one with its byte offset, failing stage
and error type; `salvage` copies the readable row groups of a damaged file
into a fresh one (verbatim chunk bytes, rewritten footer); `profile` decodes
the whole file under the span tracer and writes Chrome trace-event JSON
(load it in ui.perfetto.dev or chrome://tracing) plus the per-stage report.

    python -m parquet_tpu.tools.parquet_tool cat file.parquet
    python -m parquet_tpu.tools.parquet_tool head -n 5 file.parquet
    python -m parquet_tpu.tools.parquet_tool meta file.parquet
    python -m parquet_tpu.tools.parquet_tool schema file.parquet
    python -m parquet_tpu.tools.parquet_tool rowcount file.parquet
    python -m parquet_tpu.tools.parquet_tool split -n 100000 src.parquet out_%d.parquet
    python -m parquet_tpu.tools.parquet_tool verify damaged.parquet
    python -m parquet_tpu.tools.parquet_tool salvage damaged.parquet -o saved.parquet
    python -m parquet_tpu.tools.parquet_tool profile file.parquet -o trace.json --metrics
    python -m parquet_tpu.tools.parquet_tool scan 'shard-*.parquet' --batch-size 8192

`scan` drives the streaming dataset layer (parquet_tpu.data) over a glob and
reports end-to-end loader throughput: rows/s, batches, and the wait-time
share (how much of the wall the consumer spent starved for the next unit —
the number prefetch depth tuning moves).

`serve` runs the long-running scan/query daemon (parquet_tpu.serve): POST
/v1/scan streams filtered, projected rows as jsonl or Arrow IPC with
warm-cache planning and admission control; GET /v1/plan dry-runs the same
request; /metrics and /healthz feed scrapers and load balancers.

    python -m parquet_tpu.tools.parquet_tool serve --root /data --port 8080

`debug` is the operator's client for the daemon's flight recorder: list
recent requests (ids, status, duration, queue-wait), fetch one record in
full, or export a sampled/slow/errored request's span tree as
Perfetto-loadable Chrome-trace JSON.

    python -m parquet_tpu.tools.parquet_tool debug http://127.0.0.1:8080 --slow
    python -m parquet_tpu.tools.parquet_tool debug http://127.0.0.1:8080 \
        --id demo --trace -o trace.json
"""

from __future__ import annotations

import argparse
import json
import sys

from ..core.reader import FileReader
from ..core.writer import FileWriter
from ..meta.parquet_types import CompressionCodec, Encoding, Type
from ..schema.dsl import schema_to_string

__all__ = ["main"]


def _json_default(v):
    # THE definition lives in serve/protocol.py (shared so daemon bytes
    # match cat/head bytes); imported lazily per call — only reached for
    # non-JSON-native values — so `parquet-tool cat` never pays the serve
    # package import
    from ..serve.protocol import json_default

    return json_default(v)


def _coerce(raw: str):
    raw = raw.strip()
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "'\"":
        return raw[1:-1]  # quoted: force string ('7' stays "7")
    try:
        return int(raw)
    except ValueError:
        try:
            return float(raw)
        except ValueError:
            return raw


def _split_members(inner: str):
    """Split a set-literal body on commas OUTSIDE quotes, so quoted members
    may themselves contain commas ('a,b' stays one member)."""
    parts = []
    cur = []
    quote = None
    for ch in inner:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            cur.append(ch)
        elif ch == ",":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if quote:
        raise ValueError(f"unterminated quote in set literal {inner!r}")
    if cur or parts:
        parts.append("".join(cur))
    return parts


def _parse_filters(specs):
    """['col >= 10', 'name == x', 'id in (1,2,3)'] -> [(col, op, value)]
    triples; values try int, then float, then stay strings. Set membership
    ('in'/'not_in' with a parenthesized list) rides the full pruning stack,
    including bloom-filter consultation for 'in'. Comparison ops parse
    FIRST so a quoted value containing the word 'in' stays a value."""
    if not specs:
        return None

    def find_outside_quotes(spec: str, token: str) -> int:
        quote = None
        i = 0
        while i < len(spec):
            ch = spec[i]
            if quote:
                if ch == quote:
                    quote = None
            elif ch in "'\"":
                quote = ch
            elif spec.startswith(token, i):
                return i
            i += 1
        return -1

    out = []
    for spec in specs:
        for op in ("==", "!=", "<=", ">=", "<", ">"):
            k = find_outside_quotes(spec, f" {op} ")
            if k >= 0:
                col = spec[:k]
                raw = spec[k + len(op) + 2 :]
                out.append((col.strip(), op, _coerce(raw)))
                break
        else:
            for op in ("not_in", "in"):
                head, sep, tail = spec.partition(f" {op} ")
                if sep and head.strip() and "(" not in head:
                    raw = tail.strip()
                    if not (raw.startswith("(") and raw.endswith(")")):
                        raise ValueError(
                            f"bad --filter {spec!r} ({op} needs a "
                            "parenthesized list: 'col in (1,2,3)')"
                        )
                    inner = raw[1:-1].strip()
                    values = [_coerce(x) for x in _split_members(inner)]
                    out.append((head.strip(), op, values))
                    break
            else:
                raise ValueError(
                    f"bad --filter {spec!r} (expected 'column OP value', "
                    "OP one of == != < <= > >= in not_in)"
                )
    return out


def cmd_cat(args) -> int:
    cols = args.columns.split(",") if args.columns else None
    filters = _parse_filters(args.filter)
    with FileReader(args.file, columns=cols) as r:
        for row in r.iter_rows(raw=args.raw, filters=filters):
            print(json.dumps(row, default=_json_default))
    return 0


def cmd_head(args) -> int:
    n = args.n
    cols = args.columns.split(",") if args.columns else None
    filters = _parse_filters(args.filter)
    with FileReader(args.file, columns=cols) as r:
        for i, row in enumerate(r.iter_rows(raw=args.raw, filters=filters)):
            if i >= n:
                break
            print(json.dumps(row, default=_json_default))
    return 0


def cmd_rowcount(args) -> int:
    with FileReader(args.file) as r:
        print(r.num_rows)
    return 0


def cmd_schema(args) -> int:
    with FileReader(args.file) as r:
        print(schema_to_string(r.schema))
    return 0


def cmd_meta(args) -> int:
    """Flat per-column metadata incl. max R/D levels
    (reference: cmds/readfile.go:110-142 printFlatSchema)."""
    with FileReader(args.file) as r:
        m = r.metadata
        print(f"version: {m.version}")
        print(f"created by: {m.created_by}")
        print(f"rows: {m.num_rows}")
        print(f"row groups: {len(m.row_groups or [])}")
        for kv in m.key_value_metadata or []:
            print(f"kv: {kv.key} = {kv.value}")
        for gi, rg in enumerate(m.row_groups or []):
            print(f"row group {gi}: rows={rg.num_rows} bytes={rg.total_byte_size}")
            for cc in rg.columns or []:
                md = cc.meta_data
                leaf = r.schema.column(tuple(md.path_in_schema))
                try:
                    codec = CompressionCodec(md.codec).name
                except ValueError:
                    codec = str(md.codec)
                encs = ",".join(
                    Encoding(e).name if e in set(Encoding) else str(e)
                    for e in (md.encodings or [])
                )
                stats = ""
                if md.statistics is not None and md.statistics.null_count is not None:
                    stats = f" nulls={md.statistics.null_count}"
                extras = []
                if cc.column_index_offset:
                    extras.append("page-index")
                if md.bloom_filter_offset:
                    extras.append("bloom")
                extra = f" [{','.join(extras)}]" if extras else ""
                print(
                    f"  {'.'.join(md.path_in_schema)}: {Type(md.type).name} "
                    f"maxR={leaf.max_rep} maxD={leaf.max_def} values={md.num_values} "
                    f"codec={codec} encodings=[{encs}]{stats}{extra}"
                )
        # per-column totals across every row group (the same shape the live
        # metrics registry accumulates per encoding during decode)
        from ..utils.metrics import summarize_columns

        for name, s in summarize_columns(m).items():
            ratio = f"{s['ratio']:.2f}x" if s["ratio"] else "n/a"
            print(
                f"column {name}: encodings=[{','.join(s['encodings'])}] "
                f"compressed={s['compressed']:,} B "
                f"uncompressed={s['uncompressed']:,} B ratio={ratio}"
            )
    return 0


def cmd_pages(args) -> int:
    """Per-page layout + statistics from the page index (beyond the
    reference: it has no page-index support)."""
    from ..core.filter import _decode_stat

    with FileReader(args.file) as r:
        any_index = False
        for gi in range(r.num_row_groups):
            num_rows = r.row_group(gi).num_rows or 0
            for path, (ci, oi) in r.read_page_index(gi).items():
                if oi is None or not oi.page_locations:
                    continue
                any_index = True
                name = ".".join(path)
                leaf = r.schema.column(path)
                locs = oi.page_locations
                for k, loc in enumerate(locs):
                    stop = (
                        locs[k + 1].first_row_index if k + 1 < len(locs) else num_rows
                    )
                    line = (
                        f"rg{gi} {name} page {k}: rows [{loc.first_row_index}, "
                        f"{stop}) offset={loc.offset} "
                        f"bytes={loc.compressed_page_size}"
                    )
                    if (
                        ci is not None
                        and ci.min_values is not None
                        and k < len(ci.min_values)
                    ):
                        if ci.null_pages and k < len(ci.null_pages) and ci.null_pages[k]:
                            line += " ALL-NULL"
                        else:
                            # decode PLAIN-packed bounds to typed values
                            # (raw bytes for ints/floats are unreadable)
                            mn = _decode_stat(leaf, ci.min_values[k], legacy=False)
                            mx = _decode_stat(leaf, ci.max_values[k], legacy=False)
                            if isinstance(mn, bytes):
                                mn = _json_default(mn)
                            if isinstance(mx, bytes):
                                mx = _json_default(mx)
                            line += f" min={mn!r} max={mx!r}"
                        if ci.null_counts and k < len(ci.null_counts):
                            line += f" nulls={ci.null_counts[k]}"
                    print(line)
        if not any_index:
            print("(file carries no page index)")
    return 0


def _parse_size(s: str) -> int:
    """'10M', '512K', '1G', or plain bytes; rejects malformed/non-positive."""
    raw = s.strip().upper()
    mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}.get(raw[-1:], 1)
    try:
        n = int(raw[:-1] if mult != 1 else raw) * mult
    except ValueError:
        raise argparse.ArgumentTypeError(f"invalid size {s!r} (use bytes or K/M/G)")
    if n <= 0:
        raise argparse.ArgumentTypeError(f"size must be positive, got {s!r}")
    return n


def cmd_split(args) -> int:
    """Re-shard into parts bounded by rows (-n) or by target file size
    (--size, the reference's unit: cmds/split.go:31-117 rolls to the next
    part once the current file reaches the target)."""
    pattern = args.out
    if "%d" not in pattern:
        print("split: output pattern must contain %d", file=sys.stderr)
        return 2
    if getattr(args, "groups", None) is not None:
        if args.n is not None or args.size is not None:
            print("split: --groups excludes -n/--size", file=sys.stderr)
            return 2
        if args.codec is not None:
            print(
                "split: --groups copies chunk bytes verbatim; --codec has "
                "no effect there (use -n/--size to re-encode)",
                file=sys.stderr,
            )
            return 2
        from ..core.merge import split_row_groups

        parts = split_row_groups(args.file, pattern, args.groups)
        print(f"wrote {len(parts)} parts (row-group copy, no re-encoding)")
        return 0
    if (args.n is None) == (args.size is None):
        print("split: pass exactly one of -n or --size", file=sys.stderr)
        return 2
    target_size = args.size
    with FileReader(args.file) as r:
        schema = r.schema
        codec = args.codec or "snappy"
        part = 0
        rows_in_part = 0
        writer = None
        for row in r.iter_rows(raw=True):
            if writer is None:
                writer = FileWriter(pattern % part, schema, codec=codec)
            writer.write_row(row)
            rows_in_part += 1
            if target_size is None:
                full = rows_in_part >= args.n
            else:
                # flushed bytes + the buffered row group's estimate, so a
                # part rolls over without waiting for an auto-flush; sampled
                # every 64 rows like the writer's own auto-flush throttle
                full = rows_in_part % 64 == 0 and (
                    writer.current_file_size + writer.estimated_buffered_size()
                    >= target_size
                )
            if full:
                writer.close()
                writer = None
                part += 1
                rows_in_part = 0
        if writer is not None:
            writer.close()
    print(f"wrote {part + (1 if rows_in_part else 0)} parts")
    return 0


def cmd_merge(args) -> int:
    """Concatenate files at row-group granularity WITHOUT re-encoding:
    chunk bytes copy verbatim, only footer offsets rewrite (compaction —
    the parquet-mr `parquet-tools merge` primitive; beyond the reference).
    Schemas must match exactly; page indexes/blooms are not carried.
    The output goes through the atomic ByteSink (tmp+rename): an
    interrupted merge never leaves a torn output file.

    Canonical form matches parquet-mr's argument order (inputs first):
        merge <inputs...> -o <output>
    The legacy output-first positional form (`merge <output> <inputs...>`)
    still parses, with a deprecation note on stderr. BOTH forms now refuse
    to overwrite an existing output unless --force is given — legacy
    invocations that relied on silent overwrite must add --force."""
    import os

    from ..core.merge import merge_files

    inputs = list(args.files)
    out = args.out
    if out is None:
        if len(inputs) < 2:
            raise ValueError("merge: need -o/--out OUTPUT and at least one input")
        out, inputs = inputs[0], inputs[1:]
        print(
            "parquet-tool: merge with a positional output is deprecated; "
            "use 'merge <inputs...> -o <output>' (note: overwriting an "
            "existing output now requires --force in both forms)",
            file=sys.stderr,
        )
    is_url = out.startswith(("http://", "https://"))
    if not is_url and os.path.exists(out) and not args.force:
        # URL outputs skip the existence probe: multipart commit replaces
        # the object atomically (last-commit-wins, like --force locally),
        # and a HEAD here would need credentials the sink already owns
        raise ValueError(
            f"merge: output {out!r} already exists (pass --force to overwrite)"
        )
    meta = merge_files(out, inputs)
    print(
        f"merged {len(inputs)} files -> {out}: "
        f"{meta.num_rows} rows, {len(meta.row_groups or [])} row groups"
    )
    return 0


def cmd_lake(args) -> int:
    """Operate on a lake table from the shell — the offline twins of the
    daemon's ingest/compaction loop, all through the same manifest commit
    protocol (a shell append and a daemon append are indistinguishable in
    the generation log):

        lake init     create the table (schema DSL + optional sort key)
        lake append   buffer rows from a jsonl file (or stdin) and commit
                      them as ONE generation
        lake compact  run one compaction pass (and optionally reap
                      crash-orphaned files)
        lake manifest print the snapshot a scan of this table would pin
                      (--gen N time-travels; --json for machines)
    """
    from ..lake import Compactor, IngestWriter, LakeError, LakeTable
    from ..lake.ingest import rows_from_payload

    try:
        if args.lake_cmd == "init":
            table = LakeTable.create(
                args.table,
                args.schema,
                sort_key=args.sort_key,
                retain=args.retain,
            )
            print(
                f"lake: created {table.root} "
                f"(sort_key={table.sort_key or '-'}, retain={args.retain})"
            )
            return 0
        table = LakeTable.open(args.table)
        if args.lake_cmd == "append":
            if args.file == "-":
                body = sys.stdin.buffer.read()
            else:
                with open(args.file, "rb") as f:
                    body = f.read()
            rows = rows_from_payload(body, "application/x-ndjson")
            if not rows:
                raise LakeError("lake: no rows in input", code="bad_payload")
            writer = IngestWriter(table)
            try:
                ack = writer.append(rows, flush=True)
            finally:
                writer.close()
            print(json.dumps(ack, sort_keys=True))
            return 0
        if args.lake_cmd == "compact":
            compactor = Compactor(
                table,
                min_files=args.min_files,
                max_files=args.max_files,
                small_file_bytes=args.small_file_mb << 20,
            )
            result = compactor.compact_once()
            if args.reap:
                reaped = table.manifest.reap_orphans(
                    grace_s=args.reap_grace_s
                )
                if reaped:
                    print(f"lake: reaped {reaped} orphan file(s)")
            if result is None:
                print("lake: nothing to compact")
                return 0
            print(json.dumps(result.to_dict(), sort_keys=True))
            return 0
        # manifest: the snapshot view (current or pinned)
        snap = table.manifest.open_snapshot(args.gen)
        if args.json:
            doc = snap.to_dict()
            doc["retained"] = table.manifest.generations()
            print(json.dumps(doc, sort_keys=True))
            return 0
        gens = table.manifest.generations()
        span = f"[{gens[0]}..{gens[-1]}]" if gens else "[]"
        print(f"table: {table.root} (sort_key={table.sort_key or '-'})")
        print(f"generation: {snap.generation} (retained {span})")
        print(
            f"files: {len(snap.files)}  rows: {snap.total_rows}  "
            f"bytes: {snap.total_bytes}"
        )
        for entry in snap.files:
            key = (
                f"  key=[{entry.min_key!r}..{entry.max_key!r}]"
                if entry.min_key is not None
                else ""
            )
            print(
                f"  {entry.path}  rows={entry.rows} bytes={entry.bytes}{key}"
            )
        return 0
    except LakeError as e:
        print(f"parquet-tool: lake: {e}", file=sys.stderr)
        return 1


def verify_file(path, validate_crc: bool = True) -> list[dict]:
    """Scan every page of every column chunk; return one report dict per
    problem found: {group, column, page, offset, stage, error, message}.

    Stages mirror the decode ladder (PTQ_STAGE_* taxonomy of the native
    walk): "footer" (metadata unreadable), "header" (Thrift page header),
    "crc" (stored checksum mismatch), "decompress" (codec-level), "decode"
    (levels/values), "layout" (page sizes exceed the chunk), "chunk"
    (cross-page invariants: value counts vs metadata). A header/layout
    failure ends that chunk's walk — subsequent page boundaries are
    unknowable — but every other stage continues to the next page, so one
    rotten page does not hide its neighbors; data pages that fail ONLY
    because an earlier dictionary page failed are not re-reported (one
    rotten dict page is one problem, not hundreds of phantom ones)."""
    from ..core.chunk import _check_crc, chunk_byte_range, iter_page_sites
    from ..core.compress import decompress_block
    from ..core.page import (
        decode_data_page_v1,
        decode_data_page_v2,
        decode_dict_page,
    )
    from ..core.reader import PARQUET_ERRORS, FileReader
    from ..meta.parquet_types import PageType

    problems: list[dict] = []

    def report(gi, col, page, offset, stage, err, note=None):
        problems.append(
            {
                "group": gi,
                "column": col,
                "page": page,
                "offset": offset,
                "stage": stage,
                "error": type(err).__name__ if err is not None else "ChunkError",
                "message": note if note is not None else str(err),
            }
        )

    try:
        reader = FileReader(path)
    except PARQUET_ERRORS as e:
        return [
            {
                "group": -1,
                "column": "",
                "page": -1,
                "offset": -1,
                "stage": "footer",
                "error": type(e).__name__,
                "message": str(e),
            }
        ]
    with reader as r:
        f = r._f
        for gi in range(r.num_row_groups):
            for tpath, cc, col in r._selected_chunks(gi):
                name = ".".join(tpath)
                md = cc.meta_data
                codec = md.codec or 0
                try:
                    offset, total = chunk_byte_range(cc)
                except PARQUET_ERRORS as e:
                    report(gi, name, -1, -1, "layout", e)
                    continue
                sites = iter_page_sites(f, cc)
                next_pos = offset
                page_idx = 0
                dictionary = None
                dict_failed = False
                seen_values = 0
                walk_complete = False
                while True:
                    try:
                        pos, header, hlen, plen = next(sites)
                    except StopIteration:
                        walk_complete = True
                        break
                    except PARQUET_ERRORS as e:
                        report(
                            gi, name, page_idx, next_pos,
                            getattr(e, "stage", "header"), e,
                        )
                        break  # page boundaries unknowable past this point
                    next_pos = pos + hlen + plen
                    f.seek(pos + hlen)
                    payload = bytes(f.read(plen))
                    if len(payload) != plen:
                        report(
                            gi, name, page_idx, pos, "layout", None,
                            "truncated page payload",
                        )
                        break
                    pt = header.type
                    failed = False
                    if validate_crc and header.crc is not None:
                        try:
                            _check_crc(header, payload)
                        except PARQUET_ERRORS as e:
                            report(gi, name, page_idx, pos, "crc", e)
                            failed = True
                    if not failed:
                        dict_size = (
                            len(dictionary) if dictionary is not None else None
                        )
                        try:
                            if pt == int(PageType.DICTIONARY_PAGE):
                                block = decompress_block(
                                    payload, codec,
                                    header.uncompressed_page_size or 0,
                                )
                                dictionary = decode_dict_page(header, block, col)
                            elif pt == int(PageType.DATA_PAGE):
                                block = decompress_block(
                                    payload, codec,
                                    header.uncompressed_page_size or 0,
                                )
                                page = decode_data_page_v1(
                                    header, block, col, dict_size
                                )
                                page.materialize(dictionary)
                                seen_values += page.num_values
                            elif pt == int(PageType.DATA_PAGE_V2):
                                page = decode_data_page_v2(
                                    header, payload, col, dict_size, codec
                                )
                                page.materialize(dictionary)
                                seen_values += page.num_values
                            # INDEX_PAGE and unknown types: skipped, like read
                        except PARQUET_ERRORS as e:
                            # a data page failing ONLY for want of the (already
                            # reported) broken dictionary is a dependent
                            # failure, not independent corruption
                            from ..core.page import MissingDictionaryError

                            dependent = dict_failed and isinstance(
                                e, MissingDictionaryError
                            )
                            if not dependent:
                                from ..core.compress import CompressionError

                                stage = (
                                    "decompress"
                                    if isinstance(e, CompressionError)
                                    else "decode"
                                )
                                report(gi, name, page_idx, pos, stage, e)
                            failed = True
                    if failed and pt == int(PageType.DICTIONARY_PAGE):
                        dict_failed = True
                    page_idx += 1
                if walk_complete:
                    expected = md.num_values or 0
                    if seen_values != expected and not any(
                        p["group"] == gi and p["column"] == name
                        for p in problems
                    ):
                        report(
                            gi, name, -1, offset, "chunk", None,
                            f"pages hold {seen_values} values, "
                            f"metadata says {expected}",
                        )
    return problems


def cmd_verify(args) -> int:
    problems = verify_file(args.file, validate_crc=not args.no_crc)
    for p in problems:
        where = (
            "footer"
            if p["stage"] == "footer"
            else f"rg{p['group']} {p['column']} page {p['page']} @byte {p['offset']}"
        )
        print(f"{where}: stage={p['stage']} {p['error']}: {p['message']}")
    if problems:
        groups = {p["group"] for p in problems}
        print(
            f"CORRUPT: {len(problems)} problem(s) in "
            f"{len(groups)} row group(s)"
        )
        return 1
    print("OK: every page decodes cleanly")
    return 0


def cmd_salvage(args) -> int:
    """Copy the readable row groups of a damaged file into a fresh one.

    A group is readable when EVERY selected column chunk decodes end to end
    (CRCs verified when present). Readable groups copy verbatim — chunk
    bytes untouched, footer offsets rewritten — via the merge/split
    machinery, so salvage never re-encodes surviving data."""
    import os

    from ..core.merge import _copy_groups
    from ..core.reader import PARQUET_ERRORS, FileReader

    out = args.out
    if os.path.exists(out) and not args.force:
        raise ValueError(
            f"salvage: output {out!r} already exists (pass --force to overwrite)"
        )
    good: list[int] = []
    bad: list[tuple[int, str]] = []
    rows_good = rows_total = 0
    with FileReader(args.file, validate_crc=not args.no_crc) as r:
        meta = r.metadata
        for gi in range(r.num_row_groups):
            rows = r.row_group(gi).num_rows or 0
            rows_total += rows
            try:
                r._read_row_group(gi, None, pack=False)
            except PARQUET_ERRORS as e:
                bad.append((gi, f"{type(e).__name__}: {e}"))
                continue
            good.append(gi)
            rows_good += rows
    _copy_groups(out, args.file, meta, good, "parquet_tpu salvage")
    for gi, why in bad:
        print(f"dropped rg{gi}: {why}", file=sys.stderr)
    print(
        f"salvaged {len(good)}/{len(good) + len(bad)} row groups "
        f"({rows_good}/{rows_total} rows) -> {out}"
    )
    return 0


def cmd_profile(args) -> int:
    """Decode the whole file under the span tracer; write the hierarchical
    spans (file → row-group → chunk → page → stage, native prepare
    sub-clocks included) as Chrome trace-event JSON and print the per-stage
    report, hottest stages first.

    The default path is the device-decode pipeline (backend="tpu_roundtrip"
    — the parity oracle), which exercises the prepare pool's worker lanes,
    the fused native walk's internal clocks, and the dispatch thread.
    --host profiles the pure host decode instead (no jax touched);
    --cpu forces jax onto the CPU platform first (profiling decode on a
    machine whose accelerator tunnel should stay untouched); --rows
    profiles an ASSEMBLED read (iter_rows) instead of the column decode —
    the assemble / assembly.rows stages then show where record assembly
    spends its time, and the metrics delta carries
    assembly_rows_total{engine=} / assembly_seconds.

    --live <url> profiles a RUNNING daemon instead of a file: it fetches
    GET /v1/debug/profile (the continuous sampling profiler, lane-
    attributed to the named pqt-* pools) for --seconds and prints the
    collapsed flamegraph text (or the --top self-time table); -o writes
    the text for flamegraph.pl / speedscope."""
    from ..utils import metrics
    from ..utils.trace import decode_trace, span

    import os

    if args.live:
        # flags that shape the FILE decode have no meaning against a
        # remote daemon — refuse rather than silently drop them
        ignored = [
            name
            for name, v in (
                ("--columns", args.columns),
                ("--rows", args.rows),
                ("--write", args.write),
                ("--host", args.host),
                ("--cpu", args.cpu),
                ("--metrics", args.metrics),
                ("--device", args.device),
                ("--filter", args.filter),
            )
            if v
        ]
        if ignored or args.file:
            what = ", ".join(ignored + (["FILE"] if args.file else []))
            print(
                f"profile: {what} applies to file mode, not --live",
                file=sys.stderr,
            )
            return 2
        return _profile_live(args)
    if args.top or args.seconds != 2.0 or args.interval_ms != 10.0:
        print(
            "profile: --top/--seconds/--interval-ms apply to --live mode "
            "only",
            file=sys.stderr,
        )
        return 2
    if not args.file or not args.out:
        print(
            "profile: FILE and -o are required (or use --live URL)",
            file=sys.stderr,
        )
        return 2
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.write and args.rows:
        print("profile: --write and --rows are mutually exclusive", file=sys.stderr)
        return 2
    if args.filter and not args.device:
        print("profile: --filter applies to --device mode", file=sys.stderr)
        return 2
    if args.device:
        if args.host or args.rows or args.write:
            print(
                "profile: --device is exclusive with --host/--rows/--write",
                file=sys.stderr,
            )
            return 2
        return _profile_device_query(args)
    backend = "host" if (args.host or args.rows or args.write) else "tpu_roundtrip"
    cols = args.columns.split(",") if args.columns else None
    snap0 = metrics.snapshot()
    with FileReader(args.file, columns=cols, backend=backend) as r:
        rows = r.num_rows
        if args.write:
            # profile the ENCODE: decode rows OUTSIDE the trace window, then
            # re-encode them (same schema, same codec) to a memory sink —
            # the trace carries only write.encode and its encode.* sub-clocks
            # plus the encode_fused_* ladder counters
            from ..core.writer import FileWriter
            from ..meta.parquet_types import CompressionCodec
            from ..sink.sink import MemorySink

            all_rows = list(r.iter_rows())
            md0 = r.metadata.row_groups[0].columns[0].meta_data if (
                r.metadata.row_groups
            ) else None
            codec = CompressionCodec(md0.codec) if md0 is not None else (
                CompressionCodec.UNCOMPRESSED
            )
            snap0 = metrics.snapshot()  # exclude the decode from the delta
            with decode_trace() as t:
                with span(
                    "file", {"path": str(args.file), "mode": "write-encode"}
                ):
                    w = FileWriter(MemorySink(), r.schema, codec=codec)
                    for row in all_rows:
                        w.write_row(row)
                    w.close()
        else:
            with decode_trace() as t:
                with span("file", {"path": str(args.file), "backend": backend}):
                    if args.rows:
                        for _row in r.iter_rows():
                            pass
                    else:
                        for i in range(r.num_row_groups):
                            r.read_row_group(i)
    doc = t.to_chrome_trace()
    # computed once: the registry is live process state, so a re-read could
    # disagree with what the file artifact recorded
    mdelta = metrics.delta(snap0)
    doc["otherData"]["metrics_delta"] = mdelta
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print(t.report())
    print()
    mode = "write-encode" if args.write else f"backend={backend}"
    print(
        f"profile: {rows:,} rows via {mode}, "
        f"{len(doc['traceEvents'])} trace events -> {args.out} "
        "(load in ui.perfetto.dev or chrome://tracing)"
    )
    if args.write:
        engaged = mdelta.get('events_total{event="encode_fused_engaged"}', 0)
        declined = mdelta.get('events_total{event="encode_fused_declined"}', 0)
        written = mdelta.get("sink_bytes_written_total", 0)
        print(
            f"profile: encode ladder fused={engaged} staged={declined}, "
            f"{written:,} B written"
        )
    else:
        # projection efficiency: the planner fetches only the projected
        # chunks' exact byte ranges, so bytes-read vs bytes-in-file shows
        # what a columns= projection actually saves at the source
        bytes_read = mdelta.get("io_bytes_read_total", 0)
        fsize = os.path.getsize(args.file)
        print(
            f"profile: io {bytes_read:,} B read / {fsize:,} B in file "
            f"({bytes_read / fsize:.1%} of file bytes)"
            if fsize
            else f"profile: io {bytes_read:,} B read"
        )
    if args.metrics:
        print()
        print("metrics delta (this profile run):")
        for k, v in sorted(mdelta.items()):
            print(f"  {k} = {v}")
        print()
        print(metrics.report())
    return 0


def _profile_device_query(args) -> int:
    """The `profile --device` body: the device QUERY path under the span
    tracer — filtered device batches (query.mask / query.take lanes) and a
    per-row-group device partial aggregate (query.aggregate lane). The
    trace shows where the predicate -> mask -> gather -> reduce pipeline
    spends its wall time; on CPU jax the lanes are real but the ratios are
    not accelerator-representative."""
    from ..core.filter_vec import VecFilterError
    from ..serve.protocol import parse_query_request
    from ..serve.query_device import DeviceQueryError, device_unit_partial
    from ..utils import metrics
    from ..utils.trace import decode_trace, span

    import numpy as np

    with FileReader(args.file) as r:
        numeric = next(
            (
                leaf
                for leaf in r.schema.leaves
                if leaf.max_rep == 0
                and leaf.type in (Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE)
            ),
            None,
        )
        if args.filter:
            filt = json.loads(args.filter)
        elif numeric is not None:
            # midpoint of the first group: a predicate that actually splits
            # rows, so the mask/take lanes carry real work
            chunk = r.read_row_group(0, [numeric.path_str]).get(numeric.path)
            vals = np.asarray(chunk.values) if chunk is not None else None
            if vals is not None and len(vals):
                filt = [[[numeric.path_str, ">=", float(np.median(vals))]]]
            else:
                filt = [[[numeric.path_str, "not_null"]]]
        else:
            print(
                "profile: --device needs a numeric column or --filter",
                file=sys.stderr,
            )
            return 2
        aggs = ["count"]
        if numeric is not None and numeric.type in (Type.INT32, Type.INT64):
            aggs.append({"op": "sum", "column": numeric.path_str})
        q = parse_query_request(
            json.dumps(
                {"paths": [args.file], "aggregates": aggs, "filters": filt}
            ).encode()
        )
        cols = args.columns.split(",") if args.columns else None
        snap0 = metrics.snapshot()
        scanned = matched = kept = 0
        agg_engine = "device"
        with decode_trace() as t:
            with span("file", {"path": str(args.file), "mode": "device-query"}):
                try:
                    for i in range(r.num_row_groups):
                        _part, n_scan, n_match = device_unit_partial(
                            r, i, q, filt
                        )
                        scanned += n_scan
                        matched += n_match
                except DeviceQueryError:
                    agg_engine = "host (device declined)"
                try:
                    for b in r.iter_device_batches(
                        1 << 15,
                        columns=cols,
                        drop_remainder=False,
                        filters=filt,
                        filter_rows=True,
                    ):
                        first = next(iter(b.values()))
                        kept += int(first.shape[0])
                except VecFilterError as e:
                    print(f"profile: filter declined by every engine: {e}")
    doc = t.to_chrome_trace()
    mdelta = metrics.delta(snap0)
    doc["otherData"]["metrics_delta"] = mdelta
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print(t.report())
    print()
    print(
        f"profile: device query over {scanned:,} rows -> {matched:,} matched "
        f"(aggregate lane: {agg_engine}), {kept:,} rows compacted into "
        f"filtered batches, {len(doc['traceEvents'])} trace events -> "
        f"{args.out} (load in ui.perfetto.dev or chrome://tracing)"
    )
    engaged = mdelta.get('events_total{event="device_filter_engaged"}', 0)
    declined = mdelta.get('events_total{event="device_filter_declined"}', 0)
    print(f"profile: mask engine device={engaged} host_fallback={declined}")
    if args.metrics:
        print()
        print("metrics delta (this profile run):")
        for k, v in sorted(mdelta.items()):
            print(f"  {k} = {v}")
        print()
        print(metrics.report())
    return 0


def _profile_live(args) -> int:
    """The `profile --live <url>` body: one /v1/debug/profile window."""
    import urllib.error
    import urllib.request

    base = args.live.rstrip("/")
    if not base.startswith(("http://", "https://")):
        base = "http://" + base
    fmt = "top" if args.top else "collapsed"
    url = (
        f"{base}/v1/debug/profile?seconds={args.seconds:g}"
        f"&interval_ms={args.interval_ms:g}&format={fmt}"
    )
    try:
        with urllib.request.urlopen(url, timeout=args.seconds + 30) as resp:
            text = resp.read().decode()
    except urllib.error.HTTPError as e:
        try:
            err = json.loads(e.read()).get("error", {})
            msg = f"{err.get('code', e.code)}: {err.get('message', '')}"
        except ValueError:
            msg = f"HTTP {e.code}"
        print(f"profile: {msg}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"profile: cannot reach {base}: {e}", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        n = len(text.splitlines())
        print(
            f"profile: wrote {n} {fmt} lines to {args.out}"
            + (
                " (feed to flamegraph.pl / speedscope)"
                if fmt == "collapsed"
                else ""
            )
        )
    else:
        sys.stdout.write(text)
    return 0


def cmd_scan(args) -> int:
    """Stream a glob through ParquetDataset and report loader throughput.

    The consumer is a minimal touch of every batch (shape check only), so
    the headline is the LOADER's rows/s — decode + rebatch + delivery —
    and the wait share shows whether prefetch is keeping up: near 0% the
    consumer never starves, near 100% the loop is decode-bound (raise
    --prefetch, add workers, or shard wider)."""
    import os
    import time

    from ..data import ParquetDataset
    from ..utils import metrics

    cols = args.columns.split(",") if args.columns else None
    if args.filters and args.filter:
        raise ValueError(
            "use either --filter (repeatable 'col OP value') or --filters "
            "(one JSON spec), not both"
        )
    if args.filters:
        # the same spec language POST /v1/scan accepts, via the same parser
        from ..serve.protocol import filters_from_spec

        try:
            spec = json.loads(args.filters)
        except ValueError as e:
            raise ValueError(f"--filters is not valid JSON: {e}") from None
        filters = filters_from_spec(spec)
    else:
        filters = _parse_filters(args.filter)
    if args.aggregate:
        return _scan_aggregate(args, filters)
    ds = ParquetDataset(
        args.glob,
        batch_size=args.batch_size,
        columns=cols,
        filters=filters,
        shuffle=args.shuffle,
        seed=args.seed,
        num_epochs=args.epochs,
        prefetch=args.prefetch,
        remainder="keep",
        on_error=args.on_error,
        nullable=args.nullable,
        cache_bytes=args.cache_mb << 20,
        cache_disk_bytes=args.cache_disk_mb << 20,
        cache_dir=args.cache_dir,
        io_autotune=args.io_autotune,
        # --slo-ms doubles as the controller opt-in: the gate measures the
        # ADAPTIVE pipeline, the same thing production would run
        slo_wait_ms=args.slo_ms,
    )
    plan = ds.plan
    for path, why in plan.skipped_files:
        print(f"scan: skipped {path}: {why}", file=sys.stderr)
    print(
        f"scan: {len(plan.files)} files, {plan.num_units} units, "
        f"{plan.total_rows:,} rows planned (shard "
        f"{ds.shard_index}/{ds.shard_count}, prefetch {ds.prefetch})"
    )
    if filters is not None:
        ps = plan.pruning_summary()
        print(
            f"scan: pruning {ps['units_admitted']}/{ps['units_total']} row "
            f"groups admitted ({ps['units_pruned_stats']} pruned by stats, "
            f"{ps['units_pruned_bloom']} by bloom)"
        )
    snap0 = metrics.snapshot()
    rows = batches = 0
    waits = []  # per-batch next() wall: the --slo-ms gate's percentiles
    t0 = time.perf_counter()
    with ds:
        it = iter(ds)
        while True:
            tb = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            waits.append(time.perf_counter() - tb)
            first = next(iter(batch.values()))
            rows += int(first.shape[0])
            batches += 1
        # snapshot BEFORE close(): an owned tiered cache tears down (and
        # zeroes its stats) when the dataset does
        cache_stats = (
            ds._block_cache.stats() if ds._block_cache is not None else None
        )
    wall = time.perf_counter() - t0
    d = metrics.delta(snap0)
    wait = d.get("dataset_wait_seconds_sum", 0.0)
    skipped = d.get('events_total{event="dataset_units_skipped"}', 0)
    share = wait / wall if wall > 0 else 0.0
    print(
        f"scan: {rows:,} rows in {batches} batches over {wall:.3f}s "
        f"= {rows / wall:,.0f} rows/s"
    )
    print(
        f"scan: wait {wait:.3f}s ({share:.1%} of wall)"
        + (f", {skipped} unit(s) skipped" if skipped else "")
    )
    # projection efficiency + cache effect: what the io layer actually
    # fetched vs what lives on disk, and how much of it came from memory
    bytes_read = d.get("io_bytes_read_total", 0)
    file_bytes = sum(
        os.path.getsize(p) for p in plan.files if os.path.exists(p)
    )
    hits = d.get("io_cache_hits_total", 0)
    misses = d.get("io_cache_misses_total", 0)
    hit_rate = hits / (hits + misses) if (hits + misses) else None
    io_line = f"scan: io {bytes_read:,} B read"
    if file_bytes:
        io_line += (
            f" / {file_bytes:,} B in files "
            f"({bytes_read / file_bytes:.1%} of file bytes)"
        )
    if hit_rate is not None:
        io_line += f", cache hit rate {hit_rate:.1%}"
    print(io_line)
    if cache_stats and "disk" in cache_stats:
        spills = d.get("cache_tier_spills_total", 0)
        print(
            f"scan: cache tiers ram {cache_stats['ram']['bytes']:,} B "
            f"({cache_stats['ram']['blocks']} blocks) / disk "
            f"{cache_stats['disk']['bytes']:,} B "
            f"({cache_stats['disk']['blocks']} blocks, "
            f"{cache_stats['disk']['segments']} segments, {spills} spills)"
        )
    slo = None
    if args.slo_ms is not None:
        from ..testing.chaos import percentile

        p50 = (percentile(waits, 0.50) or 0.0) * 1e3
        p99 = (percentile(waits, 0.99) or 0.0) * 1e3
        slo = {
            "slo_ms": args.slo_ms,
            "p50_wait_ms": round(p50, 3),
            "p99_wait_ms": round(p99, 3),
            "held": p99 <= args.slo_ms,
        }
    if args.json:
        print(
            json.dumps(
                {
                    "files": len(plan.files),
                    "units": plan.num_units,
                    "rows": rows,
                    "batches": batches,
                    "wall_s": round(wall, 5),
                    "rows_s": round(rows / wall, 1) if wall > 0 else None,
                    "wait_s": round(wait, 5),
                    "wait_share": round(share, 4),
                    "units_skipped": skipped,
                    "prefetch": ds.prefetch,
                    "io_bytes_read": bytes_read,
                    "file_bytes": file_bytes,
                    "io_cache_hit_rate": (
                        round(hit_rate, 4) if hit_rate is not None else None
                    ),
                    "pruning": plan.pruning_summary(),
                    **({"slo": slo} if slo is not None else {}),
                }
            )
        )
    if slo is not None:
        # the CI gate: ONE line either way, non-zero exit on a violation
        verdict = "held" if slo["held"] else "VIOLATED"
        print(
            f"scan: slo {verdict}: p99 wait {slo['p99_wait_ms']:.2f} ms "
            f"(p50 {slo['p50_wait_ms']:.2f} ms) vs slo {args.slo_ms:.2f} ms "
            f"over {batches} batches"
        )
        if not slo["held"]:
            return 1
    return 0


def _scan_aggregate(args, filters) -> int:
    """`scan --aggregate`: aggregation push-down over the glob, printing
    the CANONICAL query body — the exact bytes POST /v1/query would return
    for the same corpus and spec (serve/aggregate.py owns both)."""
    from ..serve.aggregate import render_query_body, run_local_query
    from ..serve.protocol import (
        DEFAULT_MAX_GROUPS,
        MAX_MAX_GROUPS,
        QueryRequest,
        ServeError,
        aggregates_from_spec,
    )

    try:
        spec = json.loads(args.aggregate)
    except ValueError as e:
        raise ValueError(f"--aggregate is not valid JSON: {e}") from None
    max_groups = (
        args.max_groups if args.max_groups is not None else DEFAULT_MAX_GROUPS
    )
    if not 1 <= max_groups <= MAX_MAX_GROUPS:
        # the same bound the daemon's request parser enforces with a 400
        raise ValueError(
            f"--max-groups must be in [1, {MAX_MAX_GROUPS}], got {max_groups}"
        )
    try:
        aggs = aggregates_from_spec(spec)
        query = QueryRequest(
            paths=[args.glob],
            filters=filters,
            aggregates=aggs,
            group_by=tuple(
                c for c in (args.group_by or "").split(",") if c
            ),
            max_groups=max_groups,
            shard=None,
            timeout_ms=None,
        )
        body = run_local_query(query.paths, query)
    except ServeError as e:
        # same typed-message discipline as the daemon, CLI-rendered
        raise ValueError(f"{e.code}: {e.message}") from None
    sys.stdout.write(render_query_body(body).decode())
    return 0


def cmd_serve(args) -> int:
    """Run the scan/query daemon (parquet_tpu.serve) in the foreground.

    SIGTERM/SIGINT drain gracefully: in-flight requests complete, new ones
    get typed 503s, then the listener stops."""
    from ..obs.log import configure_logging
    from ..serve import ScanServer, ServeConfig
    from ..serve.protocol import _parse_shard

    # the daemon is the one place the LIBRARY's silent-by-default logging
    # opts in: structured JSON lines on stderr, request ids injected
    configure_logging()
    remote_map = {}
    for spec in args.remote_map or ():
        prefix, sep, url = spec.partition("=")
        if not sep or not prefix or not url.startswith(("http://", "https://")):
            print(
                f"error: --remote-map {spec!r}: expected PREFIX=http(s)://...",
                file=sys.stderr,
            )
            return 2
        remote_map[prefix] = url
    mesh = getattr(args, "mesh", False)
    # the knobs both roles share: admission, deadlines, obs, SLO
    common = dict(
        host=args.host,
        port=args.port,
        max_inflight=args.max_inflight,
        tenant_concurrent=args.tenant_concurrent,
        tenant_budget_mb=args.tenant_budget_mb,
        budget_window_s=args.budget_window_s,
        default_timeout_s=(None if args.timeout_s == 0 else args.timeout_s),
        max_timeout_s=args.max_timeout_s,
        brownout_wait_ms=args.brownout_wait_ms,
        brownout_depth=args.brownout_depth,
        socket_timeout_s=args.socket_timeout_s,
        slo_availability=args.slo_availability,
        slo_p99_ms=args.slo_p99_ms,
        # obs flags default to None so ObsConfig (via ServeConfig) stays
        # the single owner of the numbers
        **{
            k: v
            for k, v in {
                "trace_sample_rate": args.trace_sample_rate,
                "slow_ms": args.slow_ms,
                "debug_ring_size": args.debug_ring,
                "debug_max_traces": args.debug_max_traces,
            }.items()
            if v is not None
        },
    )
    if mesh:
        from ..serve.mesh import MeshConfig, MeshRouter

        if not args.replica:
            print(
                "error: mesh mode needs at least one --replica URL",
                file=sys.stderr,
            )
            return 2
        for val, name in (
            (args.root, "--root"),
            (args.shard, "--shard"),
            (remote_map, "--remote-map"),
            (args.lake, "--lake"),
        ):
            if val:
                print(
                    f"error: {name} belongs on the replica daemons, not "
                    "the router (the router owns no corpus)",
                    file=sys.stderr,
                )
                return 2
        config = MeshConfig(
            replicas=tuple(args.replica),
            vnodes=args.vnodes,
            scatter=not args.no_scatter,
            scatter_window=args.scatter_window,
            backend_timeout_s=args.backend_timeout_s,
            hedge=not args.no_hedge,
            breaker_failures=args.breaker_failures,
            breaker_open_s=args.breaker_open_s,
            **common,
        )
        server = MeshRouter(config, verbose=args.verbose)
    else:
        config = ServeConfig(
            root=args.root,
            remote_map=remote_map or None,
            cache_mb=args.cache_mb,
            cache_disk_mb=args.cache_disk_mb,
            cache_dir=args.cache_dir,
            io_autotune=args.io_autotune,
            window=args.window,
            shard=_parse_shard(args.shard),
            lake_root=args.lake,
            lake_schema=args.lake_schema,
            lake_sort_key=args.lake_sort_key,
            lake_flush_mb=args.lake_flush_mb,
            **common,
        )
        server = ScanServer(config, verbose=args.verbose)
    server.install_signal_handlers()
    # the exact line tests/scripts parse for the ephemeral --port 0 case
    print(f"serve: listening on {server.url}", flush=True)
    if mesh:
        print(
            f"serve: mesh router over {len(config.replicas)} replica(s)",
            flush=True,
        )
    elif server.config.root:
        print(f"serve: root {server.config.root}", flush=True)
    if not mesh and server.config.lake_root:
        print(f"serve: lake {server.config.lake_root}", flush=True)
    try:
        server.serve_forever()
    finally:
        server.close()
    print("serve: drained, bye", flush=True)
    return 0


def _debug_fetch(url: str):
    """GET one debug endpoint; returns (status, parsed JSON). Typed error
    bodies come back as JSON too — the caller renders, never a traceback."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except ValueError:
            return e.code, {"error": {"code": "bad_response",
                                      "message": f"HTTP {e.code}"}}


def cmd_debug(args) -> int:
    """Query a running daemon's flight recorder (/v1/debug/requests).

    Without --id: list recent requests (newest first; --slow filters to
    the ones at/over the daemon's slow_ms). With --id: one record in full.
    With --id + --trace: the Perfetto-loadable Chrome-trace JSON, written
    to -o (or stdout) for ui.perfetto.dev / chrome://tracing. --vars
    snapshots the daemon's configuration (/v1/debug/vars); --tenants
    prints the per-tenant cost table (/v1/debug/tenants); --fleet scrapes
    every listed replica's /metrics and prints ONE merged exposition
    (counters summed, histogram buckets added, gauges kept per replica)."""
    if args.fleet:
        from ..obs import fleet as _fleet

        urls = [_fleet.normalize_peer(p) for p in args.fleet]
        view = _fleet.federate(urls)  # ValueError -> main()'s exit-1 path
        print(
            f"# fleet: merged {len(view['replicas'])} replica(s): "
            + ", ".join(view["replicas"])
        )
        for replica, why in sorted(view["errors"].items()):
            print(f"# fleet: {replica} failed: {why}")
        sys.stdout.write(view["text"])
        return 1 if view["errors"] else 0
    if not args.url:
        raise ValueError("debug: a daemon URL (or --fleet URL...) is required")
    base = args.url.rstrip("/")
    if not base.startswith(("http://", "https://")):
        base = "http://" + base
    if args.trace and not args.id:
        raise ValueError("debug: --trace requires --id REQUEST_ID")
    if args.vars:
        status, body = _debug_fetch(f"{base}/v1/debug/vars")
        if status != 200:
            err = body.get("error", {})
            print(
                f"debug: {err.get('code', status)}: {err.get('message', '')}",
                file=sys.stderr,
            )
            return 1
        print(json.dumps(body, indent=2))
        return 0
    if args.tenants:
        status, body = _debug_fetch(f"{base}/v1/debug/tenants")
        if status != 200:
            err = body.get("error", {})
            print(
                f"debug: {err.get('code', status)}: {err.get('message', '')}",
                file=sys.stderr,
            )
            return 1
        rows = body.get("tenants", [])
        if not rows:
            print("debug: no tenant usage recorded")
            return 0
        print(
            f"{'TENANT':<18} {'CPU_S':>9} {'DECODED_B':>13} {'SOURCE_B':>12} "
            f"{'PAYLOAD_B':>12} {'HIT':>6} {'MISS':>6} {'REQS':>6} {'UNITS':>6}"
        )
        for r in rows:
            print(
                f"{r['tenant']:<18} {r['cpu_seconds']:>9.3f} "
                f"{r['decoded_bytes']:>13,} {r['source_bytes']:>12,} "
                f"{r['payload_bytes']:>12,} {r['cache_hits']:>6} "
                f"{r['cache_misses']:>6} {r['requests']:>6} {r['units']:>6}"
            )
        t = body.get("totals")
        if t:
            print(
                f"{'TOTAL':<18} {t['cpu_seconds']:>9.3f} "
                f"{t['decoded_bytes']:>13,} {t['source_bytes']:>12,} "
                f"{t['payload_bytes']:>12,} {t['cache_hits']:>6} "
                f"{t['cache_misses']:>6} {t['requests']:>6} {t['units']:>6}"
            )
        return 0
    if args.id:
        path = f"{base}/v1/debug/requests/{args.id}"
        if args.trace:
            path += "/trace"
        status, body = _debug_fetch(path)
        if status != 200:
            err = body.get("error", {})
            print(
                f"debug: {err.get('code', status)}: {err.get('message', '')}",
                file=sys.stderr,
            )
            return 1
        text = json.dumps(body, indent=None if args.trace else 2)
        if args.trace and args.output:
            with open(args.output, "w") as f:
                f.write(text)
            n = len(body.get("traceEvents", []))
            print(f"debug: wrote {n} trace events to {args.output}")
        else:
            print(text)
        return 0
    qs = f"?limit={args.limit}" + ("&slow=1" if args.slow else "")
    status, body = _debug_fetch(f"{base}/v1/debug/requests{qs}")
    if status != 200:
        err = body.get("error", {})
        print(
            f"debug: {err.get('code', status)}: {err.get('message', '')}",
            file=sys.stderr,
        )
        return 1
    reqs = body.get("requests", [])
    if not reqs:
        print("debug: no recorded requests" + (" at/over slow_ms" if args.slow else ""))
        return 0
    print(
        f"{'ID':<18} {'ENDPOINT':<14} {'TENANT':<10} {'STATUS':<7} "
        f"{'MS':>9} {'BYTES':>12} {'WAIT_MS':>8} TRACE"
    )
    for r in reqs:
        dur = r.get("duration_ms")
        print(
            f"{r['id']:<18} {r['endpoint']:<14} {str(r['tenant']):<10} "
            f"{str(r['status']):<7} "
            f"{dur if dur is not None else '-':>9} {r['bytes']:>12} "
            f"{r['queue_wait_ms']:>8} "
            f"{r.get('trace_kind') or '-'}{' (open)' if r.get('open') else ''}"
        )
    return 0


def cmd_trace_merge(args) -> int:
    """Stitch per-process Chrome-trace documents (each exported by
    `debug --id X --trace -o`) into ONE Perfetto document on their shared
    trace-id: every input becomes its own named process lane, so the
    daemon's spans and the object store's spans of the same request sit
    on one timeline."""
    from ..obs.propagate import merge_chrome_traces

    docs = []
    for path in args.files:
        with open(path) as f:
            try:
                docs.append(json.load(f))
            except json.JSONDecodeError as e:
                raise ValueError(f"trace-merge: {path}: {e}") from None
    if args.label and len(args.label) != len(args.files):
        raise ValueError(
            "trace-merge: one --label per input file "
            f"(got {len(args.label)} labels for {len(args.files)} files)"
        )
    merged = merge_chrome_traces(docs, labels=args.label)
    text = json.dumps(merged)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        trace_id = merged["otherData"]["propagation"]["trace_id"]
        print(
            f"trace-merge: stitched {len(docs)} process(es), "
            f"{len(merged['traceEvents'])} events on trace {trace_id} "
            f"-> {args.out}"
        )
    else:
        print(text)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="parquet-tool", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    filter_help = (
        "predicate 'column OP value' (repeatable, ANDed; OP: == != < <= > >= "
        "in not_in — set ops take a list: 'id in (1,2,3)'); row groups and "
        "pages excluded by statistics/bloom/page-index never load"
    )
    pc = sub.add_parser("cat", help="print all rows as JSON lines")
    pc.add_argument("file")
    pc.add_argument("--raw", action="store_true", help="raw nested-map row shape")
    pc.add_argument("--columns", help="comma-separated column projection")
    pc.add_argument("--filter", action="append", help=filter_help)
    pc.set_defaults(fn=cmd_cat)

    ph = sub.add_parser("head", help="print the first N rows")
    ph.add_argument("-n", type=int, default=5)
    ph.add_argument("file")
    ph.add_argument("--raw", action="store_true")
    ph.add_argument("--columns", help="comma-separated column projection")
    ph.add_argument("--filter", action="append", help=filter_help)
    ph.set_defaults(fn=cmd_head)

    pm = sub.add_parser("meta", help="print file + column metadata")
    pm.add_argument("file")
    pm.set_defaults(fn=cmd_meta)

    pg = sub.add_parser("pages", help="per-page layout from the page index")
    pg.add_argument("file")
    pg.set_defaults(fn=cmd_pages)

    ps = sub.add_parser("schema", help="print the schema DSL")
    ps.add_argument("file")
    ps.set_defaults(fn=cmd_schema)

    pr = sub.add_parser("rowcount", help="print the number of rows")
    pr.add_argument("file")
    pr.set_defaults(fn=cmd_rowcount)

    pv = sub.add_parser(
        "verify",
        help="scan every page; report corrupt ones with offset, stage and "
        "error (exit 1 when any found)",
    )
    pv.add_argument("file")
    pv.add_argument(
        "--no-crc",
        action="store_true",
        help="skip stored-CRC verification (decode checks still run)",
    )
    pv.set_defaults(fn=cmd_verify)

    pz = sub.add_parser(
        "salvage",
        help="copy the readable row groups of a damaged file into a fresh "
        "one (verbatim chunk bytes, no re-encoding)",
    )
    pz.add_argument("file")
    pz.add_argument("-o", "--out", required=True, help="output file")
    pz.add_argument(
        "--force", action="store_true", help="overwrite an existing output"
    )
    pz.add_argument(
        "--no-crc",
        action="store_true",
        help="treat CRC-mismatched pages as readable (decode checks still run)",
    )
    pz.set_defaults(fn=cmd_salvage)

    pf = sub.add_parser(
        "profile",
        help="decode the file under the span tracer; write Chrome "
        "trace-event JSON (Perfetto/chrome://tracing) + per-stage report",
    )
    pf.add_argument("file", nargs="?", help="file to profile (omit with --live)")
    pf.add_argument(
        "-o", "--out",
        help="trace JSON output path (file mode, required there); "
        "collapsed/top text output path (--live mode, optional)",
    )
    pf.add_argument(
        "--columns",
        help="comma-separated column projection (the io line then shows the "
        "projection's bytes-read vs bytes-in-file efficiency)",
    )
    pf.add_argument(
        "--metrics",
        action="store_true",
        help="also print the process metrics delta + summary for the run",
    )
    pf.add_argument(
        "--rows",
        action="store_true",
        help="profile an assembled read (iter_rows) instead of the column "
        "decode: the assemble/assembly.rows stages show where record "
        "assembly spends its time (host path)",
    )
    pf.add_argument(
        "--write",
        action="store_true",
        help="profile an ENCODE instead of a decode: read the file's rows, "
        "then re-encode them (same schema + codec) to a memory sink under "
        "the tracer — the write.encode / encode.* stages show where the "
        "write path spends its time, fused-vs-staged counters included",
    )
    pf.add_argument(
        "--host",
        action="store_true",
        help="profile the pure host decode path (no jax) instead of the "
        "device-decode pipeline",
    )
    pf.add_argument(
        "--device",
        action="store_true",
        help="profile the device QUERY path instead: filtered device "
        "batches and per-row-group device partial aggregates — the "
        "query.mask / query.take / query.aggregate lanes show where the "
        "predicate -> mask -> gather -> reduce pipeline spends its time",
    )
    pf.add_argument(
        "--filter",
        help="DNF predicate as JSON for --device mode (e.g. "
        "'[[[\"id\", \">\", 100]]]'); default: first numeric leaf >= its "
        "first-group median",
    )
    pf.add_argument(
        "--cpu",
        action="store_true",
        help="force jax onto the CPU platform before profiling (keeps the "
        "accelerator tunnel untouched)",
    )
    pf.add_argument(
        "--live",
        metavar="URL",
        help="profile a RUNNING daemon via GET /v1/debug/profile instead "
        "of decoding a file: prints flamegraph-compatible collapsed "
        "stacks attributed to the pqt-* pool lanes",
    )
    pf.add_argument(
        "--seconds",
        type=float,
        default=2.0,
        help="live capture window length (default 2)",
    )
    pf.add_argument(
        "--interval-ms",
        type=float,
        default=10.0,
        help="live sampling interval (default 10 ms)",
    )
    pf.add_argument(
        "--top",
        action="store_true",
        help="with --live: print the top self-time table instead of "
        "collapsed stacks",
    )
    pf.set_defaults(fn=cmd_profile)

    pn = sub.add_parser(
        "scan",
        help="stream a glob through the dataset layer; report rows/s and "
        "wait-time share",
    )
    pn.add_argument("glob", help="glob pattern or single file")
    pn.add_argument("--columns", help="comma-separated column projection")
    pn.add_argument("--filter", action="append", help=filter_help)
    pn.add_argument(
        "--filters",
        help="JSON filter spec — a list of [column, op, value] triples "
        "(ANDed) or a list of such lists (ORed), exactly what POST "
        "/v1/scan accepts; mutually exclusive with --filter",
    )
    pn.add_argument("--batch-size", type=int, default=8192)
    pn.add_argument("--prefetch", type=int, default=2, help="units decoded ahead")
    pn.add_argument(
        "--cache-mb",
        type=int,
        default=0,
        help="shared block-cache budget in MiB (0 = off); enables pqt-io "
        "readahead of upcoming units' byte ranges",
    )
    pn.add_argument(
        "--cache-disk-mb",
        type=int,
        default=0,
        help="grow the block cache into a RAM->disk TieredCache with this "
        "many MiB of local-disk spill (the remote-corpus shape; 0 = RAM "
        "only)",
    )
    pn.add_argument(
        "--cache-dir",
        help="tiered-cache spill directory (default: a private temp dir "
        "removed on exit; a given dir is reused across runs — intact "
        "spilled blocks survive restarts)",
    )
    pn.add_argument(
        "--io-autotune",
        action="store_true",
        help="resolve the read coalesce gap + readahead depth per fetch "
        "from the observed per-transport latency profile (remote sources "
        "coalesce MiB-scale; local corpora keep the 64 KiB default)",
    )
    pn.add_argument("--epochs", type=int, default=1)
    pn.add_argument("--shuffle", action="store_true")
    pn.add_argument("--seed", type=int, default=0)
    pn.add_argument(
        "--on-error",
        choices=("raise", "skip", "null"),
        default="raise",
        help="per-unit corruption policy (skip: a corrupt shard degrades "
        "the scan instead of killing it)",
    )
    pn.add_argument(
        "--nullable",
        choices=("zero", "error"),
        default="zero",
        help="null handling: zero-fill (default — a throughput scan should "
        "not die on nullable data) or error",
    )
    pn.add_argument(
        "--aggregate",
        metavar="JSON",
        help="aggregation push-down instead of a throughput scan: a JSON "
        'list of aggregates — e.g. \'["count", ["sum", "v"]]\' — exactly '
        "what POST /v1/query accepts; prints the canonical query body "
        "(byte-identical to the daemon's response for the same corpus)",
    )
    pn.add_argument(
        "--group-by",
        help="comma-separated group-by columns (with --aggregate)",
    )
    pn.add_argument(
        "--max-groups",
        type=int,
        default=None,
        help="typed overflow past this many distinct groups "
        "(default: the protocol's bound)",
    )
    pn.add_argument(
        "--json", action="store_true", help="also print a JSON result line"
    )
    pn.add_argument(
        "--slo-ms",
        type=float,
        default=None,
        help="latency gate: attach the elastic-SLO controller, then exit "
        "non-zero (one-line report) when the p99 per-batch consumer wait "
        "exceeds this many milliseconds — CI-able",
    )
    pn.set_defaults(fn=cmd_scan)

    # serve and route share one flag set: `route` IS `serve --mesh`, so a
    # parent parser keeps the two surfaces from drifting apart
    pe = argparse.ArgumentParser(add_help=False)
    pe.add_argument("--host", default="127.0.0.1")
    pe.add_argument(
        "--port", type=int, default=8080, help="0 binds an ephemeral port"
    )
    pe.add_argument(
        "--root",
        help="confine requested paths to this directory (strongly "
        "recommended; escapes get typed 403s)",
    )
    pe.add_argument(
        "--lake",
        help="serve a lake table rooted at this directory: POST /v1/append "
        "ingests rows into it (flushes publish manifest generations); "
        "pair with --root so scans can read the table back",
    )
    pe.add_argument(
        "--lake-schema",
        help="schema DSL used to CREATE the lake table when --lake does "
        "not exist yet (an existing table ignores this and keeps its own)",
    )
    pe.add_argument(
        "--lake-sort-key",
        help="leaf column new tables sort/cluster by (with --lake-schema)",
    )
    pe.add_argument(
        "--lake-flush-mb",
        type=int,
        default=4,
        help="ingest buffer size in MiB; reaching it (or ?flush=1) "
        "commits the buffered rows as one generation",
    )
    pe.add_argument(
        "--cache-mb",
        type=int,
        default=64,
        help="shared block-cache budget in MiB (0 = off); footers always "
        "cache, so warm repeat plans do zero source reads",
    )
    pe.add_argument(
        "--cache-disk-mb",
        type=int,
        default=0,
        help="grow the block cache into a RAM->disk TieredCache with this "
        "many MiB of local-disk spill (tier stats ride /v1/debug/vars; "
        "0 = RAM only)",
    )
    pe.add_argument(
        "--cache-dir",
        help="tiered-cache spill directory (default: a private temp dir "
        "removed on close; a given dir is reused across restarts — "
        "intact spilled blocks re-serve after a crash)",
    )
    pe.add_argument(
        "--io-autotune",
        action="store_true",
        help="resolve executor read coalescing + readahead from observed "
        "per-transport latency profiles (matters with a remote "
        "source-factory; local roots keep the defaults)",
    )
    pe.add_argument(
        "--max-inflight",
        type=int,
        default=32,
        help="global concurrent-request cap (excess gets typed 429s)",
    )
    pe.add_argument(
        "--tenant-concurrent",
        type=int,
        default=8,
        help="per-tenant concurrent-request cap (X-Tenant header)",
    )
    pe.add_argument(
        "--tenant-budget-mb",
        type=int,
        default=None,
        help="per-tenant scanned-byte budget per window (charged with the "
        "plan estimate; exhaustion gets typed 429s with Retry-After)",
    )
    pe.add_argument(
        "--budget-window-s",
        type=float,
        default=60.0,
        help="token-bucket refill window for --tenant-budget-mb",
    )
    pe.add_argument(
        "--timeout-s",
        type=float,
        default=30.0,
        help="default per-request deadline (0 = none; X-Timeout-Ms / "
        "body timeout_ms override, clamped to --max-timeout-s)",
    )
    pe.add_argument("--max-timeout-s", type=float, default=300.0)
    pe.add_argument(
        "--brownout-wait-ms",
        type=float,
        default=None,
        help="shed NEW scans with typed 503s (+Retry-After) once the scan "
        "pool's windowed mean queue wait crosses this — degrade early and "
        "loudly instead of mass-504ing later (default: disabled)",
    )
    pe.add_argument(
        "--brownout-depth",
        type=int,
        default=None,
        help="also shed when the scan pool's queue depth crosses this "
        "(catches a fully wedged pool that produces no new wait samples)",
    )
    pe.add_argument(
        "--socket-timeout-s",
        type=float,
        default=60.0,
        help="per-socket-op timeout: a stalled client (stops sending or "
        "stops reading) frees its thread and admission slot after this",
    )
    pe.add_argument(
        "--window",
        type=int,
        default=2,
        help="per-request unit decode lookahead (the backpressure bound)",
    )
    pe.add_argument(
        "--shard",
        help="this daemon's corpus stripe as 'i/n' — run n daemons with "
        "i=0..n-1 over the same files to split one logical corpus",
    )
    pe.add_argument(
        "--remote-map",
        action="append",
        metavar="PREFIX=URL",
        help="map requested paths under PREFIX to an object-store base "
        "URL (repeatable; longest prefix wins) — e.g. "
        "--remote-map warm=https://store/bucket; mapped reads flow "
        "through the shared cache tiers, everything else stays "
        "root-confined",
    )
    pe.add_argument(
        "--verbose", action="store_true", help="log every request line"
    )
    pe.add_argument(
        "--trace-sample-rate",
        type=float,
        default=None,
        help="share of ok-and-fast requests whose full span tree the "
        "flight recorder keeps (errored/slow requests always keep "
        "theirs; default from ObsConfig: 1%%)",
    )
    pe.add_argument(
        "--slow-ms",
        type=float,
        default=None,
        help="requests at/over this wall time count as slow: "
        "serve_slow_requests_total, a warning log line, and an "
        "always-retained trace (default from ObsConfig: 1s)",
    )
    pe.add_argument(
        "--debug-ring",
        type=int,
        default=None,
        help="flight-recorder retention: how many recent requests "
        "/v1/debug/requests can list (default from ObsConfig)",
    )
    pe.add_argument(
        "--debug-max-traces",
        type=int,
        default=None,
        help="how many full span trees the flight recorder retains "
        "(each can be MBs; sampled/slow/errored requests compete for "
        "these slots, newest win; default from ObsConfig)",
    )
    pe.add_argument(
        "--slo-availability",
        type=float,
        default=0.999,
        help="the availability objective the burn-rate engine evaluates "
        "(share of requests that must not 5xx; /healthz reports "
        "'degraded' while the error budget burns at page rate on both "
        "the 5m and 1h windows; full math at /v1/debug/slo)",
    )
    pe.add_argument(
        "--slo-p99-ms",
        type=float,
        default=None,
        help="optional p99 latency objective (ms): enables the latency "
        "SLI — at most 1%% of requests may run over this bar",
    )
    pe.add_argument(
        "--replica",
        action="append",
        metavar="URL",
        help="a backend daemon's base URL (repeatable; mesh mode needs "
        "at least one) — the router consistent-hashes plan units over "
        "these and merges answers byte-identically",
    )
    pe.add_argument(
        "--vnodes",
        type=int,
        default=64,
        help="virtual nodes per replica on the hash ring (more = "
        "smoother unit spread, slower table rebuilds)",
    )
    pe.add_argument(
        "--no-scatter",
        action="store_true",
        help="mesh: forward each request whole to its owning replica "
        "instead of scattering per plan unit",
    )
    pe.add_argument(
        "--scatter-window",
        type=int,
        default=8,
        help="mesh: per-request bound on in-flight unit fetches (the "
        "scatter backpressure window)",
    )
    pe.add_argument(
        "--backend-timeout-s",
        type=float,
        default=30.0,
        help="mesh: per-hop timeout for one router->replica round trip "
        "(the request deadline still bounds the whole fan-out)",
    )
    pe.add_argument(
        "--no-hedge",
        action="store_true",
        help="mesh: disable the p95-armed duplicate attempt on the "
        "next-preference replica",
    )
    pe.add_argument(
        "--breaker-failures",
        type=int,
        default=3,
        help="mesh: consecutive failures before a replica's circuit "
        "breaker opens",
    )
    pe.add_argument(
        "--breaker-open-s",
        type=float,
        default=2.0,
        help="mesh: how long an open replica breaker rejects before "
        "half-opening one probe",
    )
    ps = sub.add_parser(
        "serve",
        parents=[pe],
        help="run the concurrent scan/query daemon (POST /v1/scan, "
        "GET /v1/plan, /metrics, /healthz); SIGTERM drains gracefully; "
        "--mesh turns it into the fleet router",
    )
    ps.add_argument(
        "--mesh",
        action="store_true",
        help="serve as the mesh router over --replica daemons instead "
        "of scanning locally (same as the `route` subcommand)",
    )
    ps.set_defaults(fn=cmd_serve)
    pr = sub.add_parser(
        "route",
        parents=[pe],
        help="run the mesh router over --replica daemons (alias for "
        "`serve --mesh`): consistent-hash scatter/gather for /v1/scan "
        "and /v1/query with byte-identical merged results",
    )
    pr.set_defaults(fn=cmd_serve, mesh=True)

    pd = sub.add_parser(
        "debug",
        help="query a running daemon's flight recorder: list recent "
        "requests, fetch one by id, or export its Perfetto trace",
    )
    pd.add_argument(
        "url",
        nargs="?",
        default=None,
        help="daemon base URL, e.g. http://127.0.0.1:8080 "
        "(not needed with --fleet)",
    )
    pd.add_argument("--id", help="one request id (the X-Request-Id echo)")
    pd.add_argument(
        "--trace",
        action="store_true",
        help="with --id: fetch the Chrome-trace JSON (ui.perfetto.dev)",
    )
    pd.add_argument(
        "-o", "--output", help="with --trace: write the trace document here"
    )
    pd.add_argument(
        "--slow",
        action="store_true",
        help="list only requests at/over the daemon's slow_ms",
    )
    pd.add_argument(
        "--limit", type=int, default=100, help="max requests to list"
    )
    pd.add_argument(
        "--vars",
        action="store_true",
        help="snapshot the daemon's /v1/debug/vars (uptime, pid, version, "
        "pool sizes, resilience policy, cache/admission budgets)",
    )
    pd.add_argument(
        "--tenants",
        action="store_true",
        help="print the per-tenant cost table (/v1/debug/tenants): CPU "
        "seconds, decoded/source bytes, cache outcomes, hottest first",
    )
    pd.add_argument(
        "--fleet",
        nargs="+",
        metavar="URL",
        help="scrape these replicas' /metrics (bare host:port works) and "
        "print one merged exposition: counters summed, histogram buckets "
        "added, gauges kept per replica under a replica= label",
    )
    pd.set_defaults(fn=cmd_debug)

    pt = sub.add_parser(
        "trace-merge",
        help="stitch per-process Chrome traces of ONE request (shared "
        "traceparent trace-id) into a single Perfetto document",
    )
    pt.add_argument(
        "files",
        nargs="+",
        help="input Chrome-trace JSON documents (from debug --trace -o); "
        "all must carry the same propagation trace-id",
    )
    pt.add_argument(
        "-o", "--out", default=None, help="merged output file (default: stdout)"
    )
    pt.add_argument(
        "--label",
        action="append",
        help="process lane name, one per input in order (default: each "
        "document's recorded endpoint)",
    )
    pt.set_defaults(fn=cmd_trace_merge)

    pp = sub.add_parser("split", help="split into parts by rows or file size")
    pp.add_argument("-n", type=int, help="rows per part")
    pp.add_argument(
        "--size",
        type=_parse_size,
        help="target bytes per part (suffixes K/M/G), like the reference",
    )
    pp.add_argument("--codec", default=None, help="re-encode codec (default snappy; invalid with --groups)")
    pp.add_argument(
        "--groups",
        type=int,
        help="row GROUPS per part: verbatim chunk-byte copy, no re-encoding "
        "(fast lane; -n/--size re-encode rows)",
    )
    pp.add_argument("file")
    pp.add_argument("out", help="output pattern containing %%d")
    pp.set_defaults(fn=cmd_split)

    pm = sub.add_parser(
        "merge", help="concatenate files at row-group level (no re-encoding)"
    )
    pm.add_argument(
        "-o",
        "--out",
        default=None,
        help="output file (canonical, parquet-mr argument order: "
        "merge <inputs...> -o <output>)",
    )
    pm.add_argument(
        "--force",
        action="store_true",
        help="overwrite the output file if it already exists",
    )
    pm.add_argument(
        "files",
        nargs="+",
        help="input files, order preserved (without -o the FIRST positional "
        "is taken as the output — deprecated legacy form)",
    )
    pm.set_defaults(fn=cmd_merge)

    pl = sub.add_parser(
        "lake",
        help="operate on a lake table: init, append rows, compact small "
        "files, or print the snapshot manifest (time travel with --gen)",
    )
    lsub = pl.add_subparsers(dest="lake_cmd", required=True)
    li = lsub.add_parser(
        "init", help="create a lake table (schema DSL + optional sort key)"
    )
    li.add_argument("table", help="table directory (created if missing)")
    li.add_argument(
        "--schema",
        required=True,
        help="schema DSL, e.g. 'message m { required int64 k; "
        "optional binary v (STRING); }'",
    )
    li.add_argument(
        "--sort-key", help="leaf column ingest/compaction cluster by"
    )
    li.add_argument(
        "--retain",
        type=int,
        default=64,
        help="generations kept for time travel before files are unlinked",
    )
    li.set_defaults(fn=cmd_lake)
    la = lsub.add_parser(
        "append",
        help="append jsonl rows from FILE (or stdin with '-') and commit "
        "them as one manifest generation",
    )
    la.add_argument("table", help="lake table directory")
    la.add_argument(
        "file",
        nargs="?",
        default="-",
        help="jsonl input file; '-' (default) reads stdin",
    )
    la.set_defaults(fn=cmd_lake)
    lc = lsub.add_parser(
        "compact",
        help="fold the snapshot's small files into sort-keyed row groups "
        "and commit the rewrite as one generation",
    )
    lc.add_argument("table", help="lake table directory")
    lc.add_argument("--min-files", type=int, default=2)
    lc.add_argument("--max-files", type=int, default=32)
    lc.add_argument(
        "--small-file-mb",
        type=int,
        default=64,
        help="files under this size are compaction candidates",
    )
    lc.add_argument(
        "--reap",
        action="store_true",
        help="also remove crash-orphaned tmp/data files past --reap-grace-s",
    )
    lc.add_argument(
        "--reap-grace-s",
        type=float,
        default=300.0,
        help="minimum age before an unreferenced file counts as an orphan",
    )
    lc.set_defaults(fn=cmd_lake)
    lm = lsub.add_parser(
        "manifest",
        help="print the snapshot a scan of this table pins "
        "(--gen N time-travels to a retained generation)",
    )
    lm.add_argument("table", help="lake table directory")
    lm.add_argument(
        "--gen", type=int, default=None, help="pin this generation"
    )
    lm.add_argument("--json", action="store_true", help="machine output")
    lm.set_defaults(fn=cmd_lake)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except (ValueError, OSError) as e:
        print(f"parquet-tool: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Sharded columnar scans: map-reduce over row groups across a device mesh.

The reference reads row groups strictly sequentially on one core
(file_reader.go:228-239, chunk_reader.go:375-404; SURVEY §2.5 "no parallelism
anywhere"). Here the row group is the distribution unit: each is decoded
straight into the memory of a mesh device (round-robin), a jitted map function
runs on every device's shard, and the small per-shard results are gathered to
the first device and folded there.
Decoded columns never pass through a single host bottleneck, and the scan's
working set is bounded by one row group per device (the streaming discipline
of SURVEY §5 "long-context": never materialize the whole file).

    devices = jax.devices()
    out = scan_row_groups(
        reader, devices,
        map_fn=lambda cols: cols[("fare",)].values.sum(),
        reduce_fn=lambda a, b: a + b,
    )

column_stats() is the canonical scan: per-column min/max/count computed on
device, reduced across the mesh — the read-side analogue of the writer's
statistics (stats.py; reference stats.go).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "scan_row_groups",
    "column_stats",
    "process_row_groups",
    "mesh_reduce_stats",
    "distributed_column_stats",
]


def scan_row_groups(reader, devices, map_fn, reduce_fn, columns=None, indices=None):
    """Decode row groups onto mesh devices round-robin and map-reduce.

    `map_fn(cols)` receives {leaf path: DeviceColumn} with arrays resident on
    the device that decoded the shard and returns a pytree of jax arrays;
    `reduce_fn(acc, x)` folds two such pytrees. `indices` restricts the scan
    to those row groups (default: all — a multi-host caller passes its own
    slice). Returns the folded result (None when no groups were scanned).

    Dispatch is asynchronous: all shards' uploads + decode programs are in
    flight before the first result is consumed.
    """
    devices = list(devices)
    if not devices:
        raise ValueError("scan: no devices given")
    if indices is None:
        indices = range(reader.num_row_groups)
    shard_results = []
    for k, i in enumerate(indices):
        # round-robin by LOCAL position: global indices striped across hosts
        # must still spread over every local device
        dev = devices[k % len(devices)]
        # device= (not a bare jax.default_device context) so the placement
        # reaches the reader's internal dispatch thread too
        cols = reader.read_row_group_device(i, columns=columns, device=dev)
        with jax.default_device(dev):
            shard_results.append(map_fn(cols))
    if not shard_results:
        return None
    # Fold on the first device: shard results are committed to the device
    # that produced them, and mixing committed arrays in one op is an error —
    # move each (small) result explicitly, then reduce.
    home = devices[0]
    pull = lambda t: jax.tree.map(lambda a: jax.device_put(a, home), t)
    acc = pull(shard_results[0])
    for x in shard_results[1:]:
        acc = reduce_fn(acc, pull(x))
    return acc


def _chunk_stats(dc):
    """Device-side min/max/count for one DeviceColumn (numeric only)."""
    v = dc.values
    n = jnp.asarray(v.shape[0], dtype=jnp.int64)
    if v.shape[0] == 0:
        info_min, info_max = _dtype_limits(v.dtype)
        return {"min": info_max, "max": info_min, "count": n}
    return {"min": v.min(), "max": v.max(), "count": n}


def _dtype_limits(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype), jnp.asarray(jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(False), jnp.asarray(True)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.min, dtype), jnp.asarray(info.max, dtype)


def _stats_map_fn(cols):
    return {p: _chunk_stats(dc) for p, dc in cols.items() if dc.values is not None}


def _stats_reduce_fn(a, b):
    out = {}
    for p in a.keys() | b.keys():
        if p not in a:
            out[p] = b[p]
        elif p not in b:
            out[p] = a[p]
        else:
            out[p] = {
                "min": jnp.minimum(a[p]["min"], b[p]["min"]),
                "max": jnp.maximum(a[p]["max"], b[p]["max"]),
                "count": a[p]["count"] + b[p]["count"],
            }
    return out


def _stats_materialize(folded) -> dict:
    # count == 0: every shard contributed only the fold identity (inverted
    # dtype extremes) — there are no values, so there are no bounds.
    return {
        p: {
            "min": np.asarray(s["min"])[()] if int(s["count"]) else None,
            "max": np.asarray(s["max"])[()] if int(s["count"]) else None,
            "count": int(s["count"]),
        }
        for p, s in folded.items()
    }


def column_stats(reader, devices, columns=None, filters=None):
    """Global per-column {min, max, count} over the whole file.

    Numeric columns only (dictionary-encoded byte-array columns have no
    device values array; project them out with `columns=`). Per-shard stats
    are computed on the decoding device; only those scalars reach the fold.
    `filters` prunes row groups (statistics + bloom) before any decode —
    note the stats then cover the SURVIVING groups whole, not exact
    predicate matches (group-granular pushdown, like iter_device_batches).
    """
    indices = reader.prune_row_groups(filters) if filters is not None else None
    folded = scan_row_groups(
        reader, devices, _stats_map_fn, _stats_reduce_fn,
        columns=columns, indices=indices,
    )
    return {} if folded is None else _stats_materialize(folded)


# -- multi-host scale-out ------------------------------------------------------
#
# Above, the distribution unit is a row group over the LOCAL devices of one
# process. Across hosts, row groups shard by process index (each host touches
# only its slice of the file — the reference's one-goroutine reader never
# distributes I/O at all), local stats fold on-host, and the tiny per-host
# partials reduce over the global mesh: psum/pmin/pmax ride ICI within a pod
# slice and DCN between slices, which is exactly where a collective of a few
# scalars belongs (the decoded data itself never crosses hosts).


def process_row_groups(num_row_groups: int, process_index=None, process_count=None):
    """The row-group indices owned by this process (round-robin by host)."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    return list(range(pi, num_row_groups, pc))


def mesh_reduce_stats(stats: dict, mesh, replicas_per_participant: int = 1) -> dict:
    """All-reduce per-column {min, max, count} over every device of `mesh`.

    Each participant's partial is replicated across its `replicas_per_
    participant` mesh positions (a host with 4 local devices contributes 4
    identical copies), so the psum'd count divides by that factor; min/max
    are idempotent. Keys MUST match across participants — build them from
    the shared schema, not from which chunks happened to decode.
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if len(mesh.axis_names) > 1:
        # stats all-reduce spans EVERY device; an N-D compute mesh (e.g.
        # pages x cols) flattens to one axis so pmin/pmax/psum cover it all
        mesh = Mesh(mesh.devices.reshape(-1), ("_all",))
    axis = mesh.axis_names[0]
    r = replicas_per_participant
    if mesh.devices.size % max(r, 1) != 0:
        raise ValueError(
            f"mesh size {mesh.devices.size} not divisible by {r} replicas"
        )

    def reduce_one(s):
        return {
            "min": jax.lax.pmin(s["min"], axis),
            "max": jax.lax.pmax(s["max"], axis),
            "count": jax.lax.psum(s["count"], axis) // r,
        }

    def step(tree):
        return {p: reduce_one(s) for p, s in tree.items()}

    reducer = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check_vma=False,
    )
    # one leading mesh-axis element per device: replicate this host's partial
    # and lay it out on the mesh (local partials live on a single device)
    n = mesh.devices.size
    sharding = NamedSharding(mesh, P(axis))
    tiled = jax.tree.map(
        lambda a: jax.device_put(
            np.broadcast_to(np.asarray(a), (n,) + np.asarray(a).shape), sharding
        ),
        stats,
    )
    out = reducer(tiled)
    # out_specs=P() leaves a size-1 leading axis on some jax versions
    return jax.tree.map(
        lambda a: a.reshape(a.shape[1:]) if a.ndim and a.shape[0] == 1 else a, out
    )


def _stats_key_nodes(reader, columns) -> list:
    """The numeric leaves every participant reports on — derived from the
    schema + projection so all hosts enter the collective with IDENTICAL
    pytree structure regardless of which chunks they decoded."""
    # honor the reader's persistent projection too: a deselected column is
    # never decoded, and reporting it as count=0 would misread as "empty"
    selected = reader._resolve_columns(columns) if columns else reader._selected
    return [
        leaf
        for leaf in reader.schema.leaves
        if _numeric_jnp_dtype(leaf) is not None
        and (selected is None or leaf.path in selected)
    ]


def _stats_identity(leaf):
    lo, hi = _dtype_limits(_numeric_jnp_dtype(leaf))
    return {"min": hi, "max": lo, "count": jnp.asarray(0, dtype=jnp.int64)}


def distributed_column_stats(
    reader, columns=None, mesh=None, devices=None, filters=None
):
    """Whole-file column stats in a multi-host program.

    Each process decodes only its own row groups (process_row_groups) on its
    local devices, folds locally, and contributes one partial per numeric
    leaf — fold identities for anything it didn't decode, so every host's
    pytree matches. Partials reduce globally over `mesh` (default: every
    device in the program, one participant per process replicated over its
    local devices). Single-process programs with no explicit mesh skip the
    collective. `devices` overrides the local device set (e.g. a CPU-pinned
    dryrun passes the mesh's host devices explicitly). `filters` prunes row
    groups (statistics + bloom) before any decode — every process prunes
    from the same metadata, so ownership stays consistent; surviving groups
    stream whole (group-granular pushdown, like column_stats)."""
    if devices is None:
        devices = jax.local_devices()
    indices = process_row_groups(reader.num_row_groups)
    if filters is not None:
        admitted = set(reader.prune_row_groups(filters))
        indices = [i for i in indices if i in admitted]
    key_nodes = _stats_key_nodes(reader, columns)
    acc = scan_row_groups(
        reader, devices, _stats_map_fn, _stats_reduce_fn,
        columns=columns, indices=indices,
    )
    # identical key set on every participant (SPMD: the collective's pytree
    # structure must not depend on local data)
    full = {leaf.path: _stats_identity(leaf) for leaf in key_nodes}
    if acc:
        full.update({p: s for p, s in acc.items() if p in full})
    acc = full
    if jax.process_count() > 1 or mesh is not None:
        if mesh is None:
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()), ("hosts",))
        replicas = mesh.devices.size // jax.process_count()
        acc = mesh_reduce_stats(acc, mesh, replicas_per_participant=replicas)
    return _stats_materialize(acc)


def _numeric_jnp_dtype(leaf):
    from ..meta.parquet_types import Type

    return {
        Type.INT32: jnp.int32,
        Type.INT64: jnp.int64,
        Type.FLOAT: jnp.float32,
        Type.DOUBLE: jnp.float64,
        Type.BOOLEAN: jnp.bool_,
    }.get(leaf.type)

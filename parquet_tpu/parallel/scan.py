"""Sharded columnar scans: map-reduce over row groups across a device mesh.

The reference reads row groups strictly sequentially on one core
(file_reader.go:228-239, chunk_reader.go:375-404; SURVEY §2.5 "no parallelism
anywhere"). Here the row group is the distribution unit: each is decoded
straight into the memory of a mesh device (round-robin), a jitted map function
runs on every device's shard, and the small per-shard results are gathered to
the first device and folded there.
Decoded columns never pass through a single host bottleneck, and the scan's
working set is bounded by one row group per device (the streaming discipline
of SURVEY §5 "long-context": never materialize the whole file).

    devices = jax.devices()
    out = scan_row_groups(
        reader, devices,
        map_fn=lambda cols: cols[("fare",)].values.sum(),
        reduce_fn=lambda a, b: a + b,
    )

column_stats() is the canonical scan: per-column min/max/count computed on
device, reduced across the mesh — the read-side analogue of the writer's
statistics (stats.py; reference stats.go).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["scan_row_groups", "column_stats"]


def scan_row_groups(reader, devices, map_fn, reduce_fn, columns=None):
    """Decode row groups onto mesh devices round-robin and map-reduce.

    `map_fn(cols)` receives {leaf path: DeviceColumn} with arrays resident on
    the device that decoded the shard and returns a pytree of jax arrays;
    `reduce_fn(acc, x)` folds two such pytrees. Returns the folded result
    (None if the file has no row groups).

    Dispatch is asynchronous: all shards' uploads + decode programs are in
    flight before the first result is consumed.
    """
    devices = list(devices)
    if not devices:
        raise ValueError("scan: no devices given")
    shard_results = []
    for i in range(reader.num_row_groups):
        dev = devices[i % len(devices)]
        with jax.default_device(dev):
            cols = reader.read_row_group_device(i, columns=columns)
            shard_results.append(map_fn(cols))
    if not shard_results:
        return None
    # Fold on the first device: shard results are committed to the device
    # that produced them, and mixing committed arrays in one op is an error —
    # move each (small) result explicitly, then reduce.
    home = devices[0]
    pull = lambda t: jax.tree.map(lambda a: jax.device_put(a, home), t)
    acc = pull(shard_results[0])
    for x in shard_results[1:]:
        acc = reduce_fn(acc, pull(x))
    return acc


def _chunk_stats(dc):
    """Device-side min/max/count for one DeviceColumn (numeric only)."""
    v = dc.values
    n = jnp.asarray(v.shape[0], dtype=jnp.int64)
    if v.shape[0] == 0:
        info_min, info_max = _dtype_limits(v.dtype)
        return {"min": info_max, "max": info_min, "count": n}
    return {"min": v.min(), "max": v.max(), "count": n}


def _dtype_limits(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype), jnp.asarray(jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(False), jnp.asarray(True)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.min, dtype), jnp.asarray(info.max, dtype)


def column_stats(reader, devices, columns=None):
    """Global per-column {min, max, count} over the whole file.

    Numeric columns only (dictionary-encoded byte-array columns have no
    device values array; project them out with `columns=`). Per-shard stats
    are computed on the decoding device; only those scalars reach the fold.
    """

    def map_fn(cols):
        return {p: _chunk_stats(dc) for p, dc in cols.items() if dc.values is not None}

    def reduce_fn(a, b):
        out = {}
        for p in a.keys() | b.keys():
            if p not in a:
                out[p] = b[p]
            elif p not in b:
                out[p] = a[p]
            else:
                out[p] = {
                    "min": jnp.minimum(a[p]["min"], b[p]["min"]),
                    "max": jnp.maximum(a[p]["max"], b[p]["max"]),
                    "count": a[p]["count"] + b[p]["count"],
                }
        return out

    folded = scan_row_groups(reader, devices, map_fn, reduce_fn, columns=columns)
    if folded is None:
        return {}
    # count == 0: every shard contributed only the fold identity (inverted
    # dtype extremes) — there are no values, so there are no bounds.
    return {
        p: {
            "min": np.asarray(s["min"])[()] if int(s["count"]) else None,
            "max": np.asarray(s["max"])[()] if int(s["count"]) else None,
            "count": int(s["count"]),
        }
        for p, s in folded.items()
    }

"""Multi-chip scale-out: sharded page-batch decode over a device mesh.

The reference is single-process and decodes columns sequentially
(reference: chunk_reader.go:375-404, SURVEY §2.5 'no parallelism anywhere');
the natural parallel axes of the workload are pages x columns x row groups.
Here those axes map onto a jax.sharding.Mesh:

  axis "pages"  data-parallel over page batches (the bulk axis; scales with
                file size, rides ICI for stat reductions only)
  axis "cols"   parallel over columns of a row group (embarrassingly parallel)

The decode step is a shard_map: each device expands its shard of the page grid
locally (same kernels as kernels/device_ops.py), then per-column statistics
(min/max/null-count — the write-side stats of stats.py) reduce across the
"pages" axis with psum/pmin/pmax over ICI. Output stays device-sharded for
downstream consumers; only stats and counts cross chips.

The page grid is a fixed-shape padded layout: P pages x R runs x W words x N
output values per page — static shapes so the whole step jits once (XLA,
SURVEY §7.1).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)  # int64 columns are first-class

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["PageGrid", "make_decode_mesh", "sharded_decode_step", "build_page_grid"]


class PageGrid:
    """Host-side padded page batch: one column's pages as fixed-shape arrays."""

    def __init__(self, words, starts, is_rle, values, bit_starts, counts, width: int):
        self.words = words  # (P, W) uint32
        self.starts = starts  # (P, R) int32 run output starts (pad: big)
        self.is_rle = is_rle  # (P, R) int32
        self.values = values  # (P, R) uint32
        self.bit_starts = bit_starts  # (P, R) int32
        self.counts = counts  # (P,) int32 real values per page
        self.width = width

    @property
    def num_pages(self) -> int:
        return self.words.shape[0]


def build_page_grid(tables, takes, width: int, out_per_page: int) -> PageGrid:
    """Pad per-page run tables (ops/rle_hybrid.py prescan) into a grid."""
    n_pages = len(tables)
    max_runs = max((len(t.counts) for t in tables), default=1)
    max_words = max((len(t.packed) + 7) // 4 + 1 for t in tables)
    words = np.zeros((n_pages, max_words), dtype=np.uint32)
    starts = np.full((n_pages, max_runs), out_per_page + 1, dtype=np.int32)
    is_rle = np.zeros((n_pages, max_runs), dtype=np.int32)
    values = np.zeros((n_pages, max_runs), dtype=np.uint32)
    bit_starts = np.zeros((n_pages, max_runs), dtype=np.int32)
    counts = np.zeros(n_pages, dtype=np.int32)
    for p, (t, take) in enumerate(zip(tables, takes)):
        w = np.frombuffer(
            bytes(t.packed) + b"\x00" * ((-len(t.packed)) % 4 + 4), dtype="<u4"
        )
        words[p, : len(w)] = w
        r = len(t.counts)
        out_start = np.zeros(r, dtype=np.int64)
        np.cumsum(t.counts[:-1], out=out_start[1:])
        starts[p, :r] = out_start
        is_rle[p, :r] = t.is_rle
        values[p, :r] = t.rle_values.astype(np.uint32)
        bit_starts[p, :r] = t.bp_offsets * 8
        counts[p] = take
    return PageGrid(words, starts, is_rle, values, bit_starts, counts, width)


def make_decode_mesh(devices=None, pages_axis: int | None = None) -> Mesh:
    """1-D decode mesh over the "pages" axis (the bulk data-parallel axis)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices) if pages_axis is None else pages_axis
    return Mesh(np.array(devices[:n]).reshape(n), ("pages",))


def _expand_one_page(words, starts, is_rle, values, bit_starts, width: int, n_out: int):
    """Expand one padded page (same math as kernels/device_ops.py)."""
    i = jnp.arange(n_out, dtype=jnp.int32).reshape(n_out, 1)
    r = jnp.sum((starts.reshape(1, -1) <= i).astype(jnp.int32), axis=1) - 1
    r = jnp.clip(r, 0, starts.shape[0] - 1)
    within = i.reshape(n_out) - starts[r]
    bitpos = bit_starts[r] + within * width
    w0 = bitpos >> 5
    s = (bitpos & 31).astype(jnp.uint32)
    lo = words[w0] >> s
    hi = jnp.where(
        s == 0,
        jnp.uint32(0),
        words[jnp.minimum(w0 + 1, words.shape[0] - 1)] << ((32 - s) & 31),
    )
    mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
    bp = (lo | hi) & mask
    return jnp.where(is_rle[r] == 1, values[r], bp)


def sharded_decode_step(mesh: Mesh, grid: PageGrid, dictionary, n_out: int):
    """One sharded decode step: expand pages + dict gather + global stats.

    Returns (decoded (P, n_out) sharded over "pages", stats dict reduced over
    the mesh). This is the 'training step' shape of this framework: bulk
    compute stays sharded; only scalar stats cross ICI.
    """
    width = grid.width
    n_dev = mesh.devices.size

    def step(words, starts, is_rle, values, bit_starts, counts, dict_dev):
        expand = jax.vmap(
            partial(_expand_one_page, width=width, n_out=n_out)
        )
        idx = expand(words, starts, is_rle, values, bit_starts)
        decoded = dict_dev[idx]  # gather per device shard
        # mask padding beyond each page's real count
        valid = (
            jnp.arange(n_out, dtype=jnp.int32).reshape(1, n_out)
            < counts.reshape(-1, 1)
        )
        big = jnp.iinfo(decoded.dtype).max if decoded.dtype.kind == "i" else jnp.inf
        masked_min = jnp.where(valid, decoded, big).min()
        masked_max = jnp.where(valid, decoded, -big).max()
        count = jnp.sum(valid.astype(jnp.int64))
        # cross-chip reduction over the pages axis (ICI collectives)
        gmin = jax.lax.pmin(masked_min, "pages")
        gmax = jax.lax.pmax(masked_max, "pages")
        gcount = jax.lax.psum(count, "pages")
        return decoded, {"min": gmin, "max": gmax, "count": gcount}

    pspec = P("pages")
    shard_step = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(pspec, pspec, pspec, pspec, pspec, pspec, P()),
        out_specs=(pspec, P()),
    )
    # pad page axis to a multiple of the mesh size
    pad_pages = (-grid.num_pages) % n_dev
    def pad(a):
        if pad_pages == 0:
            return a
        widths = [(0, pad_pages)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)
    args = (
        pad(grid.words),
        pad(grid.starts),
        pad(grid.is_rle),
        pad(grid.values),
        pad(grid.bit_starts),
        pad(grid.counts),
        np.asarray(dictionary),
    )
    sharded = [
        jax.device_put(a, NamedSharding(mesh, pspec)) for a in args[:-1]
    ]
    dict_dev = jax.device_put(args[-1], NamedSharding(mesh, P()))
    return jax.jit(shard_step)(*sharded, dict_dev)

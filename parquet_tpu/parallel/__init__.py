"""Multi-chip scale-out: sharded decode and row-group scans over device meshes."""

from .mesh import (  # noqa: F401
    PageGrid,
    build_page_grid,
    make_decode_mesh,
    sharded_decode_step,
)
from .scan import column_stats, scan_row_groups  # noqa: F401

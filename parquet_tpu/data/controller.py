"""The elastic-SLO feedback controller for ParquetDataset.

PR 4 gave the dataset bounded prefetch; PR 9 gave every pqt-* pool queue
gauges and wait histograms. Both left the knobs STATIC: prefetch depth,
pqt-data worker count and the readahead byte budget are fixed at
construction, so a latency spike stalls the train loop until a human
re-tunes. This module closes the loop:

    ds = ParquetDataset(glob, batch_size=8192, slo_wait_ms=5.0, ...)

attaches an AIMD (additive-increase / multiplicative-decrease) controller
that targets a CONSUMER-WAIT SLO — "the train loop should almost never
block more than slo_wait_ms on next()" — using windowed deltas of the
PR 9 instruments as its inputs:

  dataset_wait_seconds            how long consumers actually blocked
                                  (count + sum + the bucket <= the SLO
                                  bound -> per-window violation share)
  pool_queue_wait_seconds{pqt-data}  decode tasks queueing behind too few
                                  workers (the scale-WORKERS signal)
  dataset_prefetch_depth          in-flight units (the idle signal: a
                                  pipeline that never fills its window
                                  is over-provisioned)

Control law, evaluated once per `window_s` on the injected clock:

  pressure  (violation share over the window > tolerated, or the mean
            wait > the SLO): prefetch target += increase_step (additive),
            workers track the target, the readahead budget grows
            proportionally. One step per window: AIMD probes, it does not
            leap.
  idle      (no violations AND mean wait < idle_fraction * SLO) for
            `idle_windows` consecutive windows: target *= decrease_factor
            (multiplicative) — capacity returns quickly when the spike
            passes.
  otherwise hold.

Everything the controller changes is ADVISORY — speed, never the stream:
the epoch order, the batch grid and the checkpoint cursor are pure
functions of (seed, epoch, shard, batch_size), none of which the
controller touches, so `state_dict()` resume stays byte-identical with the
controller on, off, or mid-adaptation (pinned in tests/test_controller.py).
Controller state is therefore deliberately NOT in state_dict().

Observability: `dataset_prefetch_target` gauge (the target; the existing
dataset_prefetch_depth gauge shows actual in-flight), and
`dataset_slo_violations_total` counting wait observations over the SLO.
"""

from __future__ import annotations

import os
import threading
import time

from ..obs.log import log_event as _log_event
from ..utils import metrics as _metrics

__all__ = ["AIMDController"]


class AIMDController:
    """Clock-injectable AIMD controller over (prefetch depth, worker
    count, readahead budget). Thread-safe: tick() may be called from the
    consumer thread and targets read from the fill loop concurrently.

    Parameters
    ----------
    slo_wait_ms     the consumer-wait SLO: next() blocking longer than
                    this is a violation
    min_depth/max_depth   prefetch-target clamp (min_depth >= 1: the
                    controller always keeps the pipeline asynchronous)
    max_workers     pqt-data worker clamp (None = PQT_DATA_THREADS or cpu)
    readahead_unit_bytes  budget granted per unit of prefetch target when
                    a Readahead scheduler is attached
    window_s        control interval on the injected clock
    violation_share tolerated fraction of over-SLO waits per window
    increase_step   additive depth increase under pressure
    decrease_factor multiplicative depth decay when idle
    idle_fraction   "idle" means mean wait below this fraction of the SLO
    idle_windows    consecutive idle windows required before decaying
    clock           injectable monotonic clock (tests drive fake time)
    registry        injectable MetricsRegistry for BOTH reads and writes
                    (defaults to the process one; tests isolate their
                    histogram streams with it)

    dataset_wait_seconds is process-global and unlabeled, so two
    controlled datasets sharing the default registry read each other's
    waits (and last-write-win the dataset_prefetch_target gauge): run
    concurrent controlled datasets with per-dataset registries, or accept
    that the controllers co-steer against merged traffic.
    """

    def __init__(
        self,
        *,
        slo_wait_ms: float,
        initial_depth: int = 2,
        min_depth: int = 1,
        max_depth: int = 32,
        max_workers: int | None = None,
        readahead_unit_bytes: int = 4 << 20,
        window_s: float = 0.5,
        violation_share: float = 0.01,
        increase_step: int = 1,
        decrease_factor: float = 0.5,
        idle_fraction: float = 0.1,
        idle_windows: int = 4,
        clock=time.monotonic,
        registry=None,
    ):
        if slo_wait_ms <= 0:
            raise ValueError("controller: slo_wait_ms must be positive")
        if not 1 <= min_depth <= max_depth:
            raise ValueError("controller: need 1 <= min_depth <= max_depth")
        if window_s <= 0:
            raise ValueError("controller: window_s must be positive")
        if increase_step < 1:
            raise ValueError("controller: increase_step must be >= 1")
        if not 0.0 < decrease_factor < 1.0:
            raise ValueError("controller: decrease_factor must be in (0, 1)")
        self.slo_wait_ms = float(slo_wait_ms)
        self.slo_s = slo_wait_ms / 1e3
        self.min_depth = int(min_depth)
        self.max_depth = int(max_depth)
        if max_workers is None:
            env = os.environ.get("PQT_DATA_THREADS")
            max_workers = int(env) if env else (os.cpu_count() or 1)
        self.max_workers = max(1, int(max_workers))
        self.readahead_unit_bytes = int(readahead_unit_bytes)
        self.window_s = float(window_s)
        self.violation_share = float(violation_share)
        self.increase_step = int(increase_step)
        self.decrease_factor = float(decrease_factor)
        self.idle_fraction = float(idle_fraction)
        self.idle_windows = int(idle_windows)
        self._clock = clock
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._lock = threading.Lock()
        self._depth = max(self.min_depth, min(self.max_depth, int(initial_depth)))
        self._idle_streak = 0
        self._window_start = None  # first tick() arms the window
        self._last: dict | None = None  # histogram totals at window start
        self.ticks = 0  # completed control windows (tests pin convergence)
        self.increases = 0
        self.decreases = 0
        self._registry.set("dataset_prefetch_target", self._depth)

    # -- targets (read by the dataset's fill loop) -----------------------------

    @property
    def prefetch_target(self) -> int:
        with self._lock:
            return self._depth

    @property
    def worker_target(self) -> int:
        """Workers track the prefetch target (a window of k in-flight
        units can use at most k decoders), clamped to max_workers."""
        with self._lock:
            return max(1, min(self._depth, self.max_workers))

    @property
    def readahead_budget(self) -> int:
        with self._lock:
            return max(1, self._depth) * self.readahead_unit_bytes

    # -- the inputs ------------------------------------------------------------

    def _violation_bound(self, buckets) -> float | None:
        """The largest histogram bucket bound <= the SLO: observations past
        it are (conservatively) counted as violations. None when the SLO is
        below every bound (then only the mean-wait signal drives)."""
        best = None
        for le in buckets:
            if le <= self.slo_s:
                best = le
        return best

    def _read_inputs(self) -> dict:
        """Windowed totals of the driving instruments (monotonic — the
        delta between two reads is the window's traffic)."""
        wait = self._registry.hist_stats("dataset_wait_seconds")
        bound = self._violation_bound(wait["buckets"])
        if bound is not None:
            under = wait["bucket_counts"][wait["buckets"].index(bound)]
        else:
            # SLO below every bucket bound: no bucket can witness a
            # violation, so count nothing as one (violations stay 0) and
            # let the mean-wait signal drive alone
            under = wait["count"]
        pool_wait = self._registry.hist_stats(
            "pool_queue_wait_seconds", pool="pqt-data"
        )
        return {
            "count": wait["count"],
            "sum": wait["sum"],
            "under_slo": under,
            "pool_count": pool_wait["count"],
            "pool_sum": pool_wait["sum"],
        }

    # -- the control law -------------------------------------------------------

    def tick(self) -> bool:
        """Evaluate one control window if `window_s` has elapsed (cheap
        no-op otherwise — call freely from the consumer loop). Returns
        True when a window was evaluated."""
        now = self._clock()
        with self._lock:
            if self._window_start is None:
                self._window_start = now
                self._last = self._read_inputs()
                return False
            if now - self._window_start < self.window_s:
                return False
            cur = self._read_inputs()
            last, self._last = self._last, cur
            self._window_start = now
            self.ticks += 1
            d_count = cur["count"] - last["count"]
            d_sum = cur["sum"] - last["sum"]
            d_under = cur["under_slo"] - last["under_slo"]
            violations = max(0, d_count - d_under)
            if violations:
                self._registry.inc("dataset_slo_violations_total", violations)
            mean_wait = (d_sum / d_count) if d_count else 0.0
            share = (violations / d_count) if d_count else 0.0
            pressured = (d_count > 0) and (
                share > self.violation_share or mean_wait > self.slo_s
            )
            idle = (d_count > 0) and (
                violations == 0 and mean_wait < self.idle_fraction * self.slo_s
            )
            old = self._depth
            if pressured:
                self._idle_streak = 0
                self._depth = min(self.max_depth, old + self.increase_step)
                if self._depth != old:
                    self.increases += 1
            elif idle:
                self._idle_streak += 1
                if self._idle_streak >= self.idle_windows:
                    self._idle_streak = 0
                    self._depth = max(
                        self.min_depth, int(old * self.decrease_factor)
                    )
                    if self._depth != old:
                        self.decreases += 1
            else:
                self._idle_streak = 0
            changed = self._depth != old
            depth = self._depth
        if changed:
            self._registry.set("dataset_prefetch_target", depth)
            _log_event(
                "slo_controller_step",
                direction="up" if depth > old else "down",
                depth=depth, mean_wait_ms=round(mean_wait * 1e3, 3),
                violation_share=round(share, 4),
            )
        return True

    def state(self) -> dict:
        """Diagnostic snapshot (NOT checkpoint state — the controller is
        advisory and deliberately absent from DatasetIterator.state_dict)."""
        with self._lock:
            return {
                "depth": self._depth,
                "worker_target": max(1, min(self._depth, self.max_workers)),
                "ticks": self.ticks,
                "increases": self.increases,
                "decreases": self.decreases,
                "idle_streak": self._idle_streak,
            }

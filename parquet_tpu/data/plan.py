"""Scan planning for the streaming dataset: files -> sharded unit order.

The plan layer is pure bookkeeping, deliberately separated from the
prefetch/decode machinery in dataset.py so its determinism contracts are
trivially testable:

  * a ScanPlan is built from FOOTERS ONLY (FileReader.open_metadata — no
    data pages touched), one work unit per (file, row group) with the
    row count the footer promises;
  * `filters` prune units at plan time through the reader's normal
    statistics/bloom pruning — excluded row groups never enter the plan,
    so they are never opened, decoded, or prefetched;
  * `epoch_order(epoch)` derives each epoch's unit visit order from
    (seed, epoch) alone — any process at any time recomputes the same
    permutation, which is what makes mid-epoch checkpoint/resume and
    multi-host sharding exact: the global order is permuted identically
    everywhere, then striped across `shard_count * worker_count` slots so
    every unit is visited by EXACTLY ONE (process, worker) per epoch.

A corrupt file (unreadable footer) follows the dataset's on_error policy:
"raise" propagates, otherwise the file's units are dropped from the plan
(counted: dataset_files_skipped) and the scan degrades instead of dying.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import os
from pathlib import Path
from typing import NamedTuple

import numpy as np

from ..core.reader import PARQUET_ERRORS, FileReader
from ..utils import metrics as _metrics
from ..utils.trace import bump

__all__ = ["Unit", "ScanPlan", "expand_paths", "build_plan"]


class Unit(NamedTuple):
    """One schedulable work unit: a single row group of a single file."""

    file_index: int  # index into ScanPlan.files
    path: str
    row_group: int
    num_rows: int


def _expand_lake_ref(s: str):
    """A lake-table reference expands to ONE pinned snapshot's file list:
    a table directory (holding _lake/CURRENT) pins the current
    generation, a manifest file path (table/_lake/gen-N.json) pins
    generation N — time travel for scans. Returns None when `s` is not a
    lake reference; the file list preserves MANIFEST order (it is a
    consistent snapshot, not a directory listing to be re-sorted —
    callers that sort sort deterministically anyway)."""
    from ..lake.manifest import is_lake_table, manifest_ref_root

    ref = manifest_ref_root(s)
    if ref is not None:
        root, gen = ref
    elif os.path.isdir(s) and is_lake_table(s):
        root, gen = s, None
    else:
        return None
    from ..lake.manifest import LakeManifest

    return LakeManifest(root).open_snapshot(gen).paths(
        os.path.realpath(root)
    )


def expand_paths(paths_or_glob) -> list[str]:
    """Resolve the dataset's input spec into a deterministic file list.

    A string (or Path) is treated as a glob pattern when it contains magic
    characters, otherwise as a single file; a list/tuple passes through.
    http(s):// URLs pass through verbatim (remote objects don't glob or
    stat — existence surfaces as the open's typed error). A lake-table
    directory or manifest file expands to that snapshot's file list (see
    _expand_lake_ref) — every scan plans against exactly one generation.
    The result is lexicographically sorted — glob order is
    filesystem-dependent, and the shard/shuffle math needs every process
    to see the SAME file indices."""
    if isinstance(paths_or_glob, (str, Path)):
        s = str(paths_or_glob)
        if s.startswith(("http://", "https://")):
            return [s]
        lake = _expand_lake_ref(s)
        if lake is not None:
            return lake
        if _glob.has_magic(s):
            hits = _glob.glob(s)
            if not hits:
                raise FileNotFoundError(f"dataset: glob {s!r} matched no files")
            return sorted(hits)
        if not os.path.exists(s):
            raise FileNotFoundError(f"dataset: no such file {s!r}")
        return [s]
    out: list[str] = []
    for p in paths_or_glob:
        lake = _expand_lake_ref(str(p))
        out.extend(lake if lake is not None else [str(p)])
    if not out:
        raise ValueError("dataset: empty path list")
    return sorted(out)


class ScanPlan:
    """The global (pre-shard) work list of a dataset scan."""

    def __init__(
        self,
        files: list[str],
        metas: list,
        units: list[Unit],
        skipped_files: list[tuple[str, str]],
        *,
        units_total: int | None = None,
        units_pruned_stats: int = 0,
        units_pruned_bloom: int = 0,
    ):
        self.files = files
        # per-file FileMetaData (None for skipped files): per-unit readers
        # open with metadata= so each footer parses exactly once
        self.metas = metas
        self.units = units
        self.skipped_files = skipped_files
        # The per-plan pruning summary: how many row groups the readable
        # files held, and how many the filters excluded at plan time by
        # chunk statistics vs bloom filters. Carried ON the plan so
        # `GET /v1/plan` and `parquet-tool scan --json` report it without
        # a trace attached (units_total - pruned_stats - pruned_bloom ==
        # len(units)).
        self.units_total = len(units) if units_total is None else units_total
        self.units_pruned_stats = units_pruned_stats
        self.units_pruned_bloom = units_pruned_bloom

    @property
    def num_units(self) -> int:
        return len(self.units)

    def pruning_summary(self) -> dict:
        return {
            "units_total": self.units_total,
            "units_pruned_stats": self.units_pruned_stats,
            "units_pruned_bloom": self.units_pruned_bloom,
            "units_admitted": len(self.units),
        }

    @property
    def total_rows(self) -> int:
        return sum(u.num_rows for u in self.units)

    def fingerprint(self) -> dict:
        """What a checkpoint pins: a resumed iterator must see the same
        unit list or its cursor means nothing. The digest covers every
        unit's (file basename, row group, row count) — a renamed, reordered,
        resharded or re-rowed file set is rejected at load_state_dict even
        when the aggregate counts happen to match. Basenames, not full
        paths: moving the whole dataset directory between runs is fine.
        (File CONTENTS are not hashed — rewriting a shard in place with
        identical name and row counts is undetectable.)"""
        h = hashlib.sha1()
        for u in self.units:
            h.update(
                f"{os.path.basename(u.path)}#{u.row_group}#{u.num_rows};".encode()
            )
        return {
            "files": len(self.files),
            "units": self.num_units,
            "rows": self.total_rows,
            "digest": h.hexdigest(),
        }

    def epoch_order(
        self,
        epoch: int,
        *,
        seed: int = 0,
        shuffle: bool = False,
        shard_index: int = 0,
        shard_count: int = 1,
    ) -> list[int]:
        """This shard's unit visit order for `epoch` (indices into .units).

        The permutation is a pure function of (seed, epoch) over the GLOBAL
        unit list; every shard computes it identically and takes its
        stride-slice, so the shards' slices partition the epoch exactly.
        Without shuffle the order is the file-major plan order."""
        if not 0 <= shard_index < shard_count:
            raise ValueError(
                f"dataset: shard_index {shard_index} out of range for "
                f"shard_count {shard_count}"
            )
        n = self.num_units
        if shuffle:
            order = np.random.default_rng([seed, epoch]).permutation(n)
        else:
            order = np.arange(n)
        return [int(i) for i in order[shard_index::shard_count]]


def build_plan(
    paths_or_glob,
    *,
    filters=None,
    on_error: str = "raise",
    footer_cache=None,
    block_cache=None,
) -> ScanPlan:
    """Parse every file's footer and lay out the unit list.

    `filters` (the (column, op, value) DNF convention shared with
    FileReader) prunes row groups through the statistics/bloom path —
    pruned groups never become units, and the per-plan pruning summary
    (units_total / units_pruned_stats / units_pruned_bloom) rides the
    returned ScanPlan. With on_error != "raise" a file whose footer (or
    schema/filter resolution) fails is skipped with a counter instead of
    killing the scan. `footer_cache` (io.cache.FooterCache) makes
    re-planning the same files — new epochs, new dataset objects,
    open_many callers — parse each footer once per file generation;
    `block_cache` (io.cache.BlockCache) does the same for the bloom-filter
    pages pruning consults, so a warm repeated plan performs ZERO source
    reads even with bloom-equipped filters."""
    files = expand_paths(paths_or_glob)
    metas: list = []
    units: list[Unit] = []
    skipped: list[tuple[str, str]] = []
    units_total = pruned_stats = pruned_bloom = 0
    filters_checked = filters is None
    for fi, path in enumerate(files):
        try:
            meta = FileReader.open_metadata(path, footer_cache=footer_cache)
        except PARQUET_ERRORS + (OSError,) as e:
            if on_error == "raise":
                raise
            bump("dataset_files_skipped")
            metas.append(None)
            skipped.append((path, f"{type(e).__name__}: {e}"))
            continue
        if not filters_checked:
            # Validate the filter ONCE against the first readable schema,
            # OUTSIDE the skip policy: a misspelled filter column is a
            # configuration error that would otherwise "skip" every file
            # and silently plan an empty dataset.
            from ..core.filter import normalize_dnf
            from ..core.schema import Schema

            normalize_dnf(Schema.from_thrift(meta.schema), filters)
            filters_checked = True
        groups = meta.row_groups or []
        # per-file tallies commit only after the file planned cleanly, so
        # a mid-prune failure under the skip policy cannot skew the summary
        f_stats = f_bloom = 0
        try:
            if filters is not None:
                # statistics/bloom pruning needs a live reader (bloom pages
                # read from the file); footer-only cost when no blooms exist
                with FileReader(
                    path, metadata=meta, block_cache=block_cache
                ) as r:
                    admitted, f_stats, f_bloom = r.prune_row_groups_counted(
                        filters
                    )
            else:
                admitted = range(len(groups))
        except PARQUET_ERRORS + (OSError,) as e:
            # OSError: the file vanished (or lost read permission) between
            # the glob and the open — same degradation policy as corruption
            if on_error == "raise":
                raise
            bump("dataset_files_skipped")
            metas.append(None)
            skipped.append((path, f"{type(e).__name__}: {e}"))
            continue
        metas.append(meta)
        units_total += len(groups)
        pruned_stats += f_stats
        pruned_bloom += f_bloom
        for gi in admitted:
            units.append(Unit(fi, path, gi, int(groups[gi].num_rows or 0)))
    if pruned_stats:
        _metrics.event("plan_units_pruned_stats", pruned_stats)
    if pruned_bloom:
        _metrics.event("plan_units_pruned_bloom", pruned_bloom)
    return ScanPlan(
        files,
        metas,
        units,
        skipped,
        units_total=units_total,
        units_pruned_stats=pruned_stats,
        units_pruned_bloom=pruned_bloom,
    )

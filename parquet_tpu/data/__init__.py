"""parquet_tpu.data — sharded, prefetching, checkpointable streaming datasets.

The scheduler/runtime layer a training or bulk-inference loop consumes:
multi-file plans (plan.py: footer-only planning, deterministic shard/shuffle
math) driven by a bounded prefetch-and-rebatch pipeline (dataset.py). See
ParquetDataset for the full contract.
"""

from .controller import AIMDController  # noqa: F401
from .dataset import DatasetIterator, ParquetDataset  # noqa: F401
from .plan import ScanPlan, Unit, build_plan, expand_paths  # noqa: F401

__all__ = [
    "ParquetDataset",
    "DatasetIterator",
    "AIMDController",
    "ScanPlan",
    "Unit",
    "build_plan",
    "expand_paths",
]

"""ParquetDataset: sharded, prefetching, checkpointable streaming batches.

The scheduler/runtime layer on top of the decode core — what a training or
bulk-inference job actually consumes. Every consumer used to hand-roll a
loop over `FileReader.read_row_group` on one file; this subsystem gives the
multi-file, multi-host, overlap-I/O-with-compute path:

    ds = ParquetDataset("shard-*.parquet", columns=["x", "y"],
                        batch_size=4096, shuffle=True, seed=7,
                        prefetch=2, on_error="skip")
    for batch in ds:                      # {leaf path: np.ndarray[4096, ...]}
        step(batch)

Semantics, in the order the pipeline applies them:

  plan      footers parse lazily (once per file); one work unit per
            (file, row group); `filters` prune units through the
            statistics/bloom path before any data page is read.
            `filter_rows=True` additionally masks INDIVIDUAL rows inside
            surviving groups with the vectorized filter engine (null mode
            "row": a null fails every value predicate), so batches hold
            only matching rows — the read set silently extends to cover
            filter-referenced columns, which are dropped again before
            delivery unless projected.
  shard     the epoch's unit order is a pure function of (seed, epoch),
            computed identically on every host, then striped over
            `shard_count * worker_count` slots — each unit visited by
            exactly one (process, worker) per epoch.
  prefetch  a bounded pool ("pqt-data" threads) decodes units k+1..k+depth
            while the consumer works on k's batches; depth 0 = fully
            synchronous. Wait time is always measured (dataset_wait_seconds
            histogram + dataset.wait trace stage): a starved loop is
            visible, not mysterious.
  rebatch   decoded row groups re-slice into fixed `batch_size` batches,
            remainders carrying ACROSS unit boundaries; the epoch tail
            follows `remainder=` ("drop" | "keep" | "pad").
  deliver   host numpy dicts by default; `device=` (a jax.Device or a
            Sharding) double-buffers `jax.device_put` so batch k+1's upload
            overlaps the consumer's step on k.
  resume    iter(ds) -> DatasetIterator with state_dict()/load_state_dict():
            (epoch, unit cursor, intra-unit row offset) — a resumed
            iterator reproduces the remaining batch stream byte-identically,
            mid-epoch, under sharding and shuffling.

Corruption follows FileReader's on_error policy per unit: with "skip" a
corrupt row group (or a file with an unreadable footer) drops with a counter
(dataset_units_skipped / dataset_files_skipped) and every clean unit still
arrives exactly once; "null" substitutes nulls where the schema allows
(pair it with nullable="zero"). Device-resident training jobs that would
rather die than silently lose rows keep the default "raise".
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core.arrays import ByteArrayData
from ..core.reader import PARQUET_ERRORS, FileReader
from ..meta.file_meta import ParquetFileError
from ..obs.log import log_event
from ..obs.pool import instrumented_submit
from ..obs.recorder import recorder as _recorder
from ..utils import metrics as _metrics
from ..utils.trace import bump, span, timed_stage
from .plan import ScanPlan, build_plan

__all__ = ["ParquetDataset", "DatasetIterator"]

_STATE_VERSION = 1

# The prefetch queue-depth gauge is process-wide (one Prometheus sample),
# while iterators are many and concurrent — each tracks its own delta here
# so the exposed value is the TOTAL in-flight unit count, not whichever
# iterator wrote last (a finishing iterator must not zero a live one's
# starvation signal).
_inflight_lock = threading.Lock()
_inflight_units = 0


def _inflight_add(n: int) -> None:
    global _inflight_units
    with _inflight_lock:
        _inflight_units += n
        _metrics.set_gauge("dataset_prefetch_depth", _inflight_units)


class ParquetDataset:
    """A multi-file Parquet scan shaped for training loops.

    Construction is cheap: footers parse on first use (iteration, or any
    plan-derived property). Iterating yields {leaf path tuple: np.ndarray}
    batches of exactly `batch_size` rows (tail per `remainder=`); with
    `device=` the arrays are device-resident jax arrays instead.
    """

    def __init__(
        self,
        paths_or_glob,
        *,
        batch_size: int,
        columns=None,
        filters=None,
        filter_rows: bool = False,
        shuffle: bool = False,
        seed: int = 0,
        num_epochs: int | None = 1,
        prefetch: int = 2,
        remainder: str = "drop",
        shard=None,
        worker=None,
        on_error: str = "raise",
        nullable: str = "error",
        validate_crc: bool = False,
        device=None,
        cache_bytes: int = 0,
        cache_disk_bytes: int = 0,
        cache_dir=None,
        block_cache=None,
        readahead_bytes: int | None = None,
        io_autotune: bool = False,
        slo_wait_ms: float | None = None,
        controller=None,
    ):
        if batch_size <= 0:
            raise ValueError("dataset: batch_size must be positive")
        if remainder not in ("drop", "keep", "pad"):
            raise ValueError(
                f'dataset: remainder must be "drop", "keep" or "pad", '
                f"got {remainder!r}"
            )
        if on_error not in ("raise", "skip", "null"):
            raise ValueError(
                f'dataset: on_error must be "raise", "skip" or "null", '
                f"got {on_error!r}"
            )
        if nullable not in ("error", "zero"):
            raise ValueError(
                f'dataset: nullable must be "error" or "zero", got {nullable!r}'
            )
        if on_error == "null" and nullable != "zero":
            raise ValueError(
                'dataset: on_error="null" delivers nulled chunks, which need '
                'nullable="zero" to batch'
            )
        if filter_rows and filters is None:
            raise ValueError("dataset: filter_rows=True requires filters")
        if num_epochs is not None and num_epochs < 0:
            raise ValueError("dataset: num_epochs must be >= 0 or None")
        if prefetch < 0:
            raise ValueError("dataset: prefetch depth must be >= 0")
        if cache_bytes < 0:
            raise ValueError("dataset: cache_bytes must be >= 0")
        if cache_disk_bytes < 0:
            raise ValueError("dataset: cache_disk_bytes must be >= 0")
        self.paths_or_glob = paths_or_glob
        self.batch_size = int(batch_size)
        self.columns = list(columns) if columns is not None else None
        self.filters = filters
        self.filter_rows = bool(filter_rows)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.num_epochs = num_epochs
        self.prefetch = int(prefetch)
        self.remainder = remainder
        self.on_error = on_error
        self.nullable = nullable
        self.validate_crc = bool(validate_crc)
        self.device = device
        si, sc = self._resolve_split(shard, "shard")
        wi, wc = self._resolve_split(worker, "worker")
        # one flat slot space: process-major, worker-minor — host p's worker
        # w owns stripe p*wc + w of sc*wc
        self.shard_index = si * wc + wi
        self.shard_count = sc * wc
        self._plan: ScanPlan | None = None
        self._plan_lock = threading.Lock()
        self._pool: ThreadPoolExecutor | None = None
        self._closed = False
        # IO layer: footers cache process-wide (validated per generation by
        # size+mtime for paths, size+ETag for URLs, so it is always safe);
        # cache_bytes > 0 adds a shared byte-budgeted block cache — unit
        # decodes read through it, repeat epochs hit memory, and the pqt-io
        # readahead scheduler streams the NEXT units' planned byte ranges
        # into it while pqt-data decodes the current window
        # (readahead_bytes bounds its in-flight budget, default =
        # cache_bytes / 4). cache_disk_bytes > 0 grows the block cache
        # into a RAM -> disk TieredCache spilling to cache_dir (a private
        # temp dir when None) — the remote-corpus shape, where the hot set
        # outlives RAM but a local disk beats the store by ~100x.
        # block_cache= passes a PRE-BUILT cache (BlockCache or
        # TieredCache, caller-owned) so co-resident consumers — a serve
        # daemon and its training loaders — pool ONE tier budget.
        # io_autotune=True resolves the coalesce gap per fetch (and
        # deepens the readahead budget) from the observed per-transport
        # latency profile (io/autotune.py): local corpora keep the 64 KiB
        # default, remote ones coalesce MiB-scale.
        from ..io.cache import BlockCache, shared_footer_cache
        from ..io.planner import Readahead
        from ..io.tiercache import TieredCache

        self._footer_cache = shared_footer_cache()
        self.io_autotune = bool(io_autotune)
        self._owns_cache = block_cache is None
        if block_cache is not None:
            self._block_cache = block_cache
        elif cache_disk_bytes:
            self._block_cache = TieredCache(
                ram_bytes=cache_bytes or (64 << 20),
                disk_bytes=cache_disk_bytes,
                cache_dir=cache_dir,
            )
        elif cache_bytes:
            self._block_cache = BlockCache(cache_bytes)
        else:
            self._block_cache = None
        self._readahead = (
            Readahead(
                self._block_cache,
                budget_bytes=(
                    readahead_bytes
                    if readahead_bytes is not None
                    else max(cache_bytes // 4, 1 << 20)
                ),
                autotune=self.io_autotune,
            )
            if self._block_cache is not None
            else None
        )
        # per-file parsed Schema cache: _load_unit opens one reader PER ROW
        # GROUP, and rebuilding the schema tree from thrift every unit is
        # pure waste when the footer is already cached on the plan
        self._schemas: dict[int, object] = {}
        # elastic SLO: slo_wait_ms attaches an AIMD controller that scales
        # prefetch depth / pqt-data workers / the readahead budget to keep
        # consumer waits under the SLO. Advisory only — it never touches
        # anything state_dict() depends on, so resume stays byte-identical.
        # A pre-built AIMDController (controller=) wins, letting tests
        # inject clocks and registries.
        if controller is not None:
            self._controller = controller
        elif slo_wait_ms is not None:
            from .controller import AIMDController

            self._controller = AIMDController(
                slo_wait_ms=slo_wait_ms,
                initial_depth=max(1, self.prefetch),
                max_depth=max(32, self.prefetch),
            )
        else:
            self._controller = None

    @staticmethod
    def _resolve_split(spec, what: str) -> tuple[int, int]:
        if spec is None:
            return 0, 1
        if spec == "jax":
            if what != "shard":
                # worker="jax" would square the process stripe into a
                # diagonal — (P-1)/P of all units visited by nobody
                raise ValueError(
                    'dataset: only shard= accepts "jax"; worker= is the '
                    "per-host sub-split and needs an explicit (index, count)"
                )
            # opt-in only: importing jax initializes the backend, which a
            # pure host data loader must never do implicitly
            import jax

            return jax.process_index(), jax.process_count()
        i, n = spec
        i, n = int(i), int(n)
        if n <= 0 or not 0 <= i < n:
            raise ValueError(f"dataset: bad {what} split ({i}, {n})")
        return i, n

    # -- plan ------------------------------------------------------------------

    @property
    def plan(self) -> ScanPlan:
        """The global unit plan (footers parse on first access)."""
        with self._plan_lock:
            if self._plan is None:
                plan = build_plan(
                    self.paths_or_glob,
                    filters=self.filters,
                    on_error=self.on_error,
                    footer_cache=self._footer_cache,
                )
                # Validate the projection ONCE against the first readable
                # schema, outside the skip policy: a misspelled columns=
                # entry is a configuration error — under on_error="skip" it
                # would otherwise quarantine every unit and deliver an
                # empty dataset with no error.
                if self.columns is not None:
                    for fi, meta in enumerate(plan.metas):
                        if meta is not None:
                            with FileReader(
                                plan.files[fi], columns=self.columns,
                                metadata=meta,
                            ):
                                pass
                            break
                self._plan = plan
            return self._plan

    def _selected_leaf_paths(self, file_index: int):
        """The projection as leaf path tuples for one plan file (None = all
        columns) — what the io planner needs to compute a unit's exact byte
        ranges for readahead. Best-effort: resolution failures return None
        (readahead fetches everything; decode still raises the precise
        error)."""
        if self.columns is None:
            return None
        try:
            schema = self._file_schema(file_index)
        except Exception:  # noqa: BLE001 — advisory path only
            return None
        selected = set()
        for c in self.columns:
            path = tuple(c.split(".")) if isinstance(c, str) else tuple(c)
            selected.update(
                leaf.path
                for leaf in schema.leaves
                if leaf.path[: len(path)] == path
            )
        return selected or None

    def _unit_ranges(self, unit) -> list:
        """The planned (offset, length) byte ranges of one unit under the
        dataset's projection (readahead's shopping list)."""
        from ..io.planner import plan_ranges

        meta = self.plan.metas[unit.file_index]
        if meta is None:
            return []
        return plan_ranges(
            meta,
            row_groups=[unit.row_group],
            columns=self._selected_leaf_paths(unit.file_index),
        )

    def _file_schema(self, file_index: int):
        """The parsed Schema of one plan file (cached; footers come from
        the plan, so each file's schema tree builds exactly once no matter
        how many row groups stream from it)."""
        s = self._schemas.get(file_index)
        if s is None:
            from ..core.schema import Schema

            s = Schema.from_thrift(self.plan.metas[file_index].schema)
            # benign race: two workers may build the same schema; last
            # write wins and both values are equivalent
            self._schemas[file_index] = s
        return s

    @property
    def total_rows(self) -> int:
        """Rows the footers promise across ALL shards (before any on_error
        skipping at decode time)."""
        return self.plan.total_rows

    def epoch_order(self, epoch: int) -> list[int]:
        """This shard's unit visit order for `epoch` (plan unit indices)."""
        return self.plan.epoch_order(
            epoch,
            seed=self.seed,
            shuffle=self.shuffle,
            shard_index=self.shard_index,
            shard_count=self.shard_count,
        )

    # -- prefetch pool ---------------------------------------------------------

    def _worker_pool(self) -> ThreadPoolExecutor:
        """The dataset's own bounded decode pool ("pqt-data", sized
        min(prefetch, PQT_DATA_THREADS or cpu)). Deliberately SEPARATE from
        the chunk-prepare pool: unit-level tasks that internally fan out
        chunk work into the same pool they run in would deadlock once the
        pool saturates."""
        with self._plan_lock:
            if self._closed:
                raise RuntimeError("dataset: closed")
            if self._pool is None:
                env = os.environ.get("PQT_DATA_THREADS")
                cap = int(env) if env else (os.cpu_count() or 1)
                if self._controller is not None:
                    workers = self._controller.worker_target
                else:
                    workers = max(1, min(self.prefetch, cap))
                self._pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="pqt-data"
                )
            return self._pool

    def _apply_controller_targets(self) -> None:
        """Push the SLO controller's current targets onto the pool and the
        readahead scheduler (called from the fetch loop after each control
        tick). Worker growth takes effect on the next submit (the executor
        spawns threads lazily up to _max_workers); shrink is lazy — extra
        idle workers just park, and actual concurrency is already bounded
        by the prefetch window."""
        ctl = self._controller
        if ctl is None:
            return
        pool = self._pool
        if pool is not None:
            w = ctl.worker_target
            # _max_workers is the executor's documented-by-use sizing knob;
            # there is no public resize API in the stdlib
            if w != pool._max_workers:
                pool._max_workers = w
        if self._readahead is not None:
            self._readahead.budget_bytes = ctl.readahead_budget

    def close(self) -> None:
        """Shut the prefetch pool down (idempotent). The dataset and its
        iterators stop being usable: further iteration raises instead of
        silently resurrecting an untracked worker pool. The readahead
        scheduler stops accepting work and cancels queued fetches (running
        ones finish — they touch only the shared cache, never the pools
        being torn down)."""
        with self._plan_lock:
            self._closed = True
            pool, self._pool = self._pool, None
        if self._readahead is not None:
            self._readahead.close()
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        # a tiered cache the DATASET built owns its spill files; a passed
        # block_cache= belongs to the caller (it may be the daemon's)
        if self._owns_cache and hasattr(self._block_cache, "close"):
            self._block_cache.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- iteration -------------------------------------------------------------

    def __iter__(self) -> "DatasetIterator":
        if self._closed:
            raise RuntimeError("dataset: closed")
        return DatasetIterator(self)

    def iterator(self, state: dict | None = None) -> "DatasetIterator":
        """A fresh iterator, optionally resumed from a state_dict()."""
        it = iter(self)
        if state is not None:
            it.load_state_dict(state)
        return it


class DatasetIterator:
    """One pass (or N epochs) over a ParquetDataset's shard of the plan.

    Checkpointable: state_dict() captures (epoch, unit cursor, intra-unit
    row offset) AS OF THE BATCHES ALREADY DELIVERED — load_state_dict() on a
    fresh iterator reproduces the remaining batch stream byte-identically.
    """

    def __init__(self, dataset: ParquetDataset):
        self._ds = dataset
        self._epoch = 0
        self._pos = 0  # epoch-order position of the next row to deliver
        self._off = 0  # row offset within that unit
        self._exhausted = False
        self._started = False
        self._dtypes: dict | None = None  # cross-file schema consistency
        self._gen = None

    # -- checkpointing ---------------------------------------------------------

    def state_dict(self) -> dict:
        """Resume point covering every batch already delivered."""
        ds = self._ds
        return {
            "version": _STATE_VERSION,
            "epoch": self._epoch,
            "unit_pos": self._pos,
            "row_offset": self._off,
            "exhausted": self._exhausted,
            "seed": ds.seed,
            "shuffle": ds.shuffle,
            "batch_size": ds.batch_size,
            "remainder": ds.remainder,
            "shard": [ds.shard_index, ds.shard_count],
            "plan": ds.plan.fingerprint(),
        }

    def load_state_dict(self, state: dict) -> None:
        """Position this (not-yet-started) iterator at a checkpoint.

        The configuration a cursor's meaning depends on must match: the
        epoch permutation (seed/shuffle), the stripe (shard), the batch
        grid (batch_size/remainder) and the plan itself. Anything else
        (prefetch depth, device, worker pool size) is free to differ —
        it affects speed, never the stream."""
        if self._started:
            raise RuntimeError(
                "dataset: load_state_dict on a started iterator (make a "
                "fresh one)"
            )
        if state.get("version") != _STATE_VERSION:
            raise ValueError(
                f"dataset: unknown checkpoint version {state.get('version')!r}"
            )
        ds = self._ds
        for key, ours in (
            ("seed", ds.seed),
            ("shuffle", ds.shuffle),
            ("batch_size", ds.batch_size),
            ("remainder", ds.remainder),
            ("shard", [ds.shard_index, ds.shard_count]),
            ("plan", ds.plan.fingerprint()),
        ):
            if state.get(key) != ours:
                raise ValueError(
                    f"dataset: checkpoint {key} mismatch "
                    f"({state.get(key)!r} != {ours!r}); the cursor would "
                    "not mean the same stream"
                )
        self._epoch = int(state["epoch"])
        self._pos = int(state["unit_pos"])
        self._off = int(state["row_offset"])
        self._exhausted = bool(state.get("exhausted", False))

    # -- iteration -------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        if self._gen is None:
            self._started = True
            self._gen = self._stream()
        try:
            batch, state = next(self._gen)
        except StopIteration:
            self._exhausted = True
            raise
        # commit ONLY at delivery: with device put-pipelining, batches ahead
        # of the consumer are in flight — a checkpoint must not cover them
        self._epoch, self._pos, self._off = state
        return batch

    def close(self) -> None:
        """Abandon the iterator: queued (not yet running) prefetch work is
        cancelled; running unit decodes finish and are dropped."""
        gen, self._gen = self._gen, None
        self._exhausted = True
        if gen is not None:
            gen.close()

    # -- internals -------------------------------------------------------------

    def _stream(self):
        """(batch, state-after-batch) pairs, device-put-pipelined when the
        dataset is device-destined."""
        gen = self._batches()
        placement = self._ds.device
        if placement is None:
            yield from gen
            return
        from ..kernels.pipeline import device_put_pipelined

        states: deque = deque()

        def host_side():
            for b, s in gen:
                states.append(s)  # appended before the yield: stays aligned
                yield b

        for db in device_put_pipelined(
            host_side(), placement=placement, depth=2,
            stage_name="dataset.device_put",
        ):
            yield db, states.popleft()

    def _batches(self):
        ds = self._ds
        B = ds.batch_size
        epoch, pos, off = self._epoch, self._pos, self._off
        while ds.num_epochs is None or epoch < ds.num_epochs:
            order = ds.epoch_order(epoch)
            pending: deque = deque()  # [upos, base, cols, consumed, n]
            buffered = 0
            fetch = self._fetch_units(order, pos, off)
            try:
                for upos, base, cols, n in fetch:
                    self._check_template(cols)
                    pending.append([upos, base, cols, 0, n])
                    buffered += n
                    while buffered >= B:
                        batch, buffered, resume_pos, resume_off = self._emit(
                            pending, buffered, B
                        )
                        yield batch, (epoch, resume_pos, resume_off)
            finally:
                # closing the iterator mid-epoch must release the fetch
                # pipeline's in-flight accounting NOW — relying on GC to
                # close the sub-generator leaves the prefetch-depth gauge
                # stuck until an arbitrary later collection
                fetch.close()
            if buffered and ds.remainder != "drop":
                batch, _, _, _ = self._emit(pending, buffered, buffered)
                if ds.remainder == "pad" and buffered < B:
                    batch = {
                        p: _pad_rows(a, B) for p, a in batch.items()
                    }
                yield batch, (epoch + 1, 0, 0)
            epoch += 1
            pos = 0
            off = 0

    def _emit(self, pending: deque, buffered: int, take: int):
        """Assemble one `take`-row batch from the buffered spans; returns
        (batch, remaining buffered rows, cursor pos, cursor off)."""
        parts: dict[tuple, list] = {}
        need = take
        last_upos = -1
        while need:
            e = pending[0]
            upos, base, cols, consumed, n = e
            chunk = min(need, n - consumed)
            for p, a in cols.items():
                parts.setdefault(p, []).append(a[consumed : consumed + chunk])
            e[3] = consumed + chunk
            need -= chunk
            last_upos = upos
            if e[3] == n:
                pending.popleft()
        batch = {
            p: (ps[0] if len(ps) == 1 else np.concatenate(ps))
            for p, ps in parts.items()
        }
        if pending:
            head = pending[0]
            cursor = (head[0], head[1] + head[3])
        else:
            cursor = (last_upos + 1, 0)
        _metrics.inc("dataset_batches_total")
        _metrics.inc("dataset_rows_total", take)
        return batch, buffered - take, cursor[0], cursor[1]

    def _check_template(self, cols: dict) -> None:
        """Cross-file consistency: every unit must deliver the same columns
        with the same dtype/trailing shape, or concatenation would silently
        upcast (or crash deep in numpy with no file context)."""
        tmpl = {p: (a.dtype, a.shape[1:]) for p, a in cols.items()}
        if self._dtypes is None:
            self._dtypes = tmpl
            return
        if tmpl != self._dtypes:
            raise ParquetFileError(
                f"dataset: unit schema mismatch: {tmpl} != {self._dtypes} "
                "(files in one dataset must agree on columns and types)"
            )

    # -- unit fetch (the bounded prefetch pipeline) ----------------------------

    def _fetch_units(self, order: list[int], start_pos: int, start_off: int):
        """Yield (order position, base row offset, column arrays, rows) for
        every unit from start_pos on that delivers rows, in order, decoding
        up to `prefetch` units ahead on the pqt-data pool."""
        ds = self._ds
        units = ds.plan.units
        ctl = ds._controller
        depth = ds.prefetch if ctl is None else ctl.prefetch_target
        if depth <= 0:
            for k in range(start_pos, len(order)):
                off = start_off if k == start_pos else 0
                # the synchronous path waits for the WHOLE decode: record
                # it, or wait_share would read 0% exactly when the consumer
                # is 100% decode-bound (the tuning signal inverted)
                with timed_stage("dataset.wait") as w:
                    cols, n = self._load_unit(units[order[k]], off)
                _metrics.observe("dataset_wait_seconds", w.seconds)
                if cols is not None and n > 0:
                    yield k, off, cols, n
            return
        pool = ds._worker_pool()
        pending: deque = deque()
        nxt = start_pos
        ra_scheduled: set[int] = set()

        def readahead():
            # one IO stage ahead of decode: while pqt-data decodes the
            # window [start..nxt), pqt-io streams the NEXT units' planned
            # byte ranges into the shared block cache (advisory: budget
            # overflow drops, decode reads through either way)
            if ds._readahead is None:
                return
            for j in range(nxt, min(nxt + max(depth, 1), len(order))):
                if j in ra_scheduled:
                    continue
                ra_scheduled.add(j)
                unit = units[order[j]]
                ranges = ds._unit_ranges(unit)
                if ranges:
                    ds._readahead.schedule(unit.path, ranges)

        def fill():
            nonlocal nxt, depth
            if ctl is not None:
                # re-read the target each refill: the controller moves it
                # between batches, and the window tracks it immediately —
                # up (more submits now) or down (drain to the new bound)
                depth = ctl.prefetch_target
            added = 0
            while nxt < len(order) and len(pending) < depth:
                off = start_off if nxt == start_pos else 0
                pending.append(
                    (nxt, off, instrumented_submit(pool, self._load_unit,
                                                   units[order[nxt]], off,
                                                   pool="pqt-data"))
                )
                nxt += 1
                added += 1
            if added:
                _inflight_add(added)
            readahead()

        fill()
        try:
            while pending:
                k, off, fut = pending.popleft()
                try:
                    with timed_stage("dataset.wait") as w:
                        cols, n = fut.result()
                finally:
                    _inflight_add(-1)  # popped units always leave the gauge
                _metrics.observe("dataset_wait_seconds", w.seconds)
                if ctl is not None and ctl.tick():
                    ds._apply_controller_targets()
                fill()
                if cols is not None and n > 0:
                    yield k, off, cols, n
        finally:
            if pending:
                _inflight_add(-len(pending))
            for _k, _o, fut in pending:
                fut.cancel()

    def _load_unit(self, unit, row_offset: int):
        """Decode one (file, row group) into batchable column arrays,
        sliced from `row_offset`. Runs on pqt-data worker threads (the trace
        and log context arrive via instrumented_submit). Returns (None, 0)
        for a unit the on_error policy dropped."""
        ds = self._ds
        t0 = time.perf_counter()

        def _skipped(reason: str):
            # the noteworthy event (rate-limited) + the flight record: one
            # /v1/debug listing shows the skipped unit next to the serve
            # traffic that may have been racing it
            bump("dataset_units_skipped")
            log_event(
                "unit_quarantined", level="warning",
                file=unit.path, group=unit.row_group, reason=reason,
            )
            _recorder().record(
                "dataset.unit", status="skipped",
                duration_s=time.perf_counter() - t0,
                detail={"file": unit.path, "group": unit.row_group,
                        "reason": reason},
            )
            return None, 0

        with span(
            "dataset.unit", {"file": unit.path, "group": unit.row_group}
        ):
            try:
                reader = FileReader(
                    unit.path,
                    columns=ds.columns,
                    metadata=ds.plan.metas[unit.file_index],
                    schema=ds._file_schema(unit.file_index),
                    validate_crc=ds.validate_crc,
                    on_error=ds.on_error,
                    block_cache=ds._block_cache,
                    coalesce_gap="auto" if ds.io_autotune else None,
                )
            except PARQUET_ERRORS + (OSError,):
                if ds.on_error == "raise":
                    raise
                return _skipped("open_failed")
            try:
                read_cols = None
                normalized = None
                if ds.filter_rows:
                    # extend the read set to cover filter leaves; the
                    # projection (reader._selected) prunes them back out
                    # below so filter-only columns never need a batch form
                    from ..core.filter import normalize_dnf

                    normalized = normalize_dnf(reader.schema, ds.filters)
                    read_cols = reader._columns_with_filters(
                        ds.columns, normalized
                    )
                chunks = reader._read_row_group(
                    unit.row_group, read_cols, pack=False
                )
                if not chunks:
                    # quarantined by on_error (or empty selection)
                    return _skipped("quarantined")
                mask = None
                if normalized is not None:
                    # a VecFilterError here is a deterministic shape decline
                    # (it would quarantine EVERY unit) — always a raise, no
                    # on_error swallowing
                    from ..core.filter_vec import dnf_mask

                    nrows = int(
                        reader.row_group(unit.row_group).num_rows or 0
                    )
                    mask = dnf_mask(chunks, normalized, nrows)
                keep = reader._selected
                cols = {
                    p: self._batch_array(p, cd, reader.schema.column(p))
                    for p, cd in chunks.items()
                    if keep is None or p in keep
                }
            except OSError:
                # transport failure mid-decode (a retry ladder exhausted,
                # a circuit breaker fast-failing a blacked-out source):
                # under "skip"/"null" the unit quarantines exactly like a
                # corrupt one — the stream degrades in typed, counted
                # steps instead of killing the train loop
                if ds.on_error == "raise":
                    raise
                return _skipped("io_failed")
            finally:
                reader.close()
        lens = {a.shape[0] for a in cols.values()}
        if len(lens) != 1:
            raise ParquetFileError(
                f"dataset: columns disagree on row count in "
                f"{unit.path} group {unit.row_group}: {sorted(lens)}"
            )
        n = lens.pop()
        if mask is not None and not mask.all():
            # row filtering happens BEFORE the resume offset: row_offset
            # counts positions in the FILTERED stream, so a resumed
            # iterator replays byte-identically whether or not the
            # original run filtered
            bump("dataset_units_row_filtered")
            cols = {p: a[mask] for p, a in cols.items()}
            n = int(mask.sum())
            if not n:
                return None, 0
        if row_offset:
            if row_offset >= n:
                return None, 0
            cols = {p: a[row_offset:] for p, a in cols.items()}
            n -= row_offset
        _recorder().record(
            "dataset.unit",
            duration_s=time.perf_counter() - t0,
            nbytes=sum(int(a.nbytes) for a in cols.values()),
            detail={"file": unit.path, "group": unit.row_group, "rows": n},
        )
        return cols, n

    def _batch_array(self, path, cd, leaf) -> np.ndarray:
        """One decoded chunk -> a row-aligned numpy array (the host-side
        analogue of iter_device_batches' _array_of)."""
        name = ".".join(path)
        if cd.rep_levels is not None or leaf.max_rep > 0:
            raise ParquetFileError(
                f"dataset: column {name} is repeated; its leaf slots are "
                "not rows, so it cannot batch (project it out)"
            )
        values = cd.values
        if isinstance(values, ByteArrayData):
            raise ParquetFileError(
                f"dataset: column {name} is a raw byte array with no fixed-"
                "width batch form (project it out, or encode it as a "
                "fixed-size or integer feature upstream)"
            )
        arr = np.asarray(values)
        n = cd.num_values
        if arr.shape[0] != n:  # nulls: values are dense non-null cells
            if self._ds.nullable != "zero":
                raise ParquetFileError(
                    f"dataset: column {name} contains nulls; pass "
                    'nullable="zero" to zero-fill them (or filter upstream)'
                )
            valid = np.asarray(cd.def_levels) == leaf.max_def
            out = np.zeros((n,) + arr.shape[1:], dtype=arr.dtype)
            out[valid] = arr
            arr = out
        return arr


def _pad_rows(a, target: int):
    """Zero-pad the leading axis to `target` rows (remainder="pad")."""
    if a.shape[0] >= target:
        return a
    pad = np.zeros((target - a.shape[0],) + a.shape[1:], dtype=a.dtype)
    return np.concatenate([a, pad])

from .int96 import (  # noqa: F401
    datetime_to_int96,
    int96_to_datetime,
    int96_to_unix_nanos,
    is_after_unix_epoch,
)

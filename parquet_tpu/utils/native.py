"""ctypes loader for the optional native C++ helper library (native/).

The native library accelerates the host-side scalar hot spots that neither
NumPy nor the TPU can absorb: snappy (de)compression, PLAIN byte_array offset
scans, and hybrid/delta run-header prescans. Everything degrades gracefully to
the pure-Python implementations when the library is not built.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

_SO_NAMES = ("libparquet_tpu_native.so",)
_cached = None
_probed = False


class NativeLib:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self.has_snappy = hasattr(lib, "ptq_snappy_compress")
        if self.has_snappy:
            lib.ptq_snappy_max_compressed_length.restype = ctypes.c_size_t
            lib.ptq_snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
            lib.ptq_snappy_compress.restype = ctypes.c_ssize_t
            lib.ptq_snappy_compress.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.ptq_snappy_decompress.restype = ctypes.c_ssize_t
            lib.ptq_snappy_decompress.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
        self.has_byte_array_scan = hasattr(lib, "ptq_scan_byte_array_offsets")
        if self.has_byte_array_scan:
            lib.ptq_scan_byte_array_offsets.restype = ctypes.c_ssize_t
            lib.ptq_scan_byte_array_offsets.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]

    def snappy_compress(self, data: bytes) -> bytes:
        cap = self._lib.ptq_snappy_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(cap)
        n = self._lib.ptq_snappy_compress(data, len(data), out, cap)
        if n < 0:
            raise ValueError("native snappy: compression failed")
        return out.raw[:n]

    def snappy_decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        out = ctypes.create_string_buffer(max(uncompressed_size, 1))
        n = self._lib.ptq_snappy_decompress(data, len(data), out, uncompressed_size)
        if n < 0:
            raise ValueError("native snappy: corrupt input")
        return out.raw[:n]


def get_native() -> NativeLib | None:
    """Load the native helper library, or None if not built/loadable."""
    global _cached, _probed
    if _probed:
        return _cached
    _probed = True
    root = Path(__file__).resolve().parent.parent.parent
    candidates = [root / "native" / "build" / name for name in _SO_NAMES]
    env = os.environ.get("PARQUET_TPU_NATIVE")
    if env:
        candidates.insert(0, Path(env))
    for cand in candidates:
        if cand.exists():
            try:
                _cached = NativeLib(ctypes.CDLL(str(cand)))
                break
            except OSError:
                continue
    return _cached

"""ctypes loader for the optional native C++ helper library (native/).

The native library accelerates the host-side scalar hot spots that neither
NumPy nor the TPU can absorb: snappy (de)compression, PLAIN byte_array offset
scans, and hybrid/delta run-header prescans. Everything degrades gracefully to
the pure-Python implementations when the library is not built.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import NamedTuple

_SO_NAMES = ("libparquet_tpu_native.so",)
_cached = None
_probed = False

# ptq_chunk_prepare err_info[0] stage codes (parquet_tpu_native.h PTQ_STAGE_*).
PREPARE_STAGES = {
    0: "none",
    1: "header",
    2: "crc",
    3: "decompress",
    4: "levels",
    5: "prescan",
    6: "values",
}

# ptq_chunk_prepare terminal return codes (parquet_tpu_native.h PTQ_E_*).
PREPARE_E_CORRUPT = -1
PREPARE_E_CAPACITY = -5
PREPARE_E_CRC = -6

# ptq_chunk_encode err_info[0] stage codes (parquet_tpu_native.h PTQ_ENC_STAGE_*).
ENCODE_STAGES = {
    0: "none",
    1: "split",
    2: "levels",
    3: "values",
    4: "compress",
    5: "frame",
}


def hybrid_encode_cap(n: int, width: int) -> int:
    """Worst-case hybrid RLE/bit-pack stream size for n values at `width`
    bits — the ONE sizing formula behind hybrid_encode's output buffer and
    the fused encode walk's capacity planning (a drifted copy would turn
    into silent -5 capacity faults and a quiet staged fallback)."""
    vbytes = (width + 7) // 8
    return 64 + (n // 8 + 2) * (5 + vbytes) + ((n + 7) // 8) * max(width, 1)


def delta_encode_cap(
    n: int, nbits: int, block_size: int = 128, mini_count: int = 4
) -> int:
    """Worst-case DELTA_BINARY_PACKED size: header + per-block zigzag +
    widths + payloads at full width (shared by delta_encode and the fused
    encode walk's capacity planning)."""
    blocks = max(n // block_size + 2, 1)
    return (
        64
        + blocks * (10 + mini_count)
        + ((n + block_size) * nbits) // 8
        + block_size
    )


class EncodeFault(NamedTuple):
    """Structured failure report from the fused native chunk encode: the
    negative return code plus the stage/page context. NOT an exception —
    encode_chunk's fallback ladder retries the chunk on the staged Python
    encoder, which raises the exact typed error if the input is genuinely
    unencodable."""

    code: int
    stage: str
    page: int


class PrepareFault(NamedTuple):
    """Structured failure report from the fused native chunk walk: the
    negative return code (PREPARE_E_*) plus the stage/page/byte-offset
    context the walk recorded when it aborted. NOT an exception — the
    pipeline's fallback ladder retries the chunk on the staged Python walk,
    which raises the exact typed error if the input is genuinely corrupt."""

    code: int
    stage: str
    page: int
    offset: int


def _ptr(data):
    """(address, length, keepalive) for any contiguous readable buffer.

    Lets the hot-path wrappers accept bytes, bytearray, memoryview, or numpy
    arrays without the `bytes(data)` copy a c_char_p signature would force
    (decompressed pages are ~1 MiB each; those copies were measurable).
    """
    import numpy as np

    if isinstance(data, bytes):
        # ctypes converts bytes to a char pointer for c_void_p params directly
        return data, len(data), data
    arr = np.frombuffer(data, dtype=np.uint8)
    return arr.ctypes.data, arr.size, arr


class NativeLib:
    def __init__(self, lib: ctypes.CDLL):
        import threading

        self._lib = lib
        self._chunk_tl = threading.local()  # per-thread chunk_prepare scratch
        self.has_snappy = hasattr(lib, "ptq_snappy_compress")
        if self.has_snappy:
            lib.ptq_snappy_max_compressed_length.restype = ctypes.c_size_t
            lib.ptq_snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
            lib.ptq_snappy_compress.restype = ctypes.c_ssize_t
            lib.ptq_snappy_compress.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            lib.ptq_snappy_decompress.restype = ctypes.c_ssize_t
            lib.ptq_snappy_decompress.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_lz4 = hasattr(lib, "ptq_lz4_compress")
        if self.has_lz4:
            lib.ptq_lz4_max_compressed_length.restype = ctypes.c_size_t
            lib.ptq_lz4_max_compressed_length.argtypes = [ctypes.c_size_t]
            for fn in (
                lib.ptq_lz4_compress,
                lib.ptq_lz4_decompress,
                lib.ptq_lz4_hadoop_decompress,
            ):
                fn.restype = ctypes.c_ssize_t
                fn.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                ]
        self.has_xxh64 = hasattr(lib, "ptq_xxh64")
        if self.has_xxh64:
            lib.ptq_xxh64.restype = ctypes.c_uint64
            lib.ptq_xxh64.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_uint64,
            ]
            lib.ptq_xxh64_fixed.restype = None
            lib.ptq_xxh64_fixed.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.c_void_p,
            ]
            lib.ptq_xxh64_offsets.restype = None
            lib.ptq_xxh64_offsets.argtypes = [ctypes.c_void_p] * 2 + [
                ctypes.c_int64,
                ctypes.c_void_p,
            ]
            lib.ptq_bloom_insert.restype = None
            lib.ptq_bloom_insert.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int64,
            ]
            lib.ptq_bloom_check.restype = None
            lib.ptq_bloom_check.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
            ]
        self.has_byte_array_scan = hasattr(lib, "ptq_byte_array_gather")
        if self.has_byte_array_scan:
            lib.ptq_byte_array_gather.restype = ctypes.c_ssize_t
            lib.ptq_byte_array_gather.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_hybrid_decode = hasattr(lib, "ptq_hybrid_decode")
        if self.has_hybrid_decode:
            lib.ptq_hybrid_decode.restype = ctypes.c_ssize_t
            lib.ptq_hybrid_decode.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
        self.has_delta_decode = hasattr(lib, "ptq_delta_decode")
        if self.has_delta_decode:
            lib.ptq_delta_decode.restype = ctypes.c_ssize_t
            lib.ptq_delta_decode.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.ptq_delta_peek_total.restype = ctypes.c_ssize_t
            lib.ptq_delta_peek_total.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        self.has_bytearray_take = hasattr(lib, "ptq_bytearray_take")
        if self.has_bytearray_take:
            lib.ptq_bytearray_take.restype = ctypes.c_ssize_t
            lib.ptq_bytearray_take.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_plain_encode_ba = hasattr(lib, "ptq_plain_encode_bytearray")
        if self.has_plain_encode_ba:
            lib.ptq_plain_encode_bytearray.restype = ctypes.c_ssize_t
            lib.ptq_plain_encode_bytearray.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_prescan_delta = hasattr(lib, "ptq_prescan_delta_packed")
        if self.has_prescan_delta:
            lib.ptq_prescan_delta_packed.restype = ctypes.c_ssize_t
            lib.ptq_prescan_delta_packed.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
        self.has_parse_page_header = hasattr(lib, "ptq_parse_page_header")
        if self.has_parse_page_header:
            lib.ptq_parse_page_header.restype = ctypes.c_ssize_t
            lib.ptq_parse_page_header.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        self.has_prescan_hybrid = hasattr(lib, "ptq_prescan_hybrid")
        if self.has_prescan_hybrid:
            lib.ptq_prescan_hybrid.restype = ctypes.c_ssize_t
            lib.ptq_prescan_hybrid.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        self.has_hybrid_encode = hasattr(lib, "ptq_hybrid_encode")
        if self.has_hybrid_encode:
            lib.ptq_hybrid_encode.restype = ctypes.c_ssize_t
            lib.ptq_hybrid_encode.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_delta_encode = hasattr(lib, "ptq_delta_encode")
        if self.has_delta_encode:
            lib.ptq_delta_encode.restype = ctypes.c_ssize_t
            lib.ptq_delta_encode.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_bytes_dict = hasattr(lib, "ptq_bytes_dict_indices")
        if self.has_bytes_dict:
            lib.ptq_bytes_dict_indices.restype = ctypes.c_ssize_t
            lib.ptq_bytes_dict_indices.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
        self.has_bytes_minmax = hasattr(lib, "ptq_bytes_minmax")
        if self.has_bytes_minmax:
            lib.ptq_bytes_minmax.restype = ctypes.c_ssize_t
            lib.ptq_bytes_minmax.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
            ]
        self.has_u64_dict = hasattr(lib, "ptq_u64_dict_indices")
        if self.has_u64_dict:
            lib.ptq_u64_dict_indices.restype = ctypes.c_ssize_t
            lib.ptq_u64_dict_indices.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
        self.has_gzip_encode = hasattr(lib, "ptq_gzip_compress")
        if self.has_gzip_encode:
            lib.ptq_gzip_compress.restype = ctypes.c_ssize_t
            lib.ptq_gzip_compress.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        self.has_chunk_encode = hasattr(lib, "ptq_chunk_encode")
        if self.has_chunk_encode:
            lib.ptq_chunk_encode.restype = ctypes.c_ssize_t
            lib.ptq_chunk_encode.argtypes = (
                [ctypes.c_int]  # route
                + [ctypes.c_void_p, ctypes.c_size_t]  # values
                + [ctypes.c_void_p, ctypes.c_int64]  # ba_offsets, nv
                + [ctypes.c_int, ctypes.c_int]  # type_size, dict_width
                + [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int64]  # dict
                + [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int]  # def levels
                # codec, dpv, with_crc
                + [ctypes.c_int] * 3
                + [ctypes.c_int64]  # per_page
                + [ctypes.c_void_p, ctypes.c_size_t] * 2  # out, scratch
                + [ctypes.c_void_p, ctypes.c_size_t]  # pages
                + [ctypes.c_void_p] * 3  # totals, stage_ns, err_info
            )
        self.has_chunk_prepare = hasattr(lib, "ptq_chunk_prepare")
        if self.has_chunk_prepare:
            lib.ptq_chunk_prepare.restype = ctypes.c_ssize_t
            lib.ptq_chunk_prepare.argtypes = (
                [ctypes.c_void_p, ctypes.c_size_t]  # src
                # codec, validate_crc, max_def, max_rep, type_size, delta_nbits
                + [ctypes.c_int] * 6
                + [ctypes.c_int64]  # expected_values
                + [ctypes.c_void_p, ctypes.c_size_t]  # pages
                + [ctypes.c_void_p, ctypes.c_void_p]  # def_out, rep_out
                + [ctypes.c_void_p, ctypes.c_size_t] * 4  # values/packed/delta/scratch
                + [ctypes.c_void_p] * 4 + [ctypes.c_size_t]  # hybrid tables
                + [ctypes.c_void_p] * 4 + [ctypes.c_size_t]  # delta tables
                + [ctypes.c_void_p]  # totals
                + [ctypes.c_void_p]  # stage_ns (nullable per-stage clock)
                + [ctypes.c_void_p]  # err_info (nullable int64[4])
            )
        # The CPython-extension binding of the same walk: one call, every
        # buffer through the buffer protocol, the whole walk under
        # Py_BEGIN_ALLOW_THREADS. Preferred over ctypes when built — ctypes
        # marshals ~30 arguments under the GIL per call; the extension
        # binds them in C. Falls back transparently when the extension is
        # absent (ctypes also drops the GIL during the foreign call, so
        # multi-thread prepare stays correct either way, just slower).
        self._ext_chunk_prepare = None
        self._ext_chunk_encode = None
        if self.has_chunk_prepare or self.has_chunk_encode:
            try:
                from .. import _native_ext as _ext

                self._ext_chunk_prepare = getattr(_ext, "chunk_prepare", None)
                self._ext_chunk_encode = getattr(_ext, "chunk_encode", None)
            except ImportError:
                pass
        self.fused_gil_free = self._ext_chunk_prepare is not None

    def snappy_compress(self, data) -> bytes:
        addr, n_in, _keep = _ptr(data)
        cap = self._lib.ptq_snappy_max_compressed_length(n_in)
        out = ctypes.create_string_buffer(cap)
        n = self._lib.ptq_snappy_compress(addr, n_in, out, cap)
        if n < 0:
            raise ValueError("native snappy: compression failed")
        return out.raw[:n]

    def snappy_decompress(self, data, uncompressed_size: int):
        """Returns a memoryview over a freshly decoded buffer (no memset, no
        trailing copy — the hot path of every snappy page)."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        # 64 bytes of slack past the logical size switches the decoder into
        # its overshooting-wide-copy fast mode; the view below hides it
        out = np.empty(max(uncompressed_size, 1) + 64, dtype=np.uint8)
        n = self._lib.ptq_snappy_decompress(
            addr, n_in, ctypes.c_void_p(out.ctypes.data), uncompressed_size + 64
        )
        # n > uncompressed_size: the stream's own length claim exceeded the
        # page header's — corrupt (the pre-slack cap check used to catch it)
        if n < 0 or n > uncompressed_size:
            raise ValueError("native snappy: corrupt input")
        return memoryview(out)[:n]

    def lz4_compress(self, data) -> bytes:
        """One raw LZ4 block (no framing, no size prefix)."""
        addr, n_in, _keep = _ptr(data)
        cap = self._lib.ptq_lz4_max_compressed_length(n_in)
        out = ctypes.create_string_buffer(cap)
        n = self._lib.ptq_lz4_compress(addr, n_in, out, cap)
        if n < 0:
            raise ValueError("native lz4: compression failed")
        return out.raw[:n]

    def lz4_decompress(self, data, uncompressed_size: int, hadoop: bool = False):
        """Decode one raw LZ4 block; hadoop=True also accepts the Hadoop
        [BE usize][BE csize] framing parquet's legacy LZ4 codec uses."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        out = np.empty(max(uncompressed_size, 1), dtype=np.uint8)
        fn = (
            self._lib.ptq_lz4_hadoop_decompress
            if hadoop
            else self._lib.ptq_lz4_decompress
        )
        n = fn(addr, n_in, ctypes.c_void_p(out.ctypes.data), uncompressed_size)
        if n < 0:
            raise ValueError("native lz4: corrupt input")
        return memoryview(out)[:n]

    def xxh64(self, data, seed: int = 0) -> int:
        addr, n, _keep = _ptr(data)
        return int(self._lib.ptq_xxh64(addr, n, seed))

    def xxh64_fixed(self, data, n: int, stride: int):
        import numpy as np

        addr, _nb, _keep = _ptr(data)
        out = np.empty(n, dtype=np.uint64)
        self._lib.ptq_xxh64_fixed(addr, n, stride, ctypes.c_void_p(out.ctypes.data))
        return out

    def xxh64_offsets(self, data, offsets):
        import numpy as np

        n = len(offsets) - 1
        addr, _nb, _keep = _ptr(data)
        off = np.ascontiguousarray(offsets, dtype=np.int64)
        out = np.empty(n, dtype=np.uint64)
        self._lib.ptq_xxh64_offsets(
            addr,
            ctypes.c_void_p(off.ctypes.data),
            n,
            ctypes.c_void_p(out.ctypes.data),
        )
        return out

    def bloom_insert(self, blocks, hashes) -> None:
        h = hashes if hashes.flags["C_CONTIGUOUS"] else hashes.copy()
        self._lib.ptq_bloom_insert(
            ctypes.c_void_p(blocks.ctypes.data),
            len(blocks) // 8,
            ctypes.c_void_p(h.ctypes.data),
            len(h),
        )

    def bloom_check(self, blocks, hashes):
        import numpy as np

        h = hashes if hashes.flags["C_CONTIGUOUS"] else hashes.copy()
        out = np.empty(len(h), dtype=np.uint8)
        self._lib.ptq_bloom_check(
            ctypes.c_void_p(blocks.ctypes.data),
            len(blocks) // 8,
            ctypes.c_void_p(h.ctypes.data),
            len(h),
            ctypes.c_void_p(out.ctypes.data),
        )
        return out.astype(bool)

    def byte_array_gather(self, data, num_values: int):
        """PLAIN byte_array scan: returns (offsets int64[n+1], flat bytes, consumed)."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        offsets = np.empty(num_values + 1, dtype=np.int64)
        out = ctypes.create_string_buffer(max(n_in, 1))
        consumed = self._lib.ptq_byte_array_gather(
            addr,
            n_in,
            num_values,
            offsets.ctypes.data_as(ctypes.c_void_p),
            out,
            n_in,
        )
        if consumed < 0:
            raise ValueError("native: corrupt byte_array stream")
        # single copy of exactly the payload (out.raw would copy the whole cap)
        flat = ctypes.string_at(out, int(offsets[-1]))
        return offsets, flat, int(consumed)

    def hybrid_decode(self, data, num_values: int, width: int, nbits: int):
        """One-shot hybrid RLE/bit-pack decode. Returns (values, consumed);
        values is uint32 (nbits==32) or uint64 (nbits==64)."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        out = np.empty(num_values, dtype=np.uint32 if nbits == 32 else np.uint64)
        p = out.ctypes.data_as(ctypes.c_void_p)
        consumed = self._lib.ptq_hybrid_decode(
            addr,
            n_in,
            num_values,
            width,
            p if nbits == 32 else None,
            p if nbits == 64 else None,
        )
        if consumed < 0:
            raise ValueError("native: corrupt hybrid stream")
        return out, int(consumed)

    def delta_decode(self, data: bytes, nbits: int, max_total: int | None):
        """Full DELTA_BINARY_PACKED decode. Returns (int32/int64 values, consumed).
        Raises OverflowError when the stream's count exceeds max_total so the
        caller can report the same error as the NumPy path."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        total = np.zeros(1, dtype=np.int64)
        if self._lib.ptq_delta_peek_total(addr, n_in, total.ctypes.data_as(ctypes.c_void_p)) < 0:
            raise ValueError("native: corrupt delta header")
        cap = int(total[0])
        if max_total is not None and cap > max(max_total, 0):
            raise OverflowError(
                f"stream claims {cap} values, caller expects at most {max_total}"
            )
        out = np.empty(cap, dtype=np.int32 if nbits == 32 else np.int64)
        # max_total already enforced above on the peeked count; the C-side
        # bound (-3) is unreachable from here, so pass "no bound".
        consumed = self._lib.ptq_delta_decode(
            addr,
            n_in,
            nbits,
            -1,
            out.ctypes.data_as(ctypes.c_void_p),
            total.ctypes.data_as(ctypes.c_void_p),
        )
        if consumed < 0:
            raise ValueError("native: corrupt delta stream")
        return out, int(consumed)

    def plain_encode_bytearray(self, data, offsets) -> bytes:
        """(offsets, data) column -> PLAIN stream ([4B LE len][bytes] per
        value) in one C pass; ~memcpy speed vs the per-item Python loop."""
        import numpy as np

        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        n = len(offsets) - 1
        addr, n_in, _keep = _ptr(data)
        cap = n_in + 4 * max(n, 0)
        out = np.empty(max(cap, 1), dtype=np.uint8)
        rc = self._lib.ptq_plain_encode_bytearray(
            addr, n_in,
            offsets.ctypes.data_as(ctypes.c_void_p), n,
            ctypes.c_void_p(out.ctypes.data), cap,
        )
        if rc < 0:
            raise ValueError("native: corrupt byte-array offsets")
        return out[: int(rc)].tobytes()

    def bytearray_take(self, data: bytes, offsets, indices, new_offsets, total: int) -> bytes:
        """Gather rows of an (offsets, data) byte-array column by index."""
        import numpy as np

        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        indices = np.ascontiguousarray(indices, dtype=np.int64)
        new_offsets = np.ascontiguousarray(new_offsets, dtype=np.int64)
        addr, n_in, _keep = _ptr(data)
        out = ctypes.create_string_buffer(max(total, 1))
        rc = self._lib.ptq_bytearray_take(
            addr,
            n_in,
            offsets.ctypes.data_as(ctypes.c_void_p),
            len(offsets) - 1,
            indices.ctypes.data_as(ctypes.c_void_p),
            len(indices),
            new_offsets.ctypes.data_as(ctypes.c_void_p),
            out,
            total,
        )
        if rc < 0:
            raise ValueError("native: byte-array take index out of range")
        return ctypes.string_at(out, total)

    def prescan_hybrid(self, data: bytes, num_values: int, width: int):
        """Run-header prescan: returns (is_rle, counts, values, bp_offsets, consumed)
        with bp_offsets absolute into `data`, or None if the run table overflows."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        max_runs = 4096
        while True:
            is_rle = np.empty(max_runs, dtype=np.uint8)
            counts = np.empty(max_runs, dtype=np.int64)
            values = np.empty(max_runs, dtype=np.uint64)
            offsets = np.empty(max_runs, dtype=np.int64)
            consumed = np.zeros(1, dtype=np.int64)
            n = self._lib.ptq_prescan_hybrid(
                addr,
                n_in,
                num_values,
                width,
                is_rle.ctypes.data_as(ctypes.c_void_p),
                counts.ctypes.data_as(ctypes.c_void_p),
                values.ctypes.data_as(ctypes.c_void_p),
                offsets.ctypes.data_as(ctypes.c_void_p),
                max_runs,
                consumed.ctypes.data_as(ctypes.c_void_p),
            )
            if n == -2:
                max_runs *= 8
                continue
            if n < 0:
                raise ValueError("native: corrupt hybrid stream")
            n = int(n)
            return (
                is_rle[:n].astype(bool),
                counts[:n],
                values[:n],
                offsets[:n],
                int(consumed[0]),
            )


    _POOL_MAX_BUFS = 6
    _POOL_MAX_BYTES = 64 << 20  # don't hold giant one-off chunks
    _POOL_MAX_TOTAL = 192 << 20  # per-thread retention cap (all buffers)

    def _take_buf(self, size: int):
        """A uint8 staging buffer from the per-thread pool (best fit), or a
        fresh np.empty. Pooled buffers have their pages already faulted in,
        which is most of the cost of writing a fresh multi-MB allocation.
        Entries more than 4x the request are left for larger chunks — a
        tiny chunk pinning a pooled multi-MB buffer (its plan keeps views)
        would drain the pool of exactly the buffers worth pooling."""
        import numpy as np

        pool = getattr(self._chunk_tl, "out_pool", None)
        if pool:
            best = -1
            for k in range(len(pool)):
                n = len(pool[k])
                if size <= n <= max(4 * size, 1 << 16) and (
                    best < 0 or n < len(pool[best])
                ):
                    best = k
            if best >= 0:
                return pool.pop(best)
        return np.empty(size, dtype=np.uint8)

    def release_buffers(self, res: dict, names) -> None:
        """Hand chunk_prepare staging buffers back to this thread's pool.

        ONLY legal when the caller proves no view of the named buffers
        escapes into the returned plan (e.g. the PLAIN route releases
        packed/delta always, and values when the transfer repack replaced
        the upload). Must run on the thread that called chunk_prepare."""
        bases = res.get("_bases")
        if not bases:
            return
        tl = self._chunk_tl
        pool = getattr(tl, "out_pool", None)
        if pool is None:
            pool = tl.out_pool = []
        held = sum(len(b) for b in pool)
        for name in names:
            buf = bases.pop(name, None)
            if (
                buf is not None
                and len(buf)
                and len(buf) <= self._POOL_MAX_BYTES
                and len(pool) < self._POOL_MAX_BUFS
                and held + len(buf) <= self._POOL_MAX_TOTAL
            ):
                pool.append(buf)
                held += len(buf)

    def chunk_prepare(
        self,
        data,
        codec: int,
        max_def: int,
        max_rep: int,
        type_size: int,
        delta_nbits: int,
        expected_values: int,
        uncompressed_cap: int,
        collect_stages: bool = False,
        validate_crc: bool = False,
    ):
        """Whole-chunk prepare walk (ptq_chunk_prepare): one native call does
        header parse + (opt-in) CRC verify + decompress + level decode +
        value-stream prescan for every page, GIL-free (the CPython-extension
        binding releases it explicitly via Py_BEGIN_ALLOW_THREADS; the ctypes
        fallback drops it at the foreign-call boundary). Returns a dict of
        packed tables on success, or a PrepareFault naming the failing
        {code, stage, page, offset} when the chunk needs the Python walk
        (corrupt / unsupported / capacity-exceeded — the Python path
        reproduces the exact error semantics; the fault detail feeds the
        fallback-ladder counters and parquet-tool verify).
        collect_stages=True adds a "stage_ns" int64[5] entry (decompress,
        levels, prescan, copy, crc accumulated wall ns)."""
        import numpy as np

        addr, n_in, _keep = _ptr(data)
        cap = max(uncompressed_cap, n_in) + 64
        lv = max(expected_values, 1)
        max_pages, max_runs, max_minis = 1024, 4096, 4096
        # output buffers sized from metadata; np.empty is virtual until
        # touched — but the first WRITE then faults every page in (~0.5 ms
        # per MB), so routes that provably leak no view of a buffer hand it
        # back via release_buffers and the next chunk on this thread skips
        # the fault storm entirely
        def_out = np.empty(lv, dtype=np.uint16) if max_def > 0 else np.empty(0, np.uint16)
        rep_out = np.empty(lv, dtype=np.uint16) if max_rep > 0 else np.empty(0, np.uint16)
        values_out = self._take_buf(cap)
        packed_out = self._take_buf(cap)
        # delta_out slack covers the worst-case PLAIN->delta repack (a page
        # that sampled compressible but encodes at full width: raw size +
        # ~0.5% framing) so the C walk never has to back out mid-chunk
        delta_out = (
            self._take_buf(cap + cap // 64 + 4096)
            if delta_nbits
            else np.empty(0, np.uint8)
        )
        # The decompress scratch never escapes the C call, so it is the one
        # big buffer that can be POOLED per thread: a fresh np.empty faults
        # in every written page on first touch (~1 ms per decompressed MB on
        # this class of host), which a reused buffer pays only once.
        tl = self._chunk_tl
        # +64 bytes of physical slack past the largest page's uncompressed
        # size: decompress_page passes the physical capacity through, which
        # switches snappy into its overshooting fast mode even when a chunk
        # is one exactly-sized page
        scratch = getattr(tl, "scratch", None)
        if scratch is None or len(scratch) < cap + 64:
            scratch = tl.scratch = np.empty(cap + 64, dtype=np.uint8)
        totals = np.zeros(8, dtype=np.int64)
        stage_ns = np.zeros(5, dtype=np.int64) if collect_stages else None
        err_info = np.zeros(4, dtype=np.int64)
        ext = self._ext_chunk_prepare
        p = ctypes.c_void_p
        while True:
            if stage_ns is not None:
                stage_ns[:] = 0  # a table-growth retry re-walks from scratch:
                # keep only the final walk's split, not partial+full summed
            pages = np.empty((max_pages, 18), dtype=np.int64)
            h_is_rle = np.empty(max_runs, dtype=np.uint8)
            h_counts = np.empty(max_runs, dtype=np.int64)
            h_values = np.empty(max_runs, dtype=np.uint64)
            h_byteoff = np.empty(max_runs, dtype=np.int64)
            d_widths = np.empty(max_minis, dtype=np.uint32)
            d_bytestart = np.empty(max_minis, dtype=np.int64)
            d_outstart = np.empty(max_minis, dtype=np.int32)
            d_mins = np.empty(max_minis, dtype=np.uint64)
            if ext is not None:
                # single GIL-free transition: every buffer binds through the
                # buffer protocol and capacities derive from the buffer
                # lengths — values/packed are sliced to exactly `cap` so both
                # bindings enforce the SAME -5 overflow bound (the pool may
                # hand back a larger staging buffer than requested)
                rc = ext(
                    data if isinstance(data, (bytes, memoryview)) else _keep,
                    codec, 1 if validate_crc else 0,
                    max_def, max_rep, type_size, delta_nbits,
                    expected_values,
                    pages, def_out, rep_out,
                    memoryview(values_out)[:cap],
                    memoryview(packed_out)[:cap],
                    delta_out, scratch,
                    h_is_rle, h_counts, h_values, h_byteoff,
                    d_widths, d_bytestart, d_outstart, d_mins,
                    totals, stage_ns, err_info,
                )
            else:
                rc = self._lib.ptq_chunk_prepare(
                    addr, n_in, codec, 1 if validate_crc else 0,
                    max_def, max_rep, type_size, delta_nbits,
                    expected_values,
                    pages.ctypes.data_as(p), max_pages,
                    def_out.ctypes.data_as(p), rep_out.ctypes.data_as(p),
                    values_out.ctypes.data_as(p), cap,
                    packed_out.ctypes.data_as(p), cap,
                    delta_out.ctypes.data_as(p), len(delta_out),
                    scratch.ctypes.data_as(p), len(scratch),
                    h_is_rle.ctypes.data_as(p), h_counts.ctypes.data_as(p),
                    h_values.ctypes.data_as(p), h_byteoff.ctypes.data_as(p), max_runs,
                    d_widths.ctypes.data_as(p), d_bytestart.ctypes.data_as(p),
                    d_outstart.ctypes.data_as(p), d_mins.ctypes.data_as(p), max_minis,
                    totals.ctypes.data_as(p),
                    None if stage_ns is None else stage_ns.ctypes.data_as(p),
                    err_info.ctypes.data_as(p),
                )
            if rc == -2 and max_pages < (1 << 24):
                max_pages *= 8
                continue
            if rc == -3 and max_runs < n_in + 8:
                max_runs = min(max_runs * 8, n_in + 8)
                continue
            if rc == -4 and max_minis < n_in + 8:
                max_minis = min(max_minis * 8, n_in + 8)
                continue
            if rc < 0:
                return PrepareFault(
                    code=int(rc),
                    stage=PREPARE_STAGES.get(int(err_info[0]), "none"),
                    page=int(err_info[1]),
                    offset=int(err_info[2]),
                )
            n = int(rc)
            R = int(totals[4])
            M = int(totals[5])
            return {
                "pages": pages[:n],
                "def": def_out[: int(totals[0])] if max_def > 0 else None,
                "rep": rep_out[: int(totals[0])] if max_rep > 0 else None,
                "values": values_out[: int(totals[1])],
                "packed": packed_out[: int(totals[2])],
                "delta_stream": delta_out[: int(totals[3])],
                "_bases": {
                    "values": values_out,
                    "packed": packed_out,
                    "delta": delta_out if delta_nbits else None,
                },
                "h_is_rle": h_is_rle[:R],
                "h_counts": h_counts[:R],
                "h_values": h_values[:R],
                "h_byteoff": h_byteoff[:R],
                "d_widths": d_widths[:M],
                "d_bytestart": d_bytestart[:M],
                "d_outstart": d_outstart[:M],
                "d_mins": d_mins[:M],
                "has_dict": bool(totals[6]),
                "stage_ns": stage_ns,
            }

    def gzip_compress(self, data) -> bytes:
        """Deflate with the fused encode walk's exact gzip parameters (the
        startup identity probe against CPython's zlib)."""
        addr, n_in, _keep = _ptr(data)
        cap = n_in + n_in // 4 + 128
        out = ctypes.create_string_buffer(cap)
        n = self._lib.ptq_gzip_compress(addr, n_in, out, cap)
        if n < 0:
            raise ValueError("native gzip: compression failed")
        return out.raw[:n]

    def chunk_encode(
        self,
        route: int,
        values,
        ba_offsets,
        nv: int,
        type_size: int,
        dict_width: int,
        dict_raw,
        dict_num: int,
        def_levels,
        num_entries: int,
        max_def: int,
        codec: int,
        dpv: int,
        with_crc: bool,
        per_page: int,
        raw_worst: int,
        collect_stages: bool = False,
    ):
        """Whole-chunk encode walk (ptq_chunk_encode): ONE native call does
        page split + level pack + value encode + compress + Thrift page
        framing, GIL-free via the CPython-extension binding (ctypes
        fallback drops the GIL at the foreign-call boundary). Returns a
        dict {out, pages, totals, stage_ns} on success — `out` is a uint8
        view of exactly the framed chunk bytes — or an EncodeFault naming
        the failing {code, stage, page} when the chunk needs the staged
        Python encoder. `raw_worst` is the caller's worst-case raw
        (uncompressed) page-block bound; output/scratch capacities derive
        from it with compression-expansion slack, and a -5 capacity verdict
        retries once with doubled buffers before reporting the fault."""
        import numpy as np

        ext = self._ext_chunk_encode
        # worst case for an incompressible block: snappy adds ~n/6 + 32,
        # deflate ~n/1000 + 13 — one shared slack covers every codec
        comp_slack = raw_worst // 4 + 1024
        scratch_cap = 2 * (raw_worst + comp_slack)
        out_cap = (
            raw_worst
            + comp_slack
            + int(dict_raw.nbytes if hasattr(dict_raw, "nbytes") else len(dict_raw or b""))
            + 4096
        )
        max_pages = int(num_entries // max(per_page, 1)) + 3
        totals = np.zeros(8, dtype=np.int64)
        stage_ns = np.zeros(5, dtype=np.int64) if collect_stages else None
        err_info = np.zeros(4, dtype=np.int64)
        p = ctypes.c_void_p
        attempts = 0
        while True:
            out = np.empty(out_cap, dtype=np.uint8)
            scratch = self._take_buf(scratch_cap)
            pages = np.empty((max_pages, 8), dtype=np.int64)
            if stage_ns is not None:
                stage_ns[:] = 0
            if ext is not None:
                rc = ext(
                    route,
                    values,
                    ba_offsets,
                    nv,
                    type_size,
                    dict_width,
                    dict_raw,
                    dict_num,
                    def_levels,
                    num_entries,
                    max_def,
                    codec,
                    dpv,
                    1 if with_crc else 0,
                    per_page,
                    out,
                    memoryview(scratch)[:scratch_cap],
                    pages,
                    totals,
                    stage_ns,
                    err_info,
                )
            else:
                va, v_len, _vk = _ptr(values)
                oa = ok = da = dk = fa = fk = None
                if ba_offsets is not None:
                    oa, _n, ok = _ptr(ba_offsets)
                if dict_raw is not None:
                    da, d_len, dk = _ptr(dict_raw)
                else:
                    d_len = 0
                if def_levels is not None:
                    fa, _n, fk = _ptr(def_levels)
                rc = self._lib.ptq_chunk_encode(
                    route, va, v_len, oa, nv, type_size, dict_width,
                    da, d_len, dict_num, fa, num_entries, max_def,
                    codec, dpv, 1 if with_crc else 0, per_page,
                    ctypes.c_void_p(out.ctypes.data), out_cap,
                    ctypes.c_void_p(scratch.ctypes.data), scratch_cap,
                    pages.ctypes.data_as(p), max_pages,
                    totals.ctypes.data_as(p),
                    None if stage_ns is None else stage_ns.ctypes.data_as(p),
                    err_info.ctypes.data_as(p),
                )
                del ok, dk, fk  # keepalives live through the call
            # scratch never escapes the walk: always pool it back
            self.release_buffers({"_bases": {"scratch": scratch}}, ("scratch",))
            if rc == -2 and max_pages < (1 << 24):
                max_pages *= 8
                continue
            if rc == -5 and attempts < 2:
                attempts += 1
                out_cap *= 2
                scratch_cap *= 2
                continue
            if rc < 0:
                return EncodeFault(
                    code=int(rc),
                    stage=ENCODE_STAGES.get(int(err_info[0]), "none"),
                    page=int(err_info[1]),
                )
            return {
                "out": out[: int(totals[0])],
                "pages": pages[: int(rc)],
                "totals": totals,
                "stage_ns": stage_ns,
            }

    def hybrid_encode(self, values, width: int) -> bytes:
        """RLE/bit-pack hybrid encode of a uint64 array (byte-identical to
        ops/rle_hybrid.py encode_hybrid)."""
        import numpy as np

        v = np.ascontiguousarray(values, dtype=np.uint64)
        n = len(v)
        cap = hybrid_encode_cap(n, width)
        out = np.empty(cap, dtype=np.uint8)
        rc = self._lib.ptq_hybrid_encode(
            ctypes.c_void_p(v.ctypes.data), n, width,
            ctypes.c_void_p(out.ctypes.data), cap,
        )
        if rc < 0:
            raise ValueError(
                f"native: hybrid encode failed ({'value too wide' if rc == -1 else 'capacity'})"
            )
        return out[: int(rc)].tobytes()

    def delta_encode(self, values, nbits: int, block_size: int, mini_count: int) -> bytes:
        """DELTA_BINARY_PACKED encode (byte-identical to ops/delta.py
        encode_delta)."""
        import numpy as np

        dt = np.int32 if nbits == 32 else np.int64
        v = np.ascontiguousarray(values, dtype=dt)
        n = len(v)
        cap = delta_encode_cap(n, nbits, block_size, mini_count)
        out = np.empty(cap, dtype=np.uint8)
        rc = self._lib.ptq_delta_encode(
            ctypes.c_void_p(v.ctypes.data), n, nbits, block_size, mini_count,
            ctypes.c_void_p(out.ctypes.data), cap,
        )
        if rc < 0:
            raise ValueError("native: delta encode failed")
        return out[: int(rc)].tobytes()

    def bytes_dict_indices(self, data, offsets, max_uniques: int):
        """Dictionary probe over an (offsets, data) byte-array column.
        Returns (first_occurrence_rows uint32[U], indices uint32[n]) or None
        when uniques exceed max_uniques."""
        import numpy as np

        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        n = len(offsets) - 1
        addr, data_len, _keep = _ptr(data)
        indices = np.empty(max(n, 1), dtype=np.uint32)
        firsts = np.empty(max_uniques + 2, dtype=np.uint32)
        rc = self._lib.ptq_bytes_dict_indices(
            addr, data_len,
            ctypes.c_void_p(offsets.ctypes.data), n, max_uniques,
            ctypes.c_void_p(indices.ctypes.data),
            ctypes.c_void_p(firsts.ctypes.data),
        )
        if rc == -2:
            return None
        if rc < 0:
            raise ValueError("native: byte-array dictionary probe failed")
        return firsts[: int(rc)], indices[:n]

    def bytes_minmax(self, data, offsets):
        """(row of lexicographic min, row of max) over a byte-array column."""
        import numpy as np

        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        n = len(offsets) - 1
        addr, data_len, _keep = _ptr(data)
        out = np.empty(2, dtype=np.int64)
        rc = self._lib.ptq_bytes_minmax(
            addr, data_len, ctypes.c_void_p(offsets.ctypes.data), n,
            ctypes.c_void_p(out.ctypes.data),
        )
        if rc < 0:
            raise ValueError("native: byte-array minmax failed")
        return int(out[0]), int(out[1])

    def u64_dict_indices(self, bits, max_uniques: int):
        """Dictionary probe over uint32/uint64 bit patterns (probed in place,
        no widening copy); early-exits past the unique cutoff. Returns
        (first_rows, indices) or None over the cap."""
        import numpy as np

        v = np.ascontiguousarray(bits)
        if v.dtype not in (np.dtype(np.uint32), np.dtype(np.uint64)):
            v = v.astype(np.uint64)
        n = len(v)
        indices = np.empty(max(n, 1), dtype=np.uint32)
        firsts = np.empty(max_uniques + 2, dtype=np.uint32)
        rc = self._lib.ptq_u64_dict_indices(
            ctypes.c_void_p(v.ctypes.data), v.dtype.itemsize, n, max_uniques,
            ctypes.c_void_p(indices.ctypes.data),
            ctypes.c_void_p(firsts.ctypes.data),
        )
        if rc == -2:
            return None
        if rc < 0:
            raise ValueError("native: u64 dictionary probe failed")
        return firsts[: int(rc)], indices[:n]

    def prescan_delta_packed(self, data: bytes, nbits: int, max_total: int):
        """Header-only delta prescan. Returns (widths, byte_starts, out_starts,
        mins, first_value, total, consumed). Raises OverflowError when the
        stream's count exceeds max_total (parity with the Python path)."""
        import numpy as np

        # Negative bounds clamp to 0, matching the Python path's
        # max(max_total, 0); the C side applies the same clamp, and the table
        # is sized from the bound actually enforced.
        max_total = max(max_total, 0)
        # One table entry per miniblock with >=1 real delta; mini_len >= 8, so
        # M <= ceil((total-1)/8) and total <= max_total. Each entry also
        # consumes at least its one width byte from the stream, so M <= len:
        # a lying header with a huge count must not drive the allocation
        # (validation-before-allocation discipline).
        addr, n_in, _keep = _ptr(data)
        max_entries = min(max(max_total, 8) // 8 + 2, n_in + 2)
        widths = np.empty(max_entries, dtype=np.uint32)
        byte_starts = np.empty(max_entries, dtype=np.int64)
        out_starts = np.empty(max_entries, dtype=np.int32)
        mins = np.empty(max_entries, dtype=np.uint64)
        first = np.zeros(1, dtype=np.uint64)
        total = np.zeros(1, dtype=np.int64)
        consumed = np.zeros(1, dtype=np.int64)
        m = self._lib.ptq_prescan_delta_packed(
            addr,
            n_in,
            nbits,
            max_total,
            widths.ctypes.data_as(ctypes.c_void_p),
            byte_starts.ctypes.data_as(ctypes.c_void_p),
            out_starts.ctypes.data_as(ctypes.c_void_p),
            mins.ctypes.data_as(ctypes.c_void_p),
            max_entries,
            first.ctypes.data_as(ctypes.c_void_p),
            total.ctypes.data_as(ctypes.c_void_p),
            consumed.ctypes.data_as(ctypes.c_void_p),
        )
        if m == -3:
            raise OverflowError(
                f"stream claims more than the caller's bound of {max_total} values"
            )
        if m < 0:
            raise ValueError("native: corrupt delta stream")
        m = int(m)
        return (
            widths[:m],
            byte_starts[:m],
            out_starts[:m],
            mins[:m],
            int(first[0]),
            int(total[0]),
            int(consumed[0]),
        )

    def parse_page_header(self, window: bytes):
        """Parse one Thrift compact PageHeader from a peeked window.

        Returns the 23-slot int64 array (see ptq_parse_page_header layout),
        None when the window was too small (caller re-peeks larger), or
        raises ValueError on structurally corrupt bytes (caller falls back
        to the Python reader for its exact error)."""
        import numpy as np

        addr, n_in, _keep = _ptr(window)
        out = np.empty(23, dtype=np.int64)
        rc = self._lib.ptq_parse_page_header(
            addr, n_in, out.ctypes.data_as(ctypes.c_void_p)
        )
        if rc == -2:
            return None
        if rc < 0:
            raise ValueError("native: corrupt page header")
        return out


def get_native() -> NativeLib | None:
    """Load the native helper library, or None if not built/loadable."""
    global _cached, _probed
    if _probed:
        return _cached
    _probed = True
    root = Path(__file__).resolve().parent.parent.parent
    candidates = [root / "native" / "build" / name for name in _SO_NAMES]
    env = os.environ.get("PARQUET_TPU_NATIVE")
    if env:
        candidates.insert(0, Path(env))
    for cand in candidates:
        if cand.exists():
            try:
                _cached = NativeLib(ctypes.CDLL(str(cand)))
                break
            except OSError:
                continue
    return _cached

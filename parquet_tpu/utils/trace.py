"""Hierarchical span tracing + per-stage decode instrumentation.

The reference has no observability at all (SURVEY §5: 'no pprof hooks, no
timing instrumentation'). This module provides the opt-in, per-read layer:
a `decode_trace()` collects BOTH flat per-stage aggregates (wall time, bytes,
calls — the report() table) and hierarchical spans (file → row-group → chunk
→ page → stage, including the native prepare sub-clocks) exportable as Chrome
trace-event JSON for Perfetto / chrome://tracing. The always-on process
counters live in utils/metrics.py; `bump()` dual-reports into them.

Zero overhead when no trace is active: one contextvar read, no span
allocations (asserted by test via the span_allocations() counter).

Thread model: the active trace propagates through a `contextvars.ContextVar`,
so concurrent traces on different threads are ISOLATED (the old module-global
was racy under the 16-thread prepare pool), while pool workers doing a traced
read's prepare/dispatch work join the submitting read's trace via
`traced_submit()` (an explicit `copy_context()` carry — ThreadPoolExecutor
does not propagate context by itself). All merges into a shared trace are
lock-protected.

    from parquet_tpu.utils.trace import decode_trace

    with decode_trace() as t:
        reader.read_row_group(0)
    print(t.report())                 # per-stage table, hottest first
    t.write_chrome_trace("trace.json")  # load in ui.perfetto.dev

    with jax_profile("/tmp/trace"):   # wraps jax.profiler.trace
        reader.read_row_group(0)      # inspect with TensorBoard/XProf
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar, copy_context
from dataclasses import dataclass

from . import metrics as _metrics

__all__ = [
    "decode_trace",
    "stage",
    "timed_stage",
    "span",
    "add_bytes",
    "add_seconds",
    "add_seconds_batch",
    "bump",
    "count",
    "active",
    "current",
    "traced_submit",
    "span_allocations",
    "jax_profile",
    "DecodeTrace",
]

_active_var: ContextVar = ContextVar("pqt_decode_trace", default=None)

# Depth of stage() / timed_stage() aggregates currently OPEN in this
# context. Seconds committed while an enclosing stage aggregate is open
# (an inner decode stage under serve.execute, a native sub-clock inside a
# measured parent) are already part of that parent's wall time — they are
# marked "nested" on their own StageStats so rollups and the report TOTAL
# can count them EXACTLY once. The contextvar rides the same
# copy_context() carry as the trace itself, so nesting detected inside a
# pool worker attributes against the stage open on that worker.
_stage_depth_var: ContextVar = ContextVar("pqt_stage_depth", default=0)

# Process-wide count of span-event allocations: the zero-overhead oracle.
# A read with no trace active must leave it untouched — tests assert that by
# counter, not timing. Mutated only while some trace's lock is held, so the
# count is exact for single-trace workloads and best-effort across
# concurrently active traces.
_span_allocs = 0

# Per-trace span cap: a traced 10M-row assembled read bills stage("assemble")
# per row; past the cap events drop (counted in events_dropped) while the
# stage AGGREGATES stay exact.
_MAX_EVENTS = 1 << 17


@dataclass
class StageStats:
    seconds: float = 0.0
    bytes: int = 0
    calls: int = 0
    # the share of `seconds` that elapsed INSIDE another open stage
    # aggregate (a sub-clock): already billed to the enclosing stage, so
    # exclusive rollups subtract it — TOTAL counts wall time once
    nested_seconds: float = 0.0


class DecodeTrace:
    """One read's collected stages + spans. Safe to mutate from many threads
    (every merge takes the trace lock); read it after the `with` block."""

    def __init__(self):
        self.stages: dict[str, StageStats] = {}
        self.events_dropped = 0
        # cross-process propagation key (obs/propagate.py): an opaque
        # 32-hex trace-id set by whoever opened the request scope, or None
        # for library reads outside any scope. Carried into the Chrome
        # export so trace-merge can stitch multi-process documents.
        self.trace_id: str | None = None
        self._lock = threading.Lock()
        self._t0 = time.perf_counter_ns()
        # finished spans: (name, tid, start_ns rel to _t0, dur_ns, args|None)
        self._events: list[tuple] = []
        self._threads: dict[int, str] = {}

    # -- collection (lock-protected merge; called from pool threads) ----------

    def _stat(self, name: str) -> StageStats:
        # caller holds self._lock
        s = self.stages.get(name)
        if s is None:
            s = self.stages[name] = StageStats()
        return s

    def _commit(
        self,
        name: str,
        seconds: float = 0.0,
        nbytes: int = 0,
        calls: int = 0,
        start_ns: int | None = None,
        dur_ns: int = 0,
        args: dict | None = None,
        nested: bool = False,
    ) -> None:
        global _span_allocs
        with self._lock:
            if calls or nbytes or seconds:
                s = self._stat(name)
                s.seconds += seconds
                s.bytes += nbytes
                s.calls += calls
                if nested:
                    s.nested_seconds += seconds
            if start_ns is not None:
                tid = threading.get_ident()
                if tid not in self._threads:
                    self._threads[tid] = threading.current_thread().name
                if len(self._events) >= _MAX_EVENTS:
                    self.events_dropped += 1
                else:
                    _span_allocs += 1
                    self._events.append(
                        (name, tid, start_ns - self._t0, dur_ns, args)
                    )

    # -- reporting -------------------------------------------------------------

    def counters(self) -> dict:
        """{name: calls} for every bump()-style event collected — the
        robustness counters ride here: prepare_fused_engaged/_declined,
        prepare_fused_fault_<stage>, prepare_fallback_recovered,
        chunks_quarantined, chunks_nulled, row_groups_quarantined."""
        with self._lock:
            return {name: s.calls for name, s in self.stages.items() if s.calls}

    def stage_rollup(self) -> dict:
        """The flat per-stage aggregates as plain JSON-shaped data:
        {stage: {"seconds", "bytes", "calls"}} — what the flight recorder
        stores per request (the span TREE is sampled; this rollup is kept
        for every record, and its pool.wait entry is the record's
        queue-wait). Stages whose time elapsed inside another measured
        stage (sub-clocks: the native prepare.* split, an inner decode
        stage under serve.execute) additionally carry "nested_seconds" —
        the share already billed to their parent — so a consumer summing
        `seconds - nested_seconds` counts wall time exactly once."""
        with self._lock:
            out = {}
            for n, s in self.stages.items():
                d = {"seconds": s.seconds, "bytes": s.bytes, "calls": s.calls}
                if s.nested_seconds:
                    d["nested_seconds"] = s.nested_seconds
                out[n] = d
            return out

    def exclusive_seconds(self) -> float:
        """Wall seconds across all stages with sub-clock time counted
        ONCE: sum of per-stage (seconds - nested_seconds) — the same
        quantity the report() TOTAL footer shows (computed there inline,
        atomically with its per-stage listing); exposed as API for
        embedders and tests."""
        with self._lock:
            return sum(
                s.seconds - s.nested_seconds for s in self.stages.values()
            )

    def report(self, sort: str = "time") -> str:
        """Per-stage table. sort="time" (default) lists the hottest stages
        first (wall seconds, descending); sort="name" is alphabetical.
        A TOTAL footer sums seconds/bytes/calls across stages; sub-clock
        seconds (time a stage spent inside another measured stage — the
        native prepare.* split under its parent, inner decode stages under
        serve.execute) count toward the TOTAL exactly once, and stages
        that are partly or wholly sub-clocks are marked with a trailing
        `*` (their own line still shows inclusive seconds)."""
        if sort not in ("time", "name"):
            raise ValueError(f'report sort must be "time" or "name", got {sort!r}')
        with self._lock:
            items = [
                (n, s.seconds, s.bytes, s.calls, s.nested_seconds)
                for n, s in self.stages.items()
            ]
        if sort == "name":
            items.sort(key=lambda kv: kv[0])
        else:
            items.sort(key=lambda kv: (-kv[1], kv[0]))

        def line(name, seconds, nbytes, calls, mark=""):
            rate = f" ({nbytes / seconds / 1e6:.0f} MB/s)" if seconds > 0 and nbytes else ""
            return (
                f"{name:12s} {seconds * 1000:8.1f} ms  {nbytes:>12,} B  "
                f"{calls:>6} calls{rate}{mark}"
            )

        lines = [
            line(n, sec, b, c, "  *" if nested else "")
            for n, sec, b, c, nested in items
        ]
        lines.append(
            line(
                "TOTAL",
                sum(sec - nested for _, sec, _b, _c, nested in items),
                sum(b for _, _s, b, _c, _n in items),
                sum(c for _, _s, _b, c, _n in items),
            )
        )
        if any(nested for *_rest, nested in items):
            lines.append(
                "(* partly sub-clocked: time also inside an enclosing "
                "stage; TOTAL counts it once)"
            )
        return "\n".join(lines)

    # -- Chrome trace-event export ---------------------------------------------

    def to_chrome_trace(self) -> dict:
        """The collected spans as a Chrome trace-event JSON object (the
        format Perfetto and chrome://tracing load). Every span is a complete
        ("X") event with microsecond ts/dur relative to trace start, on its
        real thread lane; one thread_name metadata ("M") event names each
        lane (MainThread / pqt-host_* / pqt-dispatch_*). Aggregates and
        bump() counters ride in otherData."""
        pid = os.getpid()
        with self._lock:
            events = list(self._events)
            threads = dict(self._threads)
            stages = {
                n: {
                    "seconds": s.seconds,
                    "bytes": s.bytes,
                    "calls": s.calls,
                    **(
                        {"nested_seconds": s.nested_seconds}
                        if s.nested_seconds
                        else {}
                    ),
                }
                for n, s in self.stages.items()
            }
            dropped = self.events_dropped
        out = [
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "ts": 0,
                "dur": 0,
                "args": {"name": tname},
            }
            for tid, tname in sorted(threads.items())
        ]
        events.sort(key=lambda e: (e[1], e[2], -e[3]))  # (tid, start, -dur)
        for name, tid, rel_ns, dur_ns, args in events:
            ev = {
                "ph": "X",
                "name": name,
                "cat": name.split(".", 1)[0],
                "pid": pid,
                "tid": tid,
                "ts": rel_ns / 1e3,
                "dur": dur_ns / 1e3,
            }
            if args:
                ev["args"] = dict(args)
            out.append(ev)
        doc = {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {
                "stages": stages,
                "counters": {n: v["calls"] for n, v in stages.items() if v["calls"]},
                "events_dropped": dropped,
            },
        }
        if self.trace_id is not None:
            doc["otherData"]["propagation"] = {"trace_id": self.trace_id}
        return doc

    def write_chrome_trace(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


@contextmanager
def decode_trace():
    """Activate stage + span collection for the enclosed reads (this thread,
    plus any pool work submitted from it via traced_submit). Nested traces
    shadow; traces on OTHER threads are unaffected (contextvar isolation)."""
    t = DecodeTrace()
    token = _active_var.set(t)
    try:
        yield t
    finally:
        _active_var.reset(token)
        # root span: the whole traced region, on the activating thread
        t._commit(
            "decode_trace",
            start_ns=t._t0,
            dur_ns=time.perf_counter_ns() - t._t0,
        )


def _enter_stage() -> tuple:
    """Open a stage aggregate in this context: returns (reset token,
    was-nested). The depth rides the same contextvar carry as the trace,
    so sub-clocks committed inside a pool task see the stage their
    submitter (or the task itself) holds open."""
    depth = _stage_depth_var.get()
    return _stage_depth_var.set(depth + 1), depth > 0


def _exit_stage(token) -> None:
    try:
        _stage_depth_var.reset(token)
    except ValueError:  # pragma: no cover - exotic cross-context consumer
        # a generator suspended inside the stage was resumed from another
        # context: losing the reset mis-tags later commits there as
        # nested at worst — never break the decode over bookkeeping
        pass


@contextmanager
def stage(name: str, nbytes: int = 0, record_span: bool = True):
    """Time a pipeline stage: aggregates into stages[name] AND records a
    span (no-op without an active trace). record_span=False keeps the
    aggregate but skips the span event — for per-ROW micro-stages (the
    assembled-rows loop) that would otherwise flood the event budget with
    sub-microsecond spans and crowd out the meaningful hierarchy. A stage
    opened while another stage aggregate is already open commits its
    seconds as nested (sub-clocked): its wall time is part of the parent's
    and rollup TOTALs count it once."""
    t = _active_var.get()
    if t is None:
        yield
        return
    token, nested = _enter_stage()
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        dt = time.perf_counter_ns() - t0
        _exit_stage(token)
        t._commit(
            name,
            dt / 1e9,
            nbytes,
            1,
            start_ns=t0 if record_span else None,
            dur_ns=dt,
            nested=nested,
        )


class _Elapsed:
    """Result holder for timed_stage(): .seconds is valid after the block."""

    __slots__ = ("seconds",)

    def __init__(self):
        self.seconds = 0.0


@contextmanager
def timed_stage(name: str, nbytes: int = 0, record_span: bool = True):
    """Like stage(), but ALWAYS measures: yields a holder whose `.seconds`
    is the block's wall time even when no trace is active. For callers that
    feed an always-on metric (e.g. the dataset's wait-time histogram) from
    the same clock read that bills the trace stage — one perf_counter pair,
    two consumers, no skew between what the trace and the registry report."""
    t = _active_var.get()
    out = _Elapsed()
    token, nested = (None, False) if t is None else _enter_stage()
    t0 = time.perf_counter_ns()
    try:
        yield out
    finally:
        dt = time.perf_counter_ns() - t0
        out.seconds = dt / 1e9
        if t is not None:
            _exit_stage(token)
            t._commit(
                name,
                out.seconds,
                nbytes,
                1,
                start_ns=t0 if record_span else None,
                dur_ns=dt,
                nested=nested,
            )


@contextmanager
def span(name: str, args: dict | None = None):
    """Pure hierarchy span (file / row_group / chunk levels): records a
    trace event with optional args but does NOT enter the stage aggregates —
    its children (stages) already bill the time, and double-billing would
    corrupt the TOTAL row."""
    t = _active_var.get()
    if t is None:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        t._commit(name, start_ns=t0, dur_ns=time.perf_counter_ns() - t0, args=args)


def active() -> bool:
    """True while a decode_trace() is collecting in this context — callers
    use this to skip instrumentation work (e.g. native per-stage clocks)
    when nobody listens."""
    return _active_var.get() is not None


def current() -> "DecodeTrace | None":
    """The trace active in this context, or None."""
    return _active_var.get()


def traced_submit(executor, fn, *args):
    """Submit `fn(*args)` to `executor` carrying the caller's contextvars —
    including the active decode_trace — into the worker thread.
    ThreadPoolExecutor does not do this by itself; every pool hop of a
    traced read must route through here or its stages vanish."""
    return executor.submit(copy_context().run, fn, *args)


def add_bytes(name: str, nbytes: int) -> None:
    t = _active_var.get()
    if t is not None:
        t._commit(name, 0.0, nbytes, 0)


def add_seconds(name: str, seconds: float, nbytes: int = 0) -> None:
    """Credit externally-measured wall time to a stage. The span is placed
    ending 'now' (the measurement must have just finished). When a stage
    aggregate is open in this context, the credited time is part of that
    stage's wall and commits as nested (counted once in TOTALs)."""
    t = _active_var.get()
    if t is not None:
        dur = int(seconds * 1e9)
        t._commit(
            name,
            seconds,
            nbytes,
            1,
            start_ns=time.perf_counter_ns() - dur,
            dur_ns=dur,
            nested=_stage_depth_var.get() > 0,
        )


def add_seconds_batch(pairs) -> None:
    """Credit a list of (name, seconds) sub-stage clocks that together just
    finished (how the fused native chunk walk reports its internal
    decompress/levels/prescan/copy/crc split). Spans are laid back-to-back
    ENDING now, so they nest inside the enclosing span (their sum never
    exceeds the native call's wall time). Like add_seconds, the batch
    commits as nested when an enclosing stage aggregate is open — the
    sub-clocks are a BREAKDOWN of their parent, not additional wall."""
    t = _active_var.get()
    if t is None:
        return
    nested = _stage_depth_var.get() > 0
    pairs = [(n, s) for n, s in pairs if s > 0]
    cursor = time.perf_counter_ns() - sum(int(s * 1e9) for _, s in pairs)
    for name, sec in pairs:
        dur = int(sec * 1e9)
        t._commit(name, sec, 0, 1, start_ns=cursor, dur_ns=dur, nested=nested)
        cursor += dur


def bump(name: str, nbytes: int = 0) -> None:
    """Count an event (with optional byte volume) under an active trace —
    how tests pin down that an opportunistic path actually engaged. Always
    dual-reports into the process-wide metrics registry (metrics.event), so
    the count survives outside any trace."""
    _metrics.event(name)
    t = _active_var.get()
    if t is not None:
        t._commit(name, 0.0, nbytes, 1)


def count(name: str, n: int = 1) -> None:
    """Count an event under the active trace ONLY — no registry write.
    For call sites that already feed a dedicated always-on counter (the
    block cache's io_cache_hits_total) and need just the per-request
    attribution: one contextvar read when no trace is active, no extra
    lock traffic on hot paths."""
    t = _active_var.get()
    if t is not None:
        t._commit(name, 0.0, 0, n)


def span_allocations() -> int:
    """Process-wide span-event allocation count — the zero-overhead oracle:
    reads with no active trace must not move it."""
    return _span_allocs


@contextmanager
def jax_profile(logdir: str):
    """Capture a JAX/XLA device trace for the enclosed block."""
    import jax

    with jax.profiler.trace(logdir):
        yield

"""Per-stage decode instrumentation + JAX profiler integration.

The reference has no observability at all (SURVEY §5: 'no pprof hooks, no
timing instrumentation'); this module adds the per-stage counters the survey
calls for. Zero overhead when no trace is active (one global check).

    from parquet_tpu.utils.trace import decode_trace

    with decode_trace() as t:
        reader.read_row_group(0)
    print(t.report())        # per-stage wall time + bytes

    with jax_profile("/tmp/trace"):   # wraps jax.profiler.trace
        reader.read_row_group(0)      # inspect with TensorBoard/XProf
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field

__all__ = [
    "decode_trace",
    "stage",
    "add_bytes",
    "add_seconds",
    "bump",
    "active",
    "jax_profile",
    "DecodeTrace",
]

_active: "DecodeTrace | None" = None


@dataclass
class StageStats:
    seconds: float = 0.0
    bytes: int = 0
    calls: int = 0


@dataclass
class DecodeTrace:
    stages: dict = field(default_factory=dict)

    def _stat(self, name: str) -> StageStats:
        s = self.stages.get(name)
        if s is None:
            s = self.stages[name] = StageStats()
        return s

    def counters(self) -> dict:
        """{name: calls} for every bump()-style event collected — the
        robustness counters ride here: prepare_fused_engaged/_declined,
        prepare_fused_fault_<stage>, prepare_fallback_recovered,
        chunks_quarantined, chunks_nulled, row_groups_quarantined."""
        return {name: s.calls for name, s in self.stages.items() if s.calls}

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.stages.items()):
            rate = f" ({s.bytes / s.seconds / 1e6:.0f} MB/s)" if s.seconds > 0 and s.bytes else ""
            lines.append(
                f"{name:12s} {s.seconds * 1000:8.1f} ms  {s.bytes:>12,} B  "
                f"{s.calls:>6} calls{rate}"
            )
        return "\n".join(lines)


@contextmanager
def decode_trace():
    """Activate stage collection for the enclosed reads."""
    global _active
    prev = _active
    t = DecodeTrace()
    _active = t
    try:
        yield t
    finally:
        _active = prev


@contextmanager
def stage(name: str, nbytes: int = 0):
    """Time a pipeline stage (no-op when no trace is active)."""
    t = _active  # capture: the trace may deactivate concurrently
    if t is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        s = t._stat(name)
        s.seconds += time.perf_counter() - t0
        s.bytes += nbytes
        s.calls += 1


def active() -> bool:
    """True while a decode_trace() is collecting — callers use this to skip
    instrumentation work (e.g. native per-stage clocks) when nobody listens."""
    return _active is not None


def add_bytes(name: str, nbytes: int) -> None:
    if _active is not None:
        _active._stat(name).bytes += nbytes


def add_seconds(name: str, seconds: float, nbytes: int = 0) -> None:
    """Credit externally-measured wall time to a stage (how the native fused
    prepare walk reports its internal decompress/levels/prescan/copy split)."""
    if _active is not None:
        s = _active._stat(name)
        s.seconds += seconds
        s.bytes += nbytes
        s.calls += 1


def bump(name: str, nbytes: int = 0) -> None:
    """Count an event (with optional byte volume) under an active trace —
    how tests pin down that an opportunistic path actually engaged."""
    if _active is not None:
        s = _active._stat(name)
        s.calls += 1
        s.bytes += nbytes


@contextmanager
def jax_profile(logdir: str):
    """Capture a JAX/XLA device trace for the enclosed block."""
    import jax

    with jax.profiler.trace(logdir):
        yield

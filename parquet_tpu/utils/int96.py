"""INT96 legacy timestamp helpers.

Equivalent of the reference's int96_time.go (:17-28 julian-day math, :33-49
Int96ToTime/TimeToInt96, :54-56 IsAfterUnixEpoch): INT96 stores nanoseconds
since midnight in the low 8 bytes (LE) and the Julian day number in the high
4 bytes (LE) — the legacy Impala/Hive timestamp encoding.
"""

from __future__ import annotations

import datetime as dt

import numpy as np

__all__ = [
    "int96_to_datetime",
    "datetime_to_int96",
    "int96_to_unix_nanos",
    "is_after_unix_epoch",
    "JULIAN_UNIX_EPOCH",
]

# Julian day number of 1970-01-01.
JULIAN_UNIX_EPOCH = 2_440_588

_EPOCH = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)


def int96_to_unix_nanos(raw) -> int:
    """12 bytes -> nanoseconds since the unix epoch (can be negative)."""
    b = bytes(raw)
    if len(b) != 12:
        raise ValueError(f"int96: need 12 bytes, got {len(b)}")
    nanos = int.from_bytes(b[:8], "little")
    jday = int.from_bytes(b[8:], "little")
    return (jday - JULIAN_UNIX_EPOCH) * 86_400_000_000_000 + nanos


def int96_to_datetime(raw) -> dt.datetime:
    nanos = int96_to_unix_nanos(raw)
    # Python datetimes hold microseconds; sub-microsecond precision truncates.
    return _EPOCH + dt.timedelta(microseconds=nanos // 1000)


def datetime_to_int96(value: dt.datetime) -> bytes:
    if value.tzinfo is None:
        value = value.replace(tzinfo=dt.timezone.utc)
    delta = value - _EPOCH
    total_micros = (delta.days * 86_400_000_000) + delta.seconds * 1_000_000 + delta.microseconds
    days, rem = divmod(total_micros, 86_400_000_000)
    nanos = rem * 1000
    jday = days + JULIAN_UNIX_EPOCH
    return nanos.to_bytes(8, "little") + jday.to_bytes(4, "little")


def is_after_unix_epoch(raw) -> bool:
    """True if the timestamp is after 1970-01-01T00:00:00Z
    (reference: int96_time.go:54-56)."""
    return int96_to_unix_nanos(raw) > 0


def int96_array_to_unix_nanos(arr: np.ndarray) -> np.ndarray:
    """Vectorized (n, 12) uint8 -> int64 unix nanoseconds."""
    a = np.asarray(arr, dtype=np.uint8)
    if a.ndim != 2 or a.shape[1] != 12:
        raise ValueError("int96: expected (n, 12) uint8 array")
    nanos = a[:, :8].copy().view("<u8").reshape(-1).astype(np.int64)
    jday = a[:, 8:].copy().view("<u4").reshape(-1).astype(np.int64)
    return (jday - JULIAN_UNIX_EPOCH) * 86_400_000_000_000 + nanos

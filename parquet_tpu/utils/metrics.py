"""Process-wide, always-on metrics registry.

The decode trace (utils/trace.py) answers "where did THIS read spend its
time" — it only exists inside a `with decode_trace()` block. This registry
answers "what has this PROCESS decoded since it started": counters and
histograms that every read feeds unconditionally, cheap enough to stay on in
production (one small lock around a dict update per page/chunk, not per
value). SURVEY §5 calls the reference out for having neither; serving heavy
traffic needs both.

    from parquet_tpu.utils import metrics

    before = metrics.snapshot()
    reader.read_row_group(0)                  # no trace needed
    print(metrics.delta(before))              # what that read added
    print(metrics.render_prometheus())        # text exposition for scrapes
    print(metrics.report())                   # human summary (ratio, MB/s)

Key families (all under the `parquet_tpu_` prefix in exposition):
  pages_decoded_total{encoding=}    pages decoded, per wire encoding
  page_bytes_total{encoding=}       uncompressed page bytes, per encoding
  bytes_compressed_total{codec=}    wire bytes entering decompression
  bytes_uncompressed_total{codec=}  bytes leaving decompression
  chunk_decode_seconds              histogram of per-chunk decode wall time
  events_total{event=}              every trace.bump() event, always-on —
                                    prepare_fused_engaged/_declined,
                                    prepare_fallback_recovered,
                                    encode_fused_engaged/_declined (the
                                    write-side ladder: one per chunk the
                                    fused native ptq_chunk_encode walk
                                    produced / stood down from),
                                    encode_fused_fault_<stage> (native
                                    encode aborts by stage: split/levels/
                                    values/compress/frame),
                                    encode_fallback_recovered (chunks the
                                    staged Python rung salvaged after a
                                    native abort),
                                    chunks_quarantined, ... dual-report here
  io_bytes_read_total               bytes actually read from byte sources
  io_read_calls_total               source read calls (coalescing shrinks it)
  io_retries_total{reason=}         failed source attempts absorbed by the
                                    RetryingSource ladder
  io_cache_hits/misses_total        block-cache outcomes; io_cache_bytes is
                                    the resident-bytes gauge
  io_footer_cache_hits/misses_total footer/metadata cache outcomes
  io_readahead_fetched/dropped_total  pqt-io readahead accepted vs shed
                                      (budget full); _errors_total swallowed
  pages_written_total{encoding=}    pages ENCODED by the write side, per
                                    wire encoding (dict pages count PLAIN);
                                    fed by BOTH encode rungs and by the
                                    device batch-materialization path
                                    (kernels/pipeline.encode_device_column),
                                    so page accounting is rung-independent
  write_bytes_total{codec=}         encoded row-group bytes committed to
                                    byte sinks, per codec
  encode_seconds                    histogram of per-chunk encode wall time
                                    (the write-side chunk_decode_seconds)
  sink_bytes_written_total          bytes actually written to byte sinks
  sink_write_calls_total            sink write calls (BufferedSink's
                                    write-combining shrinks it)
  assembly_rows_total{engine=}      rows materialized by record assembly,
                                    per engine: "vec" = the vectorized
                                    level-scan engine (core/assembly_vec),
                                    "scalar" = the cursor-walk fallback
                                    (PQT_VEC_ASSEMBLY=0 or unprovable
                                    shapes)
  assembly_seconds                  histogram of row-materialization wall
                                    time (one observation per assembly
                                    window / scalar group; same clock as
                                    the assembly.rows trace stage)
  serve_requests_total{status=,tenant=}  scan-service requests finished,
                                    by HTTP status and X-Tenant key (499 =
                                    client disconnected mid-stream)
  serve_queue_depth                 gauge: requests currently admitted and
                                    in flight in the serve daemon
  serve_request_seconds{endpoint=}  histogram of request wall time, entry
                                    to last byte (plan + queue + execute +
                                    stream), per endpoint
  serve_scan_bytes_total            response payload bytes streamed back
                                    by /v1/scan (jsonl or arrow-ipc)
  events_total{event="serve_stream_aborted"}  responses torn mid-stream
                                    (typed terminal record, no 0-chunk)
  events_total{event="plan_units_pruned_stats"|"plan_units_pruned_bloom"}
                                    row groups excluded at plan time, also
                                    on every ScanPlan.pruning_summary()
  serve_slow_requests_total{endpoint=}  requests at/over the daemon's
                                    slow_ms threshold (the flight
                                    recorder always keeps their traces);
                                    serve_request_seconds is labeled by
                                    the same bounded endpoint set, so
                                    /v1/plan and /v1/scan latencies are
                                    separable
  pool_queue_depth{pool=}           gauge: tasks submitted to a pqt-*
                                    pool and not yet running
  pool_active_workers{pool=}        gauge: tasks currently running on a
                                    pqt-* pool
  pool_queue_wait_seconds{pool=}    histogram: submit-to-start wait per
                                    pool — the elastic-SLO controller's
                                    primary input (also credited to the
                                    submitting request's trace as the
                                    pool.wait stage)
  pool_task_seconds{pool=}          histogram: task wall time per pool
  obs_requests_recorded_total{endpoint=}  flight-recorder records opened
                                    (serve endpoints + dataset.unit /
                                    encode.group library records)
  obs_ring_evictions_total          records evicted from the bounded
                                    flight-recorder ring
  obs_traces_retained_total         span trees kept by the recorder
                                    (sampled, slow or errored requests);
                                    obs_ring_records is the occupancy
                                    gauge
  log_events_total{event=}          structured log events emitted by
                                    obs.log (counted even with no
                                    handler attached)
  log_suppressed_total{event=}      events the per-key token-bucket rate
                                    limiter absorbed
  dataset_prefetch_target           gauge: the elastic-SLO controller's
                                    current prefetch-depth target (the
                                    dataset_prefetch_depth gauge shows
                                    what is actually in flight)
  dataset_slo_violations_total      consumer-wait observations that
                                    exceeded the dataset's configured
                                    slo_wait_ms
  io_hedges_total{outcome=}         hedged duplicate reads: "launched"
                                    when a read outlives the latency-
                                    quantile bar, then "win_primary" /
                                    "win_hedge" / "failed" for how the
                                    race resolved
  io_breaker_state{source=}         gauge: circuit-breaker state per
                                    source (0 closed, 1 open, 2 half-
                                    open); the label set is bounded by
                                    BreakerRegistry.max_sources
  serve_shed_total{reason=}         requests the daemon shed before
                                    spending execution on them
                                    ("queue_wait" = brownout on pqt-serve
                                    queue pressure, "breaker_open" = a
                                    blacked-out source fast-failed)
  process_uptime_seconds            gauge: seconds since process start
                                    (refreshed at every exposition
                                    render; /v1/debug/vars reports its
                                    own service-relative uptime_s)
  serve_tenant_cpu_seconds_total{tenant=}  executor CPU seconds (thread-
                                    time deltas around row-group units)
                                    charged to the admission-resolved
                                    tenant key — the "who is spending the
                                    machine" counter; bounded by the same
                                    sanitized-tenant table as
                                    serve_requests_total
  serve_tenant_decoded_bytes_total{tenant=}  uncompressed bytes decoded
                                    on behalf of each tenant (charged
                                    from the request's trace rollup at
                                    finish); /v1/debug/tenants carries
                                    the full usage table (source-read
                                    bytes, cache hits/misses, payload)
  obs_profile_samples_total{lane=}  continuous-profiler stack samples
                                    per pool lane (pqt-io/pqt-data/
                                    pqt-serve/pqt-encode/pqt-hedge/
                                    pqt-dispatch/other) —
                                    obs_profile_windows_total counts
                                    completed capture windows
  io_http_requests_total{status=}   HTTP round trips issued by remote
                                    sources (io.remote), per response
                                    status code
  io_http_connections_total{event=} pooled-connection lifecycle: "new"
                                    sockets opened vs "reused" checkouts
                                    from the per-host persistent pool
  io_resigns_total                  presigned-URL refreshes by
                                    ObjectStoreSource (proactive expiry
                                    refresh + reactive 401/403 re-signs)
  io_put_requests_total{status=}    HTTP round trips issued by remote
                                    SINKS (io.remote_sink: part PUTs,
                                    initiate/complete/abort), per status
  io_put_bytes_total                payload bytes acknowledged by the
                                    remote store (CRC-verified parts +
                                    single-shot PUTs — retries of a part
                                    count its bytes once)
  io_put_retries_total{reason=}     per-part/commit retry ladder steps,
                                    by fault shape ("http_503",
                                    "transport", "part_etag_mismatch")
  io_sign_requests_total{method=}   requests signed by the SigV4-style
                                    header signer (io.sign), per HTTP
                                    method — symmetric GET/PUT auth
  sink_multipart_initiated_total    multipart uploads initiated by
                                    HttpSink; _parts_total counts
                                    acknowledged part PUTs,
                                    _completed_total commits (the object
                                    became visible), _aborted_total
                                    abort-upload teardowns (nothing
                                    became visible)
  cache_tier_hits_total{tier=}      tiered-cache hits per tier (ram /
                                    disk); cache_tier_misses_total
                                    counts full misses (both tiers)
  cache_tier_evictions_total{tier=} blocks evicted per tier (ram
                                    evictions SPILL to disk; disk
                                    evictions drop whole oldest
                                    segments)
  cache_tier_spills_total           blocks spilled RAM -> disk
                                    (cache_tier_spill_bytes_total is
                                    the payload byte volume)
  cache_tier_promotions_total       disk hits promoted back to RAM
  cache_tier_restored_blocks_total  intact spilled blocks re-indexed
                                    from a persistent cache_dir at
                                    startup (restart survival)
  cache_tier_torn_segments_total    spill segments found torn at replay
                                    — the rest of the segment is
                                    DISCARDED, never served
  cache_tier_bytes{tier=}           gauge: resident bytes per tier
  io_autotune_gap_bytes{profile=}   gauge: the IO tuner's current
                                    coalesce-gap verdict per transport
                                    profile ("local", "http://host:port")
  io_autotune_latency_ms{profile=}  gauge: the EWMA per-request read
                                    latency behind that verdict
  events_total{event="device_filter_engaged"|"device_filter_declined"}
                                    the device row-filter engine ladder,
                                    one per row-group mask: "engaged" =
                                    the mask reduced in HBM
                                    (core/filter_device), "declined" = a
                                    typed DeviceFilterError re-derived it
                                    on the host vec engine — output
                                    identical either way
  events_total{event="device_write_engaged"|"device_write_declined"}
                                    the device write ladder, one per
                                    write_device_column chunk at flush:
                                    "engaged" = pages encoded by
                                    encode_device_column, "declined" = a
                                    typed shape refusal (dict byte
                                    arrays, BYTE_STREAM_SPLIT, width
                                    mismatches, ...) re-encoded host-side
                                    — bytes identical either way
  events_total{event="dataset_units_row_filtered"}
                                    dataset units whose delivered batch
                                    rows were masked by
                                    ParquetDataset(filter_rows=True)
  query_device_units_total{engine=} /v1/query row-group units under
                                    ServeConfig(device=): "device" =
                                    partial aggregate reduced in HBM
                                    (serve/query_device), "host_fallback"
                                    = shape outside the device envelope
                                    (float sums, group_by, decimals),
                                    answered by the exact pyarrow host
                                    path — rendered bytes identical
  query_device_unavailable_total    units that wanted the device path but
                                    jax was not importable (device=
                                    misconfiguration made visible)
  mesh_requests_total{endpoint=,mode=}  requests the mesh router routed,
                                    "scatter" = fanned out per plan unit,
                                    "passthrough" = forwarded whole to
                                    one replica (limit/shard-pinned and
                                    0/1-unit requests)
  mesh_backend_requests_total{status=}  router->replica HTTP round trips,
                                    per response status (the router-side
                                    twin of io_http_requests_total)
  mesh_retries_total{reason=}       backend attempts the mesh client
                                    failed over: "transport" (reset/
                                    truncated/refused), "5xx", "draining"
                                    (clean shed, breaker untouched),
                                    "shed" (brownout/queue_full/429),
                                    "breaker_open" (fast-fail, no
                                    transport touch)
  mesh_hedges_total{outcome=}       hedged duplicates to a second
                                    replica: "launched" when the first
                                    attempt outlives its replica's p95,
                                    then "won_primary"/"won_hedge"
  mesh_replica_state{replica=}      gauge: composite routing state per
                                    replica (0 up, 1 degraded, 2
                                    draining, 3 open-breaker, 4 down);
                                    label set bounded by the static
                                    --replica list
  mesh_scatter_units_total{endpoint=}  plan units fanned out by
                                    scatter-gather execution
  mesh_partial_failures_total{target=}  requests that exhausted EVERY
                                    replica and surfaced the typed
                                    partial_failure error
  lake_manifest_commits_total       generations committed to a lake
                                    manifest (ingest flushes + compactor
                                    rewrites)
  lake_generation                   gauge: the current generation number
                                    of the last-touched lake table
  lake_files / lake_rows            gauges: file and row counts of the
                                    current snapshot after a commit
  lake_files_unlinked_total         data files deleted once no retained
                                    generation referenced them
  lake_orphans_reaped_total         crash leftovers (unreferenced tmp/
                                    parquet past the grace window)
                                    removed by reap_orphans
  lake_append_rows_total            rows accepted by ingest append
  lake_append_bytes_total           request payload bytes accepted by
                                    ingest append
  lake_flushes_total                ingest buffer flushes (each publishes
                                    exactly one generation)
  lake_flush_seconds                histogram: sort+encode+commit latency
                                    of one ingest flush
  lake_compactions_total            background compaction passes that
                                    committed a rewrite
  lake_compact_files_total          small input files folded away by
                                    compaction
  lake_compact_rows_total           rows rewritten into sort-keyed row
                                    groups by compaction
  lake_compact_seconds              histogram: wall time of one
                                    merge+rewrite+commit pass
  io_multirange_requests_total{outcome=}  coalesced multi-range HTTP
                                    attempts: "ok" (one multipart round
                                    trip served every range),
                                    "full_body" (200 — sliced locally),
                                    "unsupported" (server collapsed the
                                    set; per-range latched on),
                                    "transport_fallback" /
                                    "parse_fallback" (this call fell
                                    back, next call tries again)
  io_multirange_parts_total         byterange parts parsed out of
                                    multipart/byteranges responses

Exposition variants: render_prometheus() is the classic text format every
scraper understands; render_openmetrics() is the content-negotiated
OpenMetrics 1.0 document (`Accept: application/openmetrics-text` on
GET /metrics) that additionally carries EXEMPLARS — request-ids attached
to serve_request_seconds buckets via observe(exemplar=...) — and ends
with `# EOF`. The classic output is byte-for-byte unaffected by
exemplars.

Snapshot keys are flat strings in Prometheus sample syntax without the
prefix: `pages_decoded_total{encoding="PLAIN"}`. Histograms snapshot as
`<name>_count` / `<name>_sum` / `<name>_min` / `<name>_max`; min/max are
not monotonic, so `delta()` skips them.

Three kinds: counters (`inc`, monotonic), histograms (`observe`), and gauges
(`set` / module-level `set_gauge` — a last-written level such as the
dataset prefetch queue depth). Gauges snapshot at their current value and
expose as `# TYPE ... gauge`; like histogram min/max they are not
monotonic, so `delta()` skips them.
"""

from __future__ import annotations

import os
import re
import threading
import time

__all__ = [
    "MetricsRegistry",
    "REGISTRY",
    "inc",
    "observe",
    "set_gauge",
    "get",
    "snapshot",
    "delta",
    "render_prometheus",
    "render_openmetrics",
    "process_stats",
    "report",
    "event",
    "page_decoded",
    "io_bytes",
    "encoding_name",
    "codec_name",
    "summarize_columns",
]

_PREFIX = "parquet_tpu_"

# log-ish spacing covering sub-ms page decodes through multi-second chunks
_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)


def _escape_label_value(v) -> str:
    # the Prometheus text-format escapes: backslash, double-quote, newline
    # (in that order — escaping the escape character first)
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return f"{name}{{{inner}}}"


def _format_le(le) -> str:
    """A histogram bound as a plain decimal (never repr()'s scientific
    notation): 0.0005 -> "0.0005", 1.0 -> "1" — what Prometheus tooling
    and humans both read without surprises."""
    s = f"{float(le):.12f}".rstrip("0").rstrip(".")
    return s or "0"


# one-line family descriptions, rendered as `# HELP` in the exposition —
# the prose lives in the module docstring; this is the scrape-visible form
_HELP = {
    "pages_decoded_total": "pages decoded, per wire encoding",
    "page_bytes_total": "uncompressed page bytes, per encoding",
    "bytes_compressed_total": "wire bytes entering decompression, per codec",
    "bytes_uncompressed_total": "bytes leaving decompression, per codec",
    "chunk_decode_seconds": "per-chunk decode wall time",
    "events_total": "every trace.bump() event, always-on",
    "io_bytes_read_total": "bytes actually read from byte sources",
    "io_read_calls_total": "source read calls (coalescing shrinks it)",
    "io_retries_total": "failed source attempts absorbed by the retry ladder",
    "io_cache_hits_total": "block-cache hits",
    "io_cache_misses_total": "block-cache misses",
    "io_cache_bytes": "block-cache resident bytes",
    "io_footer_cache_hits_total": "footer/metadata cache hits",
    "io_footer_cache_misses_total": "footer/metadata cache misses",
    "pages_written_total": "pages encoded by the write side, per encoding",
    "write_bytes_total": "encoded row-group bytes committed to sinks, per codec",
    "encode_seconds": "per-chunk encode wall time",
    "sink_bytes_written_total": "bytes actually written to byte sinks",
    "sink_write_calls_total": "sink write calls",
    "assembly_rows_total": "rows materialized by record assembly, per engine",
    "assembly_seconds": "row-materialization wall time",
    "dataset_batches_total": "batches delivered by ParquetDataset",
    "dataset_rows_total": "rows delivered by ParquetDataset",
    "dataset_wait_seconds": "consumer wait for the next decoded unit",
    "dataset_prefetch_depth": "dataset units currently in flight",
    "serve_requests_total": "scan-service requests finished, by status and tenant",
    "serve_queue_depth": "requests admitted and in flight in the serve daemon",
    "serve_request_seconds": "request wall time entry to last byte, per endpoint",
    "serve_scan_bytes_total": "response payload bytes streamed by /v1/scan",
    "serve_slow_requests_total": "requests at/over the slow_ms threshold, per endpoint",
    "pool_queue_depth": "tasks submitted to a pqt-* pool and not yet running",
    "pool_active_workers": "tasks currently running on a pqt-* pool",
    "pool_queue_wait_seconds": "submit-to-start wait per pool",
    "pool_task_seconds": "task wall time per pool",
    "obs_requests_recorded_total": "flight-recorder records opened, per endpoint",
    "obs_ring_evictions_total": "records evicted from the flight-recorder ring",
    "obs_traces_retained_total": "span trees retained by the flight recorder",
    "obs_ring_records": "flight-recorder ring occupancy",
    "log_events_total": "structured log events emitted, per event key",
    "log_suppressed_total": "log events absorbed by the rate limiter, per event key",
    "dataset_prefetch_target": "the SLO controller's current prefetch-depth target",
    "dataset_slo_violations_total": "consumer waits that exceeded the configured SLO",
    "io_hedges_total": "hedged-read outcomes (launched, win_primary, win_hedge, failed)",
    "io_breaker_state": "circuit-breaker state per source (0 closed, 1 open, 2 half-open)",
    "serve_shed_total": "requests shed before execution, per reason",
    "process_uptime_seconds": "seconds since process start, refreshed at each exposition render",
    "serve_tenant_cpu_seconds_total": "executor CPU seconds charged per tenant",
    "serve_tenant_decoded_bytes_total": "decoded (uncompressed) bytes charged per tenant",
    "obs_profile_samples_total": "sampling-profiler stack samples, per pool lane",
    "obs_profile_windows_total": "sampling-profiler capture windows completed",
    # query push-down (PR 12): residual filtering + aggregation
    "query_rows_filtered_total": (
        "rows removed by residual predicate evaluation, per engine "
        "(vec: the chunk-level mask pipeline; arrow: pyarrow-compute "
        "fallback masks; scalar: the per-row walk)"
    ),
    "filter_mask_seconds": "vectorized residual mask build wall time",
    "serve_aggregate_requests_total": (
        "aggregation push-down queries executed (/v1/query and the CLI twin)"
    ),
    # remote IO + tiered cache + auto-tuning (PR 13)
    "io_http_requests_total": "HTTP round trips by remote sources, per status",
    "io_http_connections_total": (
        "pooled HTTP connections: new sockets vs reused checkouts"
    ),
    "io_resigns_total": "presigned-URL refreshes by ObjectStoreSource",
    # remote writes + request signing (PR 17)
    "io_put_requests_total": "HTTP round trips by remote sinks, per status",
    "io_put_bytes_total": "payload bytes acknowledged by the remote store",
    "io_put_retries_total": "remote-write retry ladder steps, per fault shape",
    "io_sign_requests_total": "requests header-signed by io.sign, per method",
    "sink_multipart_initiated_total": "multipart uploads initiated",
    "sink_multipart_parts_total": "multipart part PUTs acknowledged",
    "sink_multipart_completed_total": "multipart uploads committed",
    "sink_multipart_aborted_total": "multipart uploads aborted (torn-free)",
    "cache_tier_hits_total": "tiered-cache hits, per tier (ram/disk)",
    "cache_tier_misses_total": "tiered-cache full misses (both tiers)",
    "cache_tier_evictions_total": "tiered-cache blocks evicted, per tier",
    "cache_tier_spills_total": "blocks spilled RAM -> disk",
    "cache_tier_spill_bytes_total": "payload bytes spilled RAM -> disk",
    "cache_tier_promotions_total": "disk hits promoted back to RAM",
    "cache_tier_restored_blocks_total": (
        "spilled blocks re-indexed from a persistent cache dir at startup"
    ),
    "cache_tier_torn_segments_total": (
        "spill segments found torn at replay (their tails are discarded)"
    ),
    "cache_tier_bytes": "tiered-cache resident bytes, per tier",
    "io_autotune_gap_bytes": (
        "the IO tuner's current coalesce-gap verdict, per transport profile"
    ),
    "io_autotune_latency_ms": (
        "EWMA per-request read latency, per transport profile"
    ),
    # mesh telemetry plane (PR 18): propagation + federation + SLO
    "io_traceparent_injected_total": (
        "traceparent headers injected into outbound HTTP calls, per "
        "transport (get/put)"
    ),
    "io_traceparent_inbound_total": (
        "inbound traceparent resolution outcomes "
        "(accepted/minted/invalid)"
    ),
    "fleet_scrapes_total": "fleet federation peer scrapes, per outcome",
    "fleet_replicas": "replicas merged into the last fleet view",
    "slo_burn_rate": (
        "error-budget burn rate per SLI and window (1.0 spends the "
        "budget exactly at sustainable speed)"
    ),
    "slo_error_budget_remaining": (
        "fraction of the error budget left in the slow window, per SLI"
    ),
    "slo_verdict": "SLO health verdict (0 ok, 1 warn, 2 burning)",
    # mesh routing plane (PR 19): the sharded-serve router + mesh client
    "mesh_requests_total": (
        "requests routed by the mesh router, per endpoint and mode "
        "(scatter/passthrough)"
    ),
    "mesh_backend_requests_total": (
        "router->replica HTTP round trips, per response status"
    ),
    "mesh_retries_total": (
        "backend attempts the mesh client failed over, per reason "
        "(transport/5xx/draining/shed/breaker_open)"
    ),
    "mesh_hedges_total": (
        "hedged backend duplicates: launched past the replica p95, then "
        "won_primary/won_hedge for how the race resolved"
    ),
    "mesh_replica_state": (
        "gauge: composite replica routing state (0 up, 1 degraded, "
        "2 draining, 3 open-breaker, 4 down); one series per --replica"
    ),
    "mesh_scatter_units_total": (
        "plan units fanned out by scatter-gather, per endpoint"
    ),
    "mesh_partial_failures_total": (
        "requests that exhausted every replica (typed partial_failure), "
        "per target route"
    ),
    # the lake write path (PR 20): streaming ingest, snapshot manifest,
    # background compaction
    "lake_manifest_commits_total": (
        "generations committed to a lake manifest (ingest flushes + "
        "compactor rewrites)"
    ),
    "lake_generation": (
        "gauge: current generation number of the last-touched lake table"
    ),
    "lake_files": "gauge: file count of the current snapshot after a commit",
    "lake_rows": "gauge: row count of the current snapshot after a commit",
    "lake_files_unlinked_total": (
        "data files deleted once no retained generation referenced them"
    ),
    "lake_orphans_reaped_total": (
        "crash leftovers (unreferenced tmp/parquet past the grace window) "
        "removed by reap_orphans"
    ),
    "lake_append_rows_total": "rows accepted by ingest append",
    "lake_append_bytes_total": (
        "request payload bytes accepted by ingest append"
    ),
    "lake_flushes_total": (
        "ingest buffer flushes; each publishes exactly one generation"
    ),
    "lake_flush_seconds": (
        "sort+encode+commit latency of one ingest flush"
    ),
    "lake_compactions_total": (
        "background compaction passes that committed a rewrite"
    ),
    "lake_compact_files_total": (
        "small input files folded away by compaction"
    ),
    "lake_compact_rows_total": (
        "rows rewritten into sort-keyed row groups by compaction"
    ),
    "lake_compact_seconds": (
        "wall time of one merge+rewrite+commit compaction pass"
    ),
    "io_multirange_requests_total": (
        "coalesced multi-range HTTP attempts, per outcome "
        "(ok/full_body/unsupported/transport_fallback/parse_fallback)"
    ),
    "io_multirange_parts_total": (
        "byterange parts parsed out of multipart/byteranges responses"
    ),
    # process self-metrics, refreshed at exposition render (stdlib /proc
    # reads; absent on platforms without procfs)
    "process_resident_memory_bytes": "resident set size of this process",
    "process_open_fds": "open file descriptors held by this process",
    "process_threads_total": "OS threads in this process",
}


class _Hist:
    __slots__ = (
        "count", "total", "vmin", "vmax", "buckets", "bucket_counts",
        "exemplars",
    )

    def __init__(self, buckets=_DEFAULT_BUCKETS):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.buckets = buckets
        self.bucket_counts = [0] * len(buckets)
        # per-bucket last exemplar (index len(buckets) = the +Inf bucket):
        # (labels dict, observed value, unix ts) — allocated on first use
        # so histograms nobody attaches exemplars to pay one None
        self.exemplars: list | None = None

    def observe(self, v: float, exemplar: dict | None = None) -> None:
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        slot = len(self.buckets)  # +Inf unless a finite bound admits v
        for i, le in enumerate(self.buckets):
            if v <= le:
                self.bucket_counts[i] += 1
                slot = min(slot, i)
        if exemplar is not None:
            # last-write-wins in the value's CANONICAL (first admitting)
            # bucket: one recent trace reference per latency band, bounded
            # by the bucket count — never by traffic
            if self.exemplars is None:
                self.exemplars = [None] * (len(self.buckets) + 1)
            self.exemplars[slot] = (dict(exemplar), v, time.time())


class MetricsRegistry:
    """Lock-cheap counters + histograms with snapshot/delta and Prometheus
    text exposition. One instance (REGISTRY) serves the whole process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], int | float] = {}
        self._hists: dict[tuple[str, tuple], _Hist] = {}
        self._gauges: dict[tuple[str, tuple], int | float] = {}
        # family names that are gauges: delta() must skip them (a gauge
        # difference is as meaningless as a histogram min/max difference)
        self._gauge_names: set[str] = set()

    # -- write side ------------------------------------------------------------

    def inc(self, name: str, n=1, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def set(self, name: str, value, **labels) -> None:
        """Set a gauge to its current level (last write wins) — for
        non-monotonic quantities like queue depths or in-flight counts."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value
            self._gauge_names.add(name)

    def observe(
        self, name: str, value: float, exemplar: dict | None = None, **labels
    ) -> None:
        """Record one histogram observation. `exemplar` (a small dict such
        as {"request_id": ...}) attaches a metric→trace reference to the
        value's bucket, rendered only by the OpenMetrics exposition — the
        classic text format ignores it entirely."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            h.observe(value, exemplar)

    def hist_stats(self, name: str, **labels) -> dict:
        """One histogram's running totals — {"count", "sum", "buckets",
        "bucket_counts"} — without paying for a full snapshot(). The cheap
        windowed-delta feed for feedback controllers (the SLO controller
        polls this every control window); a never-observed histogram
        returns zeros over the default buckets."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                return {
                    "count": 0,
                    "sum": 0.0,
                    "buckets": tuple(_DEFAULT_BUCKETS),
                    "bucket_counts": [0] * len(_DEFAULT_BUCKETS),
                }
            return {
                "count": h.count,
                "sum": h.total,
                "buckets": tuple(h.buckets),
                "bucket_counts": list(h.bucket_counts),
            }

    # -- read side -------------------------------------------------------------

    def get(self, name: str, **labels):
        """Current value of one counter or gauge (0 when never written)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if key in self._gauges:
                return self._gauges[key]
            return self._counters.get(key, 0)

    def snapshot(self) -> dict:
        """Flat {sample key: value} of every counter, gauge and histogram."""
        out = {}
        with self._lock:
            for (name, labels), v in self._counters.items():
                out[_key(name, dict(labels))] = v
            for (name, labels), v in self._gauges.items():
                out[_key(name, dict(labels))] = v
            for (name, labels), h in self._hists.items():
                ld = dict(labels)
                out[_key(name + "_count", ld)] = h.count
                out[_key(name + "_sum", ld)] = h.total
                if h.count:
                    out[_key(name + "_min", ld)] = h.vmin
                    out[_key(name + "_max", ld)] = h.vmax
        return out

    def delta(self, previous: dict) -> dict:
        """What changed since `previous` (a snapshot()): {key: now - then},
        zero-diff keys omitted. Histogram _min/_max and gauges are skipped —
        they are not monotonic, so their difference is meaningless."""
        now = self.snapshot()
        with self._lock:
            gauge_names = set(self._gauge_names)
        out = {}
        for k, v in now.items():
            base = k.split("{", 1)[0]
            if base.endswith("_min") or base.endswith("_max"):
                continue
            if base in gauge_names:
                continue
            d = v - previous.get(k, 0)
            if d:
                out[k] = d
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (families prefixed parquet_tpu_)."""
        lines = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        seen_types = set()

        def family_header(name: str, kind: str) -> None:
            if name in seen_types:
                return
            seen_types.add(name)
            doc = _HELP.get(name)
            if doc:
                lines.append(f"# HELP {_PREFIX}{name} {doc}")
            lines.append(f"# TYPE {_PREFIX}{name} {kind}")

        for (name, labels), v in counters:
            family_header(name, "counter")
            lines.append(f"{_PREFIX}{_key(name, dict(labels))} {v}")
        for (name, labels), v in gauges:
            family_header(name, "gauge")
            lines.append(f"{_PREFIX}{_key(name, dict(labels))} {v}")
        for (name, labels), h in hists:
            family_header(name, "histogram")
            ld = dict(labels)
            # bucket_counts are cumulative already (observe() increments
            # every bucket whose bound admits the value)
            for le, c in zip(h.buckets, h.bucket_counts):
                lines.append(
                    f"{_PREFIX}{_key(name + '_bucket', {**ld, 'le': _format_le(le)})} {c}"
                )
            lines.append(
                f"{_PREFIX}{_key(name + '_bucket', {**ld, 'le': '+Inf'})} {h.count}"
            )
            lines.append(f"{_PREFIX}{_key(name + '_sum', ld)} {h.total}")
            lines.append(f"{_PREFIX}{_key(name + '_count', ld)} {h.count}")
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition — the content-negotiated
        variant of render_prometheus() (Accept: application/openmetrics-
        text). Differences from the classic format, per the spec:

          * counter FAMILIES drop their `_total` suffix in # TYPE/# HELP
            while samples keep it (`# TYPE ..._requests counter` +
            `..._requests_total{...} 3`);
          * histogram bucket samples may carry an EXEMPLAR — ` # {labels}
            value timestamp` — here the request-id attached via
            observe(exemplar=...), which is the dashboard→flight-recorder
            link: a latency bucket names the exact request an operator can
            fetch from /v1/debug/requests/<id>;
          * the document terminates with `# EOF`.

        Scrapers that never ask for OpenMetrics see the classic format
        unchanged (exemplars are invisible there)."""
        lines = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = [
                (k, h, list(h.exemplars) if h.exemplars else None)
                for k, h in sorted(self._hists.items())
            ]
        seen_types = set()

        def family_header(name: str, kind: str, family=None) -> None:
            if name in seen_types:
                return
            seen_types.add(name)
            fam = family if family is not None else name
            doc = _HELP.get(name)
            lines.append(f"# TYPE {_PREFIX}{fam} {kind}")
            if doc:
                lines.append(f"# HELP {_PREFIX}{fam} {doc}")

        def exemplar_suffix(ex) -> str:
            if ex is None:
                return ""
            labels, value, ts = ex
            inner = ",".join(
                f'{k}="{_escape_label_value(v)}"'
                for k, v in sorted(labels.items())
            )
            return f" # {{{inner}}} {value:g} {ts:.3f}"

        for (name, labels), v in counters:
            fam = name[: -len("_total")] if name.endswith("_total") else name
            family_header(name, "counter", family=fam)
            lines.append(f"{_PREFIX}{_key(name, dict(labels))} {v}")
        for (name, labels), v in gauges:
            family_header(name, "gauge")
            lines.append(f"{_PREFIX}{_key(name, dict(labels))} {v}")
        for ((name, labels), h, exemplars) in hists:
            family_header(name, "histogram")
            ld = dict(labels)
            for i, (le, c) in enumerate(zip(h.buckets, h.bucket_counts)):
                ex = exemplars[i] if exemplars else None
                lines.append(
                    f"{_PREFIX}{_key(name + '_bucket', {**ld, 'le': _format_le(le)})}"
                    f" {c}{exemplar_suffix(ex)}"
                )
            ex = exemplars[len(h.buckets)] if exemplars else None
            lines.append(
                f"{_PREFIX}{_key(name + '_bucket', {**ld, 'le': '+Inf'})}"
                f" {h.count}{exemplar_suffix(ex)}"
            )
            lines.append(f"{_PREFIX}{_key(name + '_sum', ld)} {h.total}")
            lines.append(f"{_PREFIX}{_key(name + '_count', ld)} {h.count}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every metric (tests only — production counters are
        monotonic for the life of the process)."""
        with self._lock:
            self._counters.clear()
            self._hists.clear()
            self._gauges.clear()
            self._gauge_names.clear()


REGISTRY = MetricsRegistry()

# Process start, for the process_uptime_seconds gauge the expositions
# refresh on every render (a scrape always sees current uptime).
_PROCESS_START = time.time()


def _refresh_uptime(registry: MetricsRegistry) -> None:
    registry.set(
        "process_uptime_seconds", round(time.time() - _PROCESS_START, 3)
    )


def process_stats() -> dict:
    """Best-effort process self-stats from /proc (stdlib only): rss bytes,
    open fd count, OS thread count. Keys are present only when their
    source is readable — on platforms without procfs the dict is simply
    empty, and the gauges never appear in the exposition."""
    out: dict = {}
    try:
        with open("/proc/self/statm", "rb") as f:
            rss_pages = int(f.read().split()[1])
        out["rss_bytes"] = rss_pages * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError):
        pass
    try:
        out["open_fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"Threads:"):
                    out["threads"] = int(line.split()[1])
                    break
    except (OSError, ValueError, IndexError):
        pass
    if "threads" not in out:
        # portable fallback: Python-visible threads (misses non-Python
        # OS threads, but beats absence on non-procfs platforms)
        out["threads"] = threading.active_count()
    return out


def _refresh_process_metrics(registry: MetricsRegistry) -> None:
    """Refresh the process self-gauges at exposition render, so every
    scrape sees current values without a background sampler thread."""
    stats = process_stats()
    if "rss_bytes" in stats:
        registry.set("process_resident_memory_bytes", stats["rss_bytes"])
    if "open_fds" in stats:
        registry.set("process_open_fds", stats["open_fds"])
    if "threads" in stats:
        registry.set("process_threads_total", stats["threads"])


# -- module-level convenience (the registry everyone means) --------------------


def inc(name: str, n=1, **labels) -> None:
    REGISTRY.inc(name, n, **labels)


def observe(
    name: str, value: float, exemplar: dict | None = None, **labels
) -> None:
    REGISTRY.observe(name, value, exemplar, **labels)


def set_gauge(name: str, value, **labels) -> None:
    REGISTRY.set(name, value, **labels)


def get(name: str, **labels):
    return REGISTRY.get(name, **labels)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def delta(previous: dict) -> dict:
    return REGISTRY.delta(previous)


def render_prometheus() -> str:
    _refresh_uptime(REGISTRY)
    _refresh_process_metrics(REGISTRY)
    return REGISTRY.render_prometheus()


def render_openmetrics() -> str:
    _refresh_uptime(REGISTRY)
    _refresh_process_metrics(REGISTRY)
    return REGISTRY.render_openmetrics()


# -- the decode plumbing's vocabulary ------------------------------------------


def event(name: str, n: int = 1) -> None:
    """Always-on counterpart of trace.bump(): every bump dual-reports here
    so fused/fallback/quarantine counts survive outside any trace."""
    REGISTRY.inc("events_total", n, event=name)


def page_decoded(encoding: str, n: int = 1, nbytes: int = 0) -> None:
    REGISTRY.inc("pages_decoded_total", n, encoding=encoding)
    if nbytes:
        REGISTRY.inc("page_bytes_total", nbytes, encoding=encoding)


def io_bytes(compressed: int, uncompressed: int, codec) -> None:
    c = codec_name(codec)
    REGISTRY.inc("bytes_compressed_total", compressed, codec=c)
    REGISTRY.inc("bytes_uncompressed_total", uncompressed, codec=c)


def encoding_name(enc) -> str:
    try:
        from ..meta.parquet_types import Encoding

        return Encoding(int(enc)).name
    except Exception:
        return str(enc)


def codec_name(codec) -> str:
    if isinstance(codec, str):
        return codec
    try:
        from ..meta.parquet_types import CompressionCodec

        return CompressionCodec(int(codec)).name
    except Exception:
        return str(codec)


_LABEL_RE = re.compile(r'^(?P<name>[^{]+)(?:\{(?P<labels>.*)\})?$')


def _sum_family(snap: dict, family: str) -> int:
    total = 0
    for k, v in snap.items():
        m = _LABEL_RE.match(k)
        if m and m.group("name") == family:
            total += v
    return total


def report(snap: dict | None = None) -> str:
    """Human summary of the process counters (or of a snapshot/delta dict):
    page counts per encoding, byte volumes, compression ratio, decode MB/s."""
    if snap is None:
        snap = REGISTRY.snapshot()
    pages = {}
    events = {}
    for k, v in snap.items():
        m = _LABEL_RE.match(k)
        if not m:
            continue
        name, labels = m.group("name"), m.group("labels") or ""
        if name == "pages_decoded_total":
            pages[labels.split('"')[1] if '"' in labels else labels] = v
        elif name == "events_total" and '"' in labels:
            events[labels.split('"')[1]] = v
    comp = _sum_family(snap, "bytes_compressed_total")
    uncomp = _sum_family(snap, "bytes_uncompressed_total")
    secs = _sum_family(snap, "chunk_decode_seconds_sum")
    lines = []
    enc_part = ", ".join(f"{e}={n}" for e, n in sorted(pages.items()))
    lines.append(f"pages decoded:      {sum(pages.values()):>12,}  ({enc_part})")
    lines.append(f"bytes compressed:   {comp:>12,}")
    lines.append(f"bytes uncompressed: {uncomp:>12,}")
    ratio = f"{uncomp / comp:.2f}x" if comp else "n/a"
    lines.append(f"compression ratio:  {ratio:>12}")
    if secs:
        lines.append(
            f"chunk decode wall:  {secs:>12.4f} s  "
            f"(~{uncomp / secs / 1e6:.0f} MB/s uncompressed)"
        )
    if events:
        ev = ", ".join(f"{k}={v}" for k, v in sorted(events.items()))
        lines.append(f"events:             {ev}")
    return "\n".join(lines)


def summarize_columns(metadata) -> dict:
    """Per-column totals across every row group of a FileMetaData:
    {dotted path: {encodings, compressed, uncompressed, ratio}} — the
    metadata-sourced feed for `parquet-tool meta`'s summary lines (the same
    shape the live registry accumulates per encoding during decode)."""
    out: dict[str, dict] = {}
    for rg in metadata.row_groups or []:
        for cc in rg.columns or []:
            md = cc.meta_data
            if md is None:
                continue
            name = ".".join(md.path_in_schema or [])
            s = out.setdefault(
                name, {"encodings": [], "compressed": 0, "uncompressed": 0}
            )
            for e in md.encodings or []:
                en = encoding_name(e)
                if en not in s["encodings"]:
                    s["encodings"].append(en)
            s["compressed"] += md.total_compressed_size or 0
            s["uncompressed"] += md.total_uncompressed_size or 0
    for s in out.values():
        s["encodings"] = sorted(s["encodings"])
        s["ratio"] = (
            s["uncompressed"] / s["compressed"] if s["compressed"] else None
        )
    return out

"""Textual schema DSL: parser, printer, validator.

The equivalent of the reference's parquetschema package (reference:
parquetschema/schema_parser.go — lexer :98-257, parser :314-729, validator
:734-955; grammar documented at schema_def.go:33-93). Same grammar:

    message <name> {
      <repetition> <type> <name> [(ANNOTATION[(args)])] [= <field id>];
      <repetition> group <name> [(LIST|MAP)] { ... }
    }

Types: boolean int32 int64 int96 float double binary fixed_len_byte_array(N).
Annotations: STRING ENUM UUID JSON BSON DATE MAP LIST MAP_KEY_VALUE INTERVAL,
DECIMAL(p[,s]), TIME(MILLIS|MICROS|NANOS, true|false),
TIMESTAMP(MILLIS|MICROS|NANOS, true|false), INT(8|16|32|64, true|false), plus
the legacy converted-type spellings (UTF8, TIME_MILLIS, TIMESTAMP_MICROS,
UINT_8..INT_64, ...).

parse_schema() -> Schema (the same core.schema.Schema the reader/writer use);
schema_to_string() round-trips (reference: schema_def.go:114-132 String()).
Validation: structural checks during parse; validate()/validate_strict() add
LIST/MAP/TIME/DECIMAL convention checks with the reference's lenient mode
accepting Athena's `bag`/`array_element` forms (schema_parser.go:776-833).
"""

from __future__ import annotations

import re

from ..core.schema import Column, Schema, SchemaError
from ..meta.parquet_types import (
    ConvertedType,
    DecimalType,
    FieldRepetitionType,
    IntType,
    ListType,
    LogicalType,
    MapType,
    SchemaElement,
    StringType,
    TimestampType,
    TimeType,
    TimeUnit,
    Type,
    BsonType,
    DateType,
    EnumType,
    Float16Type,
    JsonType,
    UUIDType,
)

__all__ = ["parse_schema", "schema_to_string", "SchemaParseError", "validate", "validate_strict"]


class SchemaParseError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<punct>[{}();=,])
  | (?P<num>-?\d+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
    """,
    re.VERBOSE,
)

_PHYSICAL = {
    "boolean": Type.BOOLEAN,
    "int32": Type.INT32,
    "int64": Type.INT64,
    "int96": Type.INT96,
    "float": Type.FLOAT,
    "double": Type.DOUBLE,
    "binary": Type.BYTE_ARRAY,
    "fixed_len_byte_array": Type.FIXED_LEN_BYTE_ARRAY,
}

_REPETITION = {
    "required": FieldRepetitionType.REQUIRED,
    "optional": FieldRepetitionType.OPTIONAL,
    "repeated": FieldRepetitionType.REPEATED,
}

# Simple (argument-free) annotations -> (converted type, logical ctor)
_SIMPLE_ANNOTATIONS = {
    "STRING": (ConvertedType.UTF8, lambda: LogicalType(STRING=StringType())),
    "UTF8": (ConvertedType.UTF8, lambda: LogicalType(STRING=StringType())),
    "ENUM": (ConvertedType.ENUM, lambda: LogicalType(ENUM=EnumType())),
    "JSON": (ConvertedType.JSON, lambda: LogicalType(JSON=JsonType())),
    "BSON": (ConvertedType.BSON, lambda: LogicalType(BSON=BsonType())),
    "DATE": (ConvertedType.DATE, lambda: LogicalType(DATE=DateType())),
    "UUID": (None, lambda: LogicalType(UUID=UUIDType())),
    "FLOAT16": (None, lambda: LogicalType(FLOAT16=Float16Type())),
    "MAP": (ConvertedType.MAP, lambda: LogicalType(MAP=MapType())),
    "LIST": (ConvertedType.LIST, lambda: LogicalType(LIST=ListType())),
    "MAP_KEY_VALUE": (ConvertedType.MAP_KEY_VALUE, lambda: None),
    "INTERVAL": (ConvertedType.INTERVAL, lambda: None),
    "TIME_MILLIS": (ConvertedType.TIME_MILLIS,
                    lambda: LogicalType(TIME=TimeType(isAdjustedToUTC=True, unit=TimeUnit.millis()))),
    "TIME_MICROS": (ConvertedType.TIME_MICROS,
                    lambda: LogicalType(TIME=TimeType(isAdjustedToUTC=True, unit=TimeUnit.micros()))),
    "TIMESTAMP_MILLIS": (ConvertedType.TIMESTAMP_MILLIS,
                         lambda: LogicalType(TIMESTAMP=TimestampType(isAdjustedToUTC=True, unit=TimeUnit.millis()))),
    "TIMESTAMP_MICROS": (ConvertedType.TIMESTAMP_MICROS,
                         lambda: LogicalType(TIMESTAMP=TimestampType(isAdjustedToUTC=True, unit=TimeUnit.micros()))),
}
for _bits in (8, 16, 32, 64):
    for _sign, _prefix in ((True, "INT"), (False, "UINT")):
        _SIMPLE_ANNOTATIONS[f"{_prefix}_{_bits}"] = (
            ConvertedType[f"{_prefix}_{_bits}"],
            (lambda b, s: (lambda: LogicalType(INTEGER=IntType(bitWidth=b, isSigned=s))))(_bits, _sign),
        )


class _Tokens:
    def __init__(self, text: str):
        self.tokens: list[tuple[str, str, int]] = []  # (kind, value, line)
        line = 1
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m:
                raise SchemaParseError(
                    f"schema: unexpected character {text[pos]!r} at line {line}"
                )
            kind = m.lastgroup
            value = m.group()
            if kind == "ws":
                line += value.count("\n")
            else:
                self.tokens.append((kind, value, line))
            pos = m.end()
        self.i = 0

    def peek(self):
        return self.tokens[self.i] if self.i < len(self.tokens) else ("eof", "", -1)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, value=None, kind=None):
        k, v, line = self.next()
        if value is not None and v != value:
            raise SchemaParseError(f"schema: expected {value!r}, got {v!r} at line {line}")
        if kind is not None and k != kind:
            raise SchemaParseError(f"schema: expected {kind}, got {v!r} at line {line}")
        return v

    def accept(self, value) -> bool:
        if self.peek()[1] == value:
            self.i += 1
            return True
        return False


def parse_schema(text: str) -> Schema:
    """Parse DSL text into a Schema (reference: ParseSchemaDefinition)."""
    toks = _Tokens(text)
    kw = toks.expect(kind="ident")
    if kw != "message":
        raise SchemaParseError(f"schema: expected 'message', got {kw!r}")
    name = toks.expect(kind="ident")
    toks.expect("{")
    children = _parse_group_body(toks)
    if toks.peek()[0] != "eof":
        k, v, line = toks.peek()
        raise SchemaParseError(f"schema: trailing content {v!r} at line {line}")
    root = Column(
        element=SchemaElement(name=name, num_children=len(children)),
        children=children,
    )
    return Schema(root)


def _parse_group_body(toks: _Tokens) -> list[Column]:
    children = []
    while not toks.accept("}"):
        children.append(_parse_field(toks))
    return children


def _parse_field(toks: _Tokens) -> Column:
    k, v, line = toks.next()
    rep = _REPETITION.get(v)
    if rep is None:
        raise SchemaParseError(
            f"schema: expected repetition (required/optional/repeated), got {v!r} at line {line}"
        )
    k, v, line = toks.next()
    if v == "group":
        name = toks.expect(kind="ident")
        converted = None
        logical = None
        if toks.accept("("):
            converted, logical, _, _ = _parse_annotation(toks)
        field_id = _parse_field_id(toks)
        toks.expect("{")
        children = _parse_group_body(toks)
        if not children:
            raise SchemaParseError(f"schema: group {name!r} has no children")
        elem = SchemaElement(
            name=name,
            repetition_type=int(rep),
            num_children=len(children),
            converted_type=int(converted) if converted is not None else None,
            logicalType=logical,
            field_id=field_id,
        )
        return Column(element=elem, children=children)
    ptype = _PHYSICAL.get(v)
    if ptype is None:
        raise SchemaParseError(f"schema: unknown type {v!r} at line {line}")
    type_length = None
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        toks.expect("(")
        type_length = int(toks.expect(kind="num"))
        toks.expect(")")
        if type_length <= 0:
            raise SchemaParseError(f"schema: invalid fixed length {type_length}")
    name = toks.expect(kind="ident")
    converted = logical = None
    scale = precision = None
    if toks.accept("("):
        converted, logical, scale, precision = _parse_annotation(toks)
    field_id = _parse_field_id(toks)
    toks.expect(";")
    elem = SchemaElement(
        type=int(ptype),
        type_length=type_length,
        name=name,
        repetition_type=int(rep),
        converted_type=int(converted) if converted is not None else None,
        logicalType=logical,
        scale=scale,
        precision=precision,
        field_id=field_id,
    )
    return Column(element=elem)


def _parse_field_id(toks: _Tokens):
    if toks.accept("="):
        return int(toks.expect(kind="num"))
    return None


def _parse_annotation(toks: _Tokens):
    """Inside '(...)': returns (converted, logical, scale, precision)."""
    k, v, line = toks.next()
    upper = v.upper()
    if upper in _SIMPLE_ANNOTATIONS:
        conv, mk = _SIMPLE_ANNOTATIONS[upper]
        toks.expect(")")
        return conv, mk(), None, None
    if upper == "DECIMAL":
        precision = scale = None
        if toks.accept("("):
            precision = int(toks.expect(kind="num"))
            if toks.accept(","):
                scale = int(toks.expect(kind="num"))
            toks.expect(")")
        toks.expect(")")
        scale = scale or 0
        if precision is None or precision <= 0:
            raise SchemaParseError(f"schema: DECIMAL needs positive precision at line {line}")
        if scale < 0 or scale > precision:
            raise SchemaParseError(
                f"schema: DECIMAL scale {scale} out of range for precision {precision}"
            )
        lt = LogicalType(DECIMAL=DecimalType(scale=scale, precision=precision))
        return ConvertedType.DECIMAL, lt, scale, precision
    if upper in ("TIME", "TIMESTAMP"):
        toks.expect("(")
        unit_name = toks.expect(kind="ident").upper()
        units = {"MILLIS": TimeUnit.millis, "MICROS": TimeUnit.micros, "NANOS": TimeUnit.nanos}
        if unit_name not in units:
            raise SchemaParseError(f"schema: bad {upper} unit {unit_name} at line {line}")
        toks.expect(",")
        utc_tok = toks.expect(kind="ident")
        if utc_tok not in ("true", "false"):
            raise SchemaParseError(f"schema: bad utc flag {utc_tok!r} at line {line}")
        utc = utc_tok == "true"
        toks.expect(")")
        toks.expect(")")
        unit = units[unit_name]()
        if upper == "TIME":
            conv = {
                "MILLIS": ConvertedType.TIME_MILLIS,
                "MICROS": ConvertedType.TIME_MICROS,
                "NANOS": None,
            }[unit_name] if utc else None
            return conv, LogicalType(TIME=TimeType(isAdjustedToUTC=utc, unit=unit)), None, None
        conv = {
            "MILLIS": ConvertedType.TIMESTAMP_MILLIS,
            "MICROS": ConvertedType.TIMESTAMP_MICROS,
            "NANOS": None,
        }[unit_name] if utc else None
        return conv, LogicalType(TIMESTAMP=TimestampType(isAdjustedToUTC=utc, unit=unit)), None, None
    if upper == "INT":
        toks.expect("(")
        bits = int(toks.expect(kind="num"))
        if bits not in (8, 16, 32, 64):
            raise SchemaParseError(f"schema: INT bit width {bits} invalid at line {line}")
        toks.expect(",")
        signed_tok = toks.expect(kind="ident")
        if signed_tok not in ("true", "false"):
            raise SchemaParseError(f"schema: bad signed flag {signed_tok!r} at line {line}")
        signed = signed_tok == "true"
        toks.expect(")")
        toks.expect(")")
        conv = ConvertedType[f"{'INT' if signed else 'UINT'}_{bits}"]
        return conv, LogicalType(INTEGER=IntType(bitWidth=bits, isSigned=signed)), None, None
    raise SchemaParseError(f"schema: unknown annotation {v!r} at line {line}")


# -- printer -------------------------------------------------------------------

_TYPE_NAMES = {v: k for k, v in _PHYSICAL.items()}


def schema_to_string(schema: Schema) -> str:
    """Print a Schema as DSL text; parse(schema_to_string(s)) round-trips."""
    lines = [f"message {schema.root.name} {{"]
    for child in schema.root.children:
        _print_column(child, lines, 1)
    lines.append("}")
    return "\n".join(lines)


def _print_column(col: Column, lines: list[str], depth: int) -> None:
    ind = "  " * depth
    rep = col.repetition.name.lower()
    ann = _annotation_str(col)
    fid = f" = {col.element.field_id}" if col.element.field_id is not None else ""
    if col.is_leaf:
        t = _TYPE_NAMES[col.type]
        if col.type == Type.FIXED_LEN_BYTE_ARRAY:
            t = f"{t}({col.type_length})"
        lines.append(f"{ind}{rep} {t} {col.name}{ann}{fid};")
    else:
        lines.append(f"{ind}{rep} group {col.name}{ann}{fid} {{")
        for c in col.children:
            _print_column(c, lines, depth + 1)
        lines.append(f"{ind}}}")


def _annotation_str(col: Column) -> str:
    lt = col.logical_type
    if lt is not None:
        which = lt.which()
        if which == "STRING":
            return " (STRING)"
        if which == "ENUM":
            return " (ENUM)"
        if which == "JSON":
            return " (JSON)"
        if which == "BSON":
            return " (BSON)"
        if which == "DATE":
            return " (DATE)"
        if which == "UUID":
            return " (UUID)"
        if which == "MAP":
            return " (MAP)"
        if which == "LIST":
            return " (LIST)"
        if which == "DECIMAL":
            d = lt.DECIMAL
            return f" (DECIMAL({d.precision},{d.scale}))"
        if which == "TIME":
            t = lt.TIME
            return f" (TIME({t.unit.unit_name()},{'true' if t.isAdjustedToUTC else 'false'}))"
        if which == "TIMESTAMP":
            t = lt.TIMESTAMP
            return f" (TIMESTAMP({t.unit.unit_name()},{'true' if t.isAdjustedToUTC else 'false'}))"
        if which == "INTEGER":
            i = lt.INTEGER
            return f" (INT({i.bitWidth},{'true' if i.isSigned else 'false'}))"
    ct = col.converted_type
    if ct is not None:
        return f" ({ct.name})"
    return ""


# -- validation (reference: schema_parser.go:734-955) --------------------------


def validate(schema: Schema, strict: bool = False) -> None:
    for child in schema.root.children:
        _validate_column(child, strict)


def validate_strict(schema: Schema) -> None:
    validate(schema, strict=True)


def _validate_column(col: Column, strict: bool) -> None:
    ct = col.converted_type
    lt = col.logical_type
    is_list = ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)
    is_map = ct == ConvertedType.MAP or (lt is not None and lt.MAP is not None)
    if is_list:
        _validate_list(col, strict)
    elif is_map:
        _validate_map(col, strict)
    elif col.is_leaf:
        _validate_leaf(col)
    for c in col.children:
        _validate_column(c, strict)


def _validate_list(col: Column, strict: bool) -> None:
    if col.is_leaf:
        raise SchemaError(f"schema: LIST {col.path_str or col.name} must be a group")
    if col.repetition == FieldRepetitionType.REPEATED:
        raise SchemaError(f"schema: LIST {col.name} must not be repeated")
    if len(col.children) != 1:
        raise SchemaError(f"schema: LIST {col.name} must have one child")
    mid = col.children[0]
    if mid.repetition != FieldRepetitionType.REPEATED:
        raise SchemaError(f"schema: LIST {col.name} child must be repeated")
    if strict:
        if mid.name != "list" or (not mid.is_leaf and len(mid.children) == 1 and mid.children[0].name != "element"):
            # lenient mode accepts Athena's bag/array_element naming
            raise SchemaError(
                f"schema: LIST {col.name} child must be named 'list' with child 'element' (strict)"
            )


def _validate_map(col: Column, strict: bool) -> None:
    if col.is_leaf:
        raise SchemaError(f"schema: MAP {col.name} must be a group")
    if len(col.children) != 1:
        raise SchemaError(f"schema: MAP {col.name} must have one key_value child")
    kv = col.children[0]
    if kv.repetition != FieldRepetitionType.REPEATED or kv.is_leaf:
        raise SchemaError(f"schema: MAP {col.name} child must be a repeated group")
    if len(kv.children) != 2:
        raise SchemaError(f"schema: MAP {col.name} key_value must have key and value")
    if strict:
        if kv.name != "key_value":
            raise SchemaError(f"schema: MAP {col.name} child must be named key_value (strict)")
        if kv.children[0].name != "key" or kv.children[1].name != "value":
            raise SchemaError(f"schema: MAP {col.name} needs children key, value (strict)")
        if kv.children[0].repetition != FieldRepetitionType.REQUIRED:
            raise SchemaError(f"schema: MAP {col.name} key must be required (strict)")


def _validate_leaf(col: Column) -> None:
    ct = col.converted_type
    lt = col.logical_type
    t = col.type
    if ct == ConvertedType.UTF8 and t != Type.BYTE_ARRAY:
        raise SchemaError(f"schema: {col.name}: UTF8 requires binary")
    if lt is not None and lt.UUID is not None:
        if t != Type.FIXED_LEN_BYTE_ARRAY or col.type_length != 16:
            raise SchemaError(f"schema: {col.name}: UUID requires fixed_len_byte_array(16)")
    if lt is not None and lt.FLOAT16 is not None:
        if t != Type.FIXED_LEN_BYTE_ARRAY or col.type_length != 2:
            raise SchemaError(
                f"schema: {col.name}: FLOAT16 requires fixed_len_byte_array(2)"
            )
    if lt is not None and lt.INTEGER is not None:
        bits = lt.INTEGER.bitWidth or 0
        want = Type.INT64 if bits == 64 else Type.INT32
        if t != want:
            raise SchemaError(f"schema: {col.name}: INT({bits}) requires {want.name.lower()}")
    if ct == ConvertedType.DATE and t != Type.INT32:
        raise SchemaError(f"schema: {col.name}: DATE requires int32")
    if ct == ConvertedType.TIME_MILLIS and t != Type.INT32:
        raise SchemaError(f"schema: {col.name}: TIME_MILLIS requires int32")
    if ct in (ConvertedType.TIME_MICROS, ConvertedType.TIMESTAMP_MILLIS, ConvertedType.TIMESTAMP_MICROS) and t != Type.INT64:
        raise SchemaError(f"schema: {col.name}: {ct.name} requires int64")
    if ct == ConvertedType.DECIMAL:
        prec = col.element.precision or (lt.DECIMAL.precision if lt is not None and lt.DECIMAL else None)
        if prec is None or prec <= 0:
            raise SchemaError(f"schema: {col.name}: DECIMAL requires precision")
        if t == Type.INT32 and prec > 9:
            raise SchemaError(f"schema: {col.name}: DECIMAL({prec}) too wide for int32")
        if t == Type.INT64 and prec > 18:
            raise SchemaError(f"schema: {col.name}: DECIMAL({prec}) too wide for int64")
        if t == Type.FIXED_LEN_BYTE_ARRAY:
            n = col.type_length or 0
            import math

            max_digits = math.floor(math.log10(2) * (8 * n - 1))
            if prec > max_digits:
                raise SchemaError(
                    f"schema: {col.name}: DECIMAL({prec}) exceeds fixed({n}) capacity"
                )
    if lt is not None and lt.TIME is not None:
        unit = lt.TIME.unit
        if unit is not None and unit.MILLIS is not None and t != Type.INT32:
            raise SchemaError(f"schema: {col.name}: TIME(MILLIS) requires int32")
        if unit is not None and (unit.MICROS is not None or unit.NANOS is not None) and t != Type.INT64:
            raise SchemaError(f"schema: {col.name}: TIME(MICROS/NANOS) requires int64")

"""Programmatic schema construction.

Equivalent of the reference's public schema builders (reference:
schema.go:572-647 NewDataColumn/NewListColumn/NewMapColumn,
ColumnParameters :561-568): compose Column trees without writing DSL text.

    schema = message(
        required("id", Type.INT64),
        optional("name", string()),
        list_of("tags", optional_elem=optional("element", string())),
        map_of("attrs", key=required("key", string()),
                        value=optional("value", Type.INT32)),
    )
"""

from __future__ import annotations

from ..core.schema import Column, Schema
from ..meta.parquet_types import (
    ConvertedType,
    DateType,
    DecimalType,
    FieldRepetitionType,
    IntType,
    ListType,
    LogicalType,
    MapType,
    SchemaElement,
    StringType,
    TimestampType,
    TimeType,
    TimeUnit,
    Type,
)

__all__ = [
    "message",
    "required",
    "optional",
    "repeated",
    "group",
    "list_of",
    "map_of",
    "string",
    "timestamp",
    "date",
    "time_of_day",
    "decimal",
    "int_type",
]


class _TypeSpec:
    """Physical type + annotations bundle usable in place of a bare Type."""

    def __init__(self, ptype: Type, converted=None, logical=None, type_length=None,
                 scale=None, precision=None):
        self.ptype = ptype
        self.converted = converted
        self.logical = logical
        self.type_length = type_length
        self.scale = scale
        self.precision = precision


def string() -> _TypeSpec:
    return _TypeSpec(
        Type.BYTE_ARRAY,
        converted=ConvertedType.UTF8,
        logical=LogicalType(STRING=StringType()),
    )


_TIME_UNITS = {"millis": TimeUnit.millis, "micros": TimeUnit.micros, "nanos": TimeUnit.nanos}


def _unit(unit: str):
    if unit not in _TIME_UNITS:
        raise ValueError(f"bad time unit {unit!r} (millis/micros/nanos)")
    return _TIME_UNITS[unit]


def timestamp(unit: str = "micros", utc: bool = True) -> _TypeSpec:
    u = _unit(unit)
    conv = {
        "millis": ConvertedType.TIMESTAMP_MILLIS,
        "micros": ConvertedType.TIMESTAMP_MICROS,
        "nanos": None,
    }[unit]
    return _TypeSpec(
        Type.INT64,
        converted=conv,
        logical=LogicalType(
            TIMESTAMP=TimestampType(isAdjustedToUTC=utc, unit=u())
        ),
    )


def date() -> _TypeSpec:
    return _TypeSpec(
        Type.INT32,
        converted=ConvertedType.DATE,
        logical=LogicalType(DATE=DateType()),
    )


def time_of_day(unit: str = "micros", utc: bool = True) -> _TypeSpec:
    u = _unit(unit)
    conv = {
        "millis": ConvertedType.TIME_MILLIS,
        "micros": ConvertedType.TIME_MICROS,
        "nanos": None,
    }[unit]
    return _TypeSpec(
        Type.INT32 if unit == "millis" else Type.INT64,
        converted=conv,
        logical=LogicalType(TIME=TimeType(isAdjustedToUTC=utc, unit=u())),
    )


def decimal(precision: int, scale: int = 0, fixed_width: int | None = None) -> _TypeSpec:
    """DECIMAL over the narrowest standard storage (INT32 to precision 9,
    INT64 to 18, FLBA beyond — or `fixed_width` to force FLBA)."""
    if not 1 <= precision or not 0 <= scale <= precision:
        raise ValueError("decimal: need precision >= 1 and 0 <= scale <= precision")
    min_width = 1
    while 10 ** precision > 1 << (8 * min_width - 1):
        min_width += 1
    if fixed_width is not None:
        if fixed_width < min_width:
            raise ValueError(
                f"decimal: fixed_width {fixed_width} cannot hold precision "
                f"{precision} (needs >= {min_width} bytes)"
            )
        ptype, tl = Type.FIXED_LEN_BYTE_ARRAY, fixed_width
    elif precision <= 9:
        ptype, tl = Type.INT32, None
    elif precision <= 18:
        ptype, tl = Type.INT64, None
    else:
        ptype, tl = Type.FIXED_LEN_BYTE_ARRAY, min_width
    return _TypeSpec(
        ptype,
        converted=ConvertedType.DECIMAL,
        logical=LogicalType(DECIMAL=DecimalType(scale=scale, precision=precision)),
        type_length=tl,
        scale=scale,
        precision=precision,
    )


def int_type(bits: int, signed: bool = True) -> _TypeSpec:
    ptype = Type.INT64 if bits == 64 else Type.INT32
    conv_name = f"{'INT' if signed else 'UINT'}_{bits}"
    return _TypeSpec(
        ptype,
        converted=ConvertedType[conv_name],
        logical=LogicalType(INTEGER=IntType(bitWidth=bits, isSigned=signed)),
    )


def _clone_column(col: Column) -> Column:
    """Deep-copy a Column so builder helpers never mutate caller objects."""
    elem = SchemaElement(
        **{
            fname: getattr(col.element, fname)
            for fname, _ft, _spec in SchemaElement.FIELDS.values()
        }
    )
    return Column(element=elem, children=[_clone_column(c) for c in col.children])


def _field(name: str, spec, repetition: FieldRepetitionType) -> Column:
    if isinstance(spec, Column):
        # wrap a copy of an existing group/leaf with a new name/repetition
        spec = _clone_column(spec)
        spec.element.name = name
        spec.element.repetition_type = int(repetition)
        return spec
    if isinstance(spec, Type):
        spec = _TypeSpec(spec)
    elem = SchemaElement(
        type=int(spec.ptype),
        name=name,
        repetition_type=int(repetition),
        converted_type=int(spec.converted) if spec.converted is not None else None,
        logicalType=spec.logical,
        type_length=spec.type_length,
        scale=spec.scale,
        precision=spec.precision,
    )
    return Column(element=elem)


def required(name: str, spec) -> Column:
    return _field(name, spec, FieldRepetitionType.REQUIRED)


def optional(name: str, spec) -> Column:
    return _field(name, spec, FieldRepetitionType.OPTIONAL)


def repeated(name: str, spec) -> Column:
    return _field(name, spec, FieldRepetitionType.REPEATED)


def group(name: str, *children: Column, repetition=FieldRepetitionType.OPTIONAL,
          converted=None, logical=None) -> Column:
    elem = SchemaElement(
        name=name,
        repetition_type=int(repetition),
        num_children=len(children),
        converted_type=int(converted) if converted is not None else None,
        logicalType=logical,
    )
    return Column(element=elem, children=list(children))


def list_of(name: str, element: Column, required_list: bool = False) -> Column:
    """Standard 3-level LIST: <name> (LIST) { repeated group list { element } }."""
    element = _clone_column(element)
    element.element.name = "element"
    mid = group("list", element, repetition=FieldRepetitionType.REPEATED)
    return group(
        name,
        mid,
        repetition=(
            FieldRepetitionType.REQUIRED if required_list else FieldRepetitionType.OPTIONAL
        ),
        converted=ConvertedType.LIST,
        logical=LogicalType(LIST=ListType()),
    )


def map_of(name: str, key: Column, value: Column, required_map: bool = False) -> Column:
    key = _clone_column(key)
    value = _clone_column(value)
    key.element.name = "key"
    key.element.repetition_type = int(FieldRepetitionType.REQUIRED)
    value.element.name = "value"
    kv = group("key_value", key, value, repetition=FieldRepetitionType.REPEATED,
               converted=ConvertedType.MAP_KEY_VALUE)
    return group(
        name,
        kv,
        repetition=(
            FieldRepetitionType.REQUIRED if required_map else FieldRepetitionType.OPTIONAL
        ),
        converted=ConvertedType.MAP,
        logical=LogicalType(MAP=MapType()),
    )


def message(*fields: Column, name: str = "schema") -> Schema:
    root = Column(
        element=SchemaElement(name=name, num_children=len(fields)),
        children=list(fields),
    )
    return Schema(root)

"""Batched TPU page-decode pipeline — the pluggable decoder backend.

The north-star architecture (BASELINE.json): the host walks pages, parses
Thrift headers, decompresses blocks and decodes R/D levels; the *value* streams
of a whole chunk are fused into one batch of device tensors and decoded by the
kernels in device_ops.py / pallas_ops.py. Users opt in per reader:
FileReader(..., backend="tpu") — the WithDecoderBackend(TPU) analogue.

Batching model per chunk:
  RLE_DICTIONARY  all pages' run tables concatenate into one table (bit
                  offsets rebased into one packed buffer, output starts into
                  one output index space) -> ONE device expansion for the whole
                  chunk, then one device gather against the dictionary.
  DELTA_BP        all pages' delta vectors concatenate; a single wrapping
                  cumsum decodes every page at once — per-page starts are
                  restored by subtracting the running sum at each page start
                  (valid in modular arithmetic).
  PLAIN           raw little-endian bytes upload + device bitcast.

All shapes are padded to power-of-two buckets so XLA compiles each kernel a
bounded number of times (static shapes, SURVEY §7.1).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..meta.parquet_types import Encoding, PageType, Type
from ..core.arrays import ByteArrayData
from ..core.chunk import ChunkData, ChunkError, iter_chunk_pages, _check_crc
from ..core.compress import decompress_block
from ..core.page import PageError, decode_dict_page
from ..core.schema import Column
from ..ops.bitpack import bit_width
from ..ops.levels import decode_levels_v1, decode_levels_v2
from ..ops.rle_hybrid import prescan_hybrid
from ..ops.delta import prescan_delta
from .device_ops import (
    bytes_to_words32,
    delta_decode_device,
    dict_gather_device,
    expand_hybrid_device,
)

__all__ = ["read_chunk_tpu", "TpuDecodeStats"]


def _bucket(n: int, floor: int = 1024) -> int:
    """Next power-of-two bucket >= n (>= floor)."""
    b = floor
    while b < n:
        b <<= 1
    return b


@dataclass
class TpuDecodeStats:
    pages: int = 0
    device_values: int = 0
    host_fallback_pages: int = 0


# -- per-chunk batch assembly --------------------------------------------------


class _HybridBatch:
    """Concatenated run tables of all dict-encoded pages of a chunk."""

    def __init__(self):
        self.is_rle: list[np.ndarray] = []
        self.counts: list[np.ndarray] = []
        self.values: list[np.ndarray] = []
        self.bit_starts: list[np.ndarray] = []
        self.packed: list[bytes] = []
        self.packed_bits = 0
        self.out_count = 0
        self.width: int | None = None

    def add_page(self, table, take: int, width: int):
        if self.width is None:
            self.width = width
        elif self.width != width:
            return False  # width changed mid-chunk: caller falls back per-page
        self.is_rle.append(table.is_rle)
        self.counts.append(table.counts)
        self.values.append(table.rle_values)
        self.bit_starts.append(table.bp_offsets * 8 + self.packed_bits)
        self.packed.append(table.packed)
        self.packed_bits += len(table.packed) * 8
        self.out_count += take
        return True


def _expand_hybrid_batch(batch: _HybridBatch, per_page_take: list[int]) -> np.ndarray:
    """One device expansion for a whole chunk's worth of runs.

    Pages may carry padding values in their final bit-packed group; output
    index space is built per page with that padding included, then the real
    values are sliced out per page.
    """
    width = batch.width or 0
    counts = np.concatenate(batch.counts) if batch.counts else np.zeros(0, np.int64)
    # output start of each run, with page boundaries padded to full run counts
    out_start = np.zeros(len(counts), dtype=np.int64)
    np.cumsum(counts[:-1], out=out_start[1:])
    total = int(counts.sum())
    n_pad = _bucket(max(total, 1))
    run_pad = _bucket(len(counts), 64)
    is_rle = np.zeros(run_pad, dtype=bool)
    values = np.zeros(run_pad, dtype=np.uint32)
    bit_starts = np.zeros(run_pad, dtype=np.int64)
    starts = np.full(run_pad, n_pad + 1, dtype=np.int64)
    if len(counts):
        is_rle[: len(counts)] = np.concatenate(batch.is_rle)
        values[: len(counts)] = np.concatenate(batch.values).astype(np.uint32)
        bit_starts[: len(counts)] = np.concatenate(batch.bit_starts)
        starts[: len(counts)] = out_start
    # RLE-pad the tail so padded output indices hit a dummy run
    packed = b"".join(batch.packed)
    words = bytes_to_words32(packed)
    w_pad = _bucket(len(words), 1024)
    words_p = np.zeros(w_pad, dtype=np.uint32)
    words_p[: len(words)] = words
    dev = expand_hybrid_device(
        jnp.asarray(words_p),
        jnp.asarray(is_rle),
        jnp.asarray(starts),
        jnp.asarray(values),
        jnp.asarray(bit_starts),
        width,
        n_pad,
    )
    flat = np.asarray(dev[:total])
    # slice out real values per page (drop per-page bit-pack padding)
    out = np.empty(sum(per_page_take), dtype=np.uint32)
    pos_in = 0
    pos_out = 0
    for page_counts, take in zip(batch.counts, per_page_take):
        page_total = int(page_counts.sum())
        out[pos_out : pos_out + take] = flat[pos_in : pos_in + take]
        pos_in += page_total
        pos_out += take
    return out


class _DeltaBatch:
    def __init__(self, nbits: int):
        self.nbits = nbits
        self.deltas: list[np.ndarray] = []
        self.firsts: list[int] = []
        self.totals: list[int] = []

    def add_page(self, table):
        if table.total == 0:
            return  # no values: nothing to contribute to the stream
        self.deltas.append(table.deltas_plus_min)
        self.firsts.append(table.first_value)
        self.totals.append(table.total)


def _expand_delta_batch(batch: _DeltaBatch) -> np.ndarray:
    """Decode all pages with one device cumsum.

    Concatenate deltas of all pages; the global wrapping cumsum S satisfies,
    for value k of page p with delta-range [a_p, b_p):
        value = first_p + (S[k] - S[a_p - 1])  (mod 2**nbits)
    which we realize by injecting a correction delta at each page boundary.
    """
    nbits = batch.nbits
    ud = np.uint32 if nbits == 32 else np.uint64
    mask = (1 << nbits) - 1
    parts = []
    prev_end_value = 0  # running value of the previous page's end (mod)
    # Build one delta stream where each page's first value appears as a delta
    # from the previous page's last value: cumsum then yields every value.
    for deltas, first in zip(batch.deltas, batch.firsts):
        start_delta = (first - prev_end_value) & mask
        parts.append(np.array([start_delta], dtype=ud))
        parts.append(deltas.astype(ud))
        prev_end_value = (first + int(deltas.astype(ud).sum(dtype=ud))) & mask
    if not parts:
        sd = np.int32 if nbits == 32 else np.int64
        return np.zeros(0, dtype=sd)
    stream = np.concatenate(parts)
    n = len(stream)
    n_pad = _bucket(n)
    stream_p = np.zeros(n_pad, dtype=ud)
    stream_p[:n] = stream
    dev = delta_decode_device(jnp.asarray(stream_p[1:]), int(stream_p[0]), nbits, n_pad)
    return np.asarray(dev[:n])


# -- the chunk decoder ---------------------------------------------------------


def read_chunk_tpu(
    f,
    chunk,
    column: Column,
    validate_crc: bool = False,
    alloc=None,
    stats: TpuDecodeStats | None = None,
) -> ChunkData:
    """TPU-backend chunk decode: levels on host, values on device.

    Byte-identical to core.chunk.read_chunk (the M1 oracle) — enforced by
    tests/test_tpu_backend.py on every supported shape.
    """
    md = chunk.meta_data
    codec = md.codec or 0
    dictionary = None
    dict_dev = None
    expected = md.num_values or 0

    page_infos = []  # (num_values, def, rep, kind, payload-specific)
    hybrid_batch = _HybridBatch()
    hybrid_takes: list[int] = []
    delta_batch: _DeltaBatch | None = None
    ptype = column.type

    for raw in iter_chunk_pages(f, chunk):
        header = raw.header
        if alloc is not None:
            alloc.check(header.uncompressed_page_size or 0)
        pt = header.type
        if pt == int(PageType.DICTIONARY_PAGE):
            if dictionary is not None:
                raise ChunkError("chunk: more than one dictionary page")
            if validate_crc:
                _check_crc(header, raw.payload)
            block = decompress_block(raw.payload, codec, header.uncompressed_page_size or 0)
            dictionary = decode_dict_page(header, block, column)
            if isinstance(dictionary, np.ndarray) and dictionary.ndim == 1:
                # Floats travel as bit patterns: TPU f64 transfer is not
                # bit-exact (observed 1-ulp corruption through the axon
                # runtime), and a gather is dtype-agnostic anyway.
                if dictionary.dtype.kind == "f":
                    u = np.uint32 if dictionary.dtype.itemsize == 4 else np.uint64
                    dict_dev = jnp.asarray(dictionary.view(u))
                else:
                    dict_dev = jnp.asarray(dictionary)
            continue
        if pt == int(PageType.INDEX_PAGE):
            continue
        if pt not in (int(PageType.DATA_PAGE), int(PageType.DATA_PAGE_V2)):
            raise ChunkError(f"chunk: unknown page type {pt}")
        if validate_crc:
            _check_crc(header, raw.payload)

        # -- split levels (host) from values (device) --------------------------
        if pt == int(PageType.DATA_PAGE):
            h = header.data_page_header
            n = h.num_values or 0
            block = decompress_block(raw.payload, codec, header.uncompressed_page_size or 0)
            buf = memoryview(block)
            pos = 0
            rep = None
            if column.max_rep > 0:
                rep, used = decode_levels_v1(buf, n, column.max_rep)
                pos += used
            dfl = None
            non_null = n
            if column.max_def > 0:
                dfl, used = decode_levels_v1(buf[pos:], n, column.max_def)
                pos += used
                non_null = int((dfl == column.max_def).sum())
            enc = h.encoding
            values_buf = bytes(buf[pos:])
        else:
            h = header.data_page_header_v2
            n = h.num_values or 0
            rep_len = h.repetition_levels_byte_length or 0
            def_len = h.definition_levels_byte_length or 0
            buf = memoryview(raw.payload)
            if rep_len + def_len > len(buf):
                raise ChunkError("chunk: v2 level sizes exceed page")
            rep = (
                decode_levels_v2(buf[:rep_len], n, column.max_rep)
                if column.max_rep > 0
                else None
            )
            dfl = None
            non_null = n
            if column.max_def > 0:
                dfl = decode_levels_v2(buf[rep_len : rep_len + def_len], n, column.max_def)
                non_null = int((dfl == column.max_def).sum())
            values_buf = bytes(buf[rep_len + def_len :])
            if h.is_compressed is None or h.is_compressed:
                un = (header.uncompressed_page_size or 0) - rep_len - def_len
                values_buf = decompress_block(values_buf, codec, max(un, 0))
            enc = h.encoding

        if stats is not None:
            stats.pages += 1

        # -- route the value stream --------------------------------------------
        if enc in (int(Encoding.RLE_DICTIONARY), int(Encoding.PLAIN_DICTIONARY)):
            if dictionary is None:
                raise PageError("page: dictionary encoding without dictionary")
            if non_null == 0:
                page_infos.append((n, dfl, rep, "empty", None))
                continue
            width = values_buf[0] if values_buf else 0
            if width > 32:
                raise PageError(f"page: invalid dict index width {width}")
            table = prescan_hybrid(values_buf[1:], non_null, width)
            if hybrid_batch.add_page(table, non_null, width):
                hybrid_takes.append(non_null)
                page_infos.append((n, dfl, rep, "dict", None))
            else:  # width changed mid-chunk — rare; decode alone
                from ..ops.rle_hybrid import expand_runs

                idx = expand_runs(table, non_null, width, np.uint32)
                page_infos.append((n, dfl, rep, "indices", idx))
                if stats is not None:
                    stats.host_fallback_pages += 1
        elif enc == int(Encoding.DELTA_BINARY_PACKED) and ptype in (Type.INT32, Type.INT64):
            nbits = 32 if ptype == Type.INT32 else 64
            if delta_batch is None:
                delta_batch = _DeltaBatch(nbits)
            table = prescan_delta(values_buf, nbits, max_total=non_null)
            delta_batch.add_page(table)
            page_infos.append((n, dfl, rep, "delta", table.total))
        elif enc == int(Encoding.PLAIN) and ptype in (
            Type.INT32,
            Type.INT64,
            Type.FLOAT,
            Type.DOUBLE,
        ):
            dt = {
                Type.INT32: np.int32,
                Type.INT64: np.int64,
                Type.FLOAT: np.float32,
                Type.DOUBLE: np.float64,
            }[ptype]
            need = non_null * np.dtype(dt).itemsize
            if len(values_buf) < need:
                raise PageError("page: plain payload too short")
            vals = np.frombuffer(values_buf, dtype=dt, count=non_null)
            page_infos.append((n, dfl, rep, "values", vals))
        else:
            # Anything else (byte arrays, boolean, deltas on other types):
            # host decode for this page.
            from ..core.page import _decode_values

            dict_size = len(dictionary) if dictionary is not None else None
            values, indices = _decode_values(values_buf, non_null, enc, column, dict_size)
            if indices is not None:
                page_infos.append((n, dfl, rep, "indices", indices))
            else:
                page_infos.append((n, dfl, rep, "values", values))
            if stats is not None:
                stats.host_fallback_pages += 1

    # -- device execution ------------------------------------------------------
    dict_indices_flat = None
    if hybrid_takes:
        dict_indices_flat = _expand_hybrid_batch(hybrid_batch, hybrid_takes)
        if stats is not None:
            stats.device_values += len(dict_indices_flat)
    delta_flat = None
    if delta_batch is not None:
        delta_flat = _expand_delta_batch(delta_batch)
        if stats is not None:
            stats.device_values += len(delta_flat)

    # -- reassemble per-page values in order -----------------------------------
    pages_values = []
    all_def: list[np.ndarray] = []
    all_rep: list[np.ndarray] = []
    take_iter = iter(hybrid_takes)
    hpos = 0
    dpos = 0
    num_values_total = 0
    for n, dfl, rep, kind, payload in page_infos:
        num_values_total += n
        if dfl is not None:
            all_def.append(dfl)
        if rep is not None:
            all_rep.append(rep)
        if kind == "dict":
            take = next(take_iter)
            idx = dict_indices_flat[hpos : hpos + take]
            hpos += take
            pages_values.append(_materialize(dictionary, dict_dev, idx))
        elif kind == "indices":
            pages_values.append(_materialize(dictionary, dict_dev, payload))
        elif kind == "delta":
            total = payload
            vals = delta_flat[dpos : dpos + total]
            dpos += total
            pages_values.append(vals)
        elif kind == "values":
            pages_values.append(payload)
        elif kind == "empty":
            pass

    if num_values_total != expected:
        raise ChunkError(
            f"chunk: pages hold {num_values_total} values, metadata says {expected}"
        )

    values = _concat_values(pages_values, column)
    def_levels = np.concatenate(all_def) if all_def else None
    rep_levels = np.concatenate(all_rep) if all_rep else None
    return ChunkData(
        column=column,
        num_values=num_values_total,
        values=values,
        def_levels=def_levels,
        rep_levels=rep_levels,
        dictionary=dictionary,
    )


def _materialize(dictionary, dict_dev, indices: np.ndarray):
    if isinstance(dictionary, ByteArrayData):
        return dictionary.take(np.asarray(indices, dtype=np.int64))
    if dict_dev is not None:
        out = np.asarray(dict_gather_device(dict_dev, jnp.asarray(indices)))
        if dictionary.dtype.kind == "f":  # gathered as bit patterns; view back
            out = out.view(dictionary.dtype)
        return out
    return np.asarray(dictionary)[np.asarray(indices)]


def _concat_values(parts, column: Column):
    parts = [p for p in parts if p is not None]
    if any(isinstance(p, ByteArrayData) for p in parts):
        from ..core.chunk import _concat_byte_arrays

        return _concat_byte_arrays(parts)
    arrs = [np.asarray(p) for p in parts if len(p)]
    if arrs:
        return np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
    from ..core.chunk import _empty_dtype

    if column.type == Type.BYTE_ARRAY:
        return ByteArrayData(offsets=np.zeros(1, dtype=np.int64), data=b"")
    return np.empty(0, dtype=_empty_dtype(column))

"""Batched TPU page-decode pipeline — the pluggable decoder backend.

The north-star architecture (BASELINE.json): the host walks pages, parses
Thrift headers, decompresses blocks and decodes R/D levels; the *value* streams
of a whole chunk are fused into one batch of device tensors and decoded by the
kernels in device_ops.py. Users opt in per reader:
FileReader(..., backend="tpu") — the WithDecoderBackend(TPU) analogue.

Batching model per chunk:
  RLE_DICTIONARY  all pages' run tables concatenate into one table (bit
                  offsets rebased into one packed buffer, output starts into
                  one output index space, run counts clamped to each page's
                  real value count so no padding enters the output) -> ONE
                  device expansion for the whole chunk, then one device gather
                  against the dictionary.
  DELTA_BP        all pages' delta vectors concatenate; a single wrapping
                  cumsum decodes every page at once — per-page starts are
                  restored by injecting a correction delta at each page start
                  (valid in modular arithmetic).
  PLAIN           raw little-endian bytes upload + device bitcast.

The decode of one chunk is split into two phases so a whole row group's worth
of device work can be in flight before anything synchronizes (JAX async
dispatch; the host<->device link is the scarce resource, SURVEY §7.3.4):

  plan_chunk_tpu()   host prescan + device dispatch; returns a _ChunkPlan
                     holding un-synchronized device arrays.
  plan.finalize()    fetches results and reassembles a ChunkData, byte-
                     identical to the host path.
  plan.device_column()  keeps the decoded values in HBM instead: the
                     decode-to-device delivery point (DeviceColumn).

All shapes are padded to power-of-two buckets so XLA compiles each kernel a
bounded number of times (static shapes, SURVEY §7.1). All device index math is
int32 (device_ops.py); batches are split at MAX_DEVICE_BATCH_BITS.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ..meta.parquet_types import Encoding, PageType, Type
from ..core.alloc import decoded_nbytes
from ..core.arrays import ByteArrayData
from ..core.chunk import ChunkData, ChunkError, iter_chunk_pages, _check_crc
from ..core.compress import decompress_block
from ..core.page import PageError, decode_dict_page
from ..core.schema import Column
from ..ops.packed_levels import PackedLevels
from ..ops.rle_hybrid import prescan_hybrid
from ..ops.delta import prescan_delta_packed
from ..utils import metrics as _metrics
from .device_ops import (
    MAX_DEVICE_BATCH_BITS,
    bytes_to_words32,
    bytes_to_words64,
    delta_block_encode_device,
    delta_packed_decode_device,
    dict_gather_device,
    dict_indices_device,
    expand_hybrid_device,
    plain_bytearray_encode_device,
    rle_hybrid_encode_device,
)

__all__ = [
    "read_chunk_tpu",
    "plan_chunk_tpu",
    "DeviceColumn",
    "TpuDecodeStats",
    "dispatch_pool",
    "device_put_pipelined",
    "assemble_hybrid_device_stream",
    "assemble_delta_device_stream",
    "encode_device_column",
]

# Patchable in tests to force multi-batch splitting on small inputs.
_BATCH_BITS_CAP = MAX_DEVICE_BATCH_BITS


# -- the dispatch thread -------------------------------------------------------
#
# One process-wide single-thread executor owns device dispatch (uploads +
# kernel launches). It lives HERE — next to the device pipeline it feeds —
# and is shared by every consumer (FileReader's chunk plans, the dataset
# layer's batch uploads): jax calls stay serialized in deterministic order
# while their RPC latency overlaps host-side work on other threads.

_dispatcher = None
_dispatcher_lock = threading.Lock()


def dispatch_pool():
    """The process-wide single-thread device-dispatch executor."""
    global _dispatcher
    from concurrent.futures import ThreadPoolExecutor

    with _dispatcher_lock:
        if _dispatcher is None:
            _dispatcher = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="pqt-dispatch"
            )
        return _dispatcher


def device_put_pipelined(
    batches, placement=None, depth: int = 2, stage_name: str = "device_put"
):
    """Yield device-resident copies of host pytrees, keeping up to `depth`
    transfers in flight ahead of the consumer (depth 2 = classic double
    buffering: while the consumer works on batch k, batch k+1's upload is
    already running on the dispatch thread).

    `placement` is anything jax.device_put accepts — a jax.Device, a
    Sharding laying each batch over a mesh, or None for the process default.
    Order is preserved; an exception from `batches` or from a transfer
    surfaces at the yield that would have produced that batch. Each upload
    runs under a `stage_name` stage (traced_submit carries the caller's
    active decode_trace onto the dispatch thread)."""
    from collections import deque

    from ..utils.trace import stage as _stage, traced_submit

    if depth <= 0:
        for b in batches:
            # upload INSIDE the stage, yield OUTSIDE it: a yield under the
            # context would bill arbitrary consumer time to the transfer
            with _stage(stage_name):
                out = jax.device_put(b, placement)
            yield out
        return

    def put(b):
        with _stage(stage_name):
            return jax.device_put(b, placement)

    pool = dispatch_pool()
    it = iter(batches)
    pending = deque()
    source_err = None

    def fill():
        # A source failure is DEFERRED, not raised here: batches already
        # decoded and uploaded must still reach the consumer, and the error
        # must surface at the stream position where the source actually
        # failed — raising mid-fill would drop up to `depth` in-flight
        # batches and misattribute the failure (docstring contract).
        nonlocal source_err
        if source_err is not None:
            return
        while len(pending) < depth:
            try:
                b = next(it)
            except StopIteration:
                return
            except BaseException as e:  # noqa: BLE001 — re-raised in order
                source_err = e
                return
            pending.append(traced_submit(pool, put, b))

    fill()
    while pending:
        fut = pending.popleft()
        fill()
        yield fut.result()
    if source_err is not None:
        raise source_err


def _bucket(n: int, floor: int = 1024) -> int:
    """Next power-of-two bucket >= n (>= floor)."""
    b = floor
    while b < n:
        b <<= 1
    return b


def _pad_device(arr):
    """Zero-pad a device array to its power-of-two bucket so kernels taking
    it compile a bounded number of times (one device op; no host copy)."""
    import jax.numpy as jnp

    n = int(arr.shape[0])
    pad = _bucket(max(n, 1)) - n
    if pad:
        arr = jnp.concatenate([arr, jnp.zeros(pad, dtype=arr.dtype)])
    return arr


def _page_merge_tables(page_infos, plain_entries):
    """Padded per-page tables for the mixed-merge device kernels:
    (page_kind, page_row_start, aux, n_rows). `plain_entries(payload)` maps a
    'values' payload to (aux entries consumed, rows contributed)."""
    kinds_t: list[int] = []
    row_starts: list[int] = [0]
    aux: list[int] = []
    idx_base = plain_base = rowpos = 0
    for _n, _d, _r, kind, payload in page_infos:
        if kind == "dict":
            kinds_t.append(1)
            aux.append(idx_base)
            idx_base += payload
            rowpos += payload
            row_starts.append(rowpos)
        elif kind == "values":
            adv, rows = plain_entries(payload)
            kinds_t.append(0)
            aux.append(plain_base)
            plain_base += adv
            rowpos += rows
            row_starts.append(rowpos)
    P = len(kinds_t)
    P_pad = _bucket(max(P, 1), 16)
    page_kind = np.zeros(P_pad, dtype=np.int32)
    page_kind[:P] = kinds_t
    prs = np.full(P_pad + 1, rowpos, dtype=np.int32)
    prs[: P + 1] = row_starts
    aux_np = np.zeros(P_pad, dtype=np.int32)
    aux_np[:P] = aux
    return page_kind, prs, aux_np, rowpos


def _skewed_dict_bound(dictionary, dict_rows: int, plain_bytes: int):
    """(padded byte bound, acceptable?) for the ragged byte merge: the output
    pads to the worst-case dictionary entry per row, so a skewed dictionary
    (one huge entry) must keep the host fallback — 4x the expected size or
    64 MB, whichever is larger."""
    dict_lens = np.diff(dictionary.offsets)
    n_dict = len(dictionary.offsets) - 1
    max_len = int(dict_lens.max()) if n_dict and dict_rows else 0
    mean_len = float(dict_lens.mean()) if n_dict else 0.0
    bound = plain_bytes + dict_rows * max_len
    est = plain_bytes + int(dict_rows * mean_len) + 1
    ok = bound < (1 << 31) and bound <= max(64 << 20, 4 * est)
    return bound, ok


class _FrozenHybrid(NamedTuple):
    """Upload-ready hybrid batch (built in prepare; dispatched by transfer)."""

    buf: np.ndarray
    width: int
    n_pad: int
    run_pad: int
    total: int


class _FrozenDelta(NamedTuple):
    """Upload-ready delta batch (built in prepare; dispatched by transfer)."""

    meta32: np.ndarray
    wide: np.ndarray
    nbits: int
    n_pad: int
    m_pad: int
    p_pad: int
    total: int


@dataclass
class TpuDecodeStats:
    pages: int = 0
    device_values: int = 0
    host_fallback_pages: int = 0
    device_batches: int = 0


_NUMERIC_DTYPE = {
    Type.INT32: np.int32,
    Type.INT64: np.int64,
    Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
}


# -- per-chunk batch assembly --------------------------------------------------


class _HybridBatch:
    """Concatenated, clamped run tables of dict-encoded pages of a chunk.

    Run counts are clamped so each page contributes exactly its real value
    count to the output index space (the final bit-packed group of a page may
    encode up to 7 padding values; clamping the last run's count drops them
    without touching bit offsets). The device expansion therefore yields the
    concatenation of all pages' values directly.
    """

    def __init__(self, width: int):
        self.width = width
        self.is_rle: list[np.ndarray] = []
        self.counts: list[np.ndarray] = []
        self.values: list[np.ndarray] = []
        self.bit_starts: list[np.ndarray] = []
        self.packed: list[bytes] = []
        self.packed_bits = 0
        self.out_count = 0

    def fits(self, table, width: int) -> bool:
        return (
            width == self.width
            and self.packed_bits + len(table.packed) * 8 <= _BATCH_BITS_CAP
        )

    def add_page(self, table, take: int) -> None:
        counts = table.counts.astype(np.int64)
        cum = np.cumsum(counts)
        if take > (int(cum[-1]) if len(cum) else 0):
            raise PageError("page: hybrid run table shorter than value count")
        k = int(np.searchsorted(cum, take, side="left"))
        counts = counts[: k + 1].copy()
        counts[k] = take - (int(cum[k - 1]) if k else 0)
        self.is_rle.append(table.is_rle[: k + 1])
        self.counts.append(counts)
        self.values.append(table.rle_values[: k + 1])
        self.bit_starts.append(table.bp_offsets[: k + 1] * 8 + self.packed_bits)
        self.packed.append(table.packed)
        self.packed_bits += len(table.packed) * 8
        self.out_count += take

    def freeze(self) -> tuple:
        """Build the packed upload buffer (host-only; runs in the prepare
        phase so the dispatch thread stays pure transfer/launch I/O).

        ONE packed upload: [is_rle | out_start | rle_value | bit_start |
        words] — see expand_hybrid_device layout."""
        counts = np.concatenate(self.counts)
        out_start = np.zeros(len(counts), dtype=np.int64)
        np.cumsum(counts[:-1], out=out_start[1:])
        total = int(counts.sum())
        assert total == self.out_count
        n_pad = _bucket(max(total, 1))
        run_pad = _bucket(len(counts), 64)
        packed = b"".join(self.packed)
        words = bytes_to_words32(packed)
        w_pad = _bucket(len(words), 1024)
        buf = np.zeros(4 * run_pad + w_pad, dtype=np.uint32)
        buf[run_pad : 2 * run_pad] = np.int32(n_pad + 1).view(np.uint32)  # sentinel
        k = len(counts)
        buf[:k] = np.concatenate(self.is_rle)
        buf[run_pad : run_pad + k] = out_start.astype(np.int32).view(np.uint32)
        buf[2 * run_pad : 2 * run_pad + k] = np.concatenate(self.values).astype(
            np.uint32
        )
        buf[3 * run_pad : 3 * run_pad + k] = (
            np.concatenate(self.bit_starts).astype(np.int32).view(np.uint32)
        )
        buf[4 * run_pad : 4 * run_pad + len(words)] = words
        return _FrozenHybrid(buf, self.width, n_pad, run_pad, total)

    @staticmethod
    def dispatch_frozen(frozen: "_FrozenHybrid") -> jnp.ndarray:
        dev = expand_hybrid_device(
            jnp.asarray(frozen.buf), frozen.width, frozen.n_pad, frozen.run_pad
        )
        return dev[: frozen.total]


class _DeltaBatch:
    """Concatenated *packed* delta streams of a chunk's pages.

    Only wire bytes + tiny per-miniblock/per-page tables go to the device;
    kernels/device_ops.py delta_packed_decode_device unpacks + prefix-sums
    everything in one program, segmented per page."""

    def __init__(self, nbits: int):
        self.nbits = nbits
        self.streams: list[bytes] = []
        self.stream_bytes = 0
        self.widths: list[np.ndarray] = []
        self.byte_starts: list[np.ndarray] = []
        self.out_starts: list[np.ndarray] = []
        self.mins: list[np.ndarray] = []
        self.page_starts: list[int] = []
        self.page_firsts: list[int] = []
        self.out_count = 0

    def fits(self, table) -> bool:
        return (self.stream_bytes + table.consumed) * 8 <= _BATCH_BITS_CAP

    def add_page(self, table, stream: bytes) -> None:
        if table.total == 0:
            return  # no values: nothing to contribute
        b = self.out_count
        self.widths.append(table.widths)
        self.byte_starts.append(table.byte_starts + self.stream_bytes)
        self.out_starts.append(table.out_starts + (b + 1))
        self.mins.append(table.mins)
        self.page_starts.append(b)
        self.page_firsts.append(table.first_value)
        self.streams.append(stream[: table.consumed])
        self.stream_bytes += table.consumed
        self.out_count += table.total

    def freeze(self) -> tuple | None:
        """Build the packed uploads (host-only; prepare phase — see
        _HybridBatch.freeze)."""
        if not self.page_starts:
            return None
        nbits = self.nbits
        ud = np.uint32 if nbits == 32 else np.uint64
        total = self.out_count
        n_pad = _bucket(total)
        m = sum(len(w) for w in self.widths)
        m_pad = _bucket(max(m, 1), 64)
        p = len(self.page_starts)
        p_pad = _bucket(p, 64)
        sentinel = np.int32(n_pad + 1).view(np.uint32)
        stream = b"".join(self.streams)
        words = bytes_to_words32(stream) if nbits == 32 else bytes_to_words64(stream)
        w_pad = _bucket(len(words), 1024)
        # Packed uploads — see delta_packed_decode_device field layout. The
        # wire words ride in the same upload as the tables: one transfer for
        # 32-bit values, two for 64-bit (tables at 32, words at 64).
        tail32 = (2 * m_pad + 2 * p_pad + w_pad) if nbits == 32 else 0
        meta32 = np.zeros(3 * m_pad + p_pad + tail32, dtype=np.uint32)
        meta32[2 * m_pad : 3 * m_pad] = sentinel  # out_starts padding
        meta32[3 * m_pad : 3 * m_pad + p_pad] = sentinel  # page_start padding
        if m:
            meta32[:m] = np.concatenate(self.widths)
            meta32[m_pad : m_pad + m] = (
                (np.concatenate(self.byte_starts) * 8).astype(np.int32).view(np.uint32)
            )
            meta32[2 * m_pad : 2 * m_pad + m] = (
                np.concatenate(self.out_starts).astype(np.int32).view(np.uint32)
            )
        meta32[3 * m_pad : 3 * m_pad + p] = (
            np.asarray(self.page_starts, dtype=np.int32).view(np.uint32)
        )
        if nbits == 32:
            base = 3 * m_pad + p_pad
            if m:
                meta32[base : base + m] = np.concatenate(self.mins).astype(ud)
            meta32[base + m_pad : base + m_pad + p] = np.array(
                self.page_firsts, dtype=ud
            )
            meta32[base + m_pad + p_pad : base + m_pad + p_pad + len(words)] = words
            wide = np.zeros(0, dtype=np.uint32)
        else:
            wide = np.zeros(m_pad + p_pad + w_pad, dtype=np.uint64)
            if m:
                wide[:m] = np.concatenate(self.mins).astype(ud)
            wide[m_pad : m_pad + p] = np.array(self.page_firsts, dtype=ud)
            wide[m_pad + p_pad : m_pad + p_pad + len(words)] = words
        return _FrozenDelta(meta32, wide, nbits, n_pad, m_pad, p_pad, total)

    @staticmethod
    def dispatch_frozen(frozen: "_FrozenDelta") -> jnp.ndarray:
        dev = delta_packed_decode_device(
            jnp.asarray(frozen.meta32),
            jnp.asarray(frozen.wide),
            frozen.nbits,
            frozen.n_pad,
            frozen.m_pad,
            frozen.p_pad,
        )
        return dev[: frozen.total]


# -- the chunk plan ------------------------------------------------------------


@dataclass
class DeviceColumn:
    """Decoded column delivered in device memory (HBM) — the TPU-native
    output of the decode pipeline. Numeric columns carry `values` (real
    dtype; floats bitcast on device from their wire bit patterns). Byte-array
    columns carry Arrow-style `data` + `offsets`, or — for dictionary-encoded
    chunks — device `indices` plus the (small) dictionary both host-side and
    as device `dict_data`/`dict_offsets`.

    def/rep levels stay host-side (record assembly is a host concern,
    SURVEY §7.1); under compact_levels they arrive bit-packed
    (ops.packed_levels.PackedLevels)."""

    num_values: int
    values: jnp.ndarray | None = None
    indices: jnp.ndarray | None = None
    dictionary: object | None = None  # host ByteArrayData | np.ndarray
    data: jnp.ndarray | None = None  # uint8 payload (byte arrays)
    offsets: jnp.ndarray | None = None  # int64 offsets, len = n + 1
    dict_data: jnp.ndarray | None = None  # uint8 dictionary payload
    dict_offsets: jnp.ndarray | None = None
    def_levels: "np.ndarray | PackedLevels | None" = None
    rep_levels: "np.ndarray | PackedLevels | None" = None
    # memoized device copies of the level streams (one upload, shared by
    # every list_layout() depth)
    _dev_rep: "jnp.ndarray | None" = None
    _dev_def: "jnp.ndarray | None" = None

    def list_layout(self, parent_rep: int, elem_def: int):
        """Arrow-style offsets/validity of one repeated depth, computed ON
        DEVICE from this column's level streams (device_ops.
        list_layout_device): the levels upload once (memoized) and the
        offsets/first-def arrays stay in HBM, so a JAX consumer building
        ragged batches from a device-decoded column never round-trips
        record-assembly structure through the host.

        Returns (offsets int32[n+1], first_def int32[n], n_slots int32
        scalar device array); entries past n_slots are padding. Feed
        `first_def < node.max_def` for the depth's null mask."""
        from .device_ops import list_layout_device

        if self.rep_levels is None:
            raise ValueError("list_layout: column has no repetition levels")
        if self._dev_rep is None:
            self._dev_rep = jnp.asarray(
                np.asarray(self.rep_levels), dtype=jnp.int32
            )
        if self._dev_def is None:
            dl = self.def_levels
            if dl is None:
                # a missing def stream means every entry FULLY defined (the
                # host engine's convention, assembly_vec._Stream): saturate
                # so any elem_def threshold passes and no slot reads null
                self._dev_def = jnp.full(
                    self.num_values, jnp.iinfo(jnp.int32).max, dtype=jnp.int32
                )
            else:
                self._dev_def = jnp.asarray(np.asarray(dl), dtype=jnp.int32)
        return list_layout_device(
            self._dev_rep, self._dev_def, parent_rep, elem_def
        )


class _ChunkPlan:
    """Host-side record of one chunk's in-flight device decode."""

    def __init__(self, column: Column, expected: int):
        self.column = column
        self.expected = expected
        self.page_infos: list[tuple] = []  # (n, def, rep, kind, payload)
        # whole-chunk level arrays from the native walk (page slices view
        # them); when set, finalize/device_column skip the per-page concat
        self.native_def: np.ndarray | None = None
        self.native_rep: np.ndarray | None = None
        self.dictionary = None
        self.dict_dev = None
        self.dev_hybrid: list[jnp.ndarray] = []  # per batch, page order
        self.dev_delta: list[jnp.ndarray] = []  # per batch, page order
        self.stats: TpuDecodeStats | None = None
        # host-side batches awaiting device dispatch (set by prepare phase)
        self.hybrid_batches: list[_HybridBatch] = []
        self.delta_batches: list[_DeltaBatch] = []
        # frozen upload buffers (built at the END of prepare, host-only, so
        # the dispatch thread does nothing but transfers + kernel launches)
        self.frozen_hybrid: list[tuple] = []
        self.frozen_delta: list[tuple] = []
        self.plain_host = None
        self.dev_plain: jnp.ndarray | None = None
        # BYTE_STREAM_SPLIT pages shipped raw: [( (4, n_pad) u8 host staging,
        # num_values )] -> device transpose (kernels/device_ops
        # bss_transpose_device); page order matches the "bss" page_infos
        self.bss_host: list[tuple] = []
        self.dev_bss: list[tuple] = []  # [(device streams, num_values)]
        self._dispatched = False

    # -- device dispatch (async; nothing synchronizes here) --------------------
    #
    # The only phase that touches jax: keep it on the dispatching thread so
    # the jax-free prepare phase can run on worker threads.

    def dispatch_device(self) -> "_ChunkPlan":
        if self._dispatched:
            return self
        self._dispatched = True
        d = self.dictionary
        if self.frozen_hybrid and isinstance(d, np.ndarray) and d.ndim == 1:
            # Upload the dictionary only when device-decoded indices will
            # gather against it (device_column); host reassembly gathers on
            # host. Floats travel as bit patterns: TPU f64 transfer is not
            # bit-exact (observed 1-ulp corruption through the axon
            # runtime), and a gather is dtype-agnostic anyway.
            if d.dtype.kind == "f":
                u = np.uint32 if d.dtype.itemsize == 4 else np.uint64
                self.dict_dev = jnp.asarray(d.view(u))
            else:
                self.dict_dev = jnp.asarray(d)
        # Homogeneous PLAIN numeric chunks are pure uploads (buffer already
        # concatenated at prepare time).
        if self.plain_host is not None:
            self.dev_plain = _upload_typed(self.plain_host)
            self.plain_host = None
        for streams, nv in self.bss_host:
            self.dev_bss.append((jnp.asarray(streams), nv))
            if self.stats is not None:
                self.stats.device_values += nv
                self.stats.device_batches += 1
        self.bss_host = []
        stats = self.stats
        for frozen in self.frozen_hybrid:
            self.dev_hybrid.append(_HybridBatch.dispatch_frozen(frozen))
            if stats is not None:
                stats.device_values += frozen.total
                stats.device_batches += 1
        for frozen in self.frozen_delta:
            self.dev_delta.append(_DeltaBatch.dispatch_frozen(frozen))
            if stats is not None:
                stats.device_values += frozen.total
                stats.device_batches += 1
        self.frozen_hybrid = []
        self.frozen_delta = []
        return self

    # -- fetch + host reassembly (byte-identical to core.chunk.read_chunk) ----

    def finalize(self, keep_dict_indices: bool = False) -> ChunkData:
        column = self.column
        hybrid_flat = None
        if self.dev_hybrid:
            fetched = [np.asarray(d) for d in self.dev_hybrid]
            hybrid_flat = fetched[0] if len(fetched) == 1 else np.concatenate(fetched)
        if keep_dict_indices and self.dictionary is not None:
            kinds = {k for _, _, _, k, _ in self.page_infos if k != "empty"}
            if kinds and kinds <= {"dict", "indices"}:
                # dictionary-preserving delivery: the (device- or host-)
                # decoded indices pass through unmaterialized
                parts = []
                hpos = 0
                all_def, all_rep = [], []
                total = 0
                for n, dfl, rep, kind, payload in self.page_infos:
                    total += n
                    if dfl is not None:
                        all_def.append(dfl)
                    if rep is not None:
                        all_rep.append(rep)
                    if kind == "dict":
                        parts.append(hybrid_flat[hpos : hpos + payload])
                        hpos += payload
                    elif kind == "indices":
                        parts.append(np.asarray(payload))
                if total != self.expected:
                    raise ChunkError(
                        f"chunk: pages hold {total} values, "
                        f"metadata says {self.expected}"
                    )
                idx = (
                    np.concatenate(parts)
                    if len(parts) != 1
                    else parts[0]
                ) if parts else np.empty(0, np.int32)
                if self.native_def is not None or self.native_rep is not None:
                    dl, rl = self.native_def, self.native_rep
                else:
                    dl = np.concatenate(all_def) if all_def else None
                    rl = np.concatenate(all_rep) if all_rep else None
                return ChunkData(
                    column=column,
                    num_values=total,
                    values=None,
                    def_levels=dl,
                    rep_levels=rl,
                    dictionary=self.dictionary,
                    indices=idx.astype(np.int32, copy=False),
                )
        delta_flat = None
        if self.dev_delta:
            fetched = [np.asarray(d) for d in self.dev_delta]
            delta_flat = fetched[0] if len(fetched) == 1 else np.concatenate(fetched)
        bss_pages = None
        if self.dev_bss or self.bss_host:
            # fetch the device transposes (dispatched), or transpose the
            # staged streams host-side (plan finalized without dispatch)
            from .device_ops import bss_transpose_device

            np_dt = _NUMERIC_DTYPE.get(self.column.type)
            if self.dev_bss:
                bss_pages = [
                    np.asarray(bss_transpose_device(d, nv)).view(np_dt)
                    for d, nv in self.dev_bss
                ]
            else:
                bss_pages = [
                    np.ascontiguousarray(s[:, :nv].T).view(np_dt).reshape(nv)
                    for s, nv in self.bss_host
                ]
            bss_pages = list(reversed(bss_pages))  # pop from the front
        pages_values = []
        all_def: list[np.ndarray] = []
        all_rep: list[np.ndarray] = []
        hpos = 0
        dpos = 0
        num_values_total = 0
        for n, dfl, rep, kind, payload in self.page_infos:
            num_values_total += n
            if dfl is not None:
                all_def.append(dfl)
            if rep is not None:
                all_rep.append(rep)
            if kind == "dict":
                take = payload
                idx = hybrid_flat[hpos : hpos + take]
                hpos += take
                pages_values.append(_materialize(self.dictionary, idx))
            elif kind == "indices":
                pages_values.append(
                    _materialize(self.dictionary, payload)
                )
            elif kind == "delta":
                if payload:
                    vals = delta_flat[dpos : dpos + payload]
                    dpos += payload
                    pages_values.append(vals)
            elif kind == "bss":
                pages_values.append(bss_pages.pop())
            elif kind == "values":
                pages_values.append(payload)
            elif kind == "empty":
                pass
        if num_values_total != self.expected:
            raise ChunkError(
                f"chunk: pages hold {num_values_total} values, "
                f"metadata says {self.expected}"
            )
        values = _concat_values(pages_values, column)
        if self.native_def is not None or self.native_rep is not None:
            def_levels, rep_levels = self.native_def, self.native_rep
        else:
            def_levels = np.concatenate(all_def) if all_def else None
            rep_levels = np.concatenate(all_rep) if all_rep else None
        return ChunkData(
            column=column,
            num_values=num_values_total,
            values=values,
            def_levels=def_levels,
            rep_levels=rep_levels,
            dictionary=self.dictionary,
        )

    # -- decode-to-device ------------------------------------------------------

    def device_column(self) -> DeviceColumn:
        """Deliver the chunk's decoded values in HBM (no device->host fetch of
        the value data). Falls back to host decode + upload for shapes the
        device path doesn't cover (byte-array delta pages, booleans, ...)."""
        column = self.column
        kinds = {k for _, _, _, k, _ in self.page_infos if k != "empty"}
        if self.native_def is not None or self.native_rep is not None:
            def_levels, rep_levels = self.native_def, self.native_rep
        else:
            all_def = [d for _, d, _, _, _ in self.page_infos if d is not None]
            all_rep = [r for _, _, r, _, _ in self.page_infos if r is not None]
            def_levels = np.concatenate(all_def) if all_def else None
            rep_levels = np.concatenate(all_rep) if all_rep else None
        n_total = sum(n for n, *_ in self.page_infos)
        out = DeviceColumn(
            num_values=n_total, def_levels=def_levels, rep_levels=rep_levels
        )

        if (
            kinds <= {"dict", "empty"}
            and self.dev_hybrid
            and (
                isinstance(self.dictionary, ByteArrayData)
                # dict_dev is only uploaded for 1-D numeric dictionaries;
                # 2-D FLBA dictionaries fall through to host decode + upload
                or self.dict_dev is not None
            )
        ):
            idx = self._dev_indices()
            if isinstance(self.dictionary, ByteArrayData):
                out.indices = idx
                out.dictionary = self.dictionary
                out.dict_data = jnp.asarray(
                    np.frombuffer(self.dictionary.data, dtype=np.uint8)
                )
                out.dict_offsets = jnp.asarray(self.dictionary.offsets)
            else:
                vals = dict_gather_device(self.dict_dev, idx)
                out.values = _device_bitcast(vals, column)
            return out

        if kinds <= {"delta", "empty"} and self.dev_delta:
            out.values = (
                self.dev_delta[0]
                if len(self.dev_delta) == 1
                else jnp.concatenate(self.dev_delta)
            )
            return out

        if kinds <= {"bss", "empty"} and self.dev_bss:
            from .device_ops import bss_transpose_device

            parts = [bss_transpose_device(d, nv) for d, nv in self.dev_bss]
            u = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            if column.type == Type.INT32:
                u = jax.lax.bitcast_convert_type(u, jnp.int32)
            out.values = _device_bitcast(u, column)
            return out

        if "values" in kinds and kinds <= {"values", "empty"} and column.type in _NUMERIC_DTYPE:
            if self.dev_plain is not None:
                out.values = self.dev_plain
            else:
                parts = [p for _, _, _, k, p in self.page_infos if k == "values"]
                host = parts[0] if len(parts) == 1 else np.concatenate(parts)
                out.values = _upload_typed(host)
            return out

        # Mixed dict+PLAIN numeric chunk (pyarrow's default 1MB dictionary
        # ceiling makes this the common large-dictionary case): dict pages
        # keep their device expansion+gather, PLAIN pages ride the raw
        # upload, and one fused kernel merges both in output-index space —
        # no value ever round-trips to the host.
        if (
            column.type in _NUMERIC_DTYPE
            # DOUBLE excluded: the TPU x64 emulation can neither bitcast
            # f64<->u64 (compile error) nor hold f64 bit-exactly, so mixed
            # doubles take the host-merge fallback below (FLOAT is fine —
            # u32 bitcasts are native)
            and column.type != Type.DOUBLE
            and kinds <= {"dict", "values", "empty"}
            and "dict" in kinds
            and self.dev_hybrid
            and self.dict_dev is not None
            and self.dev_plain is not None
        ):
            from .device_ops import merge_mixed_numeric_device

            page_kind, prs, aux_np, n_rows = _page_merge_tables(
                self.page_infos, lambda p: (len(p), len(p))
            )
            # merge in the uint bit-pattern domain; floats bitcast once after
            plain_u = self.dev_plain
            if plain_u.dtype.kind == "f":
                plain_u = jax.lax.bitcast_convert_type(
                    plain_u, jnp.uint32 if plain_u.dtype.itemsize == 4 else jnp.uint64
                )
            merged = merge_mixed_numeric_device(
                _pad_device(self._dev_indices()),
                _pad_device(self.dict_dev),
                _pad_device(plain_u),
                jnp.asarray(page_kind),
                jnp.asarray(prs),
                jnp.asarray(aux_np),
                _bucket(max(n_rows, 1)),
            )[:n_rows]
            out.values = _device_bitcast(merged, column)
            return out

        # Mixed dict+PLAIN byte-array chunk (config-3 shape under pyarrow's
        # default dictionary ceiling): dict pages ship indices + the (small)
        # dictionary, PLAIN pages ship their raw bytes, and one ragged device
        # gather materializes the merged (data, offsets) column in HBM.
        if (
            kinds <= {"dict", "values", "empty"}
            and "dict" in kinds
            and self.dev_hybrid
            and isinstance(self.dictionary, ByteArrayData)
            and self._merge_ragged_bytes(out)
        ):
            return out

        # Mixed, unsupported, or fully empty shapes: host decode, then upload.
        data = self.finalize()
        if isinstance(data.values, ByteArrayData):
            out.data = jnp.asarray(np.frombuffer(data.values.data, dtype=np.uint8))
            out.offsets = jnp.asarray(data.values.offsets)
        else:
            out.values = _upload_typed(np.asarray(data.values))
        return out

    def _dev_indices(self) -> jnp.ndarray:
        """All dispatched dict-index batches as one int32 device array."""
        return (
            self.dev_hybrid[0]
            if len(self.dev_hybrid) == 1
            else jnp.concatenate(self.dev_hybrid)
        ).astype(jnp.int32)

    def _merge_ragged_bytes(self, out: DeviceColumn) -> bool:
        """Device merge of a mixed dict/PLAIN byte-array chunk. Returns False
        (leaving `out` untouched) when the shape is unsuitable — a skewed
        dictionary whose max-length padding bound would blow HBM, or PLAIN
        pages that did not decode to ByteArrayData.

        Only raw page bytes, int32 plain-offset arrays and tiny per-page
        tables cross the link; merge_mixed_bytes_device derives everything
        else on device (the host baseline ships the fully-expanded column
        plus int64 offsets — roughly 40%% more bytes for string data)."""
        from .device_ops import merge_mixed_bytes_device

        d = self.dictionary
        dict_rows = plain_rows = plain_bytes = 0
        for _n, _d, _r, kind, payload in self.page_infos:
            if kind == "dict":
                dict_rows += payload
            elif kind == "values":
                if not isinstance(payload, ByteArrayData):
                    return False
                plain_rows += len(payload.offsets) - 1
                plain_bytes += len(payload.data)
        bound, ok = _skewed_dict_bound(d, dict_rows, plain_bytes)
        n_rows = dict_rows + plain_rows
        if n_rows == 0 or not ok:
            return False
        if len(d.data) + plain_bytes >= (1 << 31):
            return False  # int32 plain offsets would overflow
        # -- compact host tables ----------------------------------------------
        page_kind, prs, aux_np, _nr = _page_merge_tables(
            self.page_infos, lambda p: (len(p.offsets), len(p.offsets) - 1)
        )
        P_pad = len(page_kind)
        pools = [np.frombuffer(d.data, dtype=np.uint8)]
        base = len(d.data)
        po_parts: list[np.ndarray] = []
        src_base: list[int] = []
        for _n, _dl, _rl, kind, payload in self.page_infos:
            if kind == "dict":
                src_base.append(0)
            elif kind == "values":
                src_base.append(base)
                po_parts.append(payload.offsets.astype(np.int32))
                pools.append(np.frombuffer(payload.data, dtype=np.uint8))
                base += len(payload.data)
        srcb = np.zeros(P_pad, dtype=np.int64)
        srcb[: len(src_base)] = src_base
        po32 = np.concatenate(po_parts) if po_parts else np.zeros(2, dtype=np.int32)
        E_pad = _bucket(len(po32), 1024)
        po32p = np.zeros(E_pad, dtype=np.int32)
        po32p[: len(po32)] = po32
        pool = pools[0] if len(pools) == 1 else np.concatenate(pools)
        S_pad = _bucket(max(len(pool), 1), 1024)
        poolp = np.empty(S_pad, dtype=np.uint8)  # tail garbage is masked out
        poolp[: len(pool)] = pool
        doff_pad = _bucket(len(d.offsets), 1024)
        doffp = np.empty(doff_pad, dtype=np.int64)
        doffp[: len(d.offsets)] = d.offsets
        doffp[len(d.offsets) :] = d.offsets[-1] if len(d.offsets) else 0
        # -- device inputs -----------------------------------------------------
        idx_all = _pad_device(self._dev_indices())
        rows_pad = _bucket(n_rows, 1024)
        data, off = merge_mixed_bytes_device(
            idx_all,
            jnp.asarray(doffp),
            jnp.asarray(poolp),
            jnp.asarray(po32p),
            jnp.asarray(page_kind),
            jnp.asarray(prs),
            jnp.asarray(aux_np),
            jnp.asarray(srcb),
            jnp.int32(n_rows),
            rows_pad,
            _bucket(max(bound, 1)),
        )
        out.data = data
        out.offsets = off[: n_rows + 1]
        out.dictionary = d
        return True

# -- the chunk decoder ---------------------------------------------------------


def plan_chunk_tpu(
    f,
    chunk,
    column: Column,
    validate_crc: bool = False,
    alloc=None,
    stats: TpuDecodeStats | None = None,
) -> _ChunkPlan:
    """Phase 1: host prescan + async device dispatch for one chunk.

    Returns a _ChunkPlan whose device arrays are in flight; call .finalize()
    for a host ChunkData (byte-identical to core.chunk.read_chunk) or
    .device_column() to keep the decoded values in HBM.
    """
    return prepare_chunk_plan(
        f, chunk, column, validate_crc=validate_crc, alloc=alloc, stats=stats
    ).dispatch_device()


# Page-table column indices of the native whole-chunk walk (layout defined in
# native/parquet_tpu_native.cc ptq_chunk_prepare).
_PC_KIND, _PC_N, _PC_NONNULL, _PC_ENC, _PC_ROUTE = 0, 1, 2, 3, 4
_PC_VOFF, _PC_VLEN, _PC_LVLBASE = 5, 6, 7
_PC_RUNS, _PC_RUNE, _PC_PACKS, _PC_PACKE = 8, 9, 10, 11
_PC_MINIS, _PC_MINIE, _PC_DSTART, _PC_DCONS = 12, 13, 14, 15
_PC_EXTRA, _PC_DFIRST = 16, 17


def _native_prepare(f, chunk, column, validate_crc, alloc, stats):
    """Whole-chunk native prepare: ONE GIL-free C call walks every page
    (header parse, CRC verify when validate_crc, decompress, level decode,
    value prescan) and returns packed tables; batch assembly is then a
    handful of vectorized NumPy ops instead of a per-page Python loop (the
    dominant host cost — reference page walk: chunk_reader.go:182-263).

    Returns (plan, fault): a ready _ChunkPlan and None, or None and an
    optional PrepareFault. fault is set when the native walk RAN and aborted
    (corrupt/unsupported/capacity, with stage + page + byte offset); it is
    None when the walk was never attempted (memory ceiling, non-builtin
    codec, library absent). Either way the caller falls back to the staged
    per-page Python walk — the error-semantics reference — which raises the
    exact typed error if the chunk is genuinely corrupt (the fused -> staged
    -> raise fallback ladder; prepare_fallback_recovered counts chunks the
    staged walk salvaged after a native abort). PQT_FUSED_PREPARE=0 forces
    the staged walk (the differential-test control). Under an active
    decode_trace the outcome is pinned by the prepare_fused_engaged /
    prepare_fused_declined counters and the walk's internal stage split
    lands in prepare.* stages."""
    import os as _os

    from ..utils import trace as _trace

    if _os.environ.get("PQT_FUSED_PREPARE", "1") == "0":
        return None, None  # forced staged path: not a decline, no counter
    plan, fault = _native_prepare_impl(f, chunk, column, validate_crc, alloc, stats)
    if plan is None:
        _trace.bump("prepare_fused_declined")
        if fault is not None:
            _trace.bump(f"prepare_fused_fault_{fault.stage}")
    else:
        _trace.bump("prepare_fused_engaged")
    return plan, fault


def _native_prepare_impl(f, chunk, column, validate_crc, alloc, stats):
    if alloc is not None:
        # a memory ceiling needs the per-page accounting only the staged
        # walk performs (validate_crc, by contrast, is fused natively)
        return None, None
    from ..utils.native import PrepareFault, get_native

    lib = get_native()
    if lib is None or not lib.has_chunk_prepare:
        return None, None
    md = chunk.meta_data
    codec = int(md.codec or 0)
    from ..core.compress import is_builtin_codec

    if codec not in (0, 1, 2, 5, 7) or not is_builtin_codec(codec):
        return None, None
    if codec == 1 and not lib.has_snappy:
        return None, None
    if codec in (5, 7) and not lib.has_lz4:
        return None, None
    from ..core.chunk import chunk_byte_range

    try:
        offset, total = chunk_byte_range(chunk)
    except Exception:
        return None, None
    f.seek(offset)
    buf = f.read(total)
    if len(buf) != total:
        return None, None  # truncated: Python walk raises the exact error
    ptype = column.type
    np_dt = _NUMERIC_DTYPE.get(ptype)
    type_size = np.dtype(np_dt).itemsize if np_dt is not None else 0
    delta_nbits = 32 if ptype == Type.INT32 else (64 if ptype == Type.INT64 else 0)
    expected = int(md.num_values or 0)
    if expected < 0:
        return None, None
    import time as _time

    from ..utils import trace as _trace

    t_walk = _time.perf_counter()
    res = lib.chunk_prepare(
        buf,
        codec,
        column.max_def,
        column.max_rep,
        type_size,
        delta_nbits,
        expected,
        int(md.total_uncompressed_size or 0),
        collect_stages=_trace.active(),
        validate_crc=validate_crc,
    )
    if isinstance(res, PrepareFault):
        return None, res
    t_walk = _time.perf_counter() - t_walk
    stage_ns = res.get("stage_ns")
    if stage_ns is not None:
        # one batch: the sub-stage spans lay back-to-back ending now, so
        # they nest inside the enclosing chunk.prepare span
        _trace.add_seconds_batch(
            [
                (name, int(stage_ns[slot]) / 1e9)
                for slot, name in enumerate(
                    (
                        "prepare.decompress",
                        "prepare.levels",
                        "prepare.prescan",
                        "prepare.copy",
                        "prepare.crc",
                    )
                )
                if stage_ns[slot]
            ]
        )
    try:
        plan = _plan_from_tables(column, expected, res, stats, np_dt, delta_nbits)
    except (PageError, ChunkError):
        raise
    except Exception:
        return None, None  # unexpected table shape: let the Python walk decide
    # Always-on process counters, recorded ONLY once the plan is committed —
    # a chunk that falls back to the staged walk is counted by that walk
    # instead (never both; _plan_from_tables decodes the dict page with
    # count_metrics=False for the same reason). The fused walk bypasses
    # decompress_block's byte choke point and the per-page value decoders,
    # so it reports its own totals. Semantics vs the staged lane, by
    # necessity approximate: io_bytes covers the whole chunk window /
    # metadata uncompressed size (page headers included, where the staged
    # lane counts payload-only), and page_bytes uses each page's
    # value-stream length (levels excluded).
    _metrics.observe("chunk_decode_seconds", t_walk)
    _metrics.io_bytes(len(buf), int(md.total_uncompressed_size or 0), codec)
    # mirror decompress_block's per-trace decoded-byte account (the fused
    # walk bypasses that choke point), so cost attribution stays exact on
    # the native lane too
    _trace.add_bytes("decode.bytes", int(md.total_uncompressed_size or 0))
    pages_arr = res["pages"]
    if len(pages_arr):
        for e in np.unique(pages_arr[:, _PC_ENC]):
            sel = pages_arr[pages_arr[:, _PC_ENC] == e]
            _metrics.page_decoded(
                _metrics.encoding_name(int(e)),
                n=len(sel),
                nbytes=int(sel[:, _PC_VLEN].sum()),
            )
    return plan, None


def _plan_from_tables(column, expected, res, stats, np_dt, delta_nbits):
    plan = _ChunkPlan(column, expected)
    plan.stats = stats
    pages = res["pages"].tolist()
    values_buf = res["values"]
    def_all = res["def"]
    rep_all = res["rep"]
    n_data = sum(1 for P in pages if P[_PC_KIND] == 0)
    if stats is not None:
        stats.pages += n_data
    data_pages = []
    for P in pages:
        if P[_PC_KIND] == 1:  # dictionary page
            from ..meta.parquet_types import DictionaryPageHeader, PageHeader

            header = PageHeader(
                type=int(PageType.DICTIONARY_PAGE),
                dictionary_page_header=DictionaryPageHeader(
                    num_values=P[_PC_N], encoding=P[_PC_ENC]
                ),
            )
            block = memoryview(values_buf)[P[_PC_VOFF] : P[_PC_VOFF] + P[_PC_VLEN]]
            # count_metrics=False: the native lane's counters commit only
            # once the whole plan succeeds (see _native_prepare_impl) — a
            # later fallback to the staged walk must not leave this page
            # already counted
            plan.dictionary = decode_dict_page(
                header, block, column, count_metrics=False
            )
        elif P[_PC_KIND] == 0:
            data_pages.append(P)
    if column.max_def > 0 and data_pages:
        plan.native_def = def_all
    if column.max_rep > 0 and data_pages:
        plan.native_rep = rep_all

    def _levels(P):
        base, n = P[_PC_LVLBASE], P[_PC_N]
        dfl = def_all[base : base + n] if column.max_def > 0 else None
        rep = rep_all[base : base + n] if column.max_rep > 0 else None
        return dfl, rep

    routes = {P[_PC_ROUTE] for P in data_pages if P[_PC_ROUTE] != 4}

    if routes == {3} or not routes:  # PLAIN numeric (and/or empty pages)
        first = None
        nbytes = 0
        for P in data_pages:
            if P[_PC_ROUTE] == 4:
                continue
            if first is None:
                first = P[_PC_VOFF]
            nbytes += P[_PC_VLEN]
        whole = None
        if first is not None and np_dt is not None:
            # routes wrote values_out sequentially: one zero-copy view is the
            # whole chunk's upload buffer (no per-page concatenation)
            whole = np.frombuffer(
                values_buf, dtype=np_dt, count=nbytes // np.dtype(np_dt).itemsize,
                offset=first,
            )
        repacked = (
            whole is not None
            and delta_nbits != 0
            and _repack_plain_as_delta(plan, whole, delta_nbits)
        )
        for P in data_pages:
            dfl, rep = _levels(P)
            if P[_PC_ROUTE] == 4:
                plan.page_infos.append((P[_PC_N], dfl, rep, "empty", None))
            elif repacked:
                plan.page_infos.append(
                    (P[_PC_N], dfl, rep, "delta", P[_PC_NONNULL])
                )
            else:
                vals = np.frombuffer(
                    values_buf, dtype=np_dt, count=P[_PC_NONNULL],
                    offset=P[_PC_VOFF],
                )
                plan.page_infos.append((P[_PC_N], dfl, rep, "values", vals))
        if not repacked:
            plan.plain_host = whole
        # PLAIN routes never touch the packed/delta staging buffers, and a
        # repacked chunk's upload is a FRESH delta stream — whatever leaked
        # no view into the plan goes back to the thread pool so the next
        # chunk skips the first-touch page-fault storm on multi-MB buffers.
        # A decoded dictionary page (dict-write fallback to PLAIN pages)
        # can alias values_buf zero-copy, so 'values' is only released when
        # no dictionary rides the plan.
        from ..utils.native import get_native

        _lib = get_native()
        if _lib is not None and "_bases" in res:
            whole = None
            names = (
                ("values", "packed", "delta")
                if repacked and plan.dictionary is None
                else ("packed", "delta")
            )
            _lib.release_buffers(res, names)
        return plan

    if routes == {5} and np_dt is not None:
        # BYTE_STREAM_SPLIT 4-byte pages shipped RAW: each page's streams
        # stage into a (4, bucket) array (4 contiguous memcpys — the host
        # never strides byte-by-byte) and the DEVICE does the transpose
        # (kernels/device_ops.bss_transpose_device)
        for P in data_pages:
            dfl, rep = _levels(P)
            if P[_PC_ROUTE] == 4:
                plan.page_infos.append((P[_PC_N], dfl, rep, "empty", None))
                continue
            nv = P[_PC_NONNULL]
            raw = np.frombuffer(
                values_buf, dtype=np.uint8, count=P[_PC_VLEN], offset=P[_PC_VOFF]
            )
            staged = np.zeros((4, _bucket(max(nv, 1))), dtype=np.uint8)
            staged[:, :nv] = raw.reshape(4, nv)
            plan.bss_host.append((staged, nv))
            plan.page_infos.append((P[_PC_N], dfl, rep, "bss", nv))
        # staging copied out of values_buf: the bases can recycle (same
        # dictionary-aliasing caveat as the PLAIN branch)
        from ..utils.native import get_native

        _lib = get_native()
        if _lib is not None and "_bases" in res:
            names = (
                ("values", "packed", "delta")
                if plan.dictionary is None
                else ("packed", "delta")
            )
            _lib.release_buffers(res, names)
        return plan

    if routes == {1} or (
        routes == {1, 3} and np_dt is not None and column.type != Type.DOUBLE
        # DOUBLE mixed chunks can't merge on device (no f64<->u64 bitcast in
        # the TPU x64 emulation); freezing their batches would only upload
        # indices that finalize() fetches straight back — demote instead
    ):
        # Dictionary-encoded chunk, possibly with a mid-chunk fall-back to
        # PLAIN pages (pyarrow's 1MB dictionary ceiling): dict pages build
        # device run batches, PLAIN pages ride the contiguous raw upload,
        # and device_column merges in page order.
        frozen = _freeze_hybrid_from_tables(data_pages, res)
        if frozen is not None:
            plan.frozen_hybrid = frozen
            first = None
            nbytes = 0
            for P in data_pages:
                dfl, rep = _levels(P)
                if P[_PC_ROUTE] == 4:
                    plan.page_infos.append((P[_PC_N], dfl, rep, "empty", None))
                elif P[_PC_ROUTE] == 3:
                    vals = np.frombuffer(
                        values_buf, dtype=np_dt, count=P[_PC_NONNULL],
                        offset=P[_PC_VOFF],
                    )
                    plan.page_infos.append((P[_PC_N], dfl, rep, "values", vals))
                    if first is None:
                        first = P[_PC_VOFF]
                    nbytes += P[_PC_VLEN]
                else:
                    plan.page_infos.append(
                        (P[_PC_N], dfl, rep, "dict", P[_PC_NONNULL])
                    )
            if first is not None:
                plan.plain_host = np.frombuffer(
                    values_buf, dtype=np_dt,
                    count=nbytes // np.dtype(np_dt).itemsize, offset=first,
                )
            return plan
        # oversized page: fall through to the demote path below

    if routes == {2} and all(
        P[_PC_DCONS] * 8 <= _BATCH_BITS_CAP
        for P in data_pages
        if P[_PC_ROUTE] == 2
    ):  # delta-bp chunk (an oversized page demotes the whole chunk, as below)
        frozen = _freeze_delta_from_tables(data_pages, res, delta_nbits)
        if frozen is not None:
            plan.frozen_delta = frozen
            for P in data_pages:
                dfl, rep = _levels(P)
                if P[_PC_ROUTE] == 4:
                    plan.page_infos.append((P[_PC_N], dfl, rep, "empty", None))
                else:
                    plan.page_infos.append(
                        (P[_PC_N], dfl, rep, "delta", P[_PC_EXTRA])
                    )
            return plan

    if (
        column.type == Type.BYTE_ARRAY
        and routes <= {0, 1}
        and 1 in routes
        and all(
            P[_PC_ENC] == int(Encoding.PLAIN)
            for P in data_pages
            if P[_PC_ROUTE] == 0
        )
        and plan.dictionary is not None
        and _skewed_dict_bound(
            plan.dictionary,
            sum(P[_PC_NONNULL] for P in data_pages if P[_PC_ROUTE] == 1),
            # PLAIN stream length bounds the page's data bytes; close enough
            # for the skew gate (the merge re-checks exactly)
            sum(P[_PC_VLEN] for P in data_pages if P[_PC_ROUTE] == 0),
        )[1]
    ):
        # Dict pages with a mid-chunk PLAIN byte-array fallback: dict index
        # batches stay device-bound; PLAIN pages host-scan their offsets
        # (native byte_array_gather) and device_column's ragged merge joins
        # both in output-index space.
        frozen = _freeze_hybrid_from_tables(data_pages, res)
        if frozen is not None:
            from ..core.page import _decode_values

            plan.frozen_hybrid = frozen
            dict_size = (
                len(plan.dictionary) if plan.dictionary is not None else None
            )
            for P in data_pages:
                dfl, rep = _levels(P)
                if P[_PC_ROUTE] == 4:
                    plan.page_infos.append((P[_PC_N], dfl, rep, "empty", None))
                elif P[_PC_ROUTE] == 1:
                    plan.page_infos.append(
                        (P[_PC_N], dfl, rep, "dict", P[_PC_NONNULL])
                    )
                else:
                    stream = memoryview(values_buf)[
                        P[_PC_VOFF] : P[_PC_VOFF] + P[_PC_VLEN]
                    ]
                    values, _idx = _decode_values(
                        stream, P[_PC_NONNULL], P[_PC_ENC], column, dict_size
                    )
                    plan.page_infos.append((P[_PC_N], dfl, rep, "values", values))
                    if stats is not None:
                        stats.host_fallback_pages += 1
            return plan

    # Mixed-route chunk (or an oversized device page): host-decode in place,
    # same policy as _commit_routes — device decode only pays when the whole
    # chunk stays on device.
    from ..core.page import _decode_values

    dict_size = len(plan.dictionary) if plan.dictionary is not None else None
    for P in data_pages:
        dfl, rep = _levels(P)
        route = P[_PC_ROUTE]
        if route == 4:
            plan.page_infos.append((P[_PC_N], dfl, rep, "empty", None))
            continue
        if route == 1:
            idx = _expand_dict_from_tables(P, res)
            plan.page_infos.append((P[_PC_N], dfl, rep, "indices", idx))
            if stats is not None:
                stats.host_fallback_pages += 1
        elif route == 2:
            from ..ops.delta import decode_delta

            stream = res["delta_stream"][
                P[_PC_DSTART] : P[_PC_DSTART] + P[_PC_DCONS]
            ]
            vals, _ = decode_delta(
                memoryview(stream), delta_nbits, max_total=P[_PC_NONNULL]
            )
            plan.page_infos.append(
                (P[_PC_N], dfl, rep, "values", vals[: P[_PC_NONNULL]])
            )
            if stats is not None:
                stats.host_fallback_pages += 1
        elif route == 3:
            vals = np.frombuffer(
                values_buf, dtype=np_dt, count=P[_PC_NONNULL], offset=P[_PC_VOFF]
            )
            plan.page_infos.append((P[_PC_N], dfl, rep, "values", vals))
        elif route == 5:
            # raw BSS page in a mixed chunk: de-interleave host-side
            nv = P[_PC_NONNULL]
            raw = np.frombuffer(
                values_buf, dtype=np.uint8, count=P[_PC_VLEN], offset=P[_PC_VOFF]
            )
            vals = (
                np.ascontiguousarray(raw.reshape(4, nv).T)
                .view(np_dt)
                .reshape(nv)
            )
            plan.page_infos.append((P[_PC_N], dfl, rep, "values", vals))
        else:  # route 0: host decoder on the raw stream
            stream = memoryview(values_buf)[P[_PC_VOFF] : P[_PC_VOFF] + P[_PC_VLEN]]
            values, indices = _decode_values(
                stream, P[_PC_NONNULL], P[_PC_ENC], column, dict_size
            )
            if indices is not None:
                plan.page_infos.append((P[_PC_N], dfl, rep, "indices", indices))
            else:
                plan.page_infos.append((P[_PC_N], dfl, rep, "values", values))
            if stats is not None:
                stats.host_fallback_pages += 1
    kinds_after = {k for _, _, _, k, _ in plan.page_infos}
    kinds_after.discard("empty")
    if kinds_after == {"values"} and column.type in _NUMERIC_DTYPE:
        parts = [p for _, _, _, k, p in plan.page_infos if k == "values"]
        if parts:
            plan.plain_host = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return plan


def _freeze_hybrid_from_tables(data_pages, res) -> list | None:
    """Vectorized _HybridBatch.freeze over the native walk's global run
    tables. Pages group sequentially per index width under the bit cap (same
    policy as _commit_routes); returns None when a single page exceeds the
    cap (demote-all, matching the Python walk)."""
    cap = _BATCH_BITS_CAP
    groups: list[list] = []  # [width, rs, re, ps, pe, bits]
    cur = None
    for P in data_pages:
        if P[_PC_ROUTE] != 1:
            continue
        width = P[_PC_EXTRA]
        bits = (P[_PC_PACKE] - P[_PC_PACKS]) * 8
        if bits > cap:
            return None
        if cur is None or cur[0] != width or cur[5] + bits > cap:
            cur = [width, P[_PC_RUNS], P[_PC_RUNE], P[_PC_PACKS], P[_PC_PACKE], bits]
            groups.append(cur)
        else:
            cur[2] = P[_PC_RUNE]
            cur[4] = P[_PC_PACKE]
            cur[5] += bits
    frozen = []
    h_counts = res["h_counts"]
    h_is_rle = res["h_is_rle"]
    h_values = res["h_values"]
    h_byteoff = res["h_byteoff"]
    packed_all = res["packed"]
    for width, rs, re, ps, pe, _bits in groups:
        counts = h_counts[rs:re]
        k = len(counts)
        total = int(counts.sum())
        n_pad = _bucket(max(total, 1))
        run_pad = _bucket(k, 64)
        words = bytes_to_words32(bytes(packed_all[ps:pe]))
        w_pad = _bucket(len(words), 1024)
        buf = np.zeros(4 * run_pad + w_pad, dtype=np.uint32)
        buf[run_pad : 2 * run_pad] = np.int32(n_pad + 1).view(np.uint32)  # sentinel
        buf[:k] = h_is_rle[rs:re]
        out_start = np.zeros(k, dtype=np.int64)
        np.cumsum(counts[:-1], out=out_start[1:])
        buf[run_pad : run_pad + k] = out_start.astype(np.int32).view(np.uint32)
        buf[2 * run_pad : 2 * run_pad + k] = h_values[rs:re].astype(np.uint32)
        buf[3 * run_pad : 3 * run_pad + k] = (
            ((h_byteoff[rs:re] - ps) * 8).astype(np.int32).view(np.uint32)
        )
        buf[4 * run_pad : 4 * run_pad + len(words)] = words
        frozen.append(_FrozenHybrid(buf, width, n_pad, run_pad, total))
    return frozen


def _repack_plain_as_delta(plan: _ChunkPlan, whole: np.ndarray, nbits: int) -> bool:
    """Transfer-side re-encoding of a PLAIN int chunk: host deltas+bitpacks
    the decoded values (native DELTA_BINARY_PACKED encoder) and the existing
    device delta kernel reconstructs them bit-exactly in HBM — the wire then
    carries the column's entropy, not its width. On structured columns
    (ids, timestamps, counters) this cuts host->device bytes 10-50x, which
    is the dominant wall on a tunnel/PCIe-limited host. Incompressible
    chunks are detected by a sampled width estimate and ship raw (returns
    False, caller keeps the PLAIN upload). One whole-chunk stream (not
    per-page) keeps the device kernel's shape buckets stable. Mirrors the
    byte-minimizing intent of the reference's encoded column chunks
    (chunk_writer.go) but applied to the transfer link, not the file."""
    from ..utils.trace import bump

    n = len(whole)
    raw_bytes = n * whole.dtype.itemsize
    if n < 1 << 16 or raw_bytes < 1 << 19:
        return False  # small chunk: upload latency, not bandwidth, dominates
    from ..utils.native import get_native

    lib = get_native()
    if lib is None or not (lib.has_delta_encode and lib.has_prescan_delta):
        return False
    # profitability estimate from 4 contiguous sample windows: max zigzag
    # delta width ~ the packed width the encoder will pick
    est_bits = 0
    win = 1024
    for lo in (0, n // 3, (2 * n) // 3, n - win):
        w = whole[max(lo, 0) : max(lo, 0) + win]
        if len(w) < 2:
            continue
        d = np.diff(w.astype(np.int64, copy=False))
        if len(d):
            zz = int(np.abs(d).max()) << 1
            est_bits = max(est_bits, zz.bit_length())
    if est_bits * n >= 4 * raw_bytes:  # est packed size >= raw/2: not worth it
        bump("repack_declined", raw_bytes)
        return False
    try:
        stream = lib.delta_encode(whole, nbits, 1024, 4)
    except (ValueError, OverflowError):
        bump("repack_declined", raw_bytes)
        return False
    if len(stream) * 8 > _BATCH_BITS_CAP or len(stream) * 2 > raw_bytes:
        # sampled estimate missed: ship raw rather than inflate
        bump("repack_declined", raw_bytes)
        return False
    try:
        widths, byte_starts, out_starts, mins, first, total, consumed = (
            lib.prescan_delta_packed(stream, nbits, n)
        )
    except (ValueError, OverflowError):
        bump("repack_declined", raw_bytes)
        return False
    if int(total) != n:
        bump("repack_declined", raw_bytes)
        return False
    first_u = int(first) & ((1 << 64) - 1)
    first_i64 = first_u - (1 << 64) if first_u >= 1 << 63 else first_u
    P2 = [0] * 18
    P2[_PC_ROUTE] = 2
    P2[_PC_EXTRA] = n
    P2[_PC_DCONS] = int(consumed)
    P2[_PC_MINIS] = 0
    P2[_PC_MINIE] = len(widths)
    P2[_PC_DSTART] = 0
    P2[_PC_DFIRST] = first_i64
    res2 = {
        "d_widths": np.asarray(widths, dtype=np.uint32),
        "d_bytestart": np.asarray(byte_starts, dtype=np.int64),
        "d_outstart": np.asarray(out_starts, dtype=np.int32),
        "d_mins": np.asarray(mins, dtype=np.uint64),
        "delta_stream": np.frombuffer(stream, dtype=np.uint8),
    }
    plan.frozen_delta = _freeze_delta_from_tables([P2], res2, nbits)
    if plan.frozen_delta:
        bump("repack_engaged", len(stream))
    return bool(plan.frozen_delta)


def _freeze_delta_from_tables(data_pages, res, nbits: int) -> list:
    """Vectorized _DeltaBatch.freeze over the native walk's global miniblock
    tables (pages group sequentially under the bit cap)."""
    cap = _BATCH_BITS_CAP
    groups: list[list] = []  # [pages, ms, me, lo, hi, bits]
    cur = None
    for P in data_pages:
        if P[_PC_ROUTE] != 2 or P[_PC_EXTRA] == 0:
            continue  # empty streams contribute nothing (add_page parity)
        bits = P[_PC_DCONS] * 8
        if cur is None or cur[5] + bits > cap:
            cur = [[P], P[_PC_MINIS], P[_PC_MINIE], P[_PC_DSTART],
                   P[_PC_DSTART] + P[_PC_DCONS], bits]
            groups.append(cur)
        else:
            cur[0].append(P)
            cur[2] = P[_PC_MINIE]
            cur[4] = P[_PC_DSTART] + P[_PC_DCONS]
            cur[5] += bits
    frozen = []
    ud = np.uint32 if nbits == 32 else np.uint64
    d_widths = res["d_widths"]
    d_bytestart = res["d_bytestart"]
    d_outstart = res["d_outstart"]
    d_mins = res["d_mins"]
    stream_all = res["delta_stream"]
    for plist, ms, me, lo, hi, _bits in groups:
        totals = np.array([P[_PC_EXTRA] for P in plist], dtype=np.int64)
        bases = np.zeros(len(plist), dtype=np.int64)
        np.cumsum(totals[:-1], out=bases[1:])
        total = int(totals.sum())
        minis_per_page = np.array(
            [P[_PC_MINIE] - P[_PC_MINIS] for P in plist], dtype=np.int64
        )
        m = me - ms
        n_pad = _bucket(total)
        m_pad = _bucket(max(m, 1), 64)
        p = len(plist)
        p_pad = _bucket(p, 64)
        sentinel = np.int32(n_pad + 1).view(np.uint32)
        stream = bytes(stream_all[lo:hi])
        words = bytes_to_words32(stream) if nbits == 32 else bytes_to_words64(stream)
        w_pad = _bucket(len(words), 1024)
        tail32 = (2 * m_pad + 2 * p_pad + w_pad) if nbits == 32 else 0
        meta32 = np.zeros(3 * m_pad + p_pad + tail32, dtype=np.uint32)
        meta32[2 * m_pad : 3 * m_pad] = sentinel
        meta32[3 * m_pad : 3 * m_pad + p_pad] = sentinel
        out_starts = d_outstart[ms:me].astype(np.int64) + np.repeat(
            bases + 1, minis_per_page
        )
        if m:
            meta32[:m] = d_widths[ms:me]
            meta32[m_pad : m_pad + m] = (
                ((d_bytestart[ms:me] - lo) * 8).astype(np.int32).view(np.uint32)
            )
            meta32[2 * m_pad : 2 * m_pad + m] = (
                out_starts.astype(np.int32).view(np.uint32)
            )
        meta32[3 * m_pad : 3 * m_pad + p] = bases.astype(np.int32).view(np.uint32)
        firsts = np.array([P[_PC_DFIRST] for P in plist], dtype=np.int64).view(
            np.uint64
        )
        if nbits == 32:
            base = 3 * m_pad + p_pad
            if m:
                meta32[base : base + m] = d_mins[ms:me].astype(ud)
            meta32[base + m_pad : base + m_pad + p] = firsts.astype(ud)
            meta32[base + m_pad + p_pad : base + m_pad + p_pad + len(words)] = words
            wide = np.zeros(0, dtype=np.uint32)
        else:
            wide = np.zeros(m_pad + p_pad + w_pad, dtype=np.uint64)
            if m:
                wide[:m] = d_mins[ms:me]
            wide[m_pad : m_pad + p] = firsts
            wide[m_pad + p_pad : m_pad + p_pad + len(words)] = words
        frozen.append(_FrozenDelta(meta32, wide, nbits, n_pad, m_pad, p_pad, total))
    return frozen


def _expand_dict_from_tables(P, res) -> np.ndarray:
    """Host expansion of one dict page straight from the global run tables
    (mirrors _host_decode_dict_page without re-prescanning the stream)."""
    from ..ops.rle_hybrid import RunTable, expand_runs

    rs, re, ps = P[_PC_RUNS], P[_PC_RUNE], P[_PC_PACKS]
    width = P[_PC_EXTRA]
    is_rle = res["h_is_rle"][rs:re].astype(bool)
    counts = res["h_counts"][rs:re]
    if len(counts) and not is_rle[-1] and width > 0:
        # the native walk clamps the final run's count to the page's value
        # count; expand_runs wants the FULL bit-packed count (its dense-unpack
        # math needs multiples of 8) and clamps via `takes` itself
        counts = counts.copy()
        counts[-1] = ((P[_PC_PACKE] - int(res["h_byteoff"][re - 1])) // width) * 8
    table = RunTable(
        is_rle=is_rle,
        counts=counts,
        rle_values=res["h_values"][rs:re],
        bp_offsets=res["h_byteoff"][rs:re] - ps,
        packed=bytes(res["packed"][ps : P[_PC_PACKE]]),
        consumed=0,
    )
    return expand_runs(table, P[_PC_NONNULL], width, np.uint32)


def prepare_chunk_plan(
    f,
    chunk,
    column: Column,
    validate_crc: bool = False,
    alloc=None,
    stats: TpuDecodeStats | None = None,
) -> _ChunkPlan:
    """Host-only prepare: page walk, decompress, level decode, prescan.

    Touches no jax state, so it is safe to run on worker threads; the
    returned plan's batches go to the device via plan.dispatch_device() on
    the dispatching thread. The whole-chunk native walk handles the common
    shapes in one C call; anything it declines takes the per-page Python
    walk below (the error-semantics reference) — the decode fallback
    ladder's middle rung. A chunk the native walk ABORTED on (fault set)
    that the staged walk then decodes cleanly counts as
    prepare_fallback_recovered; a genuinely corrupt chunk raises the staged
    walk's typed error (the ladder's final rung).
    """
    import time as _time

    from ..utils import trace as _trace

    plan, fault = _native_prepare(f, chunk, column, validate_crc, alloc, stats)
    if plan is not None:
        return plan
    t0 = _time.perf_counter()
    plan = _staged_prepare(f, chunk, column, validate_crc, alloc, stats)
    _metrics.observe("chunk_decode_seconds", _time.perf_counter() - t0)
    if fault is not None:
        # the native walk aborted but the staged walk decoded cleanly
        _trace.bump("prepare_fallback_recovered")
        from ..obs.log import log_event as _log_event

        _log_event(
            "prepare_fallback_recovered", level="warning",
            column=".".join(column.path), fault=str(fault),
        )
    return plan


def _staged_prepare(
    f,
    chunk,
    column: Column,
    validate_crc: bool = False,
    alloc=None,
    stats: TpuDecodeStats | None = None,
) -> _ChunkPlan:
    """The per-page Python prepare walk (the error-semantics reference)."""
    md = chunk.meta_data
    codec = md.codec or 0
    expected = md.num_values or 0
    plan = _ChunkPlan(column, expected)
    plan.stats = stats
    ptype = column.type

    # Device-routable pages stage here until the whole chunk is walked; batch
    # building (or demotion to host decode) happens in _commit_routes.
    pending: list[tuple] = []

    for raw in iter_chunk_pages(f, chunk):
        header = raw.header
        if alloc is not None:
            alloc.check(header.uncompressed_page_size or 0)
        pt = header.type
        if pt == int(PageType.DICTIONARY_PAGE):
            if plan.dictionary is not None:
                raise ChunkError("chunk: more than one dictionary page")
            if validate_crc:
                _check_crc(header, raw.payload)
            block = decompress_block(raw.payload, codec, header.uncompressed_page_size or 0)
            plan.dictionary = decode_dict_page(header, block, column)
            if alloc is not None:
                alloc.register_buffers(plan.dictionary)
            continue
        if pt == int(PageType.INDEX_PAGE):
            continue
        if pt not in (int(PageType.DATA_PAGE), int(PageType.DATA_PAGE_V2)):
            raise ChunkError(f"chunk: unknown page type {pt}")
        if validate_crc:
            _check_crc(header, raw.payload)

        n, dfl, rep, non_null, enc, values_buf = _split_page(
            raw, header, pt, codec, column
        )
        # byte volumes ride decompress_block's choke point; pages-per-encoding
        # is counted here because this walk prescans value streams without
        # going through the core.page decoders
        _metrics.page_decoded(
            _metrics.encoding_name(enc), nbytes=header.uncompressed_page_size or 0
        )
        if stats is not None:
            stats.pages += 1
        if alloc is not None:
            # actual levels + the eventual decoded value footprint (a lying
            # header cannot understate these: non_null comes from the real
            # level stream, dict indices decode at 4 B/value, delta totals
            # are plausibility-bounded by the prescan)
            alloc.register(
                decoded_nbytes(dfl)
                + decoded_nbytes(rep)
                + len(values_buf)
                + non_null * 8
            )

        # -- route the value stream --------------------------------------------
        if enc in (int(Encoding.RLE_DICTIONARY), int(Encoding.PLAIN_DICTIONARY)):
            if plan.dictionary is None:
                from ..core.page import MissingDictionaryError

                raise MissingDictionaryError(
                    "page: dictionary encoding without dictionary"
                )
            if non_null == 0:
                plan.page_infos.append((n, dfl, rep, "empty", None))
                continue
            width = values_buf[0] if values_buf else 0
            if width > 32:
                raise PageError(f"page: invalid dict index width {width}")
            from ..core.page import typed_page_errors

            with typed_page_errors("dict index stream"):
                table = prescan_hybrid(values_buf[1:], non_null, width)
            if len(table.packed) * 8 > _BATCH_BITS_CAP:
                # One page alone exceeds the int32 bit-offset range of the
                # device kernel: decode it on host (adversarially large pages;
                # real writers page at ~1 MiB, data_store.go:149-154).
                plan.page_infos.append(
                    (n, dfl, rep, *_host_decode_dict_page(table, width, non_null, stats))
                )
                continue
            pending.append(("dict", len(plan.page_infos), table, width, non_null, None))
            plan.page_infos.append((n, dfl, rep, "dict", non_null))
        elif enc == int(Encoding.DELTA_BINARY_PACKED) and ptype in (
            Type.INT32,
            Type.INT64,
        ):
            nbits = 32 if ptype == Type.INT32 else 64
            from ..core.page import typed_page_errors

            with typed_page_errors("delta stream"):
                table = prescan_delta_packed(values_buf, nbits, max_total=non_null)
            if table.consumed * 8 > _BATCH_BITS_CAP:
                # Same int32-range guard as the hybrid path: host decode.
                plan.page_infos.append(
                    (n, dfl, rep, *_host_decode_delta_page(values_buf, nbits, non_null, stats))
                )
                continue
            pending.append(("delta", len(plan.page_infos), table, nbits, non_null, values_buf))
            plan.page_infos.append((n, dfl, rep, "delta", table.total))
        elif enc == int(Encoding.PLAIN) and ptype in _NUMERIC_DTYPE:
            dt = _NUMERIC_DTYPE[ptype]
            need = non_null * np.dtype(dt).itemsize
            if len(values_buf) < need:
                raise PageError("page: plain payload too short")
            vals = np.frombuffer(values_buf, dtype=dt, count=non_null)
            plan.page_infos.append((n, dfl, rep, "values", vals))
        else:
            # Anything else (byte arrays, boolean, deltas on other types):
            # host decode for this page.
            from ..core.page import _decode_values

            dict_size = len(plan.dictionary) if plan.dictionary is not None else None
            values, indices = _decode_values(
                values_buf, non_null, enc, column, dict_size
            )
            if indices is not None:
                plan.page_infos.append((n, dfl, rep, "indices", indices))
            else:
                plan.page_infos.append((n, dfl, rep, "values", values))
            if stats is not None:
                stats.host_fallback_pages += 1

    _commit_routes(plan, pending, stats)
    return plan


def _commit_routes(plan: _ChunkPlan, pending: list, stats) -> None:
    """Build device batches — or demote to host decode if the chunk's pages
    are not homogeneous.

    Device decode only pays when the whole chunk's values stay on device; a
    chunk that mixes device-kinds with host-kinds (e.g. pyarrow's mid-chunk
    dictionary->PLAIN fallback once the dict page overflows) would need its
    device-decoded pages FETCHED back during reassembly — the exact
    round-trip regression backend="tpu" routing exists to avoid. Deciding
    after the full page walk keeps the cliff out: mixed chunks decode
    entirely on host and device_column does one typed upload.
    """
    kinds = {k for _, _, _, k, _ in plan.page_infos}
    kinds.discard("empty")
    pending_kinds = {p[0] for p in pending}
    # Homogeneous PLAIN numeric chunks: pre-concatenate the upload buffer
    # here (host-only) so dispatch is a single transfer.
    if kinds == {"values"} and not pending and plan.column.type in _NUMERIC_DTYPE:
        parts = [p for _, _, _, k, p in plan.page_infos if k == "values"]
        plan.plain_host = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return
    homogeneous = kinds == pending_kinds and len(pending_kinds) == 1
    if homogeneous:
        hybrid_batches = plan.hybrid_batches
        delta_batches = plan.delta_batches
        for kind, _idx, table, arg, non_null, buf in pending:
            if kind == "dict":
                width = arg
                if not hybrid_batches or not hybrid_batches[-1].fits(table, width):
                    hybrid_batches.append(_HybridBatch(width))
                hybrid_batches[-1].add_page(table, non_null)
            else:
                nbits = arg
                if not delta_batches or not delta_batches[-1].fits(table):
                    delta_batches.append(_DeltaBatch(nbits))
                delta_batches[-1].add_page(table, buf)
        plan.frozen_hybrid = [b.freeze() for b in hybrid_batches]
        plan.frozen_delta = [
            f for f in (b.freeze() for b in delta_batches) if f is not None
        ]
        plan.hybrid_batches = []
        plan.delta_batches = []
        return
    # Demote: host-decode the would-be device pages in place.
    for kind, idx, table, arg, non_null, buf in pending:
        n, dfl, rep, _k, _p = plan.page_infos[idx]
        if kind == "dict":
            plan.page_infos[idx] = (
                n, dfl, rep, *_host_decode_dict_page(table, arg, non_null, stats)
            )
        else:
            plan.page_infos[idx] = (
                n, dfl, rep, *_host_decode_delta_page(buf, arg, non_null, stats)
            )
    # a demotion can leave the chunk all-'values' numeric: pre-concat so its
    # upload still happens on the dispatch thread, not in device_column
    kinds_after = {k for _, _, _, k, _ in plan.page_infos}
    kinds_after.discard("empty")
    if kinds_after == {"values"} and plan.column.type in _NUMERIC_DTYPE:
        parts = [p for _, _, _, k, p in plan.page_infos if k == "values"]
        if parts:
            plan.plain_host = parts[0] if len(parts) == 1 else np.concatenate(parts)


def _host_decode_dict_page(table, width: int, non_null: int, stats):
    """Host fallback for a dict-coded page: ('indices', expanded indices)."""
    from ..ops.rle_hybrid import expand_runs

    if stats is not None:
        stats.host_fallback_pages += 1
    return "indices", expand_runs(table, non_null, width, np.uint32)


def _host_decode_delta_page(values_buf, nbits: int, non_null: int, stats):
    """Host fallback for a delta page: ('values', decoded values)."""
    from ..core.page import typed_page_errors
    from ..ops.delta import decode_delta

    if stats is not None:
        stats.host_fallback_pages += 1
    with typed_page_errors("delta stream"):
        vals, _ = decode_delta(values_buf, nbits, max_total=non_null)
    return "values", vals[:non_null]


def _split_page(raw, header, pt, codec, column: Column):
    """Split a data page into levels (host-decoded) and the value stream."""
    from ..core.page import typed_page_errors
    from ..ops.levels import decode_levels_v1, decode_levels_v2

    if pt == int(PageType.DATA_PAGE):
        h = header.data_page_header
        if h is None:
            raise PageError("page: DATA_PAGE without data_page_header")
        n = h.num_values or 0
        block = decompress_block(raw.payload, codec, header.uncompressed_page_size or 0)
        buf = memoryview(block)
        pos = 0
        rep = None
        with typed_page_errors("v1 level stream"):
            if column.max_rep > 0:
                rep, used = decode_levels_v1(buf, n, column.max_rep)
                pos += used
            dfl = None
            non_null = n
            if column.max_def > 0:
                dfl, used, cv = decode_levels_v1(
                    buf[pos:], n, column.max_def, want_const=True
                )
                pos += used
                if cv is not None:
                    non_null = n if cv == column.max_def else 0
                else:
                    non_null = int((dfl == column.max_def).sum())
        return n, dfl, rep, non_null, h.encoding, buf[pos:]

    h = header.data_page_header_v2
    if h is None:
        raise PageError("page: DATA_PAGE_V2 without data_page_header_v2")
    n = h.num_values or 0
    rep_len = h.repetition_levels_byte_length or 0
    def_len = h.definition_levels_byte_length or 0
    buf = memoryview(raw.payload)
    if rep_len < 0 or def_len < 0 or rep_len + def_len > len(buf):
        raise ChunkError("chunk: v2 level sizes exceed page")
    with typed_page_errors("v2 level stream"):
        rep = (
            decode_levels_v2(buf[:rep_len], n, column.max_rep)
            if column.max_rep > 0
            else None
        )
        dfl = None
        non_null = n
        if column.max_def > 0:
            dfl, cv = decode_levels_v2(
                buf[rep_len : rep_len + def_len], n, column.max_def, want_const=True
            )
            if cv is not None:
                non_null = n if cv == column.max_def else 0
            else:
                non_null = int((dfl == column.max_def).sum())
    values_buf = buf[rep_len + def_len :]
    if h.is_compressed is None or h.is_compressed:
        un = (header.uncompressed_page_size or 0) - rep_len - def_len
        values_buf = decompress_block(values_buf, codec, max(un, 0))
    return n, dfl, rep, non_null, h.encoding, values_buf


def read_chunk_tpu(
    f,
    chunk,
    column: Column,
    validate_crc: bool = False,
    alloc=None,
    stats: TpuDecodeStats | None = None,
) -> ChunkData:
    """TPU-backend chunk decode: levels on host, values on device.

    Byte-identical to core.chunk.read_chunk (the M1 oracle) — enforced by
    tests/test_tpu_backend.py on every supported shape.
    """
    return plan_chunk_tpu(
        f, chunk, column, validate_crc=validate_crc, alloc=alloc, stats=stats
    ).finalize()


def _device_bitcast(vals: jnp.ndarray, column: Column) -> jnp.ndarray:
    """Bitcast gathered uint patterns back to the column's real dtype."""
    if column.type == Type.FLOAT:
        return jax.lax.bitcast_convert_type(vals, jnp.float32)
    if column.type == Type.DOUBLE:
        return jax.lax.bitcast_convert_type(vals, jnp.float64)
    return vals


def _upload_typed(host: np.ndarray) -> jnp.ndarray:
    """Upload a host array; floats travel as bit patterns (the axon f64
    transfer is not bit-exact) and are bitcast back on device."""
    if host.dtype.kind == "f":
        u = np.uint32 if host.dtype.itemsize == 4 else np.uint64
        return jax.lax.bitcast_convert_type(
            jnp.asarray(host.view(u)),
            jnp.float32 if host.dtype.itemsize == 4 else jnp.float64,
        )
    return jnp.asarray(host)


def _materialize(dictionary, indices):
    """Expand dictionary indices for HOST delivery.

    Always gathers on the host: by the time finalize() runs, the indices are
    host arrays (device batches are fetched in one batched transfer up
    front), and bouncing them through the device for the gather costs an
    upload + a fetch per page — measured ~100ms/page on the transfer link —
    for work NumPy does in microseconds. The device dictionary (dict_dev)
    exists solely for device-resident delivery (device_column).

    An index past the dictionary is corrupt input (a rotted bit in the index
    stream), not a programming error: surface it typed, never as a raw
    IndexError (fault-harness contract — the staged walk validates indices
    at decode time, this is the fused walk's equivalent boundary)."""
    try:
        if isinstance(dictionary, ByteArrayData):
            return dictionary.take(np.asarray(indices, dtype=np.int64))
        return np.asarray(dictionary)[np.asarray(indices)]
    except (IndexError, ValueError) as e:
        raise PageError(f"page: dictionary index out of range: {e}") from e


def _concat_values(parts, column: Column):
    parts = [p for p in parts if p is not None]
    if any(isinstance(p, ByteArrayData) for p in parts):
        from ..core.chunk import _concat_byte_arrays

        return _concat_byte_arrays(parts)
    arrs = [np.asarray(p) for p in parts if len(p)]
    if arrs:
        return np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
    from ..core.chunk import _empty_dtype

    if column.type == Type.BYTE_ARRAY:
        return ByteArrayData(offsets=np.zeros(1, dtype=np.int64), data=b"")
    return np.empty(0, dtype=_empty_dtype(column))


# -- write path: DeviceColumn -> encoded pages ---------------------------------
#
# The batch-materialization inverse of read_chunk_tpu: a device-resident
# numeric column (a training batch, a checkpoint shard, a DeviceColumn's
# `values`) encodes into parquet pages WITHOUT first round-tripping the raw
# column through host encode loops. The expensive transforms — the
# dictionary probe and the hybrid bit-pack — run as the jittable inverses in
# device_ops (dict_indices_device / rle_hybrid_encode_device /
# bitpack_encode_device); the host's remaining share is run-header emission
# over the (few) segments plus page framing/compression, and the bytes are
# pinned identical to sink.encoder.encode_chunk for the same values.


def assemble_hybrid_device_stream(
    in_rle: np.ndarray, rle_break: np.ndarray, packed: np.ndarray,
    width: int, value_at
) -> bytes:
    """Turn rle_hybrid_encode_device's run plan into the exact
    ops/rle_hybrid.encode_hybrid byte stream. `in_rle`/`rle_break` are the
    device masks (fetched; one byte per value — rle_break splits ADJACENT
    RLE windows of different runs, which a flat mask would fuse), `packed`
    the device-packed payload words, `value_at(pos)` resolves an RLE
    window's repeated value (a tiny device gather per segment — segments
    are few by construction)."""
    from ..ops.varint import emit_uvarint as _emit_uvarint

    n = len(in_rle)
    out = bytearray()
    if n == 0:
        return b""
    if width == 0:
        _emit_uvarint(out, n << 1)
        return bytes(out)
    vbytes = (width + 7) // 8
    packed_bytes = memoryview(np.ascontiguousarray(packed)).cast("B")
    mask = np.asarray(in_rle, dtype=bool)
    breaks = np.asarray(rle_break, dtype=bool)
    seg_start = breaks.copy()
    seg_start[0] = True
    seg_start[1:] |= mask[1:] != mask[:-1]
    starts = np.flatnonzero(seg_start)
    bounds = np.append(starts, n)
    bp_done = 0  # bit-packed values consumed (tracks the payload cursor)
    for a, b in zip(bounds[:-1], bounds[1:]):
        a, b = int(a), int(b)
        if mask[a]:
            _emit_uvarint(out, (b - a) << 1)
            out += int(value_at(a)).to_bytes(vbytes, "little")
        else:
            groups = (b - a + 7) // 8
            _emit_uvarint(out, (groups << 1) | 1)
            byte0 = (bp_done // 8) * width
            out += packed_bytes[byte0 : byte0 + groups * width]
            bp_done += groups * 8
    return bytes(out)


def assemble_delta_device_stream(
    nbits: int,
    n: int,
    first: int,  # values[0] in the UNSIGNED nbits domain (0 when n == 0)
    mins: np.ndarray,  # int32/int64[>= n_blocks]: per-block min delta, signed
    widths: np.ndarray,  # int32[>= n_blocks * 4]: per-miniblock bit widths
    payload: bytes,  # packed payloads at cumsum(4 * width) byte offsets
) -> bytes:
    """Frame delta_block_encode_device's tables into the exact
    ops/delta.encode_delta byte stream (block_size=128, mini_count=4):
    uvarint header, then per block `<zigzag min> <4 width bytes> <payloads>`.
    mini_len=32 keeps every payload 4*width bytes, so the device stream
    slices out by a running byte cursor — the only sequential work left is
    header emission over the (few) blocks, the write-side twin of the
    prescan/expand split on the read side."""
    from ..ops.delta import _to_signed
    from ..ops.varint import emit_uvarint, emit_zigzag

    out = bytearray()
    emit_uvarint(out, 128)
    emit_uvarint(out, 4)
    emit_uvarint(out, n)
    emit_zigzag(out, _to_signed(int(first), nbits))
    if n <= 1:
        return bytes(out)
    n_deltas = n - 1
    pay = 0
    for blk in range((n_deltas + 127) // 128):
        emit_zigzag(out, int(mins[blk]))
        ws = [int(widths[blk * 4 + k]) for k in range(4)]
        out += bytes(ws)
        for k, w in enumerate(ws):
            if blk * 128 + k * 32 < n_deltas:  # mini has values: full payload
                out += payload[pay : pay + 4 * w]
            pay += 4 * w
    return bytes(out)


class _DevicePageFramer:
    """Host framing of device-produced page payloads — compress + Thrift
    header + optional CRC, shared by every encode_device_column route (the
    exact mirror of core/page.encode_data_page_v1/v2 for flat REQUIRED
    columns: no levels, no nulls)."""

    def __init__(self, cfg, value_encoding):
        from ..core.compress import compress_block
        from ..core.page import _crc32_signed
        from ..meta.parquet_types import (
            DataPageHeader,
            DataPageHeaderV2,
            PageHeader,
        )

        self._cfg = cfg
        self._value_encoding = value_encoding
        self._compress = compress_block
        self._crc = _crc32_signed
        self._PageHeader = PageHeader
        self._DataPageHeader = DataPageHeader
        self._DataPageHeaderV2 = DataPageHeaderV2
        self.parts: list = []
        self.pos = 0
        self.uncompressed_total = 0
        self.n_pages = 0

    def frame(self, raw: bytes, n_values: int) -> None:
        cfg = self._cfg
        block = self._compress(raw, cfg.codec)
        if cfg.data_page_version == 1:
            header = self._PageHeader(
                type=0,
                uncompressed_page_size=len(raw),
                compressed_page_size=len(block),
                data_page_header=self._DataPageHeader(
                    num_values=n_values,
                    encoding=int(self._value_encoding),
                    definition_level_encoding=int(Encoding.RLE),
                    repetition_level_encoding=int(Encoding.RLE),
                ),
            )
        else:
            header = self._PageHeader(
                type=3,
                uncompressed_page_size=len(raw),
                compressed_page_size=len(block),
                data_page_header_v2=self._DataPageHeaderV2(
                    num_values=n_values,
                    num_nulls=0,
                    num_rows=n_values,
                    encoding=int(self._value_encoding),
                    definition_levels_byte_length=0,
                    repetition_levels_byte_length=0,
                    is_compressed=True,
                ),
            )
        if cfg.with_crc:
            header.crc = self._crc(block)
        hdr = header.dumps()
        self.parts.append(hdr)
        self.parts.append(block)
        self.pos += len(hdr) + len(block)
        self.uncompressed_total += len(hdr) + len(raw)
        self.n_pages += 1


def encode_device_column(
    column: Column,
    values,
    cfg,
    kv: dict | None = None,
    *,
    enable_dict: bool = True,
):
    """Encode one device-resident numeric column into an EncodedChunk whose
    bytes are IDENTICAL to the host encoder's for the same values — drop-in
    for sink.encoder's assemble_group/commit_group, so a device training
    batch materializes to parquet through the same sink seam.

    `values` is a 1-D int32/int64/float32/float64 jax array (or anything
    jnp.asarray accepts) — or, for a BYTE_ARRAY column, a `(data, offsets)`
    pair of device arrays (uint8 payload + n+1 value offsets, the same
    layout the device read path delivers). The column must be flat REQUIRED
    (the dense batch shape device pipelines produce — levels stay a host
    concern). The dictionary decision, index hybrid-encode, bit-pack,
    DELTA block scans and byte-array framing all run on device; the host
    frames pages and compresses blocks."""
    import jax.numpy as _jnp

    from ..core.column_store import DICT_MAX_UNIQUES
    from ..core.page import encode_dict_page
    from ..core.stats import column_is_unsigned
    from ..sink.encoder import (
        EncodedChunk,
        _ChunkEncodePlan,
        _chunk_meta,
        _split_starts,
    )

    if column.max_rep > 0 or column.max_def > 0:
        raise ValueError(
            "encode_device_column: only flat REQUIRED columns encode "
            "device-side (nested/optional batches go through the host writer)"
        )
    if cfg.write_page_index:
        # per-page stat collection lives in the host encoder's
        # _PageIndexBuilder; silently dropping a requested page index would
        # break the drop-in identity this function promises
        raise ValueError(
            "encode_device_column: write_page_index is host-encoder-only "
            "(use sink.encoder.encode_chunk for indexed chunks)"
        )
    if column.type == Type.BYTE_ARRAY:
        if enable_dict:
            # The host encoder would run its dictionary probe (and dict-
            # encode when it pays); the device route has no byte-array
            # uniqueness kernel, so declining here keeps the byte-identity
            # contract — the writer's typed fallback re-encodes on host.
            raise ValueError(
                "encode_device_column: dictionary-eligible BYTE_ARRAY "
                "columns encode host-side (disable the dictionary for "
                "this column to engage the device PLAIN route)"
            )
        return _encode_device_bytearray(column, values, cfg, kv)
    dev = _jnp.asarray(values)
    if dev.ndim != 1 or dev.dtype.itemsize not in (4, 8):
        raise ValueError(
            "encode_device_column: expected a 1-D 4/8-byte numeric column"
        )
    want = {Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8}.get(
        column.type
    )
    if want is None or dev.dtype.itemsize != want:
        # An int64 batch built before jax x64 was enabled arrives as int32:
        # encoding its 4-byte values into an INT64 chunk would write a
        # corrupt file. The typed decline routes through the host encoder,
        # which widens correctly.
        raise ValueError(
            f"encode_device_column: {column.path_str} is {column.type!s} "
            f"but the device array is {dev.dtype} — width mismatch "
            "(was the array built before jax x64 was enabled?)"
        )
    n = int(dev.shape[0])
    np_dt = np.dtype(dev.dtype.name)
    # uniqueness domain: bit patterns, so NaN payloads dedup like the host
    bits = jax.lax.bitcast_convert_type(
        dev, _jnp.uint32 if np_dt.itemsize == 4 else _jnp.uint64
    )
    dict_result = None
    indices = None
    if enable_dict and n:
        idx_dev, firsts_dev, nu_dev = dict_indices_device(bits)
        nu = int(nu_dev)
        if nu <= DICT_MAX_UNIQUES:
            width = max(int(nu - 1).bit_length(), 1)
            dict_nbytes = nu * np_dt.itemsize
            if dict_nbytes + (n * width) // 8 < n * np_dt.itemsize:
                dict_values = np.asarray(dev[firsts_dev[:nu]]).astype(
                    np_dt, copy=False
                )
                dict_result = (dict_values, None)
                indices = idx_dev.astype(_jnp.uint32)
    value_encoding = (
        Encoding.RLE_DICTIONARY
        if dict_result is not None
        else cfg.column_encodings.get(column.path, Encoding.PLAIN)
    )
    nbits = np_dt.itemsize * 8
    delta_route = (
        dict_result is None
        and value_encoding == Encoding.DELTA_BINARY_PACKED
        and column.type in (Type.INT32, Type.INT64)
        and np_dt.kind in "iu"
    )
    host_typed = None
    stats_src = None
    if dict_result is None and not delta_route:
        host_typed = np.asarray(dev).astype(np_dt, copy=False)
        stats_src = host_typed
    elif delta_route:
        # DELTA never round-trips the raw column: min/max reduce on device
        # (in the column's defined order) and a 2-element stats_src yields
        # the identical Statistics bytes. Bloom is the one consumer that
        # needs every value — download only when a spec asks for it.
        udt = _jnp.uint32 if nbits == 32 else _jnp.uint64
        view = (
            jax.lax.bitcast_convert_type(dev, udt)
            if column_is_unsigned(column) and np_dt.kind == "i"
            else dev
        )
        if n:
            stats_src = np.array(
                [int(view.min()), int(view.max())],
                dtype=np.dtype(view.dtype.name),
            ).view(np_dt)
        else:
            stats_src = np.zeros(0, dtype=np_dt)
        if cfg.bloom_specs.get(column.path) is not None:
            host_typed = np.asarray(dev).astype(np_dt, copy=False)

    framer = _DevicePageFramer(cfg, value_encoding)
    dict_offset = None
    if dict_result is not None:
        header, block = encode_dict_page(
            column, dict_result[0], cfg.codec, cfg.with_crc
        )
        hdr = header.dumps()
        dict_offset = framer.pos
        framer.parts.append(hdr)
        framer.parts.append(block)
        framer.pos += len(hdr) + len(block)
        framer.uncompressed_total += len(hdr) + (
            header.uncompressed_page_size or 0
        )
        _metrics.inc("pages_written_total", encoding="PLAIN")
        data_offset = framer.pos
        width = max(int(len(dict_result[0]) - 1).bit_length(), 1)
        for a, b in _split_starts(n, max(int(cfg.max_page_size // 4), 1)):
            page_idx = indices[a:b]
            in_rle, rle_break, packed, _n_bp = rle_hybrid_encode_device(
                page_idx, width
            )
            stream = assemble_hybrid_device_stream(
                np.asarray(in_rle),
                np.asarray(rle_break),
                np.asarray(packed),
                width,
                lambda p, _pi=page_idx: int(_pi[p]),
            )
            framer.frame(bytes([width]) + stream, b - a)
    elif delta_route:
        data_offset = framer.pos
        per_page = max(int(cfg.max_page_size // np_dt.itemsize), 1)
        udt = _jnp.uint32 if nbits == 32 else _jnp.uint64
        for a, b in _split_starts(n, per_page):
            page = dev[a:b]
            pad = _bucket(max(b - a, 1))
            if pad > b - a:
                page = _jnp.concatenate(
                    [page, _jnp.zeros(pad - (b - a), dtype=dev.dtype)]
                )
            mins, widths, words = delta_block_encode_device(page, b - a, nbits)
            first = (
                int(jax.lax.bitcast_convert_type(dev[a], udt)) if b > a else 0
            )
            stream = assemble_delta_device_stream(
                nbits,
                b - a,
                first,
                np.asarray(mins),
                np.asarray(widths),
                memoryview(np.ascontiguousarray(words)).cast("B"),
            )
            framer.frame(stream, b - a)
    else:
        if value_encoding != Encoding.PLAIN:
            raise ValueError(
                "encode_device_column: only PLAIN/dictionary/"
                "DELTA_BINARY_PACKED device encodes are supported for "
                f"numeric columns (column asks for {value_encoding})"
            )
        data_offset = framer.pos
        per_page = max(int(cfg.max_page_size // np_dt.itemsize), 1)
        for a, b in _split_starts(n, per_page):
            framer.frame(host_typed[a:b].tobytes(), b - a)
    _metrics.inc(
        "pages_written_total", framer.n_pages,
        encoding=_metrics.encoding_name(value_encoding),
    )
    plan = _ChunkEncodePlan(
        nv=n,
        num_entries=n,
        null_count=0,
        def_levels=None,
        rep_levels=None,
        typed=host_typed,
        dict_result=dict_result,
        value_encoding=value_encoding,
        page_values=None,
        dict_size=len(dict_result[0]) if dict_result is not None else None,
        stats_src=dict_result[0] if dict_result is not None else stats_src,
    )
    cc, bloom = _chunk_meta(
        cfg,
        _DeviceBuilderShim(column),
        kv,
        plan,
        uncompressed_total=framer.uncompressed_total,
        pos=framer.pos,
        data_offset=data_offset,
        dict_offset=dict_offset,
        n_pages=framer.n_pages,
    )
    return EncodedChunk(
        parts=framer.parts, nbytes=framer.pos, chunk=cc, index=None, bloom=bloom
    )


def _encode_device_bytearray(column: Column, values, cfg, kv: dict | None):
    """BYTE_ARRAY half of encode_device_column: `values` is a
    `(data, offsets)` device pair; the PLAIN framing — `<4-byte LE length>
    <bytes>` per value — materializes on device as ONE fused program
    (plain_bytearray_encode_device), and PLAIN streams concatenate, so the
    host slices page sub-ranges out of the single framed download instead
    of looping values. Statistics still scan host-side (lexicographic
    byte-string min/max has no device formulation worth its dispatch), off
    the same offsets the page split already needs."""
    import jax.numpy as _jnp

    from ..sink.encoder import (
        EncodedChunk,
        _ChunkEncodePlan,
        _chunk_meta,
        _split_starts,
        _value_width,
    )

    try:
        data, offsets = values
    except (TypeError, ValueError):
        raise ValueError(
            "encode_device_column: BYTE_ARRAY columns take a "
            "(data, offsets) device pair"
        ) from None
    value_encoding = cfg.column_encodings.get(column.path, Encoding.PLAIN)
    if value_encoding != Encoding.PLAIN:
        raise ValueError(
            "encode_device_column: only PLAIN device encodes are supported "
            f"for BYTE_ARRAY columns (column asks for {value_encoding})"
        )
    data = _jnp.asarray(data)
    offsets = _jnp.asarray(offsets)
    if data.dtype != _jnp.uint8 or data.ndim != 1 or offsets.ndim != 1:
        raise ValueError(
            "encode_device_column: BYTE_ARRAY expects 1-D uint8 data and "
            "1-D integer offsets"
        )
    host_off = np.asarray(offsets).astype(np.int64, copy=False)
    n = int(host_off.shape[0] - 1)
    total = int(host_off[-1]) if n >= 0 else 0
    out_pad = _bucket(max(4 * n + total, 1))
    framed = np.asarray(
        plain_bytearray_encode_device(
            _pad_device(data), _pad_device(offsets), n, out_pad
        )
    )
    bad = ByteArrayData(offsets=host_off, data=np.asarray(data))
    framer = _DevicePageFramer(cfg, value_encoding)
    data_offset = framer.pos
    for a, b in _split_starts(n, max(int(cfg.max_page_size // _value_width(bad)), 1)):
        lo = 4 * a + int(host_off[a])
        hi = 4 * b + int(host_off[b])
        framer.frame(framed[lo:hi].tobytes(), b - a)
    _metrics.inc(
        "pages_written_total", framer.n_pages,
        encoding=_metrics.encoding_name(value_encoding),
    )
    plan = _ChunkEncodePlan(
        nv=n,
        num_entries=n,
        null_count=0,
        def_levels=None,
        rep_levels=None,
        typed=bad,
        dict_result=None,
        value_encoding=value_encoding,
        page_values=None,
        dict_size=None,
        stats_src=bad,
    )
    cc, bloom = _chunk_meta(
        cfg,
        _DeviceBuilderShim(column),
        kv,
        plan,
        uncompressed_total=framer.uncompressed_total,
        pos=framer.pos,
        data_offset=data_offset,
        dict_offset=None,
        n_pages=framer.n_pages,
    )
    return EncodedChunk(
        parts=framer.parts, nbytes=framer.pos, chunk=cc, index=None, bloom=bloom
    )


class _DeviceBuilderShim:
    """The slice of ColumnChunkBuilder _chunk_meta actually reads."""

    def __init__(self, column: Column):
        self.column = column

"""Pallas TPU kernels for the decode hot path.

The device_ops.py formulations compile well under bare XLA, but the fused
hybrid-expansion kernel here keeps the whole run-table expansion (searchsorted
replacement + bit extraction + RLE select) in VMEM with explicit blocking,
avoiding materializing the per-value run-index and bit-position tensors in HBM
(they are 3x the output size for 32-bit data — HBM bandwidth is the bottleneck,
not FLOPs).

Design notes (see /opt/skills/guides/pallas_guide.md):
  - grid over output blocks of BLOCK values; all inputs stay whole in VMEM
    (run tables are tiny; packed words are bounded by page-batch size)
  - run lookup: instead of a per-value binary search, each output value finds
    its run with a vectorized comparison against the (small) run-start vector:
    r = sum(run_out_start <= i) - 1 — a (BLOCK, R) compare + row-sum that maps
    onto the VPU; R (runs per batch) is capped by the host driver
  - bit extraction: same two-word gather as device_ops
  - 2D iota per guide (1D iota fails on TPU)

On CPU (tests) the kernels run with interpret=True; on TPU they compile with
Mosaic. Output is bit-identical to the host path either way.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu import works on CPU too (for interpret mode / shapes)
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["hybrid_expand_pallas", "HYBRID_BLOCK"]

HYBRID_BLOCK = 4096  # output values per grid step


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _hybrid_kernel(words_ref, starts_ref, rle_ref, values_ref, bits_ref, out_ref,
                   *, width: int, block: int, n_runs: int):
    """One grid step: expand `block` output values.

    words_ref: (W32,) uint32 packed payload words (whole, VMEM)
    starts_ref: (R,) int32 run output starts (exclusive cumsum)
    rle_ref: (R,) int32 1 if run is RLE
    values_ref: (R,) uint32 RLE value per run
    bits_ref: (R,) int32 payload bit start per run
    out_ref: (block,) uint32
    """
    step = pl.program_id(0)
    base = step * block
    # (block, 1) output indices — 2D iota per TPU requirement
    i = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0) + base
    # run index: count of run starts <= i, minus 1. starts is (R,) -> (1, R)
    starts = starts_ref[:].reshape(1, n_runs)
    r = jnp.sum((starts <= i).astype(jnp.int32), axis=1, keepdims=True) - 1
    r = jnp.clip(r, 0, n_runs - 1)
    run_start = jnp.take_along_axis(
        jnp.broadcast_to(starts, (block, n_runs)), r, axis=1
    )
    within = i - run_start
    bit_start = jnp.take_along_axis(
        jnp.broadcast_to(bits_ref[:].reshape(1, n_runs), (block, n_runs)), r, axis=1
    )
    bitpos = bit_start + within * width
    w0 = (bitpos >> 5).reshape(block)
    s = (bitpos & 31).astype(jnp.uint32).reshape(block)
    words = words_ref[:]
    lo = words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint32(0), words[jnp.minimum(w0 + 1, words.shape[0] - 1)] << ((32 - s) & 31))
    mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
    bp_vals = (lo | hi) & mask
    is_rle = jnp.take_along_axis(
        jnp.broadcast_to(rle_ref[:].reshape(1, n_runs), (block, n_runs)), r, axis=1
    ).reshape(block)
    rle_val = jnp.take_along_axis(
        jnp.broadcast_to(values_ref[:].reshape(1, n_runs), (block, n_runs)), r, axis=1
    ).reshape(block)
    out_ref[:] = jnp.where(is_rle == 1, rle_val, bp_vals)


@partial(jax.jit, static_argnames=("width", "num_values", "n_runs", "interpret"))
def hybrid_expand_pallas(
    words: jnp.ndarray,
    run_out_start: jnp.ndarray,  # (R,) int32
    run_is_rle: jnp.ndarray,  # (R,) int32
    run_rle_value: jnp.ndarray,  # (R,) uint32
    run_bp_bit_start: jnp.ndarray,  # (R,) int32
    width: int,
    num_values: int,
    n_runs: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas fused hybrid expansion. num_values must be a multiple of
    HYBRID_BLOCK (host driver pads; trailing values are discarded)."""
    assert num_values % HYBRID_BLOCK == 0
    grid = (num_values // HYBRID_BLOCK,)
    kernel = partial(
        _hybrid_kernel, width=width, block=HYBRID_BLOCK, n_runs=n_runs
    )
    in_specs = (
        [pl.BlockSpec(memory_space=pltpu.VMEM)] * 5
        if _HAS_PLTPU
        else [pl.BlockSpec()] * 5
    )
    out_spec = (
        pl.BlockSpec((HYBRID_BLOCK,), lambda i: (i,), memory_space=pltpu.VMEM)
        if _HAS_PLTPU
        else pl.BlockSpec((HYBRID_BLOCK,), lambda i: (i,))
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=jax.ShapeDtypeStruct((num_values,), jnp.uint32),
        in_specs=in_specs,
        out_specs=out_spec,
        interpret=interpret,
    )(words, run_out_start, run_is_rle, run_rle_value, run_bp_bit_start)

"""Device-side (JAX/XLA) batched decode primitives.

These are the TPU formulations of the ops/ host codecs, written as jittable
functions over fixed-shape tensors (XLA: traced once, no data-dependent
shapes). The sequential run/block structure of the wire format is dissolved on
the host into flat tables (ops/rle_hybrid.py prescan, ops/delta.py prescan);
everything here is gathers, shifts, segment-broadcasts and scans — the shapes
TPU executes well (SURVEY §7.2 M3).

Key formulation — bit-unpack without byte loops: value i of width W occupies
bits [i*W, (i+1)*W) of the LSB-first stream. Load the stream as uint32 words;
then val = (words[b>>5] >> (b&31)) | (words[b>>5+1] << (32-(b&31))), masked to
W bits: two gathers + two shifts per value, fully vectorized. 64-bit widths use
the same two-gather trick on uint64 words.

All index arithmetic is int32: TPU v5e has no native 64-bit integer ALU path
(XLA emulates i64 as i32 pairs, ~10-100x slower for gather/scan-heavy code),
and every batch this framework builds is < 2^31 bits (buckets are capped by
MAX_DEVICE_BATCH_BITS; the host drivers in pipeline.py split larger chunks).
64-bit *values* (delta int64 payloads) still use uint64 lanes — only the
positions/indices stay 32-bit.

int64 value support requires jax_enable_x64; enabled at import (documented in
the package README).

Why XLA formulations and not hand-written Pallas kernels: measured, not
assumed. A fused Pallas hybrid-expansion kernel (kept through round 1 as
kernels/pallas_ops.py) could not lower on the current Mosaic TPU backend —
its essential dynamic 1-D gather (words[bitpos >> 5]) trips Mosaic's gather
lowering rule, which only supports take_along_axis-shaped indices — while
the XLA formulation of the same expansion measured ~110 G values/s on-chip
(2^21 values, width 8), ≤2% of end-to-end decode wall time, which is
host-prepare- and transfer-bound (see bench.py). XLA's fusion of the
gather/shift/select chain is already near the HBM roofline here; a Pallas
rewrite has no headroom to matter until the host side is >10x faster.
"""

from __future__ import annotations

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the decode programs compile in O(100s) on
# a real TPU backend (one-time per shape bucket); caching them on disk makes
# every process after the first start in seconds. Opt out with
# PQT_JAX_COMPILE_CACHE=0; the location is PQT_JAX_COMPILE_CACHE_DIR
# (default ~/.cache/parquet_tpu/jax). A user-set jax_compilation_cache_dir
# always wins.
if (
    os.environ.get("PQT_JAX_COMPILE_CACHE", "1") != "0"
    and getattr(jax.config, "jax_compilation_cache_dir", None) is None
):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "PQT_JAX_COMPILE_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "parquet_tpu", "jax"),
        ),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import jax.numpy as jnp
import numpy as np
from functools import partial

__all__ = [
    "MAX_DEVICE_BATCH_BITS",
    "bytes_to_words32",
    "bytes_to_words64",
    "expand_hybrid_device",
    "delta_packed_decode_device",
    "dict_gather_device",
    "list_layout_device",
    "record_starts_device",
    "predicate_mask_device",
    "list_contains_mask_device",
    "mask_take_device",
    "bitpack_encode_device",
    "rle_hybrid_encode_device",
    "dict_indices_device",
    "delta_block_encode_device",
    "plain_bytearray_encode_device",
    "masked_agg_device",
]

# Largest bit offset representable in the int32 position math (host drivers
# assert batches stay under this; 2^31 bits = 256 MiB of packed payload).
MAX_DEVICE_BATCH_BITS = 1 << 31


def bytes_to_words32(data: bytes) -> np.ndarray:
    """Pad bytes to a uint32 LE word array (+1 guard word for the hi gather)."""
    pad = (-len(data)) % 4
    buf = data + b"\x00" * (pad + 4)
    return np.frombuffer(buf, dtype="<u4")


def bytes_to_words64(data: bytes) -> np.ndarray:
    pad = (-len(data)) % 8
    buf = data + b"\x00" * (pad + 8)
    return np.frombuffer(buf, dtype="<u8")


@partial(jax.jit, static_argnames=("width", "num_values", "run_pad"))
def expand_hybrid_device(
    buf: jnp.ndarray,  # uint32: [run_meta (4*run_pad) | packed words]
    width: int,
    num_values: int,
    run_pad: int,
) -> jnp.ndarray:
    """Expand a prescanned hybrid RLE/bit-packed stream on device.

    buf packs the four per-run vectors AND the packed payload words into ONE
    upload (the host<->device link pays a fixed per-transfer latency that
    dwarfs these tiny tables). Layout, with run_pad static:
      buf[0*run_pad:1*run_pad]  is_rle      0/1
      buf[1*run_pad:2*run_pad]  out_start   exclusive cumsum of counts (int32)
      buf[2*run_pad:3*run_pad]  rle_value   broadcast value of RLE runs
      buf[3*run_pad:4*run_pad]  bit_start   bit offset of payload (int32)
      buf[4*run_pad:]           packed payload words (+1 guard word)

    For output index i: its run r = searchsorted(out_start, i, 'right')-1.
    RLE runs broadcast their value; bit-packed runs extract bits at
    bit_start[r] + (i - out_start[r]) * width.
    """
    run_is_rle = buf[:run_pad] != 0
    run_out_start = jax.lax.bitcast_convert_type(buf[run_pad : 2 * run_pad], jnp.int32)
    run_rle_value = buf[2 * run_pad : 3 * run_pad]
    run_bp_bit_start = jax.lax.bitcast_convert_type(
        buf[3 * run_pad : 4 * run_pad], jnp.int32
    )
    packed_words = buf[4 * run_pad :]
    i = jnp.arange(num_values, dtype=jnp.int32)
    r = jnp.searchsorted(run_out_start, i, side="right").astype(jnp.int32) - 1
    within = i - run_out_start[r]
    if width == 0:
        return jnp.zeros(num_values, dtype=jnp.uint32)
    bitpos = run_bp_bit_start[r] + within * width
    w0 = bitpos >> 5
    s = (bitpos & 31).astype(jnp.uint32)
    lo = packed_words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint32(0), packed_words[w0 + 1] << ((32 - s) & 31))
    mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
    bp_vals = (lo | hi) & mask
    return jnp.where(run_is_rle[r], run_rle_value[r], bp_vals)


@partial(jax.jit, static_argnames=("nbits", "num_values", "m_pad", "p_pad"))
def delta_packed_decode_device(
    meta32: jnp.ndarray,  # uint32 — packed 32-bit tables (+ words when nbits=32)
    wide: jnp.ndarray,  # uint32/uint64 — packed wide tables (+ words when nbits=64)
    nbits: int,
    num_values: int,
    m_pad: int,
    p_pad: int,
) -> jnp.ndarray:
    """Fused DELTA_BINARY_PACKED decode of a whole chunk from *wire* bytes.

    The host ships the encoded stream (plus tiny per-miniblock/per-page
    tables); the device does everything: dynamic-width bit-unpack of every
    miniblock (two-word gather; the width is data, not a static — TPU vector
    shifts take vector amounts), + block min_delta, then one wrapping
    prefix-sum segmented per page:

        value[i] = first[p(i)] + C[i] - C[page_start[p(i)]]

    with C = cumsum of the per-position deltas (positions at page starts
    contribute 0). This is the SURVEY §7.2 M3c shape — headers prescanned,
    payload never expanded host-side — and the upload is the wire size, ~5-10x
    smaller than the decoded column (the reason device decode beats
    host-decode-plus-upload on the host<->device link).

    Everything travels in at most TWO packed uploads — one when nbits=32 —
    because per-transfer latency on the link dwarfs their size:
      meta32  [widths(m) | bit_starts(m) | out_starts(m) | page_start(p)]
              (int32 fields as bit patterns); for nbits=32 the wire words
              are appended after these four tables and `wide` is empty
      wide    [mins(m) | page_first(p)] in the value dtype's width; for
              nbits=64 the wire words (uint64) are appended after
    """
    mb_width = meta32[:m_pad]
    mb_bit_start = jax.lax.bitcast_convert_type(meta32[m_pad : 2 * m_pad], jnp.int32)
    mb_out_start = jax.lax.bitcast_convert_type(
        meta32[2 * m_pad : 3 * m_pad], jnp.int32
    )
    page_start = jax.lax.bitcast_convert_type(
        meta32[3 * m_pad : 3 * m_pad + p_pad], jnp.int32
    )
    if nbits == 32:
        mb_min = meta32[3 * m_pad + p_pad : 4 * m_pad + p_pad]
        page_first = meta32[4 * m_pad + p_pad : 4 * m_pad + 2 * p_pad]
        words = meta32[4 * m_pad + 2 * p_pad :]
    else:
        mb_min = wide[:m_pad]
        page_first = wide[m_pad : m_pad + p_pad]
        words = wide[m_pad + p_pad :]
    i = jnp.arange(num_values, dtype=jnp.int32)
    m = jnp.searchsorted(mb_out_start, i, side="right").astype(jnp.int32) - 1
    w = mb_width[m]
    within = i - mb_out_start[m]
    p = jnp.searchsorted(page_start, i, side="right").astype(jnp.int32) - 1
    is_start = i == page_start[p]
    if nbits == 32:
        bitpos = mb_bit_start[m] + within * w.astype(jnp.int32)
        w0 = bitpos >> 5
        s = (bitpos & 31).astype(jnp.uint32)
        lo = words[w0] >> s
        hi = jnp.where(s == 0, jnp.uint32(0), words[w0 + 1] << ((32 - s) & 31))
        mask = jnp.where(
            w >= 32, jnp.uint32(0xFFFFFFFF), (jnp.uint32(1) << (w & 31)) - 1
        )
        d = ((lo | hi) & mask) + mb_min[m]
        d = jnp.where(is_start, jnp.uint32(0), d)
        c = jnp.cumsum(d, dtype=jnp.uint32)
        vals = page_first[p] + c - c[page_start[p]]
        return jax.lax.bitcast_convert_type(vals, jnp.int32)
    bitpos = mb_bit_start[m] + within * w.astype(jnp.int32)
    w0 = bitpos >> 6
    s = (bitpos & 63).astype(jnp.uint64)
    lo = words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint64(0), words[w0 + 1] << ((64 - s) & 63))
    wmask = w.astype(jnp.uint64)
    mask = jnp.where(
        w >= 64,
        jnp.uint64(0xFFFFFFFFFFFFFFFF),
        (jnp.uint64(1) << (wmask & 63)) - 1,
    )
    d = ((lo | hi) & mask) + mb_min[m]
    d = jnp.where(is_start, jnp.uint64(0), d)
    c = jnp.cumsum(d, dtype=jnp.uint64)
    vals = page_first[p] + c - c[page_start[p]]
    return jax.lax.bitcast_convert_type(vals, jnp.int64)


@jax.jit
def _bss_transpose_padded(streams: jnp.ndarray) -> jnp.ndarray:
    m = streams.transpose()  # (n_pad, 4) uint8, one value per row
    return jax.lax.bitcast_convert_type(m, jnp.uint32)


def bss_transpose_device(streams: jnp.ndarray, num_values: int) -> jnp.ndarray:
    """BYTE_STREAM_SPLIT de-interleave ON DEVICE for 4-byte types: the
    page's 4 byte streams arrive as a (4, n_pad) uint8 array (each row one
    stream, bucket-padded); a transpose + one bitcast yields uint32 bit
    patterns (parquet-format Encodings.md BYTE_STREAM_SPLIT; host
    analogue: ops/byte_stream_split.decode). The jitted part sees ONLY the
    padded shape — pages with different non-null counts in the same bucket
    share one compilation; the slice below is a device-side view."""
    return _bss_transpose_padded(streams)[:num_values]


@jax.jit
def dict_gather_device(dictionary: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """Dictionary expansion: one gather (reference: type_dict.go lookup loop)."""
    return dictionary[indices]


@jax.jit
def record_starts_device(rep: jnp.ndarray):
    """Record assembly scan 1: which record each level entry belongs to.

    The device formulation of ops/levels.rows_from_rep / slot_ids at the
    root: an entry starts a record iff rep == 0, so row_of = inclusive
    prefix count of starts, minus one. Returns (row_of int32[n], n_rows
    int32 scalar) — both stay on device for downstream ragged-batch math."""
    starts = (rep == 0).astype(jnp.int32)
    row_of = jnp.cumsum(starts) - 1
    return row_of, jnp.sum(starts)


@jax.jit
def list_layout_device(
    rep: jnp.ndarray,  # int32[n]: repetition levels of one leaf
    dfl: jnp.ndarray,  # int32[n]: definition levels of the same leaf
    parent_rep,  # int32 scalar: the expanded node's PARENT repetition depth
    elem_def,  # int32 scalar: def threshold at which an element exists
) -> tuple:
    """One nesting depth's offsets/validity from device-resident level
    streams — the jittable twin of ops/levels.list_layout composed with
    slot_ids, so level streams decoded (or delivered) on device assemble
    into an Arrow-style layout WITHOUT a host round-trip (the host analogue
    walks these same arrays in core/assembly_vec.py).

    An entry opens a slot iff rep <= parent_rep; it starts an element of
    this depth iff additionally-or-independently rep <= parent_rep + 1 AND
    dfl >= elem_def (below elem_def the entry is the placeholder of an
    empty or null list). All prefix sums are jnp.cumsum; the per-slot
    element counts are one scatter-add — the shapes XLA executes well
    (SURVEY §7.2 M3).

    Returns (offsets, first_def, n_slots):
      offsets    int32[n + 1]  element-count prefix sums; entries past
                               n_slots repeat the total (padding)
      first_def  int32[n]      each slot's first entry's def level (feed
                               `first_def < null_def` for the node's null
                               mask); entries past n_slots are 0
      n_slots    int32 scalar  true slot count
    """
    n = rep.shape[0]
    boundary = rep <= parent_rep
    slot_of = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    exists = dfl >= elem_def
    elem_start = (rep <= parent_rep + 1) & exists
    counts = (
        jnp.zeros(n, dtype=jnp.int32)
        .at[jnp.clip(slot_of, 0, n - 1)]
        .add(elem_start.astype(jnp.int32))
    )
    offsets = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(counts)]
    )
    first_def = (
        jnp.zeros(n, dtype=jnp.int32)
        .at[jnp.clip(slot_of, 0, n - 1)]
        .add(jnp.where(boundary, dfl, 0).astype(jnp.int32))
    )
    return offsets, first_def, jnp.sum(boundary.astype(jnp.int32))


# -- query push-down: predicate -> mask -> gather, device-resident --------------


@partial(jax.jit, static_argnames=("op", "exact"))
def predicate_mask_device(values: jnp.ndarray, op: str, lo, hi, exact: bool = True):
    """One leaf predicate as a device boolean mask — the jittable twin of
    core/filter_vec's bracket comparison, so residual filtering of
    device-resident columns (read_row_group_device / DeviceColumn values)
    never round-trips the host.

    `lo`/`hi` bracket the filter value in the column's physical domain
    exactly like normalize_filters computes them; `exact` (static) is
    lo == hi — an inexact bracket means the value falls BETWEEN
    representable stored values, so equality is impossible and ordered ops
    use the end that stays exact. Masks combine with & / | (conjunction /
    DNF) and feed mask_take_device for the gather."""
    if op == "==":
        return (values == lo) if exact else jnp.zeros(values.shape, dtype=bool)
    if op == "!=":
        return (values != lo) if exact else jnp.ones(values.shape, dtype=bool)
    if op == "<":
        return (values < lo) if exact else (values <= lo)
    if op == "<=":
        return values <= lo
    if op == ">":
        return (values > hi) if exact else (values >= hi)
    if op == ">=":
        return values >= hi
    raise ValueError(f"predicate_mask_device: unsupported op {op!r}")


@jax.jit
def list_contains_mask_device(
    rep: jnp.ndarray,  # int32[n]: repetition levels of one LIST leaf
    dfl: jnp.ndarray,  # int32[n]: definition levels of the same leaf
    dense_match: jnp.ndarray,  # bool[nv]: equality mask over the DENSE values
    elem_def,  # int32 scalar: def level at which an element is present
):
    """('tags', 'contains', x) at the list-slot level, on device: the dense
    per-element equality mask scatters through the level streams to row
    membership — the same record-start prefix scan as record_starts_device
    composed with the validity gather of list_layout_device. Returns
    (rows bool[n], n_rows int32): entries past n_rows are padding."""
    n = rep.shape[0]
    valid = dfl == elem_def
    didx = jnp.clip(
        jnp.cumsum(valid.astype(jnp.int32)) - 1,
        0,
        max(dense_match.shape[0] - 1, 0),
    )
    if dense_match.shape[0]:
        entry_match = valid & dense_match[didx]
    else:
        entry_match = jnp.zeros(n, dtype=bool)
    starts = (rep == 0).astype(jnp.int32)
    row_of = jnp.cumsum(starts) - 1
    rows = (
        jnp.zeros(n, dtype=bool)
        .at[jnp.clip(row_of, 0, max(n - 1, 0))]
        .max(entry_match)
    )
    return rows, jnp.sum(starts)


@partial(jax.jit, static_argnames=("out_pad",))
def mask_take_device(values: jnp.ndarray, mask: jnp.ndarray, out_pad: int):
    """Compact `values[mask]` into a static out_pad-sized buffer on device
    (the gather stage of predicate -> mask -> gather; static shapes bound
    the compile count, SURVEY §7.1). Returns (taken, count): positions past
    `count` hold values[0] as padding — callers slice on the host after a
    (tiny) count fetch, or carry (taken, count) into downstream masked
    kernels unsliced."""
    n = values.shape[0]
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    tgt = jnp.where(mask, pos, out_pad)
    src = (
        jnp.zeros(out_pad + 1, dtype=jnp.int32)
        .at[jnp.clip(tgt, 0, out_pad)]
        .max(jnp.arange(n, dtype=jnp.int32))[:out_pad]
    )
    taken = values[src] if n else jnp.zeros((out_pad,), values.dtype)
    return taken, jnp.sum(mask.astype(jnp.int32))


# -- write path: device ENCODE kernels (inverses of the decode formulations) ----


@partial(jax.jit, static_argnames=("width",))
def bitpack_encode_device(values: jnp.ndarray, width: int) -> jnp.ndarray:
    """LSB-first bit-pack of uint32 `values` at `width` bits — the jittable
    inverse of the two-gather unpack at the top of this module (and of
    ops/bitpack.pack_bits on host). Value i lands at bits
    [i*width, (i+1)*width): each value splits into a lo/hi uint32 word
    contribution and one scatter-add assembles the stream (contributions
    occupy disjoint bits, so add IS or and no carries can occur).

    Returns uint32 LE words covering ceil(n*width/32) (+1 guard word of
    zeros, mirroring bytes_to_words32); the host trims the byte tail.
    The caller pads `values` to a multiple of 8 where the hybrid format
    requires whole groups (pack_bits has the same contract)."""
    n = values.shape[0]
    if width == 0 or n == 0:
        return jnp.zeros(1, dtype=jnp.uint32)
    n_words = (n * width + 31) // 32 + 1
    i = jnp.arange(n, dtype=jnp.int32)
    bitpos = i * width
    w0 = bitpos >> 5
    s = (bitpos & 31).astype(jnp.uint64)
    v = values.astype(jnp.uint64) << s
    lo = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (v >> jnp.uint64(32)).astype(jnp.uint32)
    words = (
        jnp.zeros(n_words, dtype=jnp.uint32)
        .at[w0]
        .add(lo)
        .at[jnp.minimum(w0 + 1, n_words - 1)]
        .add(hi)
    )
    return words


@partial(jax.jit, static_argnames=("width",))
def rle_hybrid_encode_device(values: jnp.ndarray, width: int):
    """The device half of hybrid RLE/bit-pack ENCODE — the inverse of
    expand_hybrid_device, mirroring ops/rle_hybrid.encode_hybrid's run
    policy exactly: an 8-aligned window of >= 8 identical values becomes an
    RLE run; everything else bit-packs in groups of 8.

    All the per-value work happens here with static shapes: run discovery
    (one boundary scan + prefix sums), the 8-aligned RLE-window arithmetic
    per position, compaction of the bit-packed positions, and the packed
    payload itself (bitpack_encode_device over the compacted stream — legal
    as ONE pack because every mid-stream segment covers whole groups of 8,
    so concatenating per-segment payloads equals packing the compacted
    sequence, zero-padded only at the very end). What remains on host is
    header emission over the (few) segments — the write-side twin of the
    prescan/expand split on the read side.

    Returns (in_rle bool[n], rle_break bool[n], packed uint32 words,
    n_bp int32 scalar): in_rle marks positions covered by an RLE window;
    rle_break marks the first position of each window (adjacent windows
    from DIFFERENT runs are separate RLE runs on the wire — a flat mask
    alone would fuse them); packed holds the bit-packed payload of the
    remaining positions in order; n_bp counts them.
    kernels/pipeline.assemble_hybrid_device_stream turns this into the
    exact encode_hybrid byte stream."""
    n = values.shape[0]
    i = jnp.arange(n, dtype=jnp.int32)
    if n == 0:
        return (
            jnp.zeros(0, dtype=bool),
            jnp.zeros(0, dtype=bool),
            jnp.zeros(1, dtype=jnp.uint32),
            jnp.int32(0),
        )
    boundary = jnp.concatenate(
        [jnp.ones(1, dtype=bool), values[1:] != values[:-1]]
    )
    run_of = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    # per-position run extent via segment scatter of starts/ends
    run_start = (
        jnp.full(n, n, dtype=jnp.int32).at[run_of].min(jnp.where(boundary, i, n))
    )[run_of]
    run_end = (
        jnp.zeros(n, dtype=jnp.int32).at[run_of].max(i + 1)
    )[run_of]
    rle_s = (run_start + 7) & ~7
    rle_e = run_end & ~7
    qualifies = (run_end - run_start >= 8) & (rle_e - rle_s >= 8)
    in_rle = qualifies & (i >= rle_s) & (i < rle_e)
    rle_break = in_rle & (i == rle_s)
    n_bp = jnp.sum(~in_rle)
    # compact the bit-packed positions (stable order), pad tail with zeros
    # so the trailing partial group packs its zero padding
    pos = jnp.cumsum((~in_rle).astype(jnp.int32)) - 1
    tgt = jnp.where(~in_rle, pos, n)
    src = (
        jnp.full(n + 1, -1, dtype=jnp.int32)
        .at[jnp.clip(tgt, 0, n)]
        .max(i)[:n]
    )
    bp_vals = jnp.where(src >= 0, values[jnp.clip(src, 0, n - 1)], 0).astype(
        jnp.uint32
    )
    packed = bitpack_encode_device(bp_vals, width)
    return in_rle, rle_break, packed, n_bp.astype(jnp.int32)


@jax.jit
def dict_indices_device(values: jnp.ndarray):
    """First-occurrence dictionary probe on device — the jittable inverse of
    dict_gather_device and the twin of the host u64/bytes probes (same
    first-occurrence unique order, so the dictionary PAGE bytes match).
    `values` must already be the column's uniqueness domain (bit patterns
    for floats, like build_dictionary's view). Static shapes throughout:

      sort -> group boundaries -> group id -> first-occurrence row per
      group (segment min) -> dictionary rank = order of groups by first
      occurrence -> per-row index gather.

    Returns (indices int32[n], firsts int32[n], n_uniques int32): firsts
    holds each unique's first row in dictionary order, padded with n past
    n_uniques; dictionary value k is values[firsts[k]]."""
    n = values.shape[0]
    if n == 0:
        return (
            jnp.zeros(0, dtype=jnp.int32),
            jnp.zeros(0, dtype=jnp.int32),
            jnp.int32(0),
        )
    order = jnp.argsort(values, stable=True).astype(jnp.int32)
    sv = values[order]
    newg = jnp.concatenate([jnp.ones(1, dtype=bool), sv[1:] != sv[:-1]])
    gid_sorted = jnp.cumsum(newg.astype(jnp.int32)) - 1
    n_uniques = gid_sorted[-1] + 1
    # first occurrence row of each (sorted-domain) group
    first_of_group = (
        jnp.full(n, n, dtype=jnp.int32).at[gid_sorted].min(order)
    )
    # dictionary order = groups sorted by first occurrence; unused group
    # slots carry n and sort last
    perm = jnp.argsort(first_of_group, stable=True).astype(jnp.int32)
    rank = jnp.zeros(n, dtype=jnp.int32).at[perm].set(jnp.arange(n, dtype=jnp.int32))
    gid = jnp.zeros(n, dtype=jnp.int32).at[order].set(gid_sorted)
    indices = rank[gid]
    firsts = first_of_group[perm]
    return indices, firsts, n_uniques.astype(jnp.int32)


@partial(jax.jit, static_argnames=("nbits",))
def delta_block_encode_device(values: jnp.ndarray, n, nbits: int):
    """DELTA_BINARY_PACKED block scans + payload pack on device — the encode
    inverse of delta_packed_decode_device, mirroring ops/delta.encode_delta's
    block policy exactly (block_size=128, mini_count=4, mini_len=32).

    `values` is one page's int32/int64 (or uint bit-pattern) slice padded to a
    static multiple of 128; `n` (traced) is the true value count, so one
    compilation serves every page in a pad bucket (SURVEY §7.1). The whole
    sequential structure dissolves into segment reductions: wrapping unsigned
    deltas (one shifted subtract), per-block signed min (one reshape min),
    per-miniblock max-of-adjusted -> bit width (one reshape max + clz), and
    the byte-aligned payload itself as one scatter-add of lo/hi word
    contributions (mini_len=32 makes every miniblock payload 4*width bytes,
    so payloads butt together byte-aligned at cumsum(4*width) offsets).

    Returns (mins, widths, words):
      mins    int32/int64[p_pad/128]  per-block min delta, signed; blocks
                                      past the last real delta carry INT_MAX
      widths  int32[p_pad/32]         per-miniblock bit width; minis with no
                                      real deltas carry 0
      words   uint32 LE words         payload stream at cumsum(4*width) byte
                                      offsets (+ guard words)
    kernels/pipeline.assemble_delta_device_stream frames these into the exact
    encode_delta byte stream (uvarint header + per-block min/widths/payload)."""
    p_pad = values.shape[0]
    ut = jnp.uint32 if nbits == 32 else jnp.uint64
    st = jnp.int32 if nbits == 32 else jnp.int64
    u = jax.lax.bitcast_convert_type(values, ut)
    i = jnp.arange(p_pad, dtype=jnp.int32)
    nd = n - 1  # delta count
    valid = i < nd
    d = jnp.where(valid, jnp.roll(u, -1) - u, ut(0))
    sd = jax.lax.bitcast_convert_type(d, st)
    n_blocks = p_pad // 128
    mins = jnp.min(
        jnp.where(valid, sd, jnp.iinfo(st).max).reshape(n_blocks, 128), axis=1
    )
    adj = jnp.where(
        valid, d - jax.lax.bitcast_convert_type(mins, ut)[i >> 7], ut(0)
    )
    n_minis = p_pad // 32
    amax = jnp.max(adj.reshape(n_minis, 32), axis=1)
    widths = jnp.where(
        amax == 0, ut(0), ut(nbits) - jax.lax.clz(amax).astype(ut)
    ).astype(jnp.int32)
    pay_start = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(4 * widths)]
    )
    m = i >> 5
    w = widths[m]
    bitpos = pay_start[m] * 8 + (i & 31) * w
    n_words = n_minis * nbits + 2
    w0 = jnp.clip(bitpos >> 5, 0, n_words - 2)
    s = (bitpos & 31).astype(jnp.uint64)
    vlo = (adj & ut(0xFFFFFFFF)).astype(jnp.uint64) << s
    words = (
        jnp.zeros(n_words, dtype=jnp.uint32)
        .at[w0]
        .add((vlo & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
        .at[w0 + 1]
        .add((vlo >> jnp.uint64(32)).astype(jnp.uint32))
    )
    if nbits == 64:
        # widths past 32 bits: the hi half of each delta lands 32 bits later
        # (disjoint bits again: add is or)
        vhi = (adj >> ut(32)).astype(jnp.uint64) << s
        w1 = jnp.clip(w0 + 1, 0, n_words - 2)
        words = (
            words.at[w1]
            .add((vhi & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
            .at[w1 + 1]
            .add((vhi >> jnp.uint64(32)).astype(jnp.uint32))
        )
    return mins, widths, words


@partial(jax.jit, static_argnames=("out_pad",))
def plain_bytearray_encode_device(
    data: jnp.ndarray,  # uint8: dense value bytes
    offsets: jnp.ndarray,  # int32/int64[nv + 1]: value byte offsets
    n,  # int32 scalar: true value count (entries past it are padding)
    out_pad: int,  # static bucketed output byte capacity
) -> jnp.ndarray:
    """PLAIN BYTE_ARRAY framing on device: `<4-byte LE length><bytes>` per
    value, the encode inverse of the merge_mixed_bytes_device gather. One
    searchsorted maps every output byte to its value; headers materialize
    from the offset diffs and payload bytes gather straight out of `data` —
    no per-value host loop, and PLAIN streams concatenate, so the host
    slices page sub-ranges out of ONE framed chunk stream at
    4*a + offsets[a]. Bytes past 4*n + offsets[n] are zero padding."""
    nv = offsets.shape[0] - 1
    v_idx = jnp.arange(offsets.shape[0], dtype=jnp.int64)
    off = offsets.astype(jnp.int64)
    off = jnp.where(v_idx <= n, off, off[jnp.int64(n)])
    fout = 4 * jnp.minimum(v_idx, jnp.int64(n)) + off
    total = fout[jnp.int64(n)]
    pos = jnp.arange(out_pad, dtype=jnp.int64)
    v = jnp.clip(
        jnp.searchsorted(fout[1:], pos, side="right"), 0, max(nv - 1, 0)
    )
    rel = pos - fout[v]
    ln = off[v + 1] - off[v]
    hdr = ((ln >> (8 * jnp.clip(rel, 0, 3))) & 0xFF).astype(jnp.uint8)
    db = data[jnp.clip(off[v] + rel - 4, 0, max(data.shape[0] - 1, 0))]
    if data.shape[0] == 0:
        db = jnp.zeros(out_pad, dtype=jnp.uint8)
    return jnp.where(pos < total, jnp.where(rel < 4, hdr, db), jnp.uint8(0))


@partial(jax.jit, static_argnames=("op",))
def masked_agg_device(values: jnp.ndarray, mask: jnp.ndarray, op: str):
    """One aggregation unit's partial as ONE jnp reduction over the resident
    row mask (count/sum/min/max) — the device half of serve/aggregate's
    unit_partial; the exact pyarrow-pinned cross-group merge stays on host.
    sum accumulates in the 64-bit domain like pyarrow's sum kernel (the
    caller pre-casts to int64/uint64); min/max mask losers with the dtype's
    identity, so a matched count of zero means the scalar is garbage — the
    caller must gate on count > 0 (serve/aggregate_device does)."""
    if op == "count":
        return jnp.sum(mask.astype(jnp.int64))
    if op == "sum":
        return jnp.sum(jnp.where(mask, values, values.dtype.type(0)))
    if jnp.issubdtype(values.dtype, jnp.integer):
        info = jnp.iinfo(values.dtype)
        lose = info.max if op == "min" else info.min
    else:
        lose = jnp.inf if op == "min" else -jnp.inf
    masked = jnp.where(mask, values, values.dtype.type(lose))
    if op == "min":
        return jnp.min(masked)
    if op == "max":
        return jnp.max(masked)
    raise ValueError(f"masked_agg_device: unsupported op {op!r}")


@partial(jax.jit, static_argnames=("rows_pad",))
def merge_mixed_numeric_device(
    idx_all: jnp.ndarray,        # int32[D_pad]: dict-row indices, output order
    dictionary: jnp.ndarray,     # dict values (uint bit patterns for floats)
    plain: jnp.ndarray,          # plain values, page pools concatenated
    page_kind: jnp.ndarray,      # int32[P_pad]: 1 dict page, 0 plain page
    page_row_start: jnp.ndarray, # int32[P_pad + 1]: first output row per page
    page_aux: jnp.ndarray,       # int32[P_pad]: base into idx_all / plain
    rows_pad: int,
) -> jnp.ndarray:
    """Merge a mixed dict/PLAIN numeric chunk in output-index space: dict
    rows gather through idx_all -> dictionary, PLAIN rows read their upload
    directly — one fused program, one dispatch (a per-page slice/concat loop
    costs one host->device dispatch per page over the transfer link). Rows
    past the true count carry padding; the caller slices them off."""
    rows = jnp.arange(rows_pad, dtype=jnp.int32)
    pg = jnp.searchsorted(page_row_start[1:], rows, side="right").astype(jnp.int32)
    pg = jnp.minimum(pg, page_kind.shape[0] - 1)
    rel = rows - page_row_start[pg]
    is_dict = page_kind[pg] == 1
    src = jnp.clip(page_aux[pg] + rel, 0, None)
    dv = dictionary[
        jnp.clip(idx_all[jnp.minimum(src, idx_all.shape[0] - 1)], 0,
                 dictionary.shape[0] - 1)
    ]
    pv = plain[jnp.minimum(src, plain.shape[0] - 1)]
    return jnp.where(is_dict, dv, pv)


@partial(jax.jit, static_argnames=("rows_pad", "total_bytes_pad"))
def merge_mixed_bytes_device(
    idx_all: jnp.ndarray,        # int32[D_pad]: dict-row indices, output order
    doff: jnp.ndarray,           # int64[n_dict + 1]: dictionary offsets
    src_data: jnp.ndarray,       # uint8: [dict payload | plain page pools]
    po32: jnp.ndarray,           # int32[E_pad]: concatenated plain offset arrays
    page_kind: jnp.ndarray,      # int32[P_pad]: 1 dict page, 0 plain page
    page_row_start: jnp.ndarray, # int32[P_pad + 1]: first output row per page
    page_aux: jnp.ndarray,       # int32[P_pad]: dict: base into idx_all;
                                 #              plain: base ENTRY into po32
    page_src_base: jnp.ndarray,  # int64[P_pad]: plain: pool byte base in src_data
    n_rows: jnp.ndarray,         # int32 scalar: true row count (shape-free)
    rows_pad: int,               # static bucketed row capacity
    total_bytes_pad: int,        # static bucketed output byte capacity
):
    """Materialize a mixed dict/PLAIN byte-array chunk on device.

    Dict pages contribute rows via index gather against the dictionary's
    offsets; PLAIN pages contribute rows via their (int32-compressed) offset
    arrays — only raw page bytes, int32 offsets and tiny per-page tables
    ever cross the host->device link; the per-row source map, the offsets
    cumsum and the final byte materialization are one fused device program.
    Returns (data uint8[total_bytes_pad], offsets int64[rows_pad + 1]);
    entries past n_rows and bytes past offsets[n_rows] are padding (static
    shapes bound the compile count, SURVEY §7.1).
    """
    rows = jnp.arange(rows_pad, dtype=jnp.int32)
    pg = jnp.searchsorted(page_row_start[1:], rows, side="right").astype(jnp.int32)
    pg = jnp.minimum(pg, page_kind.shape[0] - 1)
    rel = rows - page_row_start[pg]
    is_dict = page_kind[pg] == 1
    idx = idx_all[
        jnp.clip(jnp.where(is_dict, page_aux[pg] + rel, 0), 0, idx_all.shape[0] - 1)
    ]
    idx = jnp.clip(idx, 0, doff.shape[0] - 2)
    dstart = doff[idx]
    dlen = doff[idx + 1] - doff[idx]
    e = jnp.clip(jnp.where(is_dict, 0, page_aux[pg] + rel), 0, po32.shape[0] - 2)
    p0 = po32[e].astype(jnp.int64)
    p1 = po32[e + 1].astype(jnp.int64)
    pstart = p0 + page_src_base[pg]
    plen = p1 - p0
    starts = jnp.where(is_dict, dstart, pstart)
    lengths = jnp.where(rows < n_rows, jnp.where(is_dict, dlen, plen), 0)
    lengths = jnp.maximum(lengths, 0)
    off = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int64), jnp.cumsum(lengths, dtype=jnp.int64)]
    )
    pos = jnp.arange(total_bytes_pad, dtype=jnp.int64)
    row = jnp.searchsorted(off[1:], pos, side="right")
    row = jnp.minimum(row, rows_pad - 1)
    src = starts[row] + (pos - off[row])
    src = jnp.clip(src, 0, src_data.shape[0] - 1)
    data = jnp.where(pos < off[-1], src_data[src], jnp.uint8(0))
    return data, off

"""Device-side (JAX/XLA) batched decode primitives.

These are the TPU formulations of the ops/ host codecs, written as jittable
functions over fixed-shape tensors (XLA: traced once, no data-dependent
shapes). The sequential run/block structure of the wire format is dissolved on
the host into flat tables (ops/rle_hybrid.py prescan, ops/delta.py prescan);
everything here is gathers, shifts, segment-broadcasts and scans — the shapes
TPU executes well (SURVEY §7.2 M3).

Key formulation — bit-unpack without byte loops: value i of width W occupies
bits [i*W, (i+1)*W) of the LSB-first stream. Load the stream as uint32 words;
then val = (words[b>>5] >> (b&31)) | (words[b>>5+1] << (32-(b&31))), masked to
W bits: two gathers + two shifts per value, fully vectorized. 64-bit widths use
the same two-gather trick on uint64 words.

int64 support requires jax_enable_x64; enabled at import (documented in the
package README).
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from functools import partial

__all__ = [
    "bytes_to_words32",
    "bytes_to_words64",
    "unpack_bits_device",
    "expand_hybrid_device",
    "delta_decode_device",
    "dict_gather_device",
]


def bytes_to_words32(data: bytes) -> np.ndarray:
    """Pad bytes to a uint32 LE word array (+1 guard word for the hi gather)."""
    pad = (-len(data)) % 4
    buf = data + b"\x00" * (pad + 4)
    return np.frombuffer(buf, dtype="<u4")


def bytes_to_words64(data: bytes) -> np.ndarray:
    pad = (-len(data)) % 8
    buf = data + b"\x00" * (pad + 8)
    return np.frombuffer(buf, dtype="<u8")


@partial(jax.jit, static_argnames=("width", "num_values"))
def unpack_bits_device(words: jnp.ndarray, width: int, num_values: int) -> jnp.ndarray:
    """Unpack `num_values` LSB-first `width`-bit values from uint32 words.

    Returns uint32 (width <= 32). The two-word gather handles values straddling
    word boundaries; shift-by-32 is avoided with a where on s == 0.
    """
    assert 0 < width <= 32
    i = jnp.arange(num_values, dtype=jnp.int64)
    bitpos = i * width
    w0 = (bitpos >> 5).astype(jnp.int32)
    s = (bitpos & 31).astype(jnp.uint32)
    lo = words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint32(0), words[w0 + 1] << ((32 - s) & 31))
    mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
    return (lo | hi) & mask


@partial(jax.jit, static_argnames=("width", "num_values"))
def unpack_bits_device64(words: jnp.ndarray, width: int, num_values: int) -> jnp.ndarray:
    """64-bit variant: unpack from uint64 words, return uint64 (width <= 64)."""
    assert 0 < width <= 64
    i = jnp.arange(num_values, dtype=jnp.int64)
    bitpos = i * width
    w0 = (bitpos >> 6).astype(jnp.int32)
    s = (bitpos & 63).astype(jnp.uint64)
    lo = words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint64(0), words[w0 + 1] << ((64 - s) & 63))
    mask = (
        jnp.uint64((1 << width) - 1)
        if width < 64
        else jnp.uint64(0xFFFFFFFFFFFFFFFF)
    )
    return (lo | hi) & mask


@partial(jax.jit, static_argnames=("width", "num_values"))
def expand_hybrid_device(
    packed_words: jnp.ndarray,
    run_is_rle: jnp.ndarray,  # (R,) bool
    run_out_start: jnp.ndarray,  # (R,) int64 exclusive cumsum of counts
    run_rle_value: jnp.ndarray,  # (R,) uint32
    run_bp_bit_start: jnp.ndarray,  # (R,) int64 bit offset of run payload
    width: int,
    num_values: int,
) -> jnp.ndarray:
    """Expand a prescanned hybrid RLE/bit-packed stream on device.

    For output index i: its run r = searchsorted(run_out_start, i, 'right')-1.
    RLE runs broadcast their value; bit-packed runs extract bits at
    run_bp_bit_start[r] + (i - run_out_start[r]) * width.
    """
    i = jnp.arange(num_values, dtype=jnp.int64)
    r = jnp.searchsorted(run_out_start, i, side="right") - 1
    within = i - run_out_start[r]
    if width == 0:
        return jnp.zeros(num_values, dtype=jnp.uint32)
    bitpos = run_bp_bit_start[r] + within * width
    w0 = (bitpos >> 5).astype(jnp.int32)
    s = (bitpos & 31).astype(jnp.uint32)
    lo = packed_words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint32(0), packed_words[w0 + 1] << ((32 - s) & 31))
    mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
    bp_vals = (lo | hi) & mask
    return jnp.where(run_is_rle[r], run_rle_value[r], bp_vals)


@partial(jax.jit, static_argnames=("nbits", "num_values", "width"))
def _unpack_miniblocks(words, mb_bit_start, mb_out_start, width, nbits, num_values):
    """Unpack all miniblocks of one distinct width into their delta positions."""
    # Done per distinct width by the host driver; indexes like expand_hybrid.
    i = jnp.arange(num_values, dtype=jnp.int64)
    m = jnp.searchsorted(mb_out_start, i, side="right") - 1
    within = i - mb_out_start[m]
    if nbits == 32:
        bitpos = mb_bit_start[m] + within * width
        w0 = (bitpos >> 5).astype(jnp.int32)
        s = (bitpos & 31).astype(jnp.uint32)
        lo = words[w0] >> s
        hi = jnp.where(s == 0, jnp.uint32(0), words[w0 + 1] << ((32 - s) & 31))
        mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
        return (lo | hi) & mask
    bitpos = mb_bit_start[m] + within * width
    w0 = (bitpos >> 6).astype(jnp.int32)
    s = (bitpos & 63).astype(jnp.uint64)
    lo = words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint64(0), words[w0 + 1] << ((64 - s) & 63))
    mask = (
        jnp.uint64((1 << width) - 1) if width < 64 else jnp.uint64(0xFFFFFFFFFFFFFFFF)
    )
    return (lo | hi) & mask


@partial(jax.jit, static_argnames=("nbits", "num_values"))
def delta_decode_device(
    deltas_plus_min: jnp.ndarray,  # (num_values-1,) unsigned, already + min_delta
    first_value,  # scalar unsigned
    nbits: int,
    num_values: int,
) -> jnp.ndarray:
    """Wrapping prefix-sum: values[k] = first + sum(deltas[:k]) mod 2**nbits.

    The cumulative sum is an associative scan — XLA lowers it to a logarithmic
    tree, the TPU-friendly inversion of the reference's one-value-at-a-time
    loop (deltabp_decoder.go:113-174, SURVEY §7.2 M3c).
    """
    ud = jnp.uint32 if nbits == 32 else jnp.uint64
    sd = jnp.int32 if nbits == 32 else jnp.int64
    first = jnp.asarray(first_value, dtype=ud)
    body = jnp.cumsum(deltas_plus_min.astype(ud), dtype=ud) + first
    out = jnp.concatenate([first[None], body])
    return jax.lax.bitcast_convert_type(out, sd)


@jax.jit
def dict_gather_device(dictionary: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """Dictionary expansion: one gather (reference: type_dict.go lookup loop)."""
    return dictionary[indices]

"""Device-side (JAX/XLA) batched decode primitives.

These are the TPU formulations of the ops/ host codecs, written as jittable
functions over fixed-shape tensors (XLA: traced once, no data-dependent
shapes). The sequential run/block structure of the wire format is dissolved on
the host into flat tables (ops/rle_hybrid.py prescan, ops/delta.py prescan);
everything here is gathers, shifts, segment-broadcasts and scans — the shapes
TPU executes well (SURVEY §7.2 M3).

Key formulation — bit-unpack without byte loops: value i of width W occupies
bits [i*W, (i+1)*W) of the LSB-first stream. Load the stream as uint32 words;
then val = (words[b>>5] >> (b&31)) | (words[b>>5+1] << (32-(b&31))), masked to
W bits: two gathers + two shifts per value, fully vectorized. 64-bit widths use
the same two-gather trick on uint64 words.

All index arithmetic is int32: TPU v5e has no native 64-bit integer ALU path
(XLA emulates i64 as i32 pairs, ~10-100x slower for gather/scan-heavy code),
and every batch this framework builds is < 2^31 bits (buckets are capped by
MAX_DEVICE_BATCH_BITS; the host drivers in pipeline.py split larger chunks).
64-bit *values* (delta int64 payloads) still use uint64 lanes — only the
positions/indices stay 32-bit.

int64 value support requires jax_enable_x64; enabled at import (documented in
the package README).
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from functools import partial

__all__ = [
    "MAX_DEVICE_BATCH_BITS",
    "bytes_to_words32",
    "bytes_to_words64",
    "expand_hybrid_device",
    "delta_packed_decode_device",
    "dict_gather_device",
]

# Largest bit offset representable in the int32 position math (host drivers
# assert batches stay under this; 2^31 bits = 256 MiB of packed payload).
MAX_DEVICE_BATCH_BITS = 1 << 31


def bytes_to_words32(data: bytes) -> np.ndarray:
    """Pad bytes to a uint32 LE word array (+1 guard word for the hi gather)."""
    pad = (-len(data)) % 4
    buf = data + b"\x00" * (pad + 4)
    return np.frombuffer(buf, dtype="<u4")


def bytes_to_words64(data: bytes) -> np.ndarray:
    pad = (-len(data)) % 8
    buf = data + b"\x00" * (pad + 8)
    return np.frombuffer(buf, dtype="<u8")


@partial(jax.jit, static_argnames=("width", "num_values"))
def expand_hybrid_device(
    packed_words: jnp.ndarray,
    run_is_rle: jnp.ndarray,  # (R,) bool
    run_out_start: jnp.ndarray,  # (R,) int32 exclusive cumsum of counts
    run_rle_value: jnp.ndarray,  # (R,) uint32
    run_bp_bit_start: jnp.ndarray,  # (R,) int32 bit offset of run payload
    width: int,
    num_values: int,
) -> jnp.ndarray:
    """Expand a prescanned hybrid RLE/bit-packed stream on device.

    For output index i: its run r = searchsorted(run_out_start, i, 'right')-1.
    RLE runs broadcast their value; bit-packed runs extract bits at
    run_bp_bit_start[r] + (i - run_out_start[r]) * width.
    """
    i = jnp.arange(num_values, dtype=jnp.int32)
    r = jnp.searchsorted(run_out_start, i, side="right").astype(jnp.int32) - 1
    within = i - run_out_start[r]
    if width == 0:
        return jnp.zeros(num_values, dtype=jnp.uint32)
    bitpos = run_bp_bit_start[r] + within * width
    w0 = bitpos >> 5
    s = (bitpos & 31).astype(jnp.uint32)
    lo = packed_words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint32(0), packed_words[w0 + 1] << ((32 - s) & 31))
    mask = jnp.uint32((1 << width) - 1) if width < 32 else jnp.uint32(0xFFFFFFFF)
    bp_vals = (lo | hi) & mask
    return jnp.where(run_is_rle[r], run_rle_value[r], bp_vals)


@partial(jax.jit, static_argnames=("nbits", "num_values"))
def delta_packed_decode_device(
    words: jnp.ndarray,  # packed wire bytes as uint32/uint64 words (+guard)
    mb_width: jnp.ndarray,  # (M,) uint32 miniblock bit widths
    mb_bit_start: jnp.ndarray,  # (M,) int32 bit offset of miniblock payload
    mb_out_start: jnp.ndarray,  # (M,) int32 global delta position of miniblock
    mb_min: jnp.ndarray,  # (M,) uint32/uint64 block min_delta (mod 2**nbits)
    page_start: jnp.ndarray,  # (P,) int32 global position of each page's first value
    page_first: jnp.ndarray,  # (P,) uint32/uint64 first value of each page
    nbits: int,
    num_values: int,
) -> jnp.ndarray:
    """Fused DELTA_BINARY_PACKED decode of a whole chunk from *wire* bytes.

    The host ships the encoded stream (plus tiny per-miniblock/per-page
    tables); the device does everything: dynamic-width bit-unpack of every
    miniblock (two-word gather; the width is data, not a static — TPU vector
    shifts take vector amounts), + block min_delta, then one wrapping
    prefix-sum segmented per page:

        value[i] = first[p(i)] + C[i] - C[page_start[p(i)]]

    with C = cumsum of the per-position deltas (positions at page starts
    contribute 0). This is the SURVEY §7.2 M3c shape — headers prescanned,
    payload never expanded host-side — and the upload is the wire size, ~5-10x
    smaller than the decoded column (the reason device decode beats
    host-decode-plus-upload on the host<->device link).
    """
    i = jnp.arange(num_values, dtype=jnp.int32)
    m = jnp.searchsorted(mb_out_start, i, side="right").astype(jnp.int32) - 1
    w = mb_width[m]
    within = i - mb_out_start[m]
    p = jnp.searchsorted(page_start, i, side="right").astype(jnp.int32) - 1
    is_start = i == page_start[p]
    if nbits == 32:
        bitpos = mb_bit_start[m] + within * w.astype(jnp.int32)
        w0 = bitpos >> 5
        s = (bitpos & 31).astype(jnp.uint32)
        lo = words[w0] >> s
        hi = jnp.where(s == 0, jnp.uint32(0), words[w0 + 1] << ((32 - s) & 31))
        mask = jnp.where(
            w >= 32, jnp.uint32(0xFFFFFFFF), (jnp.uint32(1) << (w & 31)) - 1
        )
        d = ((lo | hi) & mask) + mb_min[m]
        d = jnp.where(is_start, jnp.uint32(0), d)
        c = jnp.cumsum(d, dtype=jnp.uint32)
        vals = page_first[p] + c - c[page_start[p]]
        return jax.lax.bitcast_convert_type(vals, jnp.int32)
    bitpos = mb_bit_start[m] + within * w.astype(jnp.int32)
    w0 = bitpos >> 6
    s = (bitpos & 63).astype(jnp.uint64)
    lo = words[w0] >> s
    hi = jnp.where(s == 0, jnp.uint64(0), words[w0 + 1] << ((64 - s) & 63))
    wmask = w.astype(jnp.uint64)
    mask = jnp.where(
        w >= 64,
        jnp.uint64(0xFFFFFFFFFFFFFFFF),
        (jnp.uint64(1) << (wmask & 63)) - 1,
    )
    d = ((lo | hi) & mask) + mb_min[m]
    d = jnp.where(is_start, jnp.uint64(0), d)
    c = jnp.cumsum(d, dtype=jnp.uint64)
    vals = page_first[p] + c - c[page_start[p]]
    return jax.lax.bitcast_convert_type(vals, jnp.int64)


@jax.jit
def dict_gather_device(dictionary: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """Dictionary expansion: one gather (reference: type_dict.go lookup loop)."""
    return dictionary[indices]

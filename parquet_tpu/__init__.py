"""parquet_tpu — a TPU-native Apache Parquet framework.

A brand-new implementation of the capability set of fraugster/parquet-go
(see SURVEY.md), designed TPU-first: file I/O, Thrift metadata, block
decompression, and record assembly run on the host; the column-decode hot path
(RLE/bit-packing hybrid, dictionary lookup, delta-binary-packed) runs as batched
JAX/Pallas kernels behind a pluggable decoder backend
(FileReader.read_row_group_device(); host-bound reads always decode on host).

Quick start:

    import parquet_tpu as pq

    # read
    with pq.FileReader("f.parquet") as r:
        cols = r.read_row_group(0)                  # columnar arrays
        rows = list(r.iter_rows())                  # assembled records

    # write
    schema = pq.parse_schema("message m { required int64 id; }")
    with pq.FileWriter("out.parquet", schema, codec="snappy") as w:
        w.write_row({"id": 1})

    # high-level dataclass mapping
    from parquet_tpu import floor

Layout:
  meta/      Thrift compact protocol + parquet-format metadata model
  ops/       host (NumPy-vectorized) encoders/decoders — the correctness oracle
  kernels/   device (JAX/XLA + Pallas) decode ops + the batched page pipeline
  core/      pages, chunks, column stores, schema tree, FileReader/FileWriter
  io/        pluggable byte sources (lock-free local pread, in-memory,
             retrying remote-shaped), footer-driven range planning with
             coalescing + readahead, block/footer caches
  sink/      pluggable byte sinks (atomic tmp+rename local files,
             in-memory, write-combining buffer) + the parallel row-group
             encode pipeline on the pqt-encode pool
  data/      streaming dataset: sharded/shuffled multi-file plans, bounded
             prefetch, fixed-size rebatching, mid-epoch checkpoint/resume
  serve/     the scan/query daemon: typed HTTP protocol, warm-cache
             planning, streaming push-down execution, admission control
  schema/    textual schema DSL (parser/printer/validator) + builder API
  floor/     high-level record marshal/unmarshal + dataclass autoschema
  parallel/  shard_map/mesh scale-out over pages, columns, and row groups
  tools/     parquet-tool and csv2parquet CLIs
  utils/     native C++ helpers (snappy, scans), varints, INT96 time
  native/    the C++ helper library (build with `make -C native`)
"""

__version__ = "0.1.0"

from .core.reader import FileReader, MaskedColumn, RaggedColumn  # noqa: F401
from .ops.packed_levels import PackedLevels  # noqa: F401
from .core.writer import FileWriter, WriterError  # noqa: F401
from .core.schema import Column, Schema, SchemaError  # noqa: F401
from .core.arrays import ByteArrayData  # noqa: F401
from .core.alloc import AllocError  # noqa: F401
from .core.filter import FilterError  # noqa: F401
from .core.compress import register_codec, CompressionError  # noqa: F401
from .core.merge import merge_files, split_row_groups  # noqa: F401
from .meta import (  # noqa: F401
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType,
    LogicalType,
    PageType,
    ParquetFileError,
    Type,
    read_file_metadata,
)
from .schema.dsl import (  # noqa: F401
    SchemaParseError,
    parse_schema,
    schema_to_string,
    validate,
    validate_strict,
)
from .schema import builder  # noqa: F401
from . import floor  # noqa: F401
from .data import ParquetDataset  # noqa: F401  (host-only at import; jax lazy)
from .io import (  # noqa: F401
    BlockCache,
    ByteSource,
    FooterCache,
    HttpSource,
    LocalFileSource,
    MemorySource,
    ObjectStoreSource,
    RetryingSource,
    SourceError,
    TieredCache,
    TransientSourceError,
)
from .sink import (  # noqa: F401
    BufferedSink,
    ByteSink,
    FileObjectSink,
    LocalFileSink,
    MemorySink,
    SinkError,
)


def __getattr__(name):
    # `parallel` imports jax (and flips jax_enable_x64) at module load; keep
    # that out of the base import path — pure host read/write must work
    # without jax, and backend init can be slow on experimental platforms.
    if name == "parallel":
        import importlib

        module = importlib.import_module(".parallel", __name__)
        globals()["parallel"] = module
        return module
    if name == "serve":
        # the daemon layer is stdlib-only but pulls http.server machinery
        # nothing but `parquet-tool serve`/embedders need — keep it lazy
        import importlib

        module = importlib.import_module(".serve", __name__)
        globals()["serve"] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

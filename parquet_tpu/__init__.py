"""parquet_tpu — a TPU-native Apache Parquet framework.

A brand-new implementation of the capability set of fraugster/parquet-go
(see SURVEY.md), designed TPU-first: file I/O, Thrift metadata, block
decompression, and record assembly run on the host; the column-decode hot path
(RLE/bit-packing hybrid, dictionary lookup, delta-binary-packed) runs as batched
JAX/Pallas kernels behind a pluggable decoder backend.

Layout:
  meta/      Thrift compact protocol + parquet-format metadata model
  ops/       host (NumPy-vectorized) encoders/decoders — the correctness oracle
  kernels/   Pallas TPU kernels + the batched page-decode pipeline
  core/      pages, chunks, column stores, schema tree, FileReader/FileWriter
  schema/    textual schema DSL (parser/validator) + autoschema from dataclasses
  floor/     high-level record marshal/unmarshal (the reference's floor analogue)
  parallel/  shard_map/mesh scale-out over pages, columns, and row groups
  tools/     parquet-tool and csv2parquet CLI equivalents
  utils/     shared helpers (varints, buffered IO, hashing)
"""

__version__ = "0.1.0"

from .meta import (  # noqa: F401
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType,
    LogicalType,
    PageType,
    Type,
    read_file_metadata,
)

from .api import Reader, Writer  # noqa: F401
from .autoschema import AutoSchemaError, schema_from_dataclass  # noqa: F401
from .interfaces import (  # noqa: F401
    FieldNotPresentError,
    MarshalList,
    MarshalMap,
    MarshalObject,
    UnmarshalList,
    UnmarshalMap,
    UnmarshalObject,
)
from .time import Time  # noqa: F401

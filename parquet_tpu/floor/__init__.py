from .api import Reader, Writer  # noqa: F401
from .autoschema import AutoSchemaError, schema_from_dataclass  # noqa: F401

"""Nanosecond-precision time-of-day for the TIME logical type.

datetime.time caps at microseconds, which silently truncates TIME(NANOS)
columns; this type keeps the full nanos-since-midnight value plus the
isAdjustedToUTC flag, the same information the reference's floor.Time
carries (reference: floor/time.go:10-13, ctors :26-45, converters :92-105).

The read path (core/assembly.py convert_logical) returns Time for
TIME(NANOS) columns and datetime.time for MILLIS/MICROS, where no precision
exists to lose.
"""

from __future__ import annotations

import datetime as dt
import functools

__all__ = ["Time", "NANOS_PER_DAY"]

NANOS_PER_DAY = 24 * 3600 * 1_000_000_000


@functools.total_ordering
class Time:
    """Time of day as nanoseconds since midnight, with a UTC flag."""

    __slots__ = ("nanos", "utc")

    def __init__(self, hour=0, minute=0, second=0, nanosecond=0, *, utc=True):
        nanos = ((hour * 60 + minute) * 60 + second) * 1_000_000_000 + nanosecond
        if not 0 <= nanos < NANOS_PER_DAY:
            raise ValueError(f"Time: {nanos} ns outside a day")
        self.nanos = nanos
        self.utc = bool(utc)

    @classmethod
    def from_nanos(cls, nanos: int, *, utc: bool = True) -> "Time":
        t = cls.__new__(cls)
        if not 0 <= nanos < NANOS_PER_DAY:
            raise ValueError(f"Time: {nanos} ns outside a day")
        t.nanos = int(nanos)
        t.utc = bool(utc)
        return t

    @classmethod
    def from_time(cls, t: dt.time, *, utc: bool | None = None) -> "Time":
        if utc is None:
            utc = t.tzinfo is not None
        return cls(t.hour, t.minute, t.second, t.microsecond * 1000, utc=utc)

    # -- components ------------------------------------------------------------

    @property
    def hour(self) -> int:
        return self.nanos // 3_600_000_000_000

    @property
    def minute(self) -> int:
        return (self.nanos // 60_000_000_000) % 60

    @property
    def second(self) -> int:
        return (self.nanos // 1_000_000_000) % 60

    @property
    def nanosecond(self) -> int:
        return self.nanos % 1_000_000_000

    # -- conversions -----------------------------------------------------------

    def to_time(self) -> dt.time:
        """datetime.time equivalent; sub-microsecond digits are truncated."""
        return dt.time(
            self.hour,
            self.minute,
            self.second,
            self.nanosecond // 1000,
            tzinfo=dt.timezone.utc if self.utc else None,
        )

    def isoformat(self) -> str:
        ns = self.nanosecond
        frac = f".{ns:09d}".rstrip("0").rstrip(".") if ns else ""
        return f"{self.hour:02d}:{self.minute:02d}:{self.second:02d}{frac}"

    # -- dunder ----------------------------------------------------------------

    def __repr__(self):
        return f"Time({self.isoformat()!r}, utc={self.utc})"

    def __eq__(self, other):
        if isinstance(other, Time):
            return self.nanos == other.nanos and self.utc == other.utc
        return NotImplemented

    def __lt__(self, other):
        if isinstance(other, Time):
            return self.nanos < other.nanos
        return NotImplemented

    def __hash__(self):
        return hash((self.nanos, self.utc))

"""Auto-schema: Python dataclasses / type hints -> parquet Schema.

Equivalent of the reference's reflection-based generator (reference:
parquetschema/autoschema/gen.go:17-32 GenerateSchema, :60-387 generateField):
dataclass fields map to columns by type hint, Optional[...] controls
repetition, list/dict map to LIST/MAP groups, nested dataclasses to groups,
datetime types to DATE/TIME/TIMESTAMP logical types.

Mapping:
    int                 int64          float       double
    str                 binary(STRING) bytes       binary
    bool                boolean        np.int32    int32
    np.float32          float          np.int8/16  int32 (INT(8/16))
    datetime.datetime   int64 TIMESTAMP(MICROS, utc)
    datetime.date       int32 DATE
    datetime.time       int64 TIME(MICROS)
    Optional[T]         optional (else required)
    list[T]             LIST group     dict[K, V]  MAP group
    dataclass           nested group
Field name overrides via dataclasses metadata {"parquet": "name"}, the analogue
of the reference's struct tags (reference: floor/fieldname.go:8-19).
"""

from __future__ import annotations

import dataclasses
import datetime as dt

from .time import Time
import types
import typing

import numpy as np

from ..core.schema import Column, Schema
from ..meta.parquet_types import FieldRepetitionType, Type
from ..schema.builder import (
    _TypeSpec,
    _field,
    group,
    int_type,
    list_of,
    map_of,
    message,
    string,
    timestamp,
)
from ..meta.parquet_types import (
    ConvertedType,
    DateType,
    LogicalType,
    TimeType,
    TimeUnit,
)

__all__ = ["schema_from_dataclass", "AutoSchemaError"]


class AutoSchemaError(TypeError):
    pass


def _date_spec() -> _TypeSpec:
    return _TypeSpec(
        Type.INT32, converted=ConvertedType.DATE, logical=LogicalType(DATE=DateType())
    )


def _time_spec() -> _TypeSpec:
    return _TypeSpec(
        Type.INT64,
        converted=ConvertedType.TIME_MICROS,
        logical=LogicalType(
            TIME=TimeType(isAdjustedToUTC=True, unit=TimeUnit.micros())
        ),
    )


def _nanotime_spec() -> _TypeSpec:
    # floor.Time keeps nanosecond precision (reference: floor/time.go:10-13)
    return _TypeSpec(
        Type.INT64,
        logical=LogicalType(TIME=TimeType(isAdjustedToUTC=True, unit=TimeUnit.nanos())),
    )


_SCALARS = {
    int: lambda: Type.INT64,
    float: lambda: Type.DOUBLE,
    bool: lambda: Type.BOOLEAN,
    str: string,
    bytes: lambda: Type.BYTE_ARRAY,
    dt.datetime: lambda: timestamp("micros"),
    dt.date: _date_spec,
    dt.time: _time_spec,
    Time: _nanotime_spec,
    np.int64: lambda: Type.INT64,
    np.int32: lambda: Type.INT32,
    np.int16: lambda: int_type(16),
    np.int8: lambda: int_type(8),
    np.uint64: lambda: int_type(64, signed=False),
    np.uint32: lambda: int_type(32, signed=False),
    np.float64: lambda: Type.DOUBLE,
    np.float32: lambda: Type.FLOAT,
}


def schema_from_dataclass(cls, name: str | None = None) -> Schema:
    """Generate a Schema from a dataclass type."""
    if not dataclasses.is_dataclass(cls):
        raise AutoSchemaError(f"autoschema: {cls!r} is not a dataclass")
    fields = []
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        col_name = f.metadata.get("parquet", f.name) if f.metadata else f.name
        fields.append(_field_for(col_name, hints[f.name]))
    return message(*fields, name=name or cls.__name__.lower())


def _unwrap_optional(hint) -> tuple[object, bool]:
    origin = typing.get_origin(hint)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1 and len(typing.get_args(hint)) == 2:
            return args[0], True
        raise AutoSchemaError(f"autoschema: unsupported union {hint}")
    return hint, False


def _field_for(name: str, hint) -> Column:
    inner, is_opt = _unwrap_optional(hint)
    rep = FieldRepetitionType.OPTIONAL if is_opt else FieldRepetitionType.REQUIRED
    return _node_for(name, inner, rep)


def _node_for(name: str, hint, rep: FieldRepetitionType) -> Column:
    origin = typing.get_origin(hint)
    if origin in (list, typing.List):
        (elem_hint,) = typing.get_args(hint) or (int,)
        elem_inner, elem_opt = _unwrap_optional(elem_hint)
        elem = _node_for(
            "element",
            elem_inner,
            FieldRepetitionType.OPTIONAL if elem_opt else FieldRepetitionType.REQUIRED,
        )
        return list_of(name, elem, required_list=(rep == FieldRepetitionType.REQUIRED))
    if origin in (dict, typing.Dict):
        k_hint, v_hint = typing.get_args(hint) or (str, int)
        v_inner, v_opt = _unwrap_optional(v_hint)
        key = _node_for("key", k_hint, FieldRepetitionType.REQUIRED)
        value = _node_for(
            "value",
            v_inner,
            FieldRepetitionType.OPTIONAL if v_opt else FieldRepetitionType.REQUIRED,
        )
        return map_of(name, key, value, required_map=(rep == FieldRepetitionType.REQUIRED))
    if dataclasses.is_dataclass(hint):
        hints = typing.get_type_hints(hint)
        children = []
        for f in dataclasses.fields(hint):
            col_name = f.metadata.get("parquet", f.name) if f.metadata else f.name
            children.append(_field_for(col_name, hints[f.name]))
        return group(name, *children, repetition=rep)
    spec_fn = _SCALARS.get(hint)
    if spec_fn is None:
        raise AutoSchemaError(f"autoschema: unsupported type {hint!r} for field {name!r}")
    return _field(name, spec_fn(), rep)

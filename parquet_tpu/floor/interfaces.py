"""Marshal/unmarshal object model — explicit, reflection-free row building.

The dataclass path in floor.api covers most uses; these builders are the
full-control analogue of the reference's interfaces package: user types
implement

    def marshal_parquet(self, obj: MarshalObject) -> None      # write side
    def unmarshal_parquet(self, obj: UnmarshalObject) -> None  # read side

and floor.Writer/Reader detect the methods (reference:
floor/interfaces/marshaller.go:13-175, unmarshaller.go:15-293; detection in
floor/writer.go:55-58 and floor/reader.go:88-90).

MarshalObject builds the wire-shaped nested record (LIST as
{"list": [{"element": v}, ...]}, MAP as {"key_value": [{"key": k,
"value": v}, ...]}) that FileWriter shreds directly; UnmarshalObject reads
the same shape from iter_rows(raw=True), accepting Athena's legacy
`bag`/`array_element` spelling on the way in (reference:
floor/reader.go:392-397, unmarshaller.go:193-208).
"""

from __future__ import annotations

import datetime as dt

from .time import Time

__all__ = [
    "FieldNotPresentError",
    "MarshalObject",
    "MarshalElement",
    "MarshalList",
    "MarshalMap",
    "UnmarshalObject",
    "UnmarshalElement",
    "UnmarshalList",
    "UnmarshalMap",
]


class FieldNotPresentError(KeyError):
    """Raised by UnmarshalObject.get_field for absent/null fields."""


_NANOS_PER = {"MILLIS": 1_000_000, "MICROS": 1_000, "NANOS": 1}


# -- write side ----------------------------------------------------------------


class MarshalElement:
    """Setter for one value slot (a field, list element, map key/value)."""

    __slots__ = ("_sink", "_key")

    def __init__(self, sink, key):
        self._sink = sink
        self._key = key

    def _set(self, v):
        self._sink[self._key] = v

    def set_int32(self, v: int):
        self._set(int(v))

    def set_int64(self, v: int):
        self._set(int(v))

    def set_float32(self, v: float):
        self._set(float(v))

    def set_float64(self, v: float):
        self._set(float(v))

    def set_bool(self, v: bool):
        self._set(bool(v))

    def set_byte_array(self, v: bytes):
        self._set(bytes(v))

    def set_string(self, v: str):
        self._set(str(v))

    def set_int96(self, v: bytes):
        if len(v) != 12:
            raise ValueError("INT96 takes exactly 12 bytes")
        self._set(bytes(v))

    def set_time(self, v: "Time | dt.time", unit: str = "NANOS"):
        """TIME value, stored in the column's unit (pass the schema's TIME
        unit: "MILLIS" | "MICROS" | "NANOS"). floor.Time keeps nanosecond
        precision; coarser units truncate."""
        if isinstance(v, Time):
            nanos = v.nanos
        else:
            nanos = (
                ((v.hour * 60 + v.minute) * 60 + v.second) * 1_000_000_000
                + v.microsecond * 1000
            )
        self._set(nanos // _NANOS_PER[unit])

    def group(self) -> "MarshalObject":
        obj = MarshalObject()
        self._set(obj.data)
        return obj

    def list(self) -> "MarshalList":
        lst = MarshalList()
        self._set(lst.data)
        return lst

    def map(self) -> "MarshalMap":
        m = MarshalMap()
        self._set(m.data)
        return m


class MarshalObject:
    """Builder for one record / nested group."""

    __slots__ = ("data",)

    def __init__(self):
        self.data: dict = {}

    def add_field(self, name: str) -> MarshalElement:
        return MarshalElement(self.data, name)


class MarshalList:
    """Builds the canonical 3-level LIST shape."""

    __slots__ = ("data",)

    def __init__(self):
        self.data = {"list": []}

    def add(self) -> MarshalElement:
        slot: dict = {}
        self.data["list"].append(slot)
        return MarshalElement(slot, "element")


class MarshalMap:
    """Builds the canonical MAP key_value shape."""

    __slots__ = ("data",)

    def __init__(self):
        self.data = {"key_value": []}

    def add(self) -> tuple[MarshalElement, MarshalElement]:
        slot: dict = {}
        self.data["key_value"].append(slot)
        return MarshalElement(slot, "key"), MarshalElement(slot, "value")


# -- read side -----------------------------------------------------------------


class UnmarshalElement:
    """Typed accessors over one decoded value slot."""

    __slots__ = ("_v",)

    def __init__(self, v):
        self._v = v

    def _require(self, types, what: str):
        if not isinstance(self._v, types):
            raise TypeError(f"field is {type(self._v).__name__}, not {what}")
        return self._v

    def int32(self) -> int:
        return int(self._require((int,), "int"))

    def int64(self) -> int:
        return int(self._require((int,), "int"))

    def float32(self) -> float:
        return float(self._require((int, float), "float"))

    def float64(self) -> float:
        return float(self._require((int, float), "float"))

    def bool_(self) -> bool:
        return self._require((bool,), "bool")

    def byte_array(self) -> bytes:
        v = self._v
        if isinstance(v, str):
            return v.encode("utf-8")
        return bytes(self._require((bytes, bytearray, memoryview), "bytes"))

    def string(self) -> str:
        v = self._v
        if isinstance(v, bytes):
            return v.decode("utf-8")
        return self._require((str,), "str")

    def time(self, unit: str = "NANOS") -> Time:
        """TIME column value; pass the schema's TIME unit
        ("MILLIS" | "MICROS" | "NANOS") so the stored int scales correctly."""
        return Time.from_nanos(int(self._require((int,), "int")) * _NANOS_PER[unit])

    def group(self) -> "UnmarshalObject":
        return UnmarshalObject(self._require((dict,), "group"))

    def list_(self) -> "UnmarshalList":
        return UnmarshalList(self._require((dict, list), "list"))

    def map_(self) -> "UnmarshalMap":
        return UnmarshalMap(self._require((dict,), "map"))

    def raw(self):
        return self._v


class UnmarshalObject:
    """Field access over one decoded record / nested group."""

    __slots__ = ("_row",)

    def __init__(self, row: dict):
        self._row = row

    def field_names(self):
        return list(self._row)

    def get_field(self, name: str) -> UnmarshalElement:
        v = self._row.get(name)
        if v is None:
            raise FieldNotPresentError(name)
        return UnmarshalElement(v)


class UnmarshalList:
    """Iterates LIST elements; accepts the canonical list/element shape and
    Athena's bag/array_element spelling (reference: floor/reader.go:392-397)."""

    __slots__ = ("_elems", "_key")

    def __init__(self, v):
        if isinstance(v, list):  # 2-level legacy list: elements directly
            self._elems, self._key = v, None
            return
        for wrapper, elem in (("list", "element"), ("bag", "array_element")):
            if wrapper in v:
                self._elems, self._key = v[wrapper], elem
                return
        raise TypeError(f"not a LIST shape: keys {sorted(v)}")

    def __len__(self):
        return len(self._elems)

    def __iter__(self):
        for e in self._elems:
            if self._key is not None and isinstance(e, dict):
                yield UnmarshalElement(e.get(self._key))
            else:
                yield UnmarshalElement(e)


class UnmarshalMap:
    """Iterates MAP entries as (key, value) UnmarshalElement pairs."""

    __slots__ = ("_pairs",)

    def __init__(self, v: dict):
        if "key_value" not in v:
            raise TypeError(f"not a MAP shape: keys {sorted(v)}")
        self._pairs = v["key_value"]

    def __len__(self):
        return len(self._pairs)

    def __iter__(self):
        for p in self._pairs:
            yield UnmarshalElement(p.get("key")), UnmarshalElement(p.get("value"))

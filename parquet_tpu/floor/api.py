"""floor: high-level object read/write (the reference's floor package).

Write dataclass instances (or plain dicts) and read rows back as dataclass
instances, with logical-type conversions handled automatically:

    @dataclass
    class Trip:
        id: int
        vendor: Optional[str]
        ts: datetime.datetime
        tags: list[str]

    with floor.Writer("f.parquet", Trip) as w:   # schema auto-generated
        w.write(Trip(...))

    for trip in floor.Reader("f.parquet", Trip):
        ...

Equivalents: floor.NewFileWriter/Write (reference: floor/writer.go:18-70,
reflection marshalling :72-435), floor.NewFileReader/Next/Scan (reference:
floor/reader.go:17-94, reflection unmarshalling :96-436). Custom conversion
hooks: objects may define to_parquet()/from_parquet(row) (the
Marshaller/Unmarshaller interfaces, reference: floor/interfaces/).

Time handling (reference: floor/writer.go:147-212, floor/time.go):
datetime -> TIMESTAMP(MICROS) int64 (UTC), date -> DATE int32 days since
epoch, time -> TIME(MICROS) int64 micros since midnight.
"""

from __future__ import annotations

import dataclasses
import datetime as dt
import typing
import types as _types

from ..core.reader import FileReader
from ..core.writer import FileWriter
from .autoschema import schema_from_dataclass
from .interfaces import MarshalObject, UnmarshalObject
from .time import Time

__all__ = ["Writer", "Reader"]

_EPOCH_DATE = dt.date(1970, 1, 1)
_EPOCH_DT = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)


def _to_storage(v):
    """Python value -> parquet storage value (recursive)."""
    if v is None:
        return None
    if isinstance(v, Time):  # nanosecond TIME (reference: floor/time.go)
        return v.nanos
    if isinstance(v, dt.datetime):
        if v.tzinfo is None:
            v = v.replace(tzinfo=dt.timezone.utc)
        return int((v - _EPOCH_DT).total_seconds() * 1_000_000)
    if isinstance(v, dt.date):
        return (v - _EPOCH_DATE).days
    if isinstance(v, dt.time):
        return (
            v.hour * 3_600_000_000
            + v.minute * 60_000_000
            + v.second * 1_000_000
            + v.microsecond
        )
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {
            (f.metadata.get("parquet", f.name) if f.metadata else f.name): _to_storage(
                getattr(v, f.name)
            )
            for f in dataclasses.fields(v)
        }
    if isinstance(v, (list, tuple)):
        return [_to_storage(x) for x in v]
    if isinstance(v, dict):
        return {k: _to_storage(x) for k, x in v.items()}
    return v


class Writer:
    """High-level writer: schema from the dataclass, rows from instances.

    `sink` and every keyword pass straight through to FileWriter: a path
    commits ATOMICALLY at close (tmp+rename — an exception mid-write never
    leaves a torn file), any parquet_tpu.sink.ByteSink plugs in directly,
    and `parallel=` engages the pqt-encode row-group pipeline — the
    high-level API gets the fast write path for free:

        with floor.Writer("f.parquet", Trip, parallel=True) as w:
            w.write_all(trips)
    """

    def __init__(self, sink, record_type=None, schema=None, **writer_kw):
        if schema is None:
            if record_type is None:
                raise TypeError("floor.Writer needs record_type or schema")
            schema = schema_from_dataclass(record_type)
        self.record_type = record_type
        self._w = FileWriter(sink, schema, **writer_kw)

    def write(self, obj) -> None:
        if hasattr(obj, "marshal_parquet"):  # Marshaller object model
            mo = MarshalObject()
            obj.marshal_parquet(mo)
            row = mo.data
        elif hasattr(obj, "to_parquet"):  # whole-object hook
            row = obj.to_parquet()
        elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            row = _to_storage(obj)
        elif isinstance(obj, dict):
            row = _to_storage(obj)
        else:
            raise TypeError(
                f"floor: cannot write {type(obj).__name__} "
                "(expected dataclass, dict, or to_parquet())"
            )
        self._w.write_row(row)

    def write_all(self, objs) -> None:
        for o in objs:
            self.write(o)

    def close(self):
        return self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # Delegate so an error ABORTS the underlying sink (temp file
        # deleted, destination untouched) instead of committing.
        return self._w.__exit__(exc_type, exc, tb)


class Reader:
    """High-level reader: rows -> dataclass instances (or dicts)."""

    def __init__(self, source, record_type=None, filters=None, **reader_kw):
        self.record_type = record_type
        self.filters = filters  # (column, op, value) conjunction; stats-pruned
        self._r = FileReader(source, **reader_kw)
        self._hints = (
            typing.get_type_hints(record_type) if record_type is not None else None
        )

    @property
    def schema(self):
        return self._r.schema

    @property
    def num_rows(self):
        return self._r.num_rows

    def __iter__(self):
        rt = self.record_type
        if rt is not None and hasattr(rt, "unmarshal_parquet"):
            # Unmarshaller object model: gets the wire-shaped raw row
            # (reference: floor/reader.go:88-90 + interfaces/unmarshaller.go).
            # Raw rows carry the wire shape, so only row-group PRUNING
            # applies here; exact row filtering needs the ergonomic domain.
            row_groups = (
                self._r.prune_row_groups(self.filters) if self.filters else None
            )
            for row in self._r.iter_rows(raw=True, row_groups=row_groups):
                inst = rt.__new__(rt)
                inst.unmarshal_parquet(UnmarshalObject(row))
                yield inst
            return
        for row in self._r.iter_rows(filters=self.filters):
            yield self._scan(row)

    def _scan(self, row: dict):
        rt = self.record_type
        if rt is None:
            return row
        if hasattr(rt, "from_parquet"):  # whole-object hook
            return rt.from_parquet(row)
        return _build(rt, row)

    def close(self):
        self._r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _build(cls, row: dict):
    """Construct a dataclass instance from a decoded row (recursive)."""
    kwargs = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        col = f.metadata.get("parquet", f.name) if f.metadata else f.name
        kwargs[f.name] = _from_storage(hints[f.name], row.get(col))
    return cls(**kwargs)


def _from_storage(hint, v):
    if v is None:
        return None
    origin = typing.get_origin(hint)
    if origin in (typing.Union, _types.UnionType):
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        return _from_storage(args[0], v) if len(args) == 1 else v
    if origin in (list, typing.List):
        (elem,) = typing.get_args(hint) or (None,)
        return [_from_storage(elem, x) for x in v] if elem else list(v)
    if origin in (dict, typing.Dict):
        kh, vh = typing.get_args(hint) or (None, None)
        return {k: _from_storage(vh, x) if vh else x for k, x in v.items()}
    if dataclasses.is_dataclass(hint):
        return _build(hint, v)
    # The assembler's ergonomic mode may already have produced rich values.
    if hint is dt.datetime:
        if isinstance(v, dt.datetime):
            return v
        return _EPOCH_DT + dt.timedelta(microseconds=int(v))
    if hint is dt.date:
        if isinstance(v, dt.date):
            return v
        return _EPOCH_DATE + dt.timedelta(days=int(v))
    if hint is Time:
        if isinstance(v, Time):
            return v
        if isinstance(v, dt.time):
            return Time.from_time(v)
        return Time.from_nanos(int(v))
    if hint is dt.time:
        if isinstance(v, Time):
            return v.to_time().replace(tzinfo=None)
        if isinstance(v, dt.time):
            return v
        micros = int(v)
        return dt.time(
            hour=micros // 3_600_000_000,
            minute=(micros // 60_000_000) % 60,
            second=(micros // 1_000_000) % 60,
            microsecond=micros % 1_000_000,
        )
    if hint is bytes and isinstance(v, str):
        return v.encode("utf-8")
    if hint is str and isinstance(v, bytes):
        return v.decode("utf-8")
    return v

"""Continuous sampling profiler: live CPU visibility into the pqt-* pools.

The flight recorder answers "what did request X do"; the metrics registry
answers "how much has this process done". Neither answers the operator's
third question — *where is the CPU going right now* — without attaching
an external profiler to a production daemon. This module is the stdlib
answer: a daemon-thread wall-clock sampler over `sys._current_frames()`
that attributes every sample to its POOL LANE (the named pqt-io /
pqt-data / pqt-serve / pqt-encode / pqt-hedge / pqt-dispatch worker
pools, plus "main" and "other"), renders collapsed-stack text any
flamegraph tool loads (flamegraph.pl, speedscope, inferno), and a top-N
self-time table for a terminal.

Contracts:

  * bounded memory: at most `max_stacks` distinct stacks are retained
    (overflow collapses into a per-lane `~overflow~` bucket, counted, so
    totals stay exact) and stacks truncate at `max_depth` frames;
  * bounded overhead: one `sys._current_frames()` walk per interval —
    the walk is O(threads x depth) dict/tuple work with no allocation
    proportional to history; the pin (<5% on the scan headline at the
    default 10 ms interval) is asserted by tests/test_prof.py;
  * frame identity is (file stem, function, first line) — NOT the
    current line — so one hot function is one flamegraph frame instead
    of hundreds of line-level shards;
  * everything is injectable: `frames_fn` (the stack source),
    `threads_fn` (ident -> name), `clock`; `sample_once()` drives the
    sampler synchronously, so tests replay deterministic schedules with
    no thread and no timing;
  * one live capture per process: `capture()` takes a process-wide lock
    (the sampler is global by nature — two concurrent ones would just
    double the overhead and split the story); a busy capture raises
    ProfilerBusy, which the serve daemon maps to a typed 409.

Always-on counters: obs_profile_samples_total{lane=} and
obs_profile_windows_total (documented in utils/metrics.py).

    from parquet_tpu.obs.prof import capture

    prof = capture(seconds=5)           # blocks, samples the process
    print(prof.render_top(15))          # hottest self-time frames
    open("prof.txt", "w").write(prof.collapsed())  # flamegraph input

Served live by `parquet-tool serve` at GET /v1/debug/profile?seconds=N
and fetched by `parquet-tool profile --live <url>`.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..utils import metrics as _metrics

__all__ = [
    "SamplingProfiler",
    "ProfilerBusy",
    "capture",
    "lane_of",
    "POOL_LANES",
]

# the named pool prefixes samples attribute to (thread_name_prefix gives
# workers names like "pqt-serve_3"); FIRST match wins, so more specific
# prefixes are listed before the pools they would otherwise collide with
# (the daemon's accept loop and drain threads must not pollute the
# pqt-serve WORKER lane with idle select() time)
POOL_LANES = (
    "pqt-serve-http",
    "pqt-serve-drain",
    "pqt-io",
    "pqt-data",
    "pqt-serve",
    "pqt-encode",
    "pqt-hedge",
    "pqt-dispatch",
    # PR 18 lane audit: every pqt-* pool spawned since PR 11, so no
    # worker thread folds into "other"
    "pqt-mesh-http",  # the mesh router's accept loop (serve/mesh/router.py)
    "pqt-mesh",  # the router's scatter fan-out pool
    "pqt-host",  # reader prepare pool (core/reader.py)
    "pqt-flush",  # writer background flush pool (sink/encoder.py)
    "pqt-prof",  # the profiler's own sampler thread
    "pqt-httpstub",  # the testing stub's serve thread
    "pqt-flaky-replica",  # the chaos proxy's serve thread (testing/)
    "pqt-compact",  # the lake compactor's background fold loop (lake/compactor.py)
)

_OVERFLOW_FRAME = "~overflow~"


class ProfilerBusy(RuntimeError):
    """Another capture window is already sampling this process."""


def lane_of(thread_name: str) -> str:
    """The pool lane a thread name attributes to: the matching pqt-*
    prefix, "main" for MainThread, else "other" (connection handlers,
    user threads). Code-controlled vocabulary — the metrics label set is
    bounded by construction."""
    name = thread_name or ""
    for lane in POOL_LANES:
        if name.startswith(lane):
            return lane
    if name == "MainThread":
        return "main"
    return "other"


def _frame_id(frame) -> str:
    """Stable frame identity: file stem + function + definition line.
    The CURRENT line would shard one hot function into hundreds of
    flamegraph frames; the definition line disambiguates same-named
    functions in one file."""
    code = frame.f_code
    stem = os.path.splitext(os.path.basename(code.co_filename))[0]
    return f"{stem}:{code.co_name}:{code.co_firstlineno}"


class SamplingProfiler:
    """A bounded wall-clock stack sampler. start()/stop() run the daemon
    thread; sample_once() drives it synchronously (tests, embedders with
    their own scheduler). Read collapsed()/top()/snapshot() after (or
    during — reads are lock-consistent)."""

    def __init__(
        self,
        interval_s: float = 0.010,
        *,
        max_stacks: int = 2048,
        max_depth: int = 48,
        frames_fn=None,
        threads_fn=None,
        clock=time.perf_counter,
        exclude_threads=(),
    ):
        if interval_s <= 0:
            raise ValueError("prof: interval_s must be positive")
        if max_stacks < 1:
            raise ValueError("prof: max_stacks must be >= 1")
        if max_depth < 1:
            raise ValueError("prof: max_depth must be >= 1")
        self.interval_s = float(interval_s)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._frames_fn = frames_fn if frames_fn is not None else sys._current_frames
        self._threads_fn = (
            threads_fn
            if threads_fn is not None
            else lambda: {t.ident: t.name for t in threading.enumerate()}
        )
        self._clock = clock
        # thread idents never sampled by the daemon loop (the capture
        # REQUESTER's own sleep would otherwise dominate the 'other'
        # lane — the same pollution the pqt-serve-http split prevents)
        self._exclude = set(exclude_threads)
        self._lock = threading.Lock()
        self._counts: dict[tuple, int] = {}  # (lane, stack tuple) -> samples
        self._lane_totals: dict[str, int] = {}
        self._samples = 0
        self._truncated = 0  # samples folded into ~overflow~ buckets
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._t_start = None
        self._duration = 0.0

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            raise RuntimeError("prof: profiler already started")
        self._stop.clear()
        self._t_start = self._clock()
        self._thread = threading.Thread(
            target=self._run, name="pqt-prof", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        """Stop sampling (idempotent) and seal the capture duration."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if self._t_start is not None:
            self._duration = self._clock() - self._t_start
            self._t_start = None
            _metrics.inc("obs_profile_windows_total")
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self) -> None:
        skip = self._exclude | {threading.get_ident()}
        while not self._stop.wait(self.interval_s):
            self.sample_once(exclude=skip)

    # -- sampling --------------------------------------------------------------

    def sample_once(self, exclude: set | None = None) -> int:
        """Take one sample of every live thread (minus `exclude` idents
        and the calling thread when driven synchronously). Returns the
        number of thread stacks recorded. The deterministic entry point:
        the daemon loop is just clock + this."""
        frames = self._frames_fn()
        names = self._threads_fn()
        skip = exclude if exclude is not None else {threading.get_ident()}
        recorded = 0
        per_lane: dict[str, int] = {}
        entries = []
        for tid, frame in list(frames.items()):
            if tid in skip:
                continue
            lane = lane_of(names.get(tid, ""))
            stack = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                stack.append(_frame_id(f))
                f = f.f_back
            stack.reverse()  # outermost first: the collapsed-stack order
            entries.append((lane, tuple(stack)))
            per_lane[lane] = per_lane.get(lane, 0) + 1
            recorded += 1
        with self._lock:
            for key in entries:
                if key in self._counts or len(self._counts) < self.max_stacks:
                    self._counts[key] = self._counts.get(key, 0) + 1
                else:
                    # bounded: fold into the lane's overflow bucket (which
                    # may itself claim one of the remaining slots exactly
                    # once per lane) so totals stay exact
                    ok = (key[0], (_OVERFLOW_FRAME,))
                    self._counts[ok] = self._counts.get(ok, 0) + 1
                    self._truncated += 1
            for lane, n in per_lane.items():
                self._lane_totals[lane] = self._lane_totals.get(lane, 0) + n
            self._samples += recorded
        for lane, n in per_lane.items():
            _metrics.inc("obs_profile_samples_total", n, lane=lane)
        return recorded

    # -- reads -----------------------------------------------------------------

    @property
    def duration_s(self) -> float:
        """Sealed capture duration (live value while still sampling)."""
        if self._t_start is not None:
            return self._clock() - self._t_start
        return self._duration

    def snapshot(self) -> dict:
        """The capture as plain JSON-shaped data (the /v1/debug/profile
        format=json body)."""
        with self._lock:
            stacks = [
                {"lane": lane, "stack": list(stack), "count": n}
                for (lane, stack), n in sorted(
                    self._counts.items(), key=lambda kv: -kv[1]
                )
            ]
            return {
                "samples": self._samples,
                "interval_s": self.interval_s,
                "duration_s": round(self.duration_s, 6),
                "lanes": dict(sorted(self._lane_totals.items())),
                "truncated_samples": self._truncated,
                "stacks": stacks,
            }

    def collapsed(self) -> str:
        """Flamegraph-compatible collapsed-stack text: one line per
        distinct stack, `lane;frame;frame;... count`, hottest first. Feed
        straight to flamegraph.pl / speedscope / inferno."""
        with self._lock:
            items = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return "".join(
            ";".join((lane, *stack)) + f" {n}\n" for (lane, stack), n in items
        )

    def top(self, n: int = 20) -> list[dict]:
        """Hottest frames by SELF time (samples where the frame was
        innermost), with the lane split: [{"frame", "self", "pct",
        "lanes": {lane: samples}}], descending."""
        agg: dict[str, dict] = {}
        with self._lock:
            total = self._samples
            for (lane, stack), count in self._counts.items():
                leaf = stack[-1] if stack else "?"
                a = agg.setdefault(leaf, {"self": 0, "lanes": {}})
                a["self"] += count
                a["lanes"][lane] = a["lanes"].get(lane, 0) + count
        out = [
            {
                "frame": frame,
                "self": a["self"],
                "pct": round(100.0 * a["self"] / total, 1) if total else 0.0,
                "lanes": dict(sorted(a["lanes"].items())),
            }
            for frame, a in agg.items()
        ]
        out.sort(key=lambda d: (-d["self"], d["frame"]))
        return out[:n]

    def render_top(self, n: int = 20) -> str:
        """The top() table as terminal text, with a lane-share header."""
        with self._lock:  # reads are lock-consistent, mid-capture too
            snap_lanes = dict(sorted(self._lane_totals.items()))
            samples = self._samples
        total = samples or 1
        lines = [
            f"profile: {samples} samples over "
            f"{self.duration_s:.2f}s at {self.interval_s * 1e3:.0f} ms"
        ]
        if snap_lanes:
            lines.append(
                "lanes:   "
                + "  ".join(
                    f"{lane}={cnt} ({100.0 * cnt / total:.0f}%)"
                    for lane, cnt in snap_lanes.items()
                )
            )
        lines.append(f"{'SELF':>6} {'PCT':>6}  FRAME (LANES)")
        for row in self.top(n):
            lanes = ",".join(
                f"{k}:{v}" for k, v in row["lanes"].items()
            )
            lines.append(
                f"{row['self']:>6} {row['pct']:>5.1f}%  {row['frame']} ({lanes})"
            )
        return "\n".join(lines) + "\n"


# one live capture window per process: sampling is process-global, so two
# would double overhead and split the evidence; the serve endpoint maps a
# busy lock to a typed 409
_capture_lock = threading.Lock()


def capture(
    seconds: float,
    interval_s: float = 0.010,
    *,
    sleep=time.sleep,
    **kwargs,
) -> SamplingProfiler:
    """Run one bounded capture window (blocking the calling thread —
    the sampler itself is on its own daemon thread) and return the
    stopped profiler. The CALLER's thread is excluded from sampling: it
    spends the window asleep right here, and ~window/interval samples of
    this sleep would otherwise dominate the 'other' lane. Raises
    ProfilerBusy when a window is already running in this process."""
    if seconds <= 0:
        raise ValueError("prof: seconds must be positive")
    if not _capture_lock.acquire(blocking=False):
        raise ProfilerBusy(
            "a profile capture window is already running in this process"
        )
    try:
        kwargs.setdefault("exclude_threads", {threading.get_ident()})
        prof = SamplingProfiler(interval_s, **kwargs)
        prof.start()
        try:
            sleep(seconds)
        finally:
            prof.stop()
        return prof
    finally:
        _capture_lock.release()

"""parquet_tpu.obs — the operator-facing observability layer.

PR 3 built the substrate (per-read span tracing, the always-on metrics
registry); this package turns it into something an operator of a
long-running daemon can actually use at 14:02 when request X was slow:

  recorder  request-correlated flight recorder: a bounded ring of
            per-request records (id, tenant, status, plan summary, bytes,
            queue-wait, stage rollup, sampled span trees), process-wide —
            the serve daemon, ParquetDataset units and EncodePipeline
            groups all record into the same ring. Served at
            /v1/debug/requests by `parquet-tool serve`, queried by
            `parquet-tool debug`.
  log       structured JSON-lines logging (stdlib logging underneath):
            request-id/tenant context injection, token-bucket rate
            limiting per event key, silent until configure_logging().
  pool      the one instrumented submit all four pqt-* pools route
            through: queue-depth/active gauges + queue-wait/task-time
            histograms per pool.

See each module's docstring for the contracts and bounds.
"""

from .log import (  # noqa: F401
    JsonLinesFormatter,
    TokenBucketLimiter,
    configure_logging,
    log_context,
    log_event,
)
from .pool import instrumented_submit, pool_depths  # noqa: F401
from .recorder import (  # noqa: F401
    RECORDER,
    FlightRecorder,
    ObsConfig,
    RequestRecord,
    configure,
    recorder,
    sanitize_request_id,
)

__all__ = [
    "ObsConfig",
    "FlightRecorder",
    "RequestRecord",
    "RECORDER",
    "recorder",
    "configure",
    "sanitize_request_id",
    "log_event",
    "log_context",
    "configure_logging",
    "JsonLinesFormatter",
    "TokenBucketLimiter",
    "instrumented_submit",
    "pool_depths",
]

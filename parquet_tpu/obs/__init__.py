"""parquet_tpu.obs — the operator-facing observability layer.

PR 3 built the substrate (per-read span tracing, the always-on metrics
registry); this package turns it into something an operator of a
long-running daemon can actually use at 14:02 when request X was slow:

  recorder  request-correlated flight recorder: a bounded ring of
            per-request records (id, tenant, status, plan summary, bytes,
            queue-wait, stage rollup, sampled span trees), process-wide —
            the serve daemon, ParquetDataset units and EncodePipeline
            groups all record into the same ring. Served at
            /v1/debug/requests by `parquet-tool serve`, queried by
            `parquet-tool debug`.
  log       structured JSON-lines logging (stdlib logging underneath):
            request-id/tenant context injection, token-bucket rate
            limiting per event key, silent until configure_logging().
  pool      the one instrumented submit all four pqt-* pools route
            through: queue-depth/active gauges + queue-wait/task-time
            histograms per pool.
  prof      continuous sampling profiler over sys._current_frames():
            bounded, lane-attributed (the named pqt-* pools), rendered
            as flamegraph-compatible collapsed stacks and a top-N
            self-time table. Served live at /v1/debug/profile, fetched
            by `parquet-tool profile --live`.
  cost      per-tenant cost accounting: CPU seconds (thread-time deltas
            around executor units), decoded/source bytes and cache
            outcomes (from the request trace), charged to the
            admission-resolved tenant. Served at /v1/debug/tenants.
  propagate cross-process trace propagation: a W3C-traceparent-shaped
            context minted/adopted per request scope, injected into every
            outbound HTTP call, and merge_chrome_traces() — the
            `parquet-tool trace-merge` engine that stitches per-process
            Perfetto documents on the shared trace-id.
  fleet     metrics federation: scrape N replicas' /metrics and merge
            families exactly (counters sum, histogram buckets add, gauges
            keep a replica= label). Served at /v1/debug/fleet and
            `parquet-tool debug --fleet`.
  slo       multi-window burn-rate health engine over the daemon's own
            request outcomes; verdict at /v1/debug/slo, folded into
            /healthz as "degraded".

See each module's docstring for the contracts and bounds.
"""

from .cost import (  # noqa: F401
    LEDGER,
    CostLedger,
    charged_tenant,
    cost_context,
    unit_clock,
)
from .fleet import (  # noqa: F401
    federate,
    merge_expositions,
    parse_exposition,
    scrape_fleet,
)
from .log import (  # noqa: F401
    JsonLinesFormatter,
    TokenBucketLimiter,
    configure_logging,
    log_context,
    log_event,
)
from .pool import instrumented_submit, pool_depths  # noqa: F401
from .prof import (  # noqa: F401
    ProfilerBusy,
    SamplingProfiler,
    capture,
    lane_of,
)
from .propagate import (  # noqa: F401
    TraceContext,
    current_context,
    merge_chrome_traces,
    mint,
    outbound_traceparent,
    parse_traceparent,
    propagation_scope,
    resolve_inbound,
)
from .recorder import (  # noqa: F401
    RECORDER,
    FlightRecorder,
    ObsConfig,
    RequestRecord,
    configure,
    recorder,
    sanitize_request_id,
)
from .slo import (  # noqa: F401
    BurnRateEngine,
    SLOObjective,
)

__all__ = [
    "ObsConfig",
    "FlightRecorder",
    "RequestRecord",
    "RECORDER",
    "recorder",
    "configure",
    "sanitize_request_id",
    "log_event",
    "log_context",
    "configure_logging",
    "JsonLinesFormatter",
    "TokenBucketLimiter",
    "instrumented_submit",
    "pool_depths",
    "SamplingProfiler",
    "ProfilerBusy",
    "capture",
    "lane_of",
    "CostLedger",
    "LEDGER",
    "cost_context",
    "charged_tenant",
    "unit_clock",
    "TraceContext",
    "mint",
    "parse_traceparent",
    "current_context",
    "propagation_scope",
    "outbound_traceparent",
    "resolve_inbound",
    "merge_chrome_traces",
    "federate",
    "merge_expositions",
    "parse_exposition",
    "scrape_fleet",
    "BurnRateEngine",
    "SLOObjective",
]

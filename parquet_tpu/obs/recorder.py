"""The flight recorder: bounded per-request evidence for "why was request
X slow at 14:02?".

The PR-3 tracer answers that question only if someone wrapped the read in
`decode_trace()` BEFORE it ran; the registry answers it only in aggregate.
This module retains the recent past: a lock-cheap bounded ring of
RequestRecords — id, tenant, endpoint, status, plan/pruning summary, bytes
streamed, queue-wait, per-stage timing rollup, and (for sampled, slow or
errored requests) the full span tree as a Perfetto-loadable Chrome-trace
document. The serve daemon exposes the ring at /v1/debug/requests; the
library paths (ParquetDataset units, EncodePipeline groups) record into
the SAME ring, so one listing interleaves serving and pipeline activity.

Bounds, because every input here is potentially client-controlled:

  * the ring holds at most `ObsConfig.ring_size` records — old ones
    evict (obs_ring_evictions_total), and the id index evicts WITH them;
    library one-shots (`record()`: dataset units, encode groups) live in
    a SIBLING deque under the same bound, so a busy pipeline churning
    hundreds of units/s can never evict the serve-request evidence an
    operator comes back for — one merged listing still interleaves both;
  * request ids sanitize exactly like tenant keys (charset + 64-char
    truncation) — a hostile X-Request-Id can neither grow the ring past
    its bound nor smuggle bytes into the debug JSON;
  * span trees are the expensive part, so at most `max_traces` records
    keep one (oldest dropped first, the summary record stays); a trace is
    kept when the accumulator-sampler fires (`trace_sample_rate`), and
    ALWAYS for requests that errored or exceeded `slow_ms` — the requests
    an operator actually asks about;
  * error messages truncate; everything else in a record is code-shaped
    (summary dicts, stage names) and small by construction.

The sampler is a deterministic accumulator (acc += rate; fire on
overflow), not a PRNG: rate 1.0 samples everything, 0.25 exactly every
4th, and tests replay schedules without seeding anything.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass

from ..utils import metrics as _metrics

__all__ = [
    "ObsConfig",
    "RequestRecord",
    "FlightRecorder",
    "RECORDER",
    "recorder",
    "configure",
    "sanitize_request_id",
]

_ID_OK = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._:-"
)
_MAX_ID = 64
_MAX_ERROR = 300


def sanitize_request_id(raw) -> str | None:
    """The bounded, charset-safe form of a client-supplied X-Request-Id
    (the same discipline as tenant keys): strip, truncate to 64, replace
    anything outside [A-Za-z0-9._:-] with '_'. None/empty -> None (the
    recorder generates one)."""
    if raw is None:
        return None
    rid = str(raw).strip()[:_MAX_ID]
    if not rid:
        return None
    return "".join(c if c in _ID_OK else "_" for c in rid)


@dataclass
class ObsConfig:
    """The observability knobs one daemon (or embedder) runs under."""

    ring_size: int = 512  # request records retained
    trace_sample_rate: float = 0.01  # share of OK-and-fast requests whose
    #                                  span tree is kept (error/slow: always)
    slow_ms: float = 1000.0  # at/over this wall time a request is "slow"
    max_traces: int = 16  # span trees retained (each can be ~MBs)

    def __post_init__(self):
        if self.ring_size < 1:
            raise ValueError("obs: ring_size must be >= 1")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError("obs: trace_sample_rate must be in [0, 1]")
        if self.slow_ms <= 0:
            raise ValueError("obs: slow_ms must be positive")
        if self.max_traces < 0:
            raise ValueError("obs: max_traces must be >= 0")


class RequestRecord:
    """One request's (or pipeline unit's) retained evidence."""

    __slots__ = (
        "id",
        "seq",
        "endpoint",
        "tenant",
        "status",
        "start",
        "duration_ms",
        "bytes",
        "queue_wait_ms",
        "plan",
        "stages",
        "detail",
        "error",
        "trace_kind",
        "trace_id",
        "open",
        "_trace",
        "_t0",
    )

    def __init__(self, rid: str, seq: int, endpoint: str, tenant: str):
        self.id = rid
        self.seq = seq
        self.endpoint = endpoint
        self.tenant = tenant
        self.status = None
        self.start = time.time()
        self.duration_ms = None
        self.bytes = 0
        self.queue_wait_ms = 0.0
        self.plan = None  # the /v1/plan-shaped pruning/dry-run summary
        self.stages = None  # {stage: {seconds, bytes, calls}} rollup
        self.detail = None  # small code-shaped extras (file, group, rows)
        self.error = None
        self.trace_kind = None  # None | "sampled" | "slow" | "error" — why
        #   the span tree was KEPT; persists after max_traces evicts the
        #   tree itself (has_trace False + trace_kind set = evicted)
        self.trace_id = None  # cross-process propagation key (32 hex),
        #   set by the server from the resolved traceparent

        self.open = True
        self._trace = None  # the Chrome-trace doc, when retained
        self._t0 = time.perf_counter()

    def to_summary(self) -> dict:
        return {
            "id": self.id,
            "endpoint": self.endpoint,
            "tenant": self.tenant,
            "status": self.status,
            "start": self.start,
            "duration_ms": self.duration_ms,
            "bytes": self.bytes,
            "queue_wait_ms": self.queue_wait_ms,
            "has_trace": self._trace is not None,
            "trace_kind": self.trace_kind,
            "trace_id": self.trace_id,
            "open": self.open,
        }

    def to_dict(self) -> dict:
        out = self.to_summary()
        out["plan"] = self.plan
        out["stages"] = self.stages
        out["detail"] = self.detail
        out["error"] = self.error
        return out


class FlightRecorder:
    """The bounded ring + id index. Every mutation is O(1) under one lock
    held for dict/deque work only — no serialization, no IO."""

    def __init__(self, config: ObsConfig | None = None):
        self._config = config if config is not None else ObsConfig()
        self._lock = threading.Lock()
        self._ring: deque[RequestRecord] = deque()  # serve requests
        self._lib: deque[RequestRecord] = deque()  # library one-shots
        self._index: dict[str, RequestRecord] = {}
        self._traced: deque[RequestRecord] = deque()
        self._seq = 0
        self._sample_acc = 0.0

    @property
    def config(self) -> ObsConfig:
        return self._config

    def configure(self, config: ObsConfig) -> "FlightRecorder":
        """Apply new knobs (the ring trims immediately if shrunk)."""
        with self._lock:
            self._config = config
            self._trim_locked()
        return self

    # -- record lifecycle ------------------------------------------------------

    def begin(
        self, endpoint: str, tenant: str, request_id=None, *, library=False
    ) -> RequestRecord:
        """Open a record (listed immediately, flagged open until finish —
        an operator can see in-flight requests). `request_id` is the
        client-supplied value, already-or-not sanitized; None generates.
        `library` records (one-shots from record()) ring-buffer separately
        so pipeline churn cannot evict request evidence."""
        rid = sanitize_request_id(request_id) or uuid.uuid4().hex[:16]
        with self._lock:
            self._seq += 1
            rec = RequestRecord(rid, self._seq, endpoint, str(tenant)[:_MAX_ID])
            (self._lib if library else self._ring).append(rec)
            self._index[rid] = rec  # duplicate id: newest wins the lookup
            self._trim_locked()
        _metrics.inc("obs_requests_recorded_total", endpoint=endpoint)
        return rec

    def finish(
        self,
        rec: RequestRecord,
        status,
        *,
        nbytes: int = 0,
        error=None,
        trace=None,
        duration_s: float | None = None,
    ) -> RequestRecord:
        """Close a record: status, bytes, the trace's stage rollup and
        queue-wait, and — when sampled/slow/errored — the span tree."""
        cfg = self._config
        if duration_s is None:
            duration_s = time.perf_counter() - rec._t0
        rec.duration_ms = round(duration_s * 1e3, 3)
        rec.status = status
        rec.bytes = int(nbytes)
        if error is not None:
            rec.error = str(error)[:_MAX_ERROR]
        if trace is not None:
            rollup = trace.stage_rollup()
            rec.stages = rollup
            wait = rollup.get("pool.wait")
            if wait:
                rec.queue_wait_ms = round(wait["seconds"] * 1e3, 3)
            kind = None
            if error is not None or _is_error_status(status):
                kind = "error"
            elif rec.duration_ms >= cfg.slow_ms:
                kind = "slow"
            elif self._sample():
                kind = "sampled"
            if kind is not None and cfg.max_traces > 0:
                doc = trace.to_chrome_trace()
                req_meta = {
                    "id": rec.id,
                    "endpoint": rec.endpoint,
                    "tenant": rec.tenant,
                }
                if rec.trace_id is not None:
                    req_meta["trace_id"] = rec.trace_id
                doc.setdefault("otherData", {})["request"] = req_meta
                with self._lock:
                    rec._trace = doc
                    rec.trace_kind = kind
                    self._traced.append(rec)
                    while len(self._traced) > cfg.max_traces:
                        old = self._traced.popleft()
                        if old is not rec:
                            old._trace = None
                _metrics.inc("obs_traces_retained_total")
        rec.open = False
        return rec

    def record(
        self,
        endpoint: str,
        *,
        status="ok",
        duration_s: float = 0.0,
        nbytes: int = 0,
        detail: dict | None = None,
        error=None,
        tenant: str = "-",
    ) -> RequestRecord:
        """One-shot library record (a dataset unit, an encoded row group):
        begin+finish with an auto id, no trace, in the sibling ring."""
        rec = self.begin(endpoint, tenant, library=True)
        rec.detail = detail
        return self.finish(
            rec, status, nbytes=nbytes, error=error, duration_s=duration_s
        )

    # -- read side -------------------------------------------------------------

    def get(self, request_id) -> RequestRecord | None:
        rid = sanitize_request_id(request_id)
        if rid is None:
            return None
        with self._lock:
            return self._index.get(rid)

    def list(
        self,
        *,
        limit: int = 100,
        slow_only: bool = False,
        endpoint: str | None = None,
    ) -> list[dict]:
        """Newest-first record summaries, optionally filtered to slow
        requests (>= slow_ms) and/or one endpoint."""
        cfg = self._config
        with self._lock:
            # one interleaved listing across both rings, by open order
            records = sorted(
                [*self._ring, *self._lib], key=lambda r: r.seq
            )
        out = []
        for rec in reversed(records):
            if endpoint is not None and rec.endpoint != endpoint:
                continue
            if slow_only and not (
                rec.duration_ms is not None and rec.duration_ms >= cfg.slow_ms
            ):
                continue
            out.append(rec.to_summary())
            if len(out) >= limit:
                break
        return out

    def stats(self) -> dict:
        """Ring occupancy (the bounds tests hammer against)."""
        with self._lock:
            return {
                "records": len(self._ring) + len(self._lib),
                "requests": len(self._ring),
                "library": len(self._lib),
                "indexed": len(self._index),
                "traces": sum(1 for r in self._traced if r._trace is not None),
            }

    # -- internals -------------------------------------------------------------

    def _sample(self) -> bool:
        rate = self._config.trace_sample_rate
        if rate <= 0.0:
            return False
        with self._lock:
            self._sample_acc += rate
            if self._sample_acc >= 1.0 - 1e-12:
                self._sample_acc -= 1.0
                return True
        return False

    def _trim_locked(self) -> None:
        evicted = 0
        for ring in (self._ring, self._lib):
            while len(ring) > self._config.ring_size:
                old = ring.popleft()
                evicted += 1
                if self._index.get(old.id) is old:
                    del self._index[old.id]
                old._trace = None  # the traced deque skips cleared entries
        while len(self._traced) > max(self._config.max_traces, 0):
            self._traced.popleft()._trace = None
        if evicted:
            _metrics.inc("obs_ring_evictions_total", evicted)
        _metrics.set_gauge(
            "obs_ring_records", len(self._ring) + len(self._lib)
        )


def _is_error_status(status) -> bool:
    if isinstance(status, int):
        return status >= 400
    return status == "error"


# The process-wide ring: the serve daemon configures it from its
# ServeConfig; the dataset and encode pipelines record into it as-is.
RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return RECORDER


def configure(config: ObsConfig) -> FlightRecorder:
    """Point the process-wide recorder at `config` (what ScanService does
    at construction) and return it."""
    return RECORDER.configure(config)

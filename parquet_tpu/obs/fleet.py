"""Fleet metrics federation: N replica expositions merged into one view.

The ROADMAP's multi-host serve puts N daemons behind a router, and the
first operational question is "what is the FLEET doing" — total request
rate, total bytes, the latency distribution across every replica — not N
browser tabs of per-process `/metrics`. This module is the scatter-gather
seed: scrape each replica's exposition (concurrently, on the pqt-io pool,
with the request's traceparent injected like any other outbound call),
parse the classic Prometheus text format, and merge families EXACTLY:

  counters     arithmetic sum per identical label set — the merged line is
               byte-for-byte the sum of the replica lines (integer counters
               stay integers; the render is the registry's own `f"{v}"`);
  histograms   bucket counts, `_sum` and `_count` add per label set —
               cumulative buckets stay cumulative, quantile math done on
               the merged distribution is done on the true fleet data;
  gauges       NOT summed (a sum of uptimes is meaningless): each replica
               keeps its sample, tagged with a `replica="host:port"` label
               so one exposition carries every replica's value.

Merging is strict where it must be (a family typed counter on one replica
and gauge on another is a deploy skew bug — ValueError, not a guess) and
forgiving where it can be (a replica that fails to scrape is reported in
`errors` and excluded; the merge covers the replicas that answered).

Served two ways, same engine: `parquet-tool debug --fleet URL...` for the
operator's terminal, and `GET /v1/debug/fleet?peers=host:port,...` on any
daemon — meaning any replica can present the fleet view, which is exactly
the shape the future router inherits.

Families: fleet_scrapes_total{outcome=}, fleet_replicas (last merge).
"""

from __future__ import annotations

import re
import urllib.parse
import urllib.request
from dataclasses import dataclass

from ..utils import metrics as _metrics
from . import propagate as _propagate

__all__ = [
    "ReplicaScrape",
    "normalize_peer",
    "parse_exposition",
    "merge_expositions",
    "scrape_fleet",
    "federate",
]


def normalize_peer(peer: str) -> str:
    """A fleet peer spec as a scrape URL: bare `host:port` gains http://
    and a path-less URL gains /metrics — so `127.0.0.1:8081` and a full
    URL both work, on the server's `?peers=` and the CLI's `--fleet`."""
    url = peer if "://" in peer else f"http://{peer}"
    if urllib.parse.urlsplit(url).path in ("", "/"):
        url = url.rstrip("/") + "/metrics"
    return url

# one sample line: name, optional {labels} block (label values are quoted
# strings with backslash escapes — the only place '}' or ' ' may legally
# appear), the value, and an optional OpenMetrics exemplar we discard
_SAMPLE_RE = re.compile(
    r"\A([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r"\s+(\S+)"
    r"(?:\s+#\s.*)?\Z"
)
_LABEL_RE = re.compile(r"([a-zA-Z_][a-zA-Z0-9_]*)=\"((?:[^\"\\]|\\.)*)\"")


def _num(s: str):
    """int when the text is an int — so summed integer counters render
    back as integers, byte-for-byte with a native registry render."""
    try:
        return int(s)
    except ValueError:
        return float(s)


@dataclass
class _Family:
    name: str
    kind: str
    help: str | None
    # insertion-ordered: (sample_name, ((label, raw_value), ...)) -> number
    samples: dict


@dataclass
class ReplicaScrape:
    """One replica's scrape outcome: exactly one of text/error is set."""

    replica: str
    url: str
    text: str | None
    error: str | None


def parse_exposition(text: str) -> dict:
    """Parse one classic (or OpenMetrics) text exposition into an ordered
    {family_name: _Family} dict. Samples are grouped under the most recent
    `# TYPE` header, which is how both of the registry's renderers emit
    them; a sample with no preceding header gets an `untyped` family of
    its own name."""
    families: dict = {}
    current: _Family | None = None
    for line in text.splitlines():
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                name, kind = parts[2], parts[3] if len(parts) > 3 else "untyped"
                current = families.get(name)
                if current is None:
                    current = _Family(name, kind, None, {})
                    families[name] = current
                elif current.kind == "untyped":
                    # a # HELP line preceded its # TYPE (the classic
                    # render order) — adopt the type now it's declared
                    current.kind = kind
                elif current.kind != kind:
                    raise ValueError(
                        f"fleet: family {name} re-typed {current.kind} -> "
                        f"{kind} within one exposition"
                    )
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam = families.get(parts[2])
                doc = parts[3] if len(parts) > 3 else ""
                if fam is not None and fam.help is None:
                    fam.help = doc
                elif fam is None:
                    current = _Family(parts[2], "untyped", doc, {})
                    families[parts[2]] = current
            # any other comment (# EOF, exemplarish noise): skipped
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"fleet: unparseable sample line: {line!r}")
        sname, labels_raw, value = m.group(1), m.group(2), m.group(3)
        labels = tuple(sorted(_LABEL_RE.findall(labels_raw or "")))
        fam = current
        # classic format guarantees samples follow their header; guard the
        # case where they don't (or the header named a different family —
        # OpenMetrics counters drop _total in TYPE but not in samples)
        if fam is None or not sname.startswith(fam.name):
            fam = families.get(sname)
            if fam is None:
                fam = _Family(sname, "untyped", None, {})
                families[sname] = fam
        fam.samples[(sname, labels)] = _num(value)
    return families


def _render_sample(sname: str, labels: tuple, value) -> str:
    if labels:
        inner = ",".join(f'{k}="{v}"' for k, v in labels)
        return f"{sname}{{{inner}}} {value}"
    return f"{sname} {value}"


def merge_expositions(texts, replicas) -> str:
    """Merge per-replica exposition texts into one classic exposition.

    `replicas` labels each text (same order) — it becomes the `replica=`
    label on gauge samples. Counter and histogram samples with identical
    label sets sum exactly; family order and within-family sample order
    follow first appearance across the inputs, so two merges of the same
    fleet render identically."""
    texts = list(texts)
    replicas = list(replicas)
    if len(texts) != len(replicas):
        raise ValueError("fleet: one replica label per exposition required")
    docs = [parse_exposition(t) for t in texts]

    order: list = []
    kinds: dict = {}
    helps: dict = {}
    for doc in docs:
        for name, fam in doc.items():
            if name not in kinds:
                order.append(name)
                kinds[name] = fam.kind
                helps[name] = fam.help
            else:
                if fam.kind != kinds[name] and "untyped" not in (
                    fam.kind,
                    kinds[name],
                ):
                    raise ValueError(
                        f"fleet: family {name} is {kinds[name]} on one "
                        f"replica and {fam.kind} on another — refusing to "
                        "merge mismatched types (deploy skew?)"
                    )
                if helps[name] is None:
                    helps[name] = fam.help

    lines: list = []
    for name in order:
        if helps[name]:
            lines.append(f"# HELP {name} {helps[name]}")
        lines.append(f"# TYPE {name} {kinds[name]}")
        if kinds[name] == "gauge":
            # per-replica samples, replica label folded into sorted order
            seen_keys: list = []
            for doc in docs:
                fam = doc.get(name)
                if fam is None:
                    continue
                for key in fam.samples:
                    if key not in seen_keys:
                        seen_keys.append(key)
            for sname, labels in seen_keys:
                for replica, doc in zip(replicas, docs):
                    fam = doc.get(name)
                    if fam is None or (sname, labels) not in fam.samples:
                        continue
                    tagged = tuple(
                        sorted(
                            labels
                            + (
                                (
                                    "replica",
                                    _metrics._escape_label_value(replica),
                                ),
                            )
                        )
                    )
                    lines.append(
                        _render_sample(
                            sname, tagged, fam.samples[(sname, labels)]
                        )
                    )
        else:
            sums: dict = {}
            for doc in docs:
                fam = doc.get(name)
                if fam is None:
                    continue
                for key, value in fam.samples.items():
                    sums[key] = sums.get(key, 0) + value
            for (sname, labels), value in sums.items():
                lines.append(_render_sample(sname, labels, value))
    return "\n".join(lines) + "\n"


def _replica_labels(urls) -> list:
    """host:port per url, uniquified (two urls on one netloc get #i)."""
    labels: list = []
    seen: set = set()
    for i, url in enumerate(urls):
        label = urllib.parse.urlsplit(url).netloc or url
        if label in seen:
            label = f"{label}#{i}"
        seen.add(label)
        labels.append(label)
    return labels


def _default_fetch(url: str, timeout_s: float) -> str:
    req = urllib.request.Request(url)
    tp = _propagate.outbound_traceparent("get")
    if tp is not None:
        req.add_header("traceparent", tp)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", "replace")


def scrape_fleet(urls, *, timeout_s: float = 5.0, fetch=None) -> list:
    """Scrape every url concurrently on pqt-io. Never raises per-replica:
    each failure becomes a ReplicaScrape with `error` set (and an
    outcome="error" tick), so a down replica degrades the fleet view
    instead of destroying it."""
    urls = list(urls)
    fetch = fetch if fetch is not None else _default_fetch
    labels = _replica_labels(urls)
    # lazy imports: obs is imported BY the io layer, so the reverse edge
    # must not exist at module-load time
    from ..io.planner import io_pool
    from .pool import instrumented_submit

    futures = [
        instrumented_submit(io_pool(), fetch, url, timeout_s, pool="pqt-io")
        for url in urls
    ]
    out: list = []
    for label, url, fut in zip(labels, urls, futures):
        try:
            text = fut.result(timeout=timeout_s + 10.0)
            out.append(ReplicaScrape(label, url, text, None))
            _metrics.inc("fleet_scrapes_total", outcome="ok")
        except Exception as exc:  # noqa: BLE001 — per-replica containment
            out.append(
                ReplicaScrape(label, url, None, f"{type(exc).__name__}: {exc}")
            )
            _metrics.inc("fleet_scrapes_total", outcome="error")
    return out


def federate(urls, *, timeout_s: float = 5.0, fetch=None) -> dict:
    """Scrape + merge: the full fleet view. Returns {"text": merged
    exposition, "replicas": [labels merged], "errors": {label: why}}.
    Raises ValueError when no urls are given or NO replica answered (the
    server endpoint maps that to a typed 502)."""
    urls = list(urls)
    if not urls:
        raise ValueError("fleet: at least one peer url required")
    scrapes = scrape_fleet(urls, timeout_s=timeout_s, fetch=fetch)
    ok = [s for s in scrapes if s.text is not None]
    errors = {s.replica: s.error for s in scrapes if s.error is not None}
    _metrics.set_gauge("fleet_replicas", len(ok))
    if not ok:
        raise ValueError(
            "fleet: no replica answered: "
            + "; ".join(f"{r}: {e}" for r, e in errors.items())
        )
    merged = merge_expositions(
        [s.text for s in ok], [s.replica for s in ok]
    )
    return {
        "text": merged,
        "replicas": [s.replica for s in ok],
        "errors": errors,
    }

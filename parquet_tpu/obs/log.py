"""Structured JSON-lines logging with request context and rate limiting.

The package had ZERO logging until this module: noteworthy events (a
quarantined unit, a retry storm, an admission rejection, a SIGTERM drain)
either bumped a counter — visible only to someone already scraping
/metrics — or vanished. This is the operator-facing event stream that
complements the counters: one JSON object per line on a stdlib `logging`
logger, so it composes with any handler/shipper an embedder already runs.

Design rules, in order of importance:

  * the LIBRARY never prints: the "parquet_tpu" logger starts with a
    NullHandler and propagate=False, so importing parquet_tpu emits
    nothing anywhere until someone calls `configure_logging()` (the
    `parquet-tool serve` daemon does; embedders attach their own handler);
  * every event is rate-limited per event key through a token bucket
    (default: burst 20, refill 5/s) BEFORE formatting, so a hot failure
    loop (a flaky source retrying thousands of times a second) costs a
    counter bump, not a disk full of identical lines — the next admitted
    line carries `"suppressed": N` so the gap is visible, and
    `log_suppressed_total{event=}` counts what the limiter absorbed;
  * request context injects automatically: the serve daemon wraps each
    request in `log_context(request_id=, tenant=)` and every event logged
    anywhere below (executor, reader, source retry ladder) carries the
    ids — the grep key that joins the log stream to /v1/debug/requests;
  * emission is counted (`log_events_total{event=}`) whether or not a
    handler is attached, so tests pin wiring without configuring output.

    from parquet_tpu.obs.log import configure_logging, log_event

    configure_logging()                      # JSON lines on stderr
    log_event("unit_quarantined", level="warning", file=path, group=3)
    # {"ts":"2026-08-03T14:02:11.042Z","level":"warning",
    #  "event":"unit_quarantined","request_id":"r01","file":...,"group":3}
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from datetime import datetime, timezone

from ..utils import metrics as _metrics

__all__ = [
    "log_event",
    "log_context",
    "configure_logging",
    "JsonLinesFormatter",
    "TokenBucketLimiter",
    "request_id",
    "tenant",
    "trace_id",
]

LOGGER_NAME = "parquet_tpu"

_request_id_var: ContextVar = ContextVar("pqt_log_request_id", default=None)
_tenant_var: ContextVar = ContextVar("pqt_log_tenant", default=None)
_trace_id_var: ContextVar = ContextVar("pqt_log_trace_id", default=None)

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_logger = logging.getLogger(LOGGER_NAME)
# silent-by-default library discipline: no output (and no propagation into
# the embedder's root handlers) until configure_logging() opts in
_logger.addHandler(logging.NullHandler())
_logger.propagate = False


def request_id() -> str | None:
    """The request id bound to this context (None outside a request)."""
    return _request_id_var.get()


def tenant() -> str | None:
    """The tenant key bound to this context (None outside a request)."""
    return _tenant_var.get()


def trace_id() -> str | None:
    """The propagation trace-id bound to this context (None outside a
    propagated request) — the cross-PROCESS join key, where request_id
    joins within one daemon."""
    return _trace_id_var.get()


@contextmanager
def log_context(
    request_id: str | None = None,
    tenant: str | None = None,
    trace_id: str | None = None,
):
    """Bind request_id/tenant/trace_id for every log_event in the enclosed
    block — including pool workers the block submits through
    instrumented_submit (contextvars carry, exactly like the decode
    trace)."""
    tok_r = _request_id_var.set(request_id)
    tok_t = _tenant_var.set(tenant)
    tok_tr = _trace_id_var.set(trace_id)
    try:
        yield
    finally:
        _request_id_var.reset(tok_r)
        _tenant_var.reset(tok_t)
        _trace_id_var.reset(tok_tr)


class TokenBucketLimiter:
    """Per-key token bucket: `burst` immediate events per key, refilling at
    `rate` per second. admit() returns (admitted, suppressed_since_last) so
    the first line after a suppression window can say how much it hides.
    Keys are CODE-controlled event names — the table is bounded by the
    vocabulary of call sites, never by input."""

    def __init__(self, rate: float = 5.0, burst: int = 20, clock=time.monotonic):
        if rate <= 0 or burst < 1:
            raise ValueError("log limiter: rate must be > 0 and burst >= 1")
        self.rate = float(rate)
        self.burst = int(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._state: dict[str, list] = {}  # key -> [tokens, last, suppressed]

    def admit(self, key: str) -> tuple[bool, int]:
        now = self._clock()
        with self._lock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = [float(self.burst), now, 0]
            st[0] = min(float(self.burst), st[0] + (now - st[1]) * self.rate)
            st[1] = now
            if st[0] >= 1.0:
                st[0] -= 1.0
                suppressed, st[2] = st[2], 0
                return True, suppressed
            st[2] += 1
            return False, st[2]


_limiter = TokenBucketLimiter()
_limiter_lock = threading.Lock()


def set_limiter(limiter: TokenBucketLimiter) -> TokenBucketLimiter:
    """Swap the process-wide rate limiter (tests inject a pinned clock);
    returns the previous one so tests can restore it."""
    global _limiter
    with _limiter_lock:
        prev, _limiter = _limiter, limiter
    return prev


class JsonLinesFormatter(logging.Formatter):
    """One JSON object per line: ts / level / event / request context /
    event fields. Values that don't serialize render via str() — a log
    line must never raise."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": datetime.fromtimestamp(record.created, timezone.utc)
            .isoformat(timespec="milliseconds")
            .replace("+00:00", "Z"),
            "level": record.levelname.lower(),
            "event": getattr(record, "pqt_event", record.getMessage()),
        }
        rid = getattr(record, "pqt_request_id", None)
        if rid is not None:
            doc["request_id"] = rid
        ten = getattr(record, "pqt_tenant", None)
        if ten is not None:
            doc["tenant"] = ten
        tid = getattr(record, "pqt_trace_id", None)
        if tid is not None:
            doc["trace_id"] = tid
        fields = getattr(record, "pqt_fields", None)
        if fields:
            for k, v in fields.items():
                doc.setdefault(k, v)  # reserved keys (ts/level/event) win
        return json.dumps(doc, default=str)


def configure_logging(stream=None, level=logging.INFO) -> logging.Handler:
    """Attach the JSON-lines handler (stderr by default) and open the
    logger at `level`. Replaces a previously configured obs handler, so
    calling it twice (two ScanServers in one process) doesn't double every
    line. Returns the handler (tests hand a StringIO and detach after)."""
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(JsonLinesFormatter())
    handler._pqt_obs_handler = True  # the replace-don't-stack marker
    for h in list(_logger.handlers):
        if getattr(h, "_pqt_obs_handler", False):
            _logger.removeHandler(h)
    _logger.addHandler(handler)
    _logger.setLevel(level)
    return handler


def log_event(event: str, *, level: str = "info", **fields) -> bool:
    """Emit one structured event (rate-limited per event key). Returns
    True when the line was admitted, False when the limiter absorbed it.
    Either way the always-on registry counts it (log_events_total /
    log_suppressed_total), so wiring is testable with no handler."""
    admitted, suppressed = _limiter.admit(event)
    if not admitted:
        _metrics.inc("log_suppressed_total", event=event)
        return False
    _metrics.inc("log_events_total", event=event)
    if suppressed:
        fields = {**fields, "suppressed": suppressed}
    _logger.log(
        _LEVELS.get(level, logging.INFO),
        event,
        extra={
            "pqt_event": event,
            "pqt_fields": fields,
            "pqt_request_id": _request_id_var.get(),
            "pqt_tenant": _tenant_var.get(),
            "pqt_trace_id": _trace_id_var.get(),
        },
    )
    return True

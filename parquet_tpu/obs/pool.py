"""One instrumented submit shared by every pqt-* worker pool.

The process runs four dedicated pools — pqt-io (readahead), pqt-data
(dataset unit decode), pqt-serve (scan execution), pqt-encode (parallel
row-group encode) — and until now none of them exported the two numbers
every capacity question starts with: how deep is the queue, and how long
does work wait in it. This wrapper is the ONE choke point they all submit
through, feeding:

  pool_queue_depth{pool=}         gauge: tasks submitted, not yet running
  pool_active_workers{pool=}      gauge: tasks currently running
  pool_queue_wait_seconds{pool=}  histogram: submit -> first instruction
  pool_task_seconds{pool=}        histogram: task wall time

— the direct inputs the ROADMAP's elastic-SLO controller needs (scale a
pool when queue_wait grows, shrink when depth stays 0). The `pool` label
set is code-controlled (the four pqt-* names + test pools), so it is
bounded by construction.

instrumented_submit() subsumes trace.traced_submit(): it carries the
caller's contextvars (active decode_trace, log_context request ids) into
the worker AND credits the measured queue wait to the trace as a
`pool.wait` stage — which is how a request record's queue-wait rollup is
exact, not sampled. Cancelled futures (executor drain, error teardown)
release their queue-depth contribution through a done-callback.
"""

from __future__ import annotations

import threading
import time
from contextvars import copy_context

from ..utils import metrics as _metrics
from ..utils import trace as _trace

__all__ = ["instrumented_submit", "pool_depths"]

_lock = threading.Lock()
_queued: dict[str, int] = {}
_active: dict[str, int] = {}


def _adjust(pool: str, dq: int = 0, da: int = 0) -> None:
    with _lock:
        if dq:
            _queued[pool] = _queued.get(pool, 0) + dq
            _metrics.set_gauge("pool_queue_depth", _queued[pool], pool=pool)
        if da:
            _active[pool] = _active.get(pool, 0) + da
            _metrics.set_gauge("pool_active_workers", _active[pool], pool=pool)


def pool_depths() -> dict:
    """{pool: {"queued": n, "active": n}} right now (tests/diagnostics)."""
    with _lock:
        names = set(_queued) | set(_active)
        return {
            n: {"queued": _queued.get(n, 0), "active": _active.get(n, 0)}
            for n in names
        }


def _run(pool: str, ctx, t_submit: float, fn, args):
    wait = time.perf_counter() - t_submit
    _adjust(pool, dq=-1, da=+1)
    _metrics.observe("pool_queue_wait_seconds", wait, pool=pool)
    t0 = time.perf_counter()
    try:
        return ctx.run(_credit_wait_and_call, wait, fn, args)
    finally:
        _adjust(pool, da=-1)
        _metrics.observe(
            "pool_task_seconds", time.perf_counter() - t0, pool=pool
        )


def _credit_wait_and_call(wait: float, fn, args):
    # inside the carried context: the submitting request's DecodeTrace (if
    # any) aggregates this task's queue wait under the pool.wait stage —
    # the flight recorder reads it back as the record's queue_wait_ms
    _trace.add_seconds("pool.wait", wait)
    return fn(*args)


def instrumented_submit(executor, fn, *args, pool: str | None = None, ctx=None):
    """Submit `fn(*args)` to `executor` with contextvars carry (the
    traced_submit contract) plus queue/active gauges and wait/task-time
    histograms under the `pool` label (defaults to the executor's thread
    name prefix). The drop-in replacement for traced_submit at every
    pqt-* pool call site. Callers fanning ONE logical group out as N tasks
    pass a shared `ctx` template (snapshotted once per group): each task
    still receives a private copy — Context.run refuses re-entry on a
    shared object, and group tasks overlap — but the per-task cost drops
    to Context.copy instead of a fresh per-submit thread-state snapshot."""
    name = pool or getattr(executor, "_thread_name_prefix", "") or "pool"
    ctx = ctx.copy() if ctx is not None else copy_context()
    _adjust(name, dq=+1)
    t_submit = time.perf_counter()
    try:
        fut = executor.submit(_run, name, ctx, t_submit, fn, args)
    except BaseException:
        _adjust(name, dq=-1)  # shutdown race: the task never queued
        raise

    def _on_done(f):
        if f.cancelled():  # cancel-before-start: _run never decremented
            _adjust(name, dq=-1)

    fut.add_done_callback(_on_done)
    return fut

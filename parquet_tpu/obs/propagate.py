"""Cross-process trace propagation: the W3C-traceparent-shaped header.

Every telemetry primitive so far stops at the process boundary: a
DecodeTrace's span tree covers one daemon, the flight recorder one ring,
and a request that fans out to a remote object store (or, soon, across a
router to N sharded daemons) fractures into disconnected traces with no
shared key. This module is the Dapper-style joint: one context —
trace-id / span-id / flags, wire-shaped exactly like a W3C `traceparent`
header (`00-<32 hex>-<16 hex>-<2 hex>`) — minted (or adopted) per request
scope and injected into EVERY outbound HTTP call the request makes:

  * `HttpSource` / `ObjectStoreSource` range GETs (io/remote.py),
    including the serve daemon's `--remote-map` fetches, which resolve to
    the same transport;
  * `HttpSink` multipart initiate / part PUTs / complete (io/remote_sink);

so a store-side access log (or the loopback httpstub, which records the
headers it receives) lines up with the daemon's flight record on one
trace-id, and `parquet-tool trace-merge` can stitch the per-process
Perfetto documents into one timeline.

Discipline, in order of importance:

  * propagation is CONTEXT, not globals: the binding rides a contextvar,
    so instrumented_submit carries it across pqt-* pool hops exactly like
    the decode trace and log context — and library reads outside any
    request scope propagate NOTHING (no header, no counter bump);
  * inbound values are hostile until proven hex: `resolve_inbound()`
    sanitizes like X-Request-Id — a malformed, all-zero or oversized
    header is counted (`io_traceparent_inbound_total{result="invalid"}`)
    and REPLACED by a freshly minted context, never echoed back raw;
  * every outbound call gets its OWN child span-id under the bound
    trace-id (a retry storm is distinguishable per attempt in the store's
    log), and every injection counts
    `io_traceparent_injected_total{transport="get"|"put"}`.

`merge_chrome_traces()` is the offline half: given N Chrome-trace
documents whose `otherData.propagation.trace_id` agree, it re-lanes each
document onto its own pid (with a process_name metadata event) and emits
ONE Perfetto-loadable document — the daemon's spans and the remote
client's spans side by side under the shared trace-id.
"""

from __future__ import annotations

import os
import re
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass

from ..utils import metrics as _metrics

__all__ = [
    "TraceContext",
    "mint",
    "parse_traceparent",
    "format_traceparent",
    "current_context",
    "propagation_scope",
    "outbound_traceparent",
    "resolve_inbound",
    "merge_chrome_traces",
]

_VERSION = "00"
# strict wire shape: version-traceid-spanid-flags, lowercase hex only.
# Version "ff" is forbidden by the spec; all-zero ids are "absent".
_TRACEPARENT_RE = re.compile(
    r"\A([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})\Z"
)
_MAX_HEADER = 128  # sanitization bound, like request ids: hostile input
#                    is length-capped before the regex ever runs


@dataclass(frozen=True)
class TraceContext:
    """One propagation binding: the request's trace-id plus the span-id
    of the CURRENT hop (the daemon's own span when inbound, a per-call
    child span when outbound)."""

    trace_id: str  # 32 lowercase hex, never all-zero
    span_id: str  # 16 lowercase hex, never all-zero
    flags: str = "01"  # 01 = sampled (we always record; flags pass through)

    def header(self) -> str:
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{self.flags}"

    def child(self) -> "TraceContext":
        """A fresh span under the same trace — one per outbound call, so
        each attempt/part/range is individually addressable."""
        return TraceContext(self.trace_id, _rand_hex(8), self.flags)


def _rand_hex(nbytes: int) -> str:
    """`nbytes` of os.urandom as lowercase hex, re-drawn on the (2^-64 at
    worst) all-zero value the spec reserves for "absent"."""
    while True:
        h = os.urandom(nbytes).hex()
        if int(h, 16):
            return h


def mint(flags: str = "01") -> TraceContext:
    """A brand-new context: fresh trace-id, fresh span-id."""
    return TraceContext(_rand_hex(16), _rand_hex(8), flags)


def parse_traceparent(raw) -> TraceContext | None:
    """Strict parse of one traceparent header value: None unless it is
    exactly version-traceid-spanid-flags lowercase hex with non-zero ids
    and a known-parseable version (ff is reserved)."""
    if raw is None:
        return None
    s = str(raw).strip()
    if len(s) > _MAX_HEADER:
        return None
    m = _TRACEPARENT_RE.match(s)
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return TraceContext(trace_id, span_id, flags)


def format_traceparent(ctx: TraceContext) -> str:
    return ctx.header()


_ctx_var: ContextVar = ContextVar("pqt_trace_context", default=None)


def current_context() -> TraceContext | None:
    """The propagation context bound in this execution context, or None
    (library use outside any request scope)."""
    return _ctx_var.get()


@contextmanager
def propagation_scope(ctx: TraceContext | None):
    """Bind `ctx` for the enclosed block — including pool work submitted
    through instrumented_submit (contextvars carry, exactly like the
    decode trace and the log context). None binds nothing-propagates."""
    token = _ctx_var.set(ctx)
    try:
        yield ctx
    finally:
        _ctx_var.reset(token)


def outbound_traceparent(transport: str | None = None) -> str | None:
    """The header value for ONE outbound HTTP call: a fresh child span-id
    under the bound trace, or None when no scope is bound (reads outside
    a request propagate nothing). `transport` ("get"/"put") counts the
    injection; None skips the counter (the caller counts)."""
    ctx = _ctx_var.get()
    if ctx is None:
        return None
    if transport is not None:
        _metrics.inc("io_traceparent_injected_total", transport=transport)
    return ctx.child().header()


def resolve_inbound(raw) -> tuple[TraceContext, str]:
    """Resolve a client-supplied traceparent header into the context this
    request runs under — the X-Request-Id discipline applied to trace
    context. Returns (context, result):

      accepted   valid header: ADOPT the trace-id, mint a fresh span-id
                 for this hop (never reuse the caller's span-id as ours)
      minted     no header: a brand-new context
      invalid    malformed/all-zero/oversized: counted, replaced by mint

    Every outcome counts io_traceparent_inbound_total{result=}."""
    if raw is None:
        ctx, result = mint(), "minted"
    else:
        parsed = parse_traceparent(raw)
        if parsed is None:
            ctx, result = mint(), "invalid"
        else:
            ctx = TraceContext(parsed.trace_id, _rand_hex(8), parsed.flags)
            result = "accepted"
    _metrics.inc("io_traceparent_inbound_total", result=result)
    return ctx, result


# -- offline stitching ---------------------------------------------------------


def merge_chrome_traces(docs, labels=None) -> dict:
    """Stitch N Chrome-trace documents into one on their shared trace-id.

    Each input keeps its events verbatim but moves to its OWN pid lane
    (input order), with a process_name metadata event naming the lane
    (`labels[i]`, else the document's recorded request endpoint, else
    "process-<i>"). Documents that carry `otherData.propagation.trace_id`
    must all agree — mixing trace-ids is a caller error (you are merging
    two unrelated requests), raised as ValueError. Timebases are NOT
    aligned: each process's ts values are relative to its own trace
    start, which is what per-process lanes in Perfetto present anyway.
    """
    docs = list(docs)
    if not docs:
        raise ValueError("trace-merge: no input documents")
    trace_ids = []
    for i, doc in enumerate(docs):
        if not isinstance(doc, dict) or "traceEvents" not in doc:
            raise ValueError(
                f"trace-merge: input {i} is not a Chrome-trace document "
                "(no traceEvents)"
            )
        tid = (doc.get("otherData") or {}).get("propagation", {}).get(
            "trace_id"
        )
        if tid is not None:
            trace_ids.append(tid)
    if len(set(trace_ids)) > 1:
        raise ValueError(
            "trace-merge: inputs span "
            f"{len(set(trace_ids))} distinct trace ids "
            f"({sorted(set(trace_ids))}) — merge stitches ONE request's "
            "processes, not unrelated traces"
        )
    merged_events = []
    sources = []
    for i, doc in enumerate(docs):
        other = doc.get("otherData") or {}
        label = None
        if labels is not None and i < len(labels):
            label = labels[i]
        if label is None:
            label = (other.get("request") or {}).get("endpoint")
        if label is None:
            label = f"process-{i}"
        merged_events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": i,
                "tid": 0,
                "ts": 0,
                "dur": 0,
                "args": {"name": str(label)},
            }
        )
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = i
            merged_events.append(ev)
        sources.append(
            {
                "label": str(label),
                "events": len(doc["traceEvents"]),
                "request": other.get("request"),
            }
        )
    out = {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "otherData": {"merged_from": sources},
    }
    if trace_ids:
        out["otherData"]["propagation"] = {"trace_id": trace_ids[0]}
    return out

"""Per-tenant cost accounting: who is spending the machine.

Admission (serve/admission.py) bounds what a tenant MAY do — concurrency
and a scanned-byte budget. Nothing so far records what each tenant DID:
by the time the byte-budget 429s fire, the operator still cannot name the
tenant that heated the daemon. This module is the ledger between those
two moments, fed through the request's existing scope:

  * CPU seconds — `time.thread_time()` deltas bracketing each executor
    unit (one row group decoded on a pqt-serve worker). Thread time is
    exact per-thread CPU, so concurrent tenants on one pool never bleed
    into each other's bill;
  * decoded bytes, source-read bytes, cache hits/misses — read from the
    request-scoped DecodeTrace's stage rollup when the request finishes
    (the same rollup the flight recorder stores), charged once per
    request;
  * payload bytes and request counts — from the serve handler's finish
    path.

The charge key travels on a contextvar (`cost_context(tenant)`) exactly
like the log context and the decode trace: instrumented_submit carries it
onto pool workers, so a unit task bills the tenant whose request
submitted it with no threading of arguments. The tenant value is the
ADMISSION-RESOLVED key (sanitized, truncated, overflow-collapsed), and
the ledger itself enforces the same bound (`max_tenants`, shared
`__overflow__` bucket) so an embedder bypassing admission still cannot
grow it.

Two always-on metric families ride every charge (documented in
utils/metrics.py): serve_tenant_cpu_seconds_total{tenant=} and
serve_tenant_decoded_bytes_total{tenant=}. The full usage table is served
at GET /v1/debug/tenants and by `parquet-tool debug <url> --tenants`.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar

from ..utils import metrics as _metrics

__all__ = [
    "CostLedger",
    "LEDGER",
    "ledger",
    "cost_context",
    "charged_tenant",
    "unit_clock",
    "charge_request_from_trace",
]

OVERFLOW_TENANT = "__overflow__"  # the admission layer's shared bucket

_tenant_var: ContextVar = ContextVar("pqt_cost_tenant", default=None)


def charged_tenant() -> str | None:
    """The tenant this context's work bills to (None outside a request)."""
    return _tenant_var.get()


@contextmanager
def cost_context(tenant: str | None):
    """Bind the charge key for the enclosed block — including pool work
    it submits through instrumented_submit (contextvars carry)."""
    token = _tenant_var.set(tenant)
    try:
        yield
    finally:
        _tenant_var.reset(token)


class _Usage:
    __slots__ = (
        "cpu_seconds",
        "decoded_bytes",
        "source_bytes",
        "payload_bytes",
        "cache_hits",
        "cache_misses",
        "requests",
        "units",
    )

    def __init__(self):
        self.cpu_seconds = 0.0
        self.decoded_bytes = 0
        self.source_bytes = 0
        self.payload_bytes = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.requests = 0
        self.units = 0

    def to_dict(self) -> dict:
        return {
            "cpu_seconds": round(self.cpu_seconds, 6),
            "decoded_bytes": self.decoded_bytes,
            "source_bytes": self.source_bytes,
            "payload_bytes": self.payload_bytes,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "requests": self.requests,
            "units": self.units,
        }


class CostLedger:
    """Bounded per-tenant usage accumulators (thread-safe, O(1) charges).

    Keys saturate exactly like the admission tenant table: past
    `max_tenants` distinct names everything new collapses into the shared
    overflow bucket, so a hostile header flood cannot grow the ledger or
    the serve_tenant_* label sets."""

    def __init__(self, max_tenants: int = 1024, registry=None):
        if max_tenants <= 0:
            raise ValueError("cost: max_tenants must be positive")
        self.max_tenants = int(max_tenants)
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._lock = threading.Lock()
        self._tenants: dict[str, _Usage] = {}

    def _usage(self, tenant) -> tuple[str, _Usage]:
        # caller holds self._lock
        key = str(tenant if tenant is not None else "default")[:64] or "default"
        u = self._tenants.get(key)
        if u is None:
            if len(self._tenants) >= self.max_tenants:
                key = OVERFLOW_TENANT
                u = self._tenants.get(key)
                if u is not None:
                    return key, u
            u = self._tenants[key] = _Usage()
        return key, u

    # -- charges ---------------------------------------------------------------

    def charge_cpu(self, tenant, seconds: float, units: int = 1) -> None:
        """Bill `seconds` of executor CPU (one unit's thread-time delta)."""
        if seconds < 0:
            seconds = 0.0
        with self._lock:
            key, u = self._usage(tenant)
            u.cpu_seconds += seconds
            u.units += units
        self._registry.inc(
            "serve_tenant_cpu_seconds_total", seconds, tenant=key
        )

    def charge_request(
        self,
        tenant,
        *,
        decoded_bytes: int = 0,
        source_bytes: int = 0,
        payload_bytes: int = 0,
        cache_hits: int = 0,
        cache_misses: int = 0,
    ) -> None:
        """Bill one finished request's byte/cache usage (from its trace
        rollup — see charge_request_from_trace)."""
        with self._lock:
            key, u = self._usage(tenant)
            u.decoded_bytes += int(decoded_bytes)
            u.source_bytes += int(source_bytes)
            u.payload_bytes += int(payload_bytes)
            u.cache_hits += int(cache_hits)
            u.cache_misses += int(cache_misses)
            u.requests += 1
        if decoded_bytes:
            self._registry.inc(
                "serve_tenant_decoded_bytes_total",
                int(decoded_bytes),
                tenant=key,
            )

    # -- reads -----------------------------------------------------------------

    def table(self) -> list[dict]:
        """The /v1/debug/tenants body: per-tenant usage rows, hottest CPU
        first."""
        with self._lock:
            rows = [
                {"tenant": k, **u.to_dict()} for k, u in self._tenants.items()
            ]
        rows.sort(key=lambda r: (-r["cpu_seconds"], r["tenant"]))
        return rows

    def totals(self) -> dict:
        """Usage summed across every tenant (the reconciliation side of
        the tests: per-tenant charges must sum to process totals)."""
        total = _Usage()
        with self._lock:
            for u in self._tenants.values():
                total.cpu_seconds += u.cpu_seconds
                total.decoded_bytes += u.decoded_bytes
                total.source_bytes += u.source_bytes
                total.payload_bytes += u.payload_bytes
                total.cache_hits += u.cache_hits
                total.cache_misses += u.cache_misses
                total.requests += u.requests
                total.units += u.units
        return total.to_dict()

    def reset(self) -> None:
        """Drop every accumulator (tests only)."""
        with self._lock:
            self._tenants.clear()


# the process-wide ledger the serve daemon charges (embedders may build
# their own and pass it where a ledger is accepted)
LEDGER = CostLedger()


def ledger() -> CostLedger:
    return LEDGER


@contextmanager
def unit_clock(ledger: CostLedger | None = None):
    """Bill the enclosed block's CPU (thread-time delta — exact for this
    thread, blind to neighbors) to the context's tenant. The executor
    wraps each row-group unit in one of these; outside a cost_context it
    measures and discards, costing two clock reads."""
    t0 = time.thread_time()
    try:
        yield
    finally:
        dt = time.thread_time() - t0
        tenant = _tenant_var.get()
        if tenant is not None:
            (ledger if ledger is not None else LEDGER).charge_cpu(tenant, dt)


def charge_request_from_trace(
    tenant, trace, nbytes: int = 0, ledger: CostLedger | None = None
) -> None:
    """Charge one finished request's byte/cache usage out of its
    request-scoped DecodeTrace: decoded bytes from the `decode.bytes`
    account (credited at the decompress_block choke point and by the
    fused native walk — the per-trace mirror of bytes_uncompressed_total,
    so tenant totals reconcile exactly with the process counter),
    source-read bytes from `io.read` (the planner's batched source
    fetches; the window-replay `io` stage would double-bill the same
    bytes on top), and the cache hit/miss split from the io_cache_hit/
    io_cache_miss counts BlockCache records into the active trace."""
    if tenant is None or trace is None:
        return
    rollup = trace.stage_rollup()

    def _get(stage, field):
        s = rollup.get(stage)
        return s[field] if s else 0

    decoded = _get("decode.bytes", "bytes")
    source = _get("io.read", "bytes") or _get("io", "bytes")
    (ledger if ledger is not None else LEDGER).charge_request(
        tenant,
        decoded_bytes=decoded,
        source_bytes=source,
        payload_bytes=int(nbytes),
        cache_hits=_get("io_cache_hit", "calls"),
        cache_misses=_get("io_cache_miss", "calls"),
    )

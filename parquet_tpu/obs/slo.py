"""SLO burn-rate health engine: a typed, windowed verdict for routers.

`/healthz` so far answers "is the process up and not draining" — a useful
liveness bit, but the ROADMAP's multi-host serve needs the ROUTING
question: "is this replica healthy ENOUGH", where "enough" is an error
budget being consumed at a survivable rate, not a human eyeballing
`/metrics`. This module is the Google-SRE multi-window burn-rate model
over the serve daemon's own request outcomes:

  SLIs        availability — the share of finished requests that did NOT
              fail server-side (5xx; client errors and client-gone 499s
              spend nobody's budget), against `SLOObjective.availability`
              (default 99.9%). Optionally latency — the share of requests
              at/under `p99_ms`, against an implied 99% target (a "p99
              objective" IS "at most 1% of requests slower than the bar").

  burn rate   error_fraction / error_budget per window: burn 1.0 spends
              the budget exactly at sustainable speed, 14.4 empties a
              30-day budget in 50 hours — the classic page threshold.

  windows     fast 5m + slow 1h, BOTH required to fire: the fast window
              alone flaps on a single bad minute, the slow window alone
              pages an hour late. Implemented as a bounded ring of
              10-second buckets (requests / bad / slow / latency
              histogram), so memory is fixed and a fake clock replays any
              schedule deterministically.

  verdict     "burning"  fast AND slow burn >= `page_burn` (either SLI)
              "warn"     fast burn >= `warn_burn` on either SLI
              "ok"       otherwise

The daemon feeds `record()` from the same `_finish` path that observes
serve_request_seconds, evaluates on demand (`GET /v1/debug/slo` returns
the full window math), and folds the verdict into `/healthz` as a
`degraded` status — still HTTP 200, deliberately distinct from
`draining`'s 503: a degraded replica can still serve (a router may
deprioritize it), a draining one must not be routed to at all.

Gauges (refreshed at every evaluate()): slo_burn_rate{sli=,window=},
slo_error_budget_remaining{sli=} (windowed, slow window), and
slo_verdict (0 ok / 1 warn / 2 burning).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..utils import metrics as _metrics

__all__ = ["SLOObjective", "BurnRateEngine", "VERDICT_LEVELS"]

VERDICT_LEVELS = {"ok": 0, "warn": 1, "burning": 2}

# the reported-p99 estimate buckets (seconds): serve_request_seconds'
# bounds, reused so the debug body and the exposition agree on shape
_LAT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

# the latency SLI's implied objective: "p99 <= bar" == "at most 1% of
# requests over the bar" — a 1% bad-event budget
_LATENCY_BUDGET = 0.01


@dataclass(frozen=True)
class SLOObjective:
    """What this replica promises. availability in (0, 1); p99_ms None
    disables the latency SLI entirely."""

    availability: float = 0.999
    p99_ms: float | None = None
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    page_burn: float = 14.4  # both windows at/over this -> burning
    warn_burn: float = 1.0  # fast window at/over this -> warn

    def __post_init__(self):
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                "slo: availability must be in (0, 1), got "
                f"{self.availability!r}"
            )
        if self.p99_ms is not None and self.p99_ms <= 0:
            raise ValueError("slo: p99_ms must be positive (None disables)")
        if not 0 < self.fast_window_s <= self.slow_window_s:
            raise ValueError(
                "slo: need 0 < fast_window_s <= slow_window_s"
            )
        if self.page_burn < self.warn_burn or self.warn_burn <= 0:
            raise ValueError("slo: need 0 < warn_burn <= page_burn")


class _Bucket:
    """One 10-second aggregate: counts only, fixed size."""

    __slots__ = ("start", "requests", "bad", "slow", "lat_counts")

    def __init__(self, start: float):
        self.start = start
        self.requests = 0
        self.bad = 0  # 5xx server failures (availability burn)
        self.slow = 0  # over the p99 bar (latency burn)
        self.lat_counts = [0] * (len(_LAT_BUCKETS) + 1)  # +Inf tail


class BurnRateEngine:
    """Bounded-memory multi-window burn-rate evaluation (see module
    docstring). `clock` is injectable (time.monotonic by default) so
    tests replay fault schedules without sleeping; `bucket_s` trades
    window-edge resolution for ring length."""

    def __init__(
        self,
        objective: SLOObjective | None = None,
        *,
        clock=time.monotonic,
        bucket_s: float = 10.0,
    ):
        if bucket_s <= 0:
            raise ValueError("slo: bucket_s must be positive")
        self.objective = objective if objective is not None else SLOObjective()
        self._clock = clock
        self.bucket_s = float(bucket_s)
        self._lock = threading.Lock()
        self._buckets: list[_Bucket] = []
        # ring length: the slow window plus one bucket of slack
        self._max_buckets = int(self.objective.slow_window_s / bucket_s) + 1

    # -- ingest ----------------------------------------------------------------

    def record(self, status, seconds: float) -> None:
        """One finished request: its HTTP status (int, or "error"/"ok"
        strings from library records) and wall seconds. Client errors
        (4xx) and client-gone (499) spend no budget — the replica did its
        job; 5xx and "error" burn availability."""
        bad = (
            status == "error"
            if not isinstance(status, int)
            else status >= 500
        )
        p99_ms = self.objective.p99_ms
        slow = p99_ms is not None and seconds * 1e3 > p99_ms
        now = self._clock()
        with self._lock:
            b = self._bucket_locked(now)
            b.requests += 1
            if bad:
                b.bad += 1
            if slow:
                b.slow += 1
            slot = len(_LAT_BUCKETS)
            for i, le in enumerate(_LAT_BUCKETS):
                if seconds <= le:
                    slot = i
                    break
            b.lat_counts[slot] += 1

    def _bucket_locked(self, now: float) -> _Bucket:
        start = now - (now % self.bucket_s)
        if not self._buckets or self._buckets[-1].start < start:
            self._buckets.append(_Bucket(start))
            if len(self._buckets) > self._max_buckets:
                del self._buckets[: len(self._buckets) - self._max_buckets]
        return self._buckets[-1]

    # -- evaluate --------------------------------------------------------------

    def _window_locked(self, now: float, window_s: float) -> dict:
        cutoff = now - window_s
        requests = bad = slow = 0
        lat = [0] * (len(_LAT_BUCKETS) + 1)
        for b in self._buckets:
            # a bucket is IN the window when any part of it is: edge
            # buckets count whole — the 10 s quantization the ring buys
            if b.start + self.bucket_s <= cutoff:
                continue
            requests += b.requests
            bad += b.bad
            slow += b.slow
            for i, c in enumerate(b.lat_counts):
                lat[i] += c
        return {"requests": requests, "bad": bad, "slow": slow, "lat": lat}

    @staticmethod
    def _p99_estimate_ms(lat: list, requests: int) -> float | None:
        """Upper-bound p99 from the coarse latency histogram: the first
        bound whose cumulative count covers 99% (None with no data; the
        +Inf tail reports as the top finite bound — "over the scale")."""
        if not requests:
            return None
        want = 0.99 * requests
        acc = 0
        for i, c in enumerate(lat):
            acc += c
            if acc >= want:
                if i < len(_LAT_BUCKETS):
                    return _LAT_BUCKETS[i] * 1e3
                return _LAT_BUCKETS[-1] * 1e3
        return _LAT_BUCKETS[-1] * 1e3

    def evaluate(self) -> dict:
        """The full verdict + window math (the /v1/debug/slo body).
        Refreshes the slo_* gauges as a side effect, so any scrape after
        an evaluate sees the current burn rates."""
        obj = self.objective
        now = self._clock()
        with self._lock:
            fast = self._window_locked(now, obj.fast_window_s)
            slow = self._window_locked(now, obj.slow_window_s)

        def burn(win: dict, kind: str) -> float:
            if not win["requests"]:
                return 0.0
            if kind == "availability":
                frac = win["bad"] / win["requests"]
                budget = 1.0 - obj.availability
            else:
                frac = win["slow"] / win["requests"]
                budget = _LATENCY_BUDGET
            return frac / budget

        slis = {"availability": (burn(fast, "availability"),
                                 burn(slow, "availability"))}
        if obj.p99_ms is not None:
            slis["latency"] = (burn(fast, "latency"), burn(slow, "latency"))

        verdict = "ok"
        for fast_burn, slow_burn in slis.values():
            if fast_burn >= obj.page_burn and slow_burn >= obj.page_burn:
                verdict = "burning"
                break
            if fast_burn >= obj.warn_burn:
                verdict = "warn"

        windows = {}
        for label, win in (("5m", fast), ("1h", slow)):
            entry = {
                "seconds": (
                    obj.fast_window_s if label == "5m" else obj.slow_window_s
                ),
                "requests": win["requests"],
                "errors": win["bad"],
                "error_rate": (
                    round(win["bad"] / win["requests"], 6)
                    if win["requests"]
                    else 0.0
                ),
                "p99_ms_estimate": self._p99_estimate_ms(
                    win["lat"], win["requests"]
                ),
            }
            if obj.p99_ms is not None:
                entry["slow_requests"] = win["slow"]
            windows[label] = entry

        body = {
            "verdict": verdict,
            "objective": {
                "availability": obj.availability,
                "p99_ms": obj.p99_ms,
                "page_burn": obj.page_burn,
                "warn_burn": obj.warn_burn,
            },
            "windows": windows,
            "burn_rates": {
                sli: {"5m": round(f, 4), "1h": round(s, 4)}
                for sli, (f, s) in slis.items()
            },
        }

        # gauge mirror: burn per (sli, window), windowed budget remaining
        # (slow window — the budget a router would reason about), verdict
        for sli, (f, s) in slis.items():
            _metrics.set_gauge("slo_burn_rate", round(f, 4), sli=sli,
                               window="5m")
            _metrics.set_gauge("slo_burn_rate", round(s, 4), sli=sli,
                               window="1h")
            budget = (
                1.0 - obj.availability
                if sli == "availability"
                else _LATENCY_BUDGET
            )
            win = slow
            used = (
                (win["bad"] if sli == "availability" else win["slow"])
                / win["requests"]
                if win["requests"]
                else 0.0
            )
            _metrics.set_gauge(
                "slo_error_budget_remaining",
                round(max(0.0, 1.0 - used / budget), 4),
                sli=sli,
            )
        _metrics.set_gauge("slo_verdict", VERDICT_LEVELS[verdict])
        return body

// Native host-side helpers for parquet_tpu.
//
// The TPU absorbs the bulk value decode (kernels/), but three host-side scalar
// walks remain on the critical path and are too branchy for NumPy:
//   1. snappy block (de)compression   (the reference links a Go snappy lib;
//      this implements the public snappy block format from its spec)
//   2. PLAIN byte_array offset scan   (data-dependent 4-byte length chain,
//      reference: type_bytearray.go:24-45)
//   3. hybrid RLE/bit-pack run-header prescan
//      (reference: hybrid_decoder.go:142-165; feeds the device run table)
//
// Exposed as a plain C ABI consumed via ctypes (utils/native.py). All
// functions validate sizes before writing and return -1 on corrupt input.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstddef>
#include <ctime>        // per-stage prepare clocks (chunk_prepare stage_ns)
#include <sys/types.h>  // ssize_t
#include <zlib.h>       // gzip pages in the whole-chunk prepare walk

#include "parquet_tpu_native.h"  // shared ptq_chunk_prepare prototype (pyext)

extern "C" {

// ---------------------------------------------------------------------------
// snappy block format
// ---------------------------------------------------------------------------

size_t ptq_snappy_max_compressed_length(size_t n) {
  // Worst case: all literals (header <= 5 bytes per element, one element) plus
  // copies that are only emitted when profitable (see emit rules), + varint.
  return 32 + n + n / 6;
}

// Tag-dispatch table for the fast decode loop: one lookup replaces the
// per-kind branch ladder. entry = (extra_trailer_bytes << 11) |
// (offset_high_bits << 8) | base_copy_length. Literal tags (kind 0) are
// dispatched before the table is consulted.
static uint16_t g_snappy_tag[256];
static const bool g_snappy_tag_init = [] {
  for (int c = 0; c < 256; c++) {
    uint16_t e = 0;
    switch (c & 3) {
      case 1:  // copy, 1-byte offset trailer, 3 offset bits in the tag
        e = static_cast<uint16_t>((1u << 11) | ((static_cast<uint32_t>(c) >> 5) << 8) |
                                  (((static_cast<uint32_t>(c) >> 2) & 7) + 4));
        break;
      case 2:  // copy, 2-byte little-endian offset
        e = static_cast<uint16_t>((2u << 11) | ((static_cast<uint32_t>(c) >> 2) + 1));
        break;
      case 3:  // copy, 4-byte little-endian offset
        e = static_cast<uint16_t>((4u << 11) | ((static_cast<uint32_t>(c) >> 2) + 1));
        break;
    }
    g_snappy_tag[c] = e;
  }
  return true;
}();
static const uint32_t g_snappy_wordmask[5] = {0, 0xffu, 0xffffu, 0xffffffu,
                                              0xffffffffu};

// Overshooting match copy: writes in 8/16-byte blocks, spilling at most 15
// bytes past out+length into the caller-guaranteed slack. Correct for every
// offset >= 1 (short periods are strided by the first period multiple >= 8).
static inline void snappy_copy_fast(char* op, const char* from, uint32_t length,
                                    uint32_t offset) {
  if (offset >= 8 && length <= 8) {
    std::memcpy(op, from, 8);
  } else if (offset >= 8 && length <= 16) {
    // the dominant op on structured numeric data (e.g. a 7-byte match at
    // offset 8 per int64): two fixed 8-byte moves, no loop, no call.
    // Reading from+8 may touch bytes the first move just wrote — for
    // offset 8..15 those bytes repeat the pattern, which is exactly what
    // the match semantics require.
    std::memcpy(op, from, 8);
    std::memcpy(op + 8, from + 8, 8);
  } else if (offset >= 16) {
    for (uint32_t i = 0; i < length; i += 16) std::memcpy(op + i, from + i, 16);
  } else if (offset >= 8) {
    for (uint32_t i = 0; i < length; i += 8) std::memcpy(op + i, from + i, 8);
  } else {
    // short period: byte-copy one full period multiple >= 8 (<= 14 bytes),
    // then stride by that multiple — still the same pattern, but each
    // 8-byte block is non-overlapping
    uint32_t off2 = offset;
    while (off2 < 8) off2 += offset;
    uint32_t head = off2 < length ? off2 : length;
    for (uint32_t i = 0; i < head; i++) op[i] = from[i];
    for (uint32_t i = head; i < length; i += 8) std::memcpy(op + i, op + i - off2, 8);
  }
}

ssize_t ptq_snappy_decompress(const char* src_c, size_t src_len,
                              char* dst, size_t dst_cap) {
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t pos = 0;
  uint64_t expect = 0;
  int shift = 0;
  // preamble: uncompressed length varint
  for (;;) {
    if (pos >= src_len || shift > 63) return -1;
    uint8_t b = src[pos++];
    expect |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  if (expect > dst_cap) return -1;
  // Fast mode: a destination with >= 64 bytes of physical slack past `expect`
  // (chunk_prepare's scratch/values buffers are allocated that way) lets
  // copies run in overshooting 8/16-byte blocks and lets the tag trailer be
  // read as one unconditional 4-byte load — the decode stays LOGICALLY
  // bounded by `expect`, only the access granularity spills into the slack.
  // Exactly-sized destinations (the public codec entry point) take the
  // byte-exact careful loop below.
  const bool fast = dst_cap >= expect + 64;
  size_t out = 0;
  while (pos < src_len) {
    uint8_t tag = src[pos++];
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      uint32_t len = tag >> 2;
      if (len >= 60) {
        uint32_t extra = len - 59;  // 1..4 length bytes
        if (pos + extra > src_len) return -1;
        len = 0;
        for (uint32_t i = 0; i < extra; i++) len |= static_cast<uint32_t>(src[pos + i]) << (8 * i);
        pos += extra;
      }
      uint64_t n = static_cast<uint64_t>(len) + 1;
      if (pos + n > src_len || out + n > expect) return -1;
      if (fast && n <= 8 && pos + 8 <= src_len) {
        std::memcpy(dst + out, src + pos, 8);
      } else {
        std::memcpy(dst + out, src + pos, n);
      }
      out += n;
      pos += n;
    } else {
      uint32_t length, offset;
      if (fast && pos + 4 <= src_len) {
        // tag-dispatch: one table lookup + one unconditional 4-byte load
        // replaces the per-kind branch ladder (trailer bytes beyond the
        // tag's count are masked off, never consumed)
        const uint16_t e = g_snappy_tag[tag];
        const uint32_t extra = e >> 11;
        uint32_t data;
        std::memcpy(&data, src + pos, 4);
        offset = (e & 0x700u) + (data & g_snappy_wordmask[extra]);
        length = e & 0xffu;
        pos += extra;
      } else if (kind == 1) {
        if (pos + 1 > src_len) return -1;
        length = ((tag >> 2) & 7) + 4;
        offset = (static_cast<uint32_t>(tag >> 5) << 8) | src[pos];
        pos += 1;
      } else if (kind == 2) {
        if (pos + 2 > src_len) return -1;
        length = (tag >> 2) + 1;
        offset = static_cast<uint32_t>(src[pos]) | (static_cast<uint32_t>(src[pos + 1]) << 8);
        pos += 2;
      } else {
        if (pos + 4 > src_len) return -1;
        length = (tag >> 2) + 1;
        offset = static_cast<uint32_t>(src[pos]) | (static_cast<uint32_t>(src[pos + 1]) << 8) |
                 (static_cast<uint32_t>(src[pos + 2]) << 16) | (static_cast<uint32_t>(src[pos + 3]) << 24);
        pos += 4;
      }
      if (offset == 0 || offset > out || out + length > expect) return -1;
      const char* from = dst + out - offset;
      char* op = dst + out;
      if (fast) {
        snappy_copy_fast(op, from, length, offset);
      } else if (offset >= 8) {
        // Non-overlapping at 8-byte granularity for the body (~2x on
        // match-heavy pages vs the byte loop); the sub-8 tail is copied
        // byte-wise so no write ever lands past `expect` — an exactly-sized
        // destination buffer is safe, no out-of-band spare-capacity contract.
        uint32_t wide = length & ~7u;
        for (uint32_t i = 0; i < wide; i += 8) std::memcpy(op + i, from + i, 8);
        for (uint32_t i = wide; i < length; i++) op[i] = from[i];
      } else {
        // overlapping copy must run forward byte-by-byte (RLE-style matches)
        for (uint32_t i = 0; i < length; i++) op[i] = from[i];
      }
      out += length;
    }
  }
  return out == expect ? static_cast<ssize_t>(out) : -1;
}

static inline uint32_t snappy_hash(uint32_t v) {
  return (v * 0x1e35a7bdu) >> 18;  // 14-bit table
}

// Emits one literal element (callers never pass len >= 2^32). Returns false on
// insufficient space in dst.
static bool emit_literal(const uint8_t* src, size_t from, size_t len,
                         char* dst, size_t dst_cap, size_t* out) {
  if (len == 0) return true;
  if (*out + 5 + len > dst_cap) return false;
  size_t n = len - 1;
  if (n < 60) {
    dst[(*out)++] = static_cast<char>(n << 2);
  } else if (n < (1u << 8)) {
    dst[(*out)++] = static_cast<char>(60 << 2);
    dst[(*out)++] = static_cast<char>(n);
  } else if (n < (1u << 16)) {
    dst[(*out)++] = static_cast<char>(61 << 2);
    dst[(*out)++] = static_cast<char>(n);
    dst[(*out)++] = static_cast<char>(n >> 8);
  } else if (n < (1u << 24)) {
    dst[(*out)++] = static_cast<char>(62 << 2);
    dst[(*out)++] = static_cast<char>(n);
    dst[(*out)++] = static_cast<char>(n >> 8);
    dst[(*out)++] = static_cast<char>(n >> 16);
  } else {
    dst[(*out)++] = static_cast<char>(63 << 2);
    dst[(*out)++] = static_cast<char>(n);
    dst[(*out)++] = static_cast<char>(n >> 8);
    dst[(*out)++] = static_cast<char>(n >> 16);
    dst[(*out)++] = static_cast<char>(n >> 24);
  }
  std::memcpy(dst + *out, src + from, len);
  *out += len;
  return true;
}

static bool emit_copy(size_t offset, size_t len, char* dst, size_t dst_cap,
                      size_t* out) {
  while (len > 0) {
    size_t chunk = len > 64 ? 64 : len;
    // keep the final chunk >= 4 (canonical decoders may reject shorter copies)
    if (chunk == 64 && len - chunk > 0 && len - chunk < 4) chunk = 60;
    if (*out + 5 > dst_cap) return false;
    if (chunk >= 4 && chunk <= 11 && offset < 2048) {
      dst[(*out)++] = static_cast<char>(((offset >> 8) << 5) | ((chunk - 4) << 2) | 1);
      dst[(*out)++] = static_cast<char>(offset & 0xff);
    } else if (offset < (1u << 16)) {
      dst[(*out)++] = static_cast<char>(((chunk - 1) << 2) | 2);
      dst[(*out)++] = static_cast<char>(offset & 0xff);
      dst[(*out)++] = static_cast<char>(offset >> 8);
    } else {
      dst[(*out)++] = static_cast<char>(((chunk - 1) << 2) | 3);
      dst[(*out)++] = static_cast<char>(offset & 0xff);
      dst[(*out)++] = static_cast<char>((offset >> 8) & 0xff);
      dst[(*out)++] = static_cast<char>((offset >> 16) & 0xff);
      dst[(*out)++] = static_cast<char>((offset >> 24) & 0xff);
    }
    len -= chunk;
  }
  return true;
}

ssize_t ptq_snappy_compress(const char* src_c, size_t src_len,
                            char* dst, size_t dst_cap) {
  if (dst_cap < ptq_snappy_max_compressed_length(src_len)) return -1;
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t out = 0;
  // preamble
  {
    uint64_t v = src_len;
    while (v >= 0x80) { dst[out++] = static_cast<char>(v | 0x80); v >>= 7; }
    dst[out++] = static_cast<char>(v);
  }
  if (src_len == 0) return static_cast<ssize_t>(out);
  constexpr size_t kTableSize = 1 << 14;
  static thread_local uint32_t table[kTableSize];
  std::memset(table, 0, sizeof(table));
  size_t lit_start = 0;
  size_t pos = 0;
  if (src_len >= 8) {
    const size_t limit = src_len - 4;
    // google-snappy's miss-acceleration: after 32 consecutive misses the
    // scan starts stepping 2, then 3, ... bytes at a time — incompressible
    // input (bit-packed dictionary indices, already-compressed blobs) costs
    // ~O(n/step) hash probes instead of one per byte. A found match resets
    // the window. (Output stays valid snappy; the ratio on borderline data
    // trades a hair for a large incompressible-page speedup.)
    uint32_t skip = 32;
    while (pos < limit) {
      uint32_t cur;
      std::memcpy(&cur, src + pos, 4);
      uint32_t h = snappy_hash(cur);
      size_t cand = table[h];
      table[h] = static_cast<uint32_t>(pos);
      uint32_t cv;
      if (cand < pos && pos - cand < (1ull << 32) &&
          (std::memcpy(&cv, src + cand, 4), cv == cur)) {
        // extend match
        size_t len = 4;
        while (pos + len < src_len && src[cand + len] == src[pos + len]) len++;
        size_t offset = pos - cand;
        // Profitability: a far copy costs 5 bytes; only take it when it beats
        // the literal it replaces, which also keeps the advertised
        // max_compressed_length bound valid (no expanding elements).
        if (offset >= (1u << 16) && len < 8) {
          pos++;
          continue;
        }
        if (pos > lit_start &&
            !emit_literal(src, lit_start, pos - lit_start, dst, dst_cap, &out))
          return -1;
        if (!emit_copy(offset, len, dst, dst_cap, &out)) return -1;
        pos += len;
        lit_start = pos;
        skip = 32;
      } else {
        pos += skip++ >> 5;
      }
    }
  }
  if (lit_start < src_len &&
      !emit_literal(src, lit_start, src_len - lit_start, dst, dst_cap, &out))
    return -1;
  return static_cast<ssize_t>(out);
}

// ---------------------------------------------------------------------------
// LZ4 block format (+ the Hadoop framing parquet's legacy LZ4 codec uses)
//
// Implemented from the public LZ4 block format description: sequences of
// [token: literal-length nibble | match-length nibble][literals]
// [2-byte LE match offset][length extension bytes], final sequence literals
// only. Strict bounds validation before every write; -1 on corrupt input.
// ---------------------------------------------------------------------------

size_t ptq_lz4_max_compressed_length(size_t n) {
  // worst case: one literal run (1 token + ceil(n/255) extensions + n bytes)
  return 16 + n + n / 255;
}

ssize_t ptq_lz4_decompress(const char* src_c, size_t src_len,
                           char* dst, size_t expect) {
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t pos = 0;
  size_t out = 0;
  if (src_len == 0) return expect == 0 ? 0 : -1;
  while (pos < src_len) {
    uint8_t token = src[pos++];
    // literals
    uint64_t lit = token >> 4;
    if (lit == 15) {
      for (;;) {
        if (pos >= src_len) return -1;
        uint8_t b = src[pos++];
        lit += b;
        if (b != 255) break;
        if (lit > (1ull << 40)) return -1;  // length bomb
      }
    }
    if (pos + lit > src_len || out + lit > expect) return -1;
    std::memcpy(dst + out, src + pos, lit);
    out += lit;
    pos += lit;
    if (pos == src_len) break;  // last sequence carries literals only
    // match
    if (pos + 2 > src_len) return -1;
    uint32_t offset = static_cast<uint32_t>(src[pos]) |
                      (static_cast<uint32_t>(src[pos + 1]) << 8);
    pos += 2;
    if (offset == 0 || offset > out) return -1;
    uint64_t mlen = token & 15;
    if (mlen == 15) {
      for (;;) {
        if (pos >= src_len) return -1;
        uint8_t b = src[pos++];
        mlen += b;
        if (b != 255) break;
        if (mlen > (1ull << 40)) return -1;
      }
    }
    mlen += 4;  // minmatch
    if (out + mlen > expect) return -1;
    const char* from = dst + out - offset;
    char* op = dst + out;
    if (offset >= 8) {
      // non-overlapping at 8-byte granularity; sub-8 tail byte-wise so no
      // write lands past `expect` (same contract as the snappy decoder)
      uint64_t wide = mlen & ~7ull;
      for (uint64_t i = 0; i < wide; i += 8) std::memcpy(op + i, from + i, 8);
      for (uint64_t i = wide; i < mlen; i++) op[i] = from[i];
    } else {
      for (uint64_t i = 0; i < mlen; i++) op[i] = from[i];  // RLE overlap
    }
    out += mlen;
  }
  return out == expect ? static_cast<ssize_t>(out) : -1;
}

static inline uint32_t lz4_hash(uint32_t v) {
  return (v * 2654435761u) >> 19;  // 13-bit table
}

// Append a literal/match length in LZ4's nibble + 255-extension form.
static inline bool lz4_put_len(uint64_t extra, char* dst, size_t dst_cap,
                               size_t* out) {
  while (extra >= 255) {
    if (*out >= dst_cap) return false;
    dst[(*out)++] = static_cast<char>(255);
    extra -= 255;
  }
  if (*out >= dst_cap) return false;
  dst[(*out)++] = static_cast<char>(extra);
  return true;
}

ssize_t ptq_lz4_compress(const char* src_c, size_t src_len,
                         char* dst, size_t dst_cap) {
  if (dst_cap < ptq_lz4_max_compressed_length(src_len)) return -1;
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t out = 0;
  size_t lit_start = 0;
  size_t pos = 0;
  constexpr size_t kTableSize = 1 << 13;
  static thread_local uint32_t table[kTableSize];
  // The format forbids matches in the final 12 bytes (spec end-of-block
  // rule: last sequence is literals-only and >= 5 bytes, matches must not
  // start within the last 12) — canonical decoders rely on it.
  if (src_len > 12) {
    std::memset(table, 0, sizeof(table));
    const size_t match_limit = src_len - 12;
    while (pos <= match_limit) {
      uint32_t cur;
      std::memcpy(&cur, src + pos, 4);
      uint32_t h = lz4_hash(cur);
      size_t cand = table[h];
      table[h] = static_cast<uint32_t>(pos);
      uint32_t cv;
      if (cand < pos && pos - cand < (1u << 16) &&
          (std::memcpy(&cv, src + cand, 4), cv == cur)) {
        // extend, but never into the last 5 bytes (they must stay literal)
        size_t max_len = src_len - 5 - pos;
        size_t len = 4;
        while (len < max_len && src[cand + len] == src[pos + len]) len++;
        size_t lit = pos - lit_start;
        uint8_t tok_lit = lit >= 15 ? 15 : static_cast<uint8_t>(lit);
        uint8_t tok_m = (len - 4) >= 15 ? 15 : static_cast<uint8_t>(len - 4);
        if (out >= dst_cap) return -1;
        dst[out++] = static_cast<char>((tok_lit << 4) | tok_m);
        if (tok_lit == 15 && !lz4_put_len(lit - 15, dst, dst_cap, &out))
          return -1;
        if (out + lit > dst_cap) return -1;
        std::memcpy(dst + out, src + lit_start, lit);
        out += lit;
        size_t offset = pos - cand;
        if (out + 2 > dst_cap) return -1;
        dst[out++] = static_cast<char>(offset & 0xff);
        dst[out++] = static_cast<char>(offset >> 8);
        if (tok_m == 15 && !lz4_put_len(len - 4 - 15, dst, dst_cap, &out))
          return -1;
        pos += len;
        lit_start = pos;
      } else {
        pos++;
      }
    }
  }
  // trailing literals (the whole input when src_len <= 12)
  {
    size_t lit = src_len - lit_start;
    uint8_t tok_lit = lit >= 15 ? 15 : static_cast<uint8_t>(lit);
    if (out >= dst_cap) return -1;
    dst[out++] = static_cast<char>(tok_lit << 4);
    if (tok_lit == 15 && !lz4_put_len(lit - 15, dst, dst_cap, &out)) return -1;
    if (out + lit > dst_cap) return -1;
    std::memcpy(dst + out, src + lit_start, lit);
    out += lit;
  }
  return static_cast<ssize_t>(out);
}

// Parquet's legacy LZ4 codec (id 5) is Hadoop-framed on disk: repeated
// [4B BE uncompressed size][4B BE compressed size][raw block]; some writers
// emit bare raw blocks instead. Mirror parquet-cpp: try the framing, fall
// back to one raw block.
ssize_t ptq_lz4_hadoop_decompress(const char* src_c, size_t src_len,
                                  char* dst, size_t expect) {
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t pos = 0;
  size_t out = 0;
  bool framed = true;
  while (pos < src_len) {
    if (pos + 8 > src_len) { framed = false; break; }
    uint64_t usz = (static_cast<uint32_t>(src[pos]) << 24) |
                   (static_cast<uint32_t>(src[pos + 1]) << 16) |
                   (static_cast<uint32_t>(src[pos + 2]) << 8) |
                   static_cast<uint32_t>(src[pos + 3]);
    uint64_t csz = (static_cast<uint32_t>(src[pos + 4]) << 24) |
                   (static_cast<uint32_t>(src[pos + 5]) << 16) |
                   (static_cast<uint32_t>(src[pos + 6]) << 8) |
                   static_cast<uint32_t>(src[pos + 7]);
    if (pos + 8 + csz > src_len || out + usz > expect) { framed = false; break; }
    ssize_t got = ptq_lz4_decompress(src_c + pos + 8, csz, dst + out, usz);
    if (got < 0 || static_cast<uint64_t>(got) != usz) { framed = false; break; }
    out += usz;
    pos += 8 + csz;
  }
  if (framed && out == expect) return static_cast<ssize_t>(out);
  return ptq_lz4_decompress(src_c, src_len, dst, expect);
}

// ---------------------------------------------------------------------------
// XXH64 + split-block bloom filter (parquet-format BloomFilter.md)
//
// Implemented from the public xxHash specification and the parquet split-
// block bloom description: 32-byte blocks of 8 uint32 words; a value's
// block comes from the hash's top 32 bits, its 8 bit positions from the
// low 32 bits multiplied by 8 fixed odd salts.
// ---------------------------------------------------------------------------

static const uint64_t XP1 = 0x9E3779B185EBCA87ull;
static const uint64_t XP2 = 0xC2B2AE3D27D4EB4Full;
static const uint64_t XP3 = 0x165667B19E3779F9ull;
static const uint64_t XP4 = 0x85EBCA77C2B2AE63ull;
static const uint64_t XP5 = 0x27D4EB2F165667C5ull;

static inline uint64_t xrotl(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t xread64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (matches the rest of this file)
}

static inline uint32_t xread32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t ptq_xxh64(const uint8_t* p, size_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + XP1 + XP2, v2 = seed + XP2, v3 = seed, v4 = seed - XP1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xrotl(v1 + xread64(p) * XP2, 31) * XP1;
      v2 = xrotl(v2 + xread64(p + 8) * XP2, 31) * XP1;
      v3 = xrotl(v3 + xread64(p + 16) * XP2, 31) * XP1;
      v4 = xrotl(v4 + xread64(p + 24) * XP2, 31) * XP1;
      p += 32;
    } while (p <= limit);
    h = xrotl(v1, 1) + xrotl(v2, 7) + xrotl(v3, 12) + xrotl(v4, 18);
    h = (h ^ (xrotl(v1 * XP2, 31) * XP1)) * XP1 + XP4;
    h = (h ^ (xrotl(v2 * XP2, 31) * XP1)) * XP1 + XP4;
    h = (h ^ (xrotl(v3 * XP2, 31) * XP1)) * XP1 + XP4;
    h = (h ^ (xrotl(v4 * XP2, 31) * XP1)) * XP1 + XP4;
  } else {
    h = seed + XP5;
  }
  h += static_cast<uint64_t>(len);
  while (p + 8 <= end) {
    h = xrotl(h ^ (xrotl(xread64(p) * XP2, 31) * XP1), 27) * XP1 + XP4;
    p += 8;
  }
  if (p + 4 <= end) {
    h = xrotl(h ^ (static_cast<uint64_t>(xread32(p)) * XP1), 23) * XP2 + XP3;
    p += 4;
  }
  while (p < end) {
    h = xrotl(h ^ (static_cast<uint64_t>(*p) * XP5), 11) * XP1;
    p++;
  }
  h ^= h >> 33;
  h *= XP2;
  h ^= h >> 29;
  h *= XP3;
  h ^= h >> 32;
  return h;
}

// Hash n fixed-width elements (stride bytes each, contiguous).
void ptq_xxh64_fixed(const uint8_t* src, int64_t n, int stride, uint64_t* out) {
  for (int64_t i = 0; i < n; i++)
    out[i] = ptq_xxh64(src + static_cast<size_t>(i) * stride, stride, 0);
}

// Hash n variable-length elements addressed by int64 offsets[n+1].
void ptq_xxh64_offsets(const uint8_t* data, const int64_t* offsets, int64_t n,
                       uint64_t* out) {
  for (int64_t i = 0; i < n; i++)
    out[i] = ptq_xxh64(data + offsets[i],
                       static_cast<size_t>(offsets[i + 1] - offsets[i]), 0);
}

static const uint32_t BLOOM_SALT[8] = {
    0x47b6137bu, 0x44974d91u, 0x8824ad5bu, 0xa2b7289du,
    0x705495c7u, 0x2df1424bu, 0x9efc4947u, 0x5c6bfb31u};

void ptq_bloom_insert(uint32_t* blocks, int64_t num_blocks,
                      const uint64_t* hashes, int64_t n) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = hashes[i];
    uint64_t bi = ((h >> 32) * static_cast<uint64_t>(num_blocks)) >> 32;
    uint32_t x = static_cast<uint32_t>(h);
    uint32_t* b = blocks + bi * 8;
    for (int j = 0; j < 8; j++) b[j] |= 1u << ((x * BLOOM_SALT[j]) >> 27);
  }
}

// out[i] = 1 if hashes[i] might be present.
void ptq_bloom_check(const uint32_t* blocks, int64_t num_blocks,
                     const uint64_t* hashes, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = hashes[i];
    uint64_t bi = ((h >> 32) * static_cast<uint64_t>(num_blocks)) >> 32;
    uint32_t x = static_cast<uint32_t>(h);
    const uint32_t* b = blocks + bi * 8;
    uint8_t hit = 1;
    for (int j = 0; j < 8; j++)
      hit &= static_cast<uint8_t>((b[j] >> ((x * BLOOM_SALT[j]) >> 27)) & 1);
    out[i] = hit;
  }
}

// ---------------------------------------------------------------------------
// PLAIN byte_array scan: 4-byte LE length + payload, repeated
// ---------------------------------------------------------------------------

// Fills offsets[0..num_values] (compacted) and copies payloads into data_out.
// Returns bytes consumed from src, or -1 on corrupt input / overflow.
ssize_t ptq_byte_array_gather(const char* src, size_t src_len, int64_t num_values,
                              int64_t* offsets, char* data_out, size_t data_cap) {
  size_t pos = 0;
  int64_t total = 0;
  offsets[0] = 0;
  for (int64_t i = 0; i < num_values; i++) {
    if (pos + 4 > src_len) return -1;
    uint32_t len;
    std::memcpy(&len, src + pos, 4);  // little-endian hosts only (x86/arm64)
    pos += 4;
    if (pos + len > src_len) return -1;
    if (static_cast<size_t>(total) + len > data_cap) return -1;
    std::memcpy(data_out + total, src + pos, len);
    pos += len;
    total += len;
    offsets[i + 1] = total;
  }
  return static_cast<ssize_t>(pos);
}

// ---------------------------------------------------------------------------
// hybrid RLE/bit-pack run-header prescan
// ---------------------------------------------------------------------------

// Outputs one row per run. bp_offsets are ABSOLUTE byte offsets into src
// (the caller uses src itself as the packed buffer). Returns the number of
// runs, or -1 on corrupt input, or -2 if max_runs is too small.
ssize_t ptq_prescan_hybrid(const uint8_t* src, size_t src_len, int64_t num_values,
                           int width, uint8_t* is_rle, int64_t* counts,
                           uint64_t* values, int64_t* bp_offsets,
                           size_t max_runs, int64_t* consumed) {
  if (width < 0 || width > 64) return -1;
  const size_t vbytes = (width + 7) / 8;
  size_t pos = 0;
  int64_t produced = 0;
  size_t runs = 0;
  while (produced < num_values) {
    uint64_t header = 0;
    int shift = 0;
    for (;;) {
      if (pos >= src_len || shift > 63) return -1;
      uint8_t b = src[pos++];
      if (shift == 63 && (b & 0x7e)) return -1;  // overflows uint64
      header |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    if (runs >= max_runs) return -2;
    if (header & 1) {
      uint64_t groups = header >> 1;
      // overflow guards before any multiply (the Python fallback rejects these
      // via arbitrary-precision arithmetic; keep parity)
      if (groups == 0 || groups > (1ull << 40)) return -1;
      uint64_t count = groups * 8;
      uint64_t nbytes = groups * static_cast<uint64_t>(width);
      if (pos + nbytes > src_len) return -1;
      is_rle[runs] = 0;
      counts[runs] = static_cast<int64_t>(count);
      values[runs] = 0;
      bp_offsets[runs] = static_cast<int64_t>(pos);
      pos += nbytes;
      produced += static_cast<int64_t>(count);
    } else {
      uint64_t count = header >> 1;
      if (count == 0 || count > (1ull << 40) || pos + vbytes > src_len) return -1;
      uint64_t v = 0;
      for (size_t i = 0; i < vbytes; i++) v |= static_cast<uint64_t>(src[pos + i]) << (8 * i);
      if (width < 64 && v >= (1ull << width)) return -1;
      pos += vbytes;
      is_rle[runs] = 1;
      counts[runs] = static_cast<int64_t>(count);
      values[runs] = v;
      bp_offsets[runs] = 0;
      produced += static_cast<int64_t>(count);
    }
    runs++;
  }
  *consumed = static_cast<int64_t>(pos);
  return static_cast<ssize_t>(runs);
}

// ---------------------------------------------------------------------------
// bit-stream reader (LSB-first, parquet bit-packed order)
// ---------------------------------------------------------------------------

struct BitReader {
  const uint8_t* src;
  size_t len;
  size_t pos;     // next byte
  uint64_t buf;   // pending bits, LSB first
  int bits;       // number of pending bits
};

static inline void br_init(BitReader* r, const uint8_t* src, size_t len) {
  r->src = src; r->len = len; r->pos = 0; r->buf = 0; r->bits = 0;
}

// Reads `w` bits (0 <= w <= 64). Caller guarantees the underlying payload is
// in bounds (all call sites bounds-check the whole run/miniblock first).
static inline uint64_t br_read(BitReader* r, int w) {
  uint64_t v = 0;
  int got = 0;
  while (got < w) {
    if (r->bits == 0) {
      r->buf = r->src[r->pos++];
      r->bits = 8;
    }
    int take = w - got;
    if (take > r->bits) take = r->bits;
    v |= (r->buf & ((take == 64) ? ~0ull : ((1ull << take) - 1))) << got;
    r->buf >>= take;
    r->bits -= take;
    got += take;
  }
  return v;
}

// ---------------------------------------------------------------------------
// one-shot hybrid RLE/bit-pack decode (prescan + expand fused, host hot path)
// ---------------------------------------------------------------------------

// Decodes `num_values` into out32 or out64 (exactly one non-null). Returns
// bytes consumed, or -1 on corrupt input. Semantics mirror prescan_hybrid +
// expand_runs in ops/rle_hybrid.py (the NumPy reference implementation).
ssize_t ptq_hybrid_decode(const uint8_t* src, size_t src_len, int64_t num_values,
                          int width, uint32_t* out32, uint64_t* out64) {
  if (width < 0 || width > 64) return -1;
  if (width > 32 && out32) return -1;
  const size_t vbytes = (width + 7) / 8;
  size_t pos = 0;
  int64_t produced = 0;
  while (produced < num_values) {
    uint64_t header = 0;
    int shift = 0;
    for (;;) {
      if (pos >= src_len || shift > 63) return -1;
      uint8_t b = src[pos++];
      if (shift == 63 && (b & 0x7e)) return -1;  // overflows uint64
      header |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    if (header & 1) {
      uint64_t groups = header >> 1;
      if (groups == 0 || groups > (1ull << 40)) return -1;
      uint64_t count = groups * 8;
      uint64_t nbytes = groups * static_cast<uint64_t>(width);
      if (pos + nbytes > src_len) return -1;
      int64_t take = num_values - produced;
      if (static_cast<uint64_t>(take) > count) take = static_cast<int64_t>(count);
      BitReader r;
      br_init(&r, src + pos, nbytes);
      if (out32) {
        for (int64_t i = 0; i < take; i++) out32[produced + i] = static_cast<uint32_t>(br_read(&r, width));
      } else {
        for (int64_t i = 0; i < take; i++) out64[produced + i] = br_read(&r, width);
      }
      pos += nbytes;
      produced += take;
    } else {
      uint64_t count = header >> 1;
      if (count == 0 || count > (1ull << 40) || pos + vbytes > src_len) return -1;
      uint64_t v = 0;
      for (size_t i = 0; i < vbytes; i++) v |= static_cast<uint64_t>(src[pos + i]) << (8 * i);
      if (width < 64 && v >= (1ull << width)) return -1;
      pos += vbytes;
      int64_t take = num_values - produced;
      if (static_cast<uint64_t>(take) > count) take = static_cast<int64_t>(count);
      if (out32) {
        uint32_t v32 = static_cast<uint32_t>(v);
        for (int64_t i = 0; i < take; i++) out32[produced + i] = v32;
      } else {
        for (int64_t i = 0; i < take; i++) out64[produced + i] = v;
      }
      produced += take;
    }
  }
  return static_cast<ssize_t>(pos);
}

// ---------------------------------------------------------------------------
// DELTA_BINARY_PACKED decode (header walk + miniblock unpack + wrapping cumsum)
// ---------------------------------------------------------------------------

static inline bool read_uvarint64(const uint8_t* src, size_t src_len, size_t* pos,
                                  uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  for (;;) {
    if (*pos >= src_len || shift > 63) return false;
    uint8_t b = src[(*pos)++];
    if (shift == 63 && (b & 0x7e)) return false;  // overflows uint64
    v |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = v;
  return true;
}

// Full decode of a DELTA_BINARY_PACKED stream into out (int32 when nbits==32,
// int64 when nbits==64; the buffer must hold the header's value count, which
// is bounded by max_total). Returns bytes consumed, -1 on corrupt input, -3
// if the stream's count exceeds max_total (validation-before-allocation: the
// caller probes the count first via ptq_delta_peek_total).
// Semantics mirror ops/delta.py prescan_delta + decode_delta exactly,
// including wrapping min-delta arithmetic (reference: deltabp_encoder.go:58-61)
// and trailing-miniblock payload rules (reference: deltabp_decoder.go flush()).
ssize_t ptq_delta_decode(const uint8_t* src, size_t src_len, int nbits,
                         int64_t max_total, void* out_v, int64_t* total_out) {
  if (nbits != 32 && nbits != 64) return -1;
  size_t pos = 0;
  uint64_t block_size, mini_count, total_u;
  if (!read_uvarint64(src, src_len, &pos, &block_size)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &mini_count)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &total_u)) return -1;
  uint64_t first_zz;
  if (!read_uvarint64(src, src_len, &pos, &first_zz)) return -1;
  uint64_t first = (first_zz >> 1) ^ (~(first_zz & 1) + 1);  // zigzag decode
  if (block_size == 0 || block_size % 128 != 0 || block_size > (1ull << 20)) return -1;
  if (mini_count == 0 || mini_count > 512 || block_size % mini_count != 0) return -1;
  uint64_t mini_len = block_size / mini_count;
  if (mini_len % 8 != 0) return -1;
  int64_t total = static_cast<int64_t>(total_u);
  if (total_u > (1ull << 62)) return -1;
  if (max_total >= 0 && total > max_total) return -3;
  // plausibility backstop (parity with prescan_delta)
  uint64_t plausible = 1 + (src_len / (1 + mini_count) + 1) * block_size;
  if (total_u > plausible) return -3;
  *total_out = total;

  const uint64_t mask = (nbits == 64) ? ~0ull : ((1ull << nbits) - 1);
  int32_t* out32 = (nbits == 32) ? static_cast<int32_t*>(out_v) : nullptr;
  int64_t* out64 = (nbits == 64) ? static_cast<int64_t*>(out_v) : nullptr;
  uint64_t acc = first & mask;
  if (total > 0) {
    if (out32) out32[0] = static_cast<int32_t>(static_cast<uint32_t>(acc));
    else out64[0] = static_cast<int64_t>(acc);
  }
  int64_t n_deltas = total > 1 ? total - 1 : 0;
  int64_t produced = 0;
  while (produced < n_deltas) {
    uint64_t md_zz;
    if (!read_uvarint64(src, src_len, &pos, &md_zz)) return -1;
    uint64_t min_delta = (md_zz >> 1) ^ (~(md_zz & 1) + 1);
    if (pos + mini_count > src_len) return -1;
    const uint8_t* widths = src + pos;
    pos += mini_count;
    for (uint64_t m = 0; m < mini_count; m++) {
      int64_t remaining = n_deltas - produced;
      if (remaining <= 0) continue;  // unused trailing miniblock: no payload
      int w = widths[m];
      if (w > nbits) return -1;
      uint64_t payload = (mini_len / 8) * static_cast<uint64_t>(w);
      if (pos + payload > src_len) return -1;
      int64_t take = remaining < static_cast<int64_t>(mini_len)
                         ? remaining : static_cast<int64_t>(mini_len);
      BitReader r;
      br_init(&r, src + pos, payload);
      if (out32) {
        uint32_t a = static_cast<uint32_t>(acc);
        uint32_t md32 = static_cast<uint32_t>(min_delta);
        for (int64_t i = 0; i < take; i++) {
          a += static_cast<uint32_t>(br_read(&r, w)) + md32;
          out32[produced + 1 + i] = static_cast<int32_t>(a);
        }
        acc = a;
      } else {
        uint64_t a = acc;
        for (int64_t i = 0; i < take; i++) {
          a += br_read(&r, w) + min_delta;
          out64[produced + 1 + i] = static_cast<int64_t>(a);
        }
        acc = a;
      }
      pos += payload;
      produced += take;
    }
  }
  return static_cast<ssize_t>(pos);
}

// Header probe for pre-allocation: validates the full header (same rules as
// ptq_delta_decode, including the plausibility backstop that bounds the value
// count by the stream length — validation-before-allocation) and returns the
// value count. Returns 0 on success, -1 on corrupt/implausible header.
ssize_t ptq_delta_peek_total(const uint8_t* src, size_t src_len, int64_t* total) {
  size_t pos = 0;
  uint64_t bs, mc, t, fz;
  if (!read_uvarint64(src, src_len, &pos, &bs)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &mc)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &t)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &fz)) return -1;
  if (bs == 0 || bs % 128 != 0 || bs > (1ull << 20)) return -1;
  if (mc == 0 || mc > 512 || bs % mc != 0) return -1;
  if ((bs / mc) % 8 != 0) return -1;
  if (t > (1ull << 62)) return -1;
  uint64_t plausible = 1 + (src_len / (1 + mc) + 1) * bs;
  if (t > plausible) return -1;
  *total = static_cast<int64_t>(t);
  return 0;
}

// ---------------------------------------------------------------------------
// byte-array dictionary gather (ByteArrayData.take hot path)
// ---------------------------------------------------------------------------

// out must hold sum of the gathered lengths (caller computes via new_offsets,
// which it builds with a NumPy cumsum). Returns 0, or -1 on a bad index.
ssize_t ptq_bytearray_take(const char* data, size_t data_len,
                           const int64_t* offsets, int64_t n_src,
                           const int64_t* indices, int64_t n_idx,
                           const int64_t* new_offsets, char* out, size_t out_cap) {
  for (int64_t k = 0; k < n_idx; k++) {
    int64_t i = indices[k];
    if (i < 0 || i >= n_src) return -1;
    int64_t start = offsets[i];
    int64_t len = offsets[i + 1] - start;
    int64_t dst = new_offsets[k];
    if (start < 0 || len < 0 || static_cast<size_t>(start + len) > data_len ||
        static_cast<size_t>(dst + len) > out_cap)
      return -1;
    std::memcpy(out + dst, data + start, len);
  }
  return 0;
}

// PLAIN BYTE_ARRAY encode: [4B LE length][bytes] per value, straight from
// an (offsets, data) column — the write path's hot loop for string chunks.
// out must hold data_len + 4*n bytes.
ssize_t ptq_plain_encode_bytearray(const char* data, size_t data_len,
                                   const int64_t* offsets, int64_t n,
                                   char* out, size_t out_cap) {
  size_t pos = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t start = offsets[i];
    int64_t len = offsets[i + 1] - start;
    if (start < 0 || len < 0 || static_cast<size_t>(start + len) > data_len)
      return -1;
    if (len > static_cast<int64_t>(UINT32_MAX)) return -1;  // 4B prefix cap
    if (pos + 4 + static_cast<size_t>(len) > out_cap) return -1;
    uint32_t l32 = static_cast<uint32_t>(len);
    std::memcpy(out + pos, &l32, 4);
    std::memcpy(out + pos + 4, data + start, static_cast<size_t>(len));
    pos += 4 + static_cast<size_t>(len);
  }
  return static_cast<ssize_t>(pos);
}

// ---------------------------------------------------------------------------
// DELTA_BINARY_PACKED header-only prescan (device-decode planning hot path)
// ---------------------------------------------------------------------------

// Walks block/miniblock headers only (payload bytes stay packed for the
// device kernel). One table entry per miniblock covering >=1 real delta.
// Semantics mirror ops/delta.py prescan_delta_packed exactly. Returns the
// number of entries M, or -1 corrupt, -2 table overflow, -3 count exceeds
// max_total / implausible.
ssize_t ptq_prescan_delta_packed(const uint8_t* src, size_t src_len, int nbits,
                                 int64_t max_total, uint32_t* widths,
                                 int64_t* byte_starts, int32_t* out_starts,
                                 uint64_t* mins, size_t max_entries,
                                 uint64_t* first_value, int64_t* total_out,
                                 int64_t* consumed) {
  if (nbits != 32 && nbits != 64) return -1;
  size_t pos = 0;
  uint64_t block_size, mini_count, total_u, first_zz;
  if (!read_uvarint64(src, src_len, &pos, &block_size)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &mini_count)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &total_u)) return -1;
  if (!read_uvarint64(src, src_len, &pos, &first_zz)) return -1;
  if (block_size == 0 || block_size % 128 != 0 || block_size > (1ull << 20)) return -1;
  if (mini_count == 0 || mini_count > 512 || block_size % mini_count != 0) return -1;
  uint64_t mini_len = block_size / mini_count;
  if (mini_len % 8 != 0) return -1;
  if (total_u > (1ull << 62)) return -1;
  int64_t total = static_cast<int64_t>(total_u);
  if (max_total < 0) max_total = 0;  // match Python's max(max_total, 0) clamp
  if (total > max_total) return -3;
  uint64_t plausible = 1 + (src_len / (1 + mini_count) + 1) * block_size;
  if (total_u > plausible) return -3;
  const uint64_t mask = (nbits == 64) ? ~0ull : ((1ull << nbits) - 1);
  *first_value = ((first_zz >> 1) ^ (~(first_zz & 1) + 1)) & mask;
  *total_out = total;

  int64_t n_deltas = total > 1 ? total - 1 : 0;
  int64_t produced = 0;
  size_t m = 0;
  while (produced < n_deltas) {
    uint64_t md_zz;
    if (!read_uvarint64(src, src_len, &pos, &md_zz)) return -1;
    uint64_t min_delta = ((md_zz >> 1) ^ (~(md_zz & 1) + 1)) & mask;
    if (pos + mini_count > src_len) return -1;
    const uint8_t* wb = src + pos;
    pos += mini_count;
    for (uint64_t i = 0; i < mini_count; i++) {
      int64_t remaining = n_deltas - produced;
      if (remaining <= 0) continue;  // unused trailing miniblock: no payload
      int w = wb[i];
      if (w > nbits) return -1;
      uint64_t payload = (mini_len / 8) * static_cast<uint64_t>(w);
      if (pos + payload > src_len) return -1;
      if (m >= max_entries) return -2;
      widths[m] = static_cast<uint32_t>(w);
      byte_starts[m] = static_cast<int64_t>(pos);
      out_starts[m] = static_cast<int32_t>(produced);
      mins[m] = min_delta;
      m++;
      pos += payload;
      produced += remaining < static_cast<int64_t>(mini_len)
                      ? remaining : static_cast<int64_t>(mini_len);
    }
  }
  *consumed = static_cast<int64_t>(pos);
  return static_cast<ssize_t>(m);
}

// ---------------------------------------------------------------------------
// Thrift compact-protocol PageHeader parser (one header per page — the hot
// metadata path, SURVEY §7.3.6). Unknown/unneeded fields (statistics) are
// skipped by wire type exactly like generated Thrift readers.
// ---------------------------------------------------------------------------

namespace {

struct CpReader {
  const uint8_t* src;
  size_t len;
  size_t pos;
  bool truncated;  // ran off the window (retry with a larger peek)
};

inline bool cp_byte(CpReader* r, uint8_t* out) {
  if (r->pos >= r->len) { r->truncated = true; return false; }
  *out = r->src[r->pos++];
  return true;
}

inline bool cp_uvarint(CpReader* r, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  for (;;) {
    uint8_t b;
    if (!cp_byte(r, &b)) return false;
    if (shift > 63) return false;
    v |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = v;
  return true;
}

inline bool cp_zigzag(CpReader* r, int64_t* out) {
  uint64_t u;
  if (!cp_uvarint(r, &u)) return false;
  *out = static_cast<int64_t>((u >> 1) ^ (~(u & 1) + 1));
  return true;
}

bool cp_skip(CpReader* r, int wire, int depth);

// Skip the fields of a struct up to and including STOP.
bool cp_skip_struct(CpReader* r, int depth) {
  if (depth > 16) return false;
  for (;;) {
    uint8_t fh;
    if (!cp_byte(r, &fh)) return false;
    if (fh == 0) return true;  // STOP
    if (!(fh >> 4)) {          // long form: explicit zigzag field id
      int64_t fid;
      if (!cp_zigzag(r, &fid)) return false;
    }
    if (!cp_skip(r, fh & 0x0F, depth)) return false;
  }
}

bool cp_skip(CpReader* r, int wire, int depth) {
  if (depth > 16) return false;
  uint64_t u;
  int64_t s;
  uint8_t b;
  switch (wire) {
    case 1: case 2: return true;        // bool true/false: value in type nibble
    case 3: return cp_byte(r, &b);      // byte
    case 4: case 5: case 6:             // i16/i32/i64: zigzag varint
      return cp_zigzag(r, &s);
    case 7:                             // double: 8 bytes
      if (r->pos + 8 > r->len) { r->truncated = true; return false; }
      r->pos += 8;
      return true;
    case 8:                             // binary: len + bytes
      if (!cp_uvarint(r, &u)) return false;
      // Subtraction form: pos <= len is invariant, so len-pos cannot
      // underflow, and a near-2^64 u cannot wrap the addition-form check.
      if (u > r->len - r->pos) { r->truncated = true; return false; }
      r->pos += u;
      return true;
    case 9: case 10: {                  // list/set: (size<<4)|etype
      if (!cp_byte(r, &b)) return false;
      uint64_t n = b >> 4;
      int etype = b & 0x0F;
      if (n == 15 && !cp_uvarint(r, &n)) return false;
      // Preflight size guard: every element occupies >= 1 wire byte, EXCEPT
      // bool (kind 1/2), whose cp_skip consumes nothing — a lying count
      // there would spin this loop for up to 2^64 iterations (a hang, not
      // an overread). pos <= len is invariant, so len-pos cannot underflow.
      if (n > r->len - r->pos) { r->truncated = true; return false; }
      if (etype == 1 || etype == 2) {   // bool list: 1 byte per element
        r->pos += n;
        return true;
      }
      for (uint64_t i = 0; i < n; i++)
        if (!cp_skip(r, etype, depth + 1)) return false;
      return true;
    }
    case 11: {                          // map: size==0 -> empty, else kv types
      if (!cp_uvarint(r, &u)) return false;
      if (u == 0) return true;
      if (!cp_byte(r, &b)) return false;
      // Same hang guard as list/set: a bool key/value type would make each
      // iteration consume zero bytes, so an adversarial count must be
      // rejected against the remaining window up front.
      if (u > r->len - r->pos) { r->truncated = true; return false; }
      int kt = b >> 4, vt = b & 0x0F;
      for (uint64_t i = 0; i < u; i++) {
        // map bool keys/values occupy one byte each on the wire (unlike
        // bool STRUCT fields, whose value rides the field header)
        if (kt == 1 || kt == 2) {
          if (r->pos >= r->len) { r->truncated = true; return false; }
          r->pos++;
        } else if (!cp_skip(r, kt, depth + 1)) {
          return false;
        }
        if (vt == 1 || vt == 2) {
          if (r->pos >= r->len) { r->truncated = true; return false; }
          r->pos++;
        } else if (!cp_skip(r, vt, depth + 1)) {
          return false;
        }
      }
      return true;
    }
    case 12: return cp_skip_struct(r, depth + 1);
    default: return false;              // unknown wire type: corrupt
  }
}

// Parse one nested header struct, keeping declared fields into keep[fid-1].
// kinds[fid-1] gives the declared type: 'i' int (i16/i32/i64), 'b' bool.
// A field whose wire type mismatches its declaration is skipped by wire type
// (left absent), matching the Python reader's _wire_matches discipline.
bool cp_parse_flat_struct(CpReader* r, int64_t* keep, const char* kinds,
                          int n_keep) {
  int64_t fid = 0;
  for (;;) {
    uint8_t fh;
    if (!cp_byte(r, &fh)) return false;
    if (fh == 0) return true;
    int delta = fh >> 4;
    int wire = fh & 0x0F;
    if (delta) fid += delta;
    else if (!cp_zigzag(r, &fid)) return false;
    char kind = (fid >= 1 && fid <= n_keep) ? kinds[fid - 1] : 0;
    if (kind == 'b' && (wire == 1 || wire == 2)) {
      keep[fid - 1] = (wire == 1) ? 1 : 0;
    } else if (kind == 'i' && wire == 5) {  // exact CT_I32, like _wire_matches
      int64_t v;
      if (!cp_zigzag(r, &v)) return false;
      keep[fid - 1] = v;
    } else {
      if (!cp_skip(r, wire, 0)) return false;
    }
  }
}

}  // namespace

// Slot layout of out[28] (absent = INT64_MIN):
//   0 consumed bytes         1 type    2 uncompressed_size  3 compressed_size
//   4 crc
//   5 v1 present   6..9   v1 {num_values, encoding, def_enc, rep_enc}
//  10 dict present 11..13 dict {num_values, encoding, is_sorted}
//  14 v2 present   15..21 v2 {num_values, num_nulls, num_rows, encoding,
//                             def_len, rep_len, is_compressed}
//  22 index present
// Returns 0 on success, -1 corrupt, -2 window truncated (retry larger).
ssize_t ptq_parse_page_header(const uint8_t* src, size_t src_len, int64_t* out) {
  const int64_t ABSENT = INT64_MIN;
  for (int i = 0; i < 23; i++) out[i] = ABSENT;
  CpReader r{src, src_len, 0, false};
  int64_t fid = 0;
  for (;;) {
    uint8_t fh;
    if (!cp_byte(&r, &fh)) return r.truncated ? -2 : -1;
    if (fh == 0) break;  // STOP
    int delta = fh >> 4;
    int wire = fh & 0x0F;
    if (delta) fid += delta;
    else if (!cp_zigzag(&r, &fid)) return r.truncated ? -2 : -1;
    bool ok = true;
    if (fid >= 1 && fid <= 4 && wire == 5) {  // all i32 fields: exact CT_I32
      int64_t v;
      ok = cp_zigzag(&r, &v);
      if (ok) out[fid] = v;
    } else if (fid == 5 && wire == 12) {
      int64_t keep[4] = {ABSENT, ABSENT, ABSENT, ABSENT};
      ok = cp_parse_flat_struct(&r, keep, "iiii", 4);
      if (ok) { out[5] = 1; for (int i = 0; i < 4; i++) out[6 + i] = keep[i]; }
    } else if (fid == 6 && wire == 12) {
      ok = cp_skip_struct(&r, 1);
      if (ok) out[22] = 1;
    } else if (fid == 7 && wire == 12) {
      int64_t keep[3] = {ABSENT, ABSENT, ABSENT};
      ok = cp_parse_flat_struct(&r, keep, "iib", 3);
      if (ok) { out[10] = 1; for (int i = 0; i < 3; i++) out[11 + i] = keep[i]; }
    } else if (fid == 8 && wire == 12) {
      int64_t keep[7] = {ABSENT, ABSENT, ABSENT, ABSENT, ABSENT, ABSENT, ABSENT};
      ok = cp_parse_flat_struct(&r, keep, "iiiiiib", 7);
      if (ok) { out[14] = 1; for (int i = 0; i < 7; i++) out[15 + i] = keep[i]; }
    } else {
      ok = cp_skip(&r, wire, 0);
    }
    if (!ok) return r.truncated ? -2 : -1;
  }
  out[0] = static_cast<int64_t>(r.pos);
  return 0;
}

// ---------------------------------------------------------------------------
// Whole-chunk prepare walk (one native call per chunk).
//
// The per-page Python loop (header parse -> decompress -> level decode ->
// prescan -> route) is the dominant host cost of the device decode pipeline
// on wide files (reference page walk: chunk_reader.go:182-263). This fuses
// the entire walk: the caller hands the chunk's bytes plus output buffers
// and gets back packed per-page tables ready for vectorized batch assembly.
// Any input the walk cannot handle (unknown codec, corrupt stream, capacity
// overflow) returns a negative code and the caller falls back to the Python
// walk, which reproduces the exact error semantics.
// ---------------------------------------------------------------------------

namespace {

// gzip/zlib inflate with exact-size output (bomb guard: an output larger than
// `expect` fails instead of allocating; mirrors core/compress.py _Gzip).
bool gzip_inflate(const uint8_t* src, size_t src_len, uint8_t* dst, size_t expect) {
  z_stream s;
  std::memset(&s, 0, sizeof(s));
  if (inflateInit2(&s, 15 + 32) != Z_OK) return false;  // auto gzip/zlib header
  s.next_in = const_cast<Bytef*>(src);
  s.avail_in = static_cast<uInt>(src_len);
  s.next_out = dst;
  s.avail_out = static_cast<uInt>(expect);
  int rc = inflate(&s, Z_FINISH);
  bool ok = (rc == Z_STREAM_END && s.total_out == expect && s.avail_in == 0);
  inflateEnd(&s);
  return ok;
}

inline int level_bit_width(int max_level) {
  int w = 0;
  while (max_level) { w++; max_level >>= 1; }  // bit_length
  return w;
}

// Decompress one page block into scratch. Returns 0 ok, -1 corrupt/unknown
// codec, -5 scratch too small (same code contract as ptq_chunk_prepare).
int decompress_page(int codec, const uint8_t* src, size_t src_len,
                    uint8_t* scratch, size_t scratch_cap, size_t expect) {
  if (expect > scratch_cap) return -5;
  if (codec == 1) {
    // pass the PHYSICAL capacity: chunk_prepare allocates scratch with
    // >= 64 bytes of slack past the chunk's uncompressed size, which
    // switches the decoder into overshooting fast mode; the result is
    // still validated against the page's claimed size
    if (ptq_snappy_decompress(reinterpret_cast<const char*>(src), src_len,
                              reinterpret_cast<char*>(scratch), scratch_cap) !=
        static_cast<ssize_t>(expect))
      return -1;
    return 0;
  }
  if (codec == 2) return gzip_inflate(src, src_len, scratch, expect) ? 0 : -1;
  if (codec == 5)  // legacy LZ4: hadoop framing with raw-block fallback
    return ptq_lz4_hadoop_decompress(reinterpret_cast<const char*>(src),
                                     src_len, reinterpret_cast<char*>(scratch),
                                     expect) == static_cast<ssize_t>(expect)
               ? 0
               : -1;
  if (codec == 7)  // LZ4_RAW: one raw block
    return ptq_lz4_decompress(reinterpret_cast<const char*>(src), src_len,
                              reinterpret_cast<char*>(scratch), expect) ==
                   static_cast<ssize_t>(expect)
               ? 0
               : -1;
  return -1;
}

// Hybrid-decode a level stream into uint16, validating every value
// <= max_level (parity with ops/levels.py _check) and counting values equal
// to `target`. Returns bytes consumed, or -1 on corrupt input.
ssize_t decode_levels16(const uint8_t* src, size_t src_len, int64_t n,
                        int max_level, uint16_t* out, int target,
                        int64_t* eq_count) {
  const int width = level_bit_width(max_level);
  const size_t vbytes = (width + 7) / 8;
  size_t pos = 0;
  int64_t produced = 0;
  int64_t eq = 0;
  while (produced < n) {
    uint64_t header = 0;
    int shift = 0;
    for (;;) {
      if (pos >= src_len || shift > 63) return -1;
      uint8_t b = src[pos++];
      if (shift == 63 && (b & 0x7e)) return -1;
      header |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    if (header & 1) {
      uint64_t groups = header >> 1;
      if (groups == 0 || groups > (1ull << 40)) return -1;
      uint64_t count = groups * 8;
      uint64_t nbytes = groups * static_cast<uint64_t>(width);
      if (pos + nbytes > src_len) return -1;
      int64_t take = n - produced;
      if (static_cast<uint64_t>(take) > count) take = static_cast<int64_t>(count);
      if (width <= 4 && (8 % width) == 0) {
        // levels are almost always width 1 or 2: unpack whole bytes instead
        // of feeding a bit reader one value at a time (the nested-column
        // hot loop — every leaf value decodes max_rep + max_def levels)
        const int per = 8 / width;
        const uint16_t mask = static_cast<uint16_t>((1u << width) - 1);
        const uint8_t* bp = src + pos;
        uint16_t* op = out + produced;
        int64_t full = take / per;
        uint64_t bad = 0;
        for (int64_t b = 0; b < full; b++) {
          uint16_t byte = bp[b];
          for (int j = 0; j < per; j++) {
            uint16_t v = (byte >> (j * width)) & mask;
            op[b * per + j] = v;
            bad |= (v > max_level);
            eq += (v == target);
          }
        }
        for (int64_t i = full * per; i < take; i++) {
          uint16_t v = (bp[i / per] >> ((i % per) * width)) & mask;
          op[i] = v;
          bad |= (v > max_level);
          eq += (v == target);
        }
        if (bad) return -1;
      } else {
        BitReader r;
        br_init(&r, src + pos, nbytes);
        for (int64_t i = 0; i < take; i++) {
          uint64_t v = br_read(&r, width);
          if (v > static_cast<uint64_t>(max_level)) return -1;
          out[produced + i] = static_cast<uint16_t>(v);
          eq += (static_cast<int>(v) == target);
        }
      }
      pos += nbytes;
      produced += take;
    } else {
      uint64_t count = header >> 1;
      if (count == 0 || count > (1ull << 40) || pos + vbytes > src_len) return -1;
      uint64_t v = 0;
      for (size_t i = 0; i < vbytes; i++) v |= static_cast<uint64_t>(src[pos + i]) << (8 * i);
      if (width < 64 && v >= (1ull << width)) return -1;
      if (v > static_cast<uint64_t>(max_level)) return -1;
      pos += vbytes;
      int64_t take = n - produced;
      if (static_cast<uint64_t>(take) > count) take = static_cast<int64_t>(count);
      uint16_t v16 = static_cast<uint16_t>(v);
      for (int64_t i = 0; i < take; i++) out[produced + i] = v16;
      if (static_cast<int>(v) == target) eq += take;
      produced += take;
    }
  }
  if (eq_count) *eq_count = eq;
  return static_cast<ssize_t>(pos);
}

// Per-stage wall clock for the whole-chunk walk. All accounting is skipped
// when the caller passes no stage array (ns == nullptr): production calls pay
// one branch per stage boundary, the bench pays ~25 ns per clock_gettime.
struct StageClock {
  int64_t* ns;
  int64_t t0;
  static inline int64_t now() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<int64_t>(ts.tv_sec) * 1000000000ll + ts.tv_nsec;
  }
  inline void start() {
    if (ns) t0 = now();
  }
  inline void stop(int slot) {
    if (ns) {
      int64_t t = now();
      ns[slot] += t - t0;
      t0 = t;
    }
  }
};

// stage_ns slots (accumulated nanoseconds)
enum { ST_DECOMPRESS = 0, ST_LEVELS = 1, ST_PRESCAN = 2, ST_COPY = 3, ST_CRC = 4 };

}  // namespace

// Page-table column layout (int64[n_pages][18]); absent fields are 0 unless
// noted. Routes: 0 host-decoded ("other"), 1 dict indices (hybrid run table),
// 2 delta-bp (miniblock table), 3 PLAIN numeric (bytes in values_out),
// 4 empty (no non-null values).
enum {
  PC_KIND = 0,      // 0 data page, 1 dictionary page, 2 index page
  PC_N = 1,         // num_values incl. nulls
  PC_NONNULL = 2,
  PC_ENC = 3,
  PC_ROUTE = 4,
  PC_VOFF = 5,      // offset of this page's value bytes in values_out
  PC_VLEN = 6,
  PC_LVLBASE = 7,   // start index of this page's levels in def_out/rep_out
  PC_RUNS = 8,      // first hybrid run index (route 1)
  PC_RUNE = 9,
  PC_PACKS = 10,    // packed_out byte range of this page's bit-packed payloads
  PC_PACKE = 11,
  PC_MINIS = 12,    // first delta miniblock entry (route 2)
  PC_MINIE = 13,
  PC_DSTART = 14,   // delta_out byte offset of this page's stream
  PC_DCONS = 15,    // bytes of delta stream consumed
  PC_EXTRA = 16,    // route 1: dict index bit width; route 2: stream total
  PC_DFIRST = 17,   // route 2: first value (uint64 bit pattern)
};
#define PT_COLS 18

// Returns n_pages >= 0 on success. Negative: -1 corrupt/unsupported (caller
// falls back to the Python walk for exact errors), -2 page table full,
// -3 hybrid run table full, -4 delta miniblock table full, -5 level/value
// capacity exceeded (metadata understated the chunk), -6 stored page CRC
// mismatch (validate_crc only; definite corruption, not "unsupported").
// err_info (nullable int64[4]) reports {stage, page index, page byte offset
// in the chunk, 0} for any negative return — the structured error channel
// parquet-tool verify and the fallback-ladder counters consume.
ssize_t ptq_chunk_prepare(
    const uint8_t* src, size_t src_len,
    int codec,               // 0 UNCOMPRESSED, 1 SNAPPY, 2 GZIP
    int validate_crc,        // nonzero: verify stored page CRCs in the walk
    int max_def, int max_rep,
    int type_size,           // PLAIN itemsize for numeric types, else 0
    int delta_nbits,         // 32/64 when delta-bp is device-eligible, else 0
    int64_t expected_values, // level buffer capacity (metadata num_values)
    int64_t* pages, size_t max_pages,
    uint16_t* def_out, uint16_t* rep_out,
    uint8_t* values_out, size_t values_cap,
    uint8_t* packed_out, size_t packed_cap,
    uint8_t* delta_out, size_t delta_cap,
    uint8_t* scratch, size_t scratch_cap,
    uint8_t* h_is_rle, int64_t* h_counts, uint64_t* h_values,
    int64_t* h_byteoff, size_t max_runs,
    uint32_t* d_widths, int64_t* d_bytestart, int32_t* d_outstart,
    uint64_t* d_mins, size_t max_minis,
    int64_t* totals, /* [8]: lvl_total, values_used, packed_used, delta_used,
                        runs, minis, has_dict, reserved */
    int64_t* stage_ns, /* nullable [5]: accumulated ns per stage (decompress,
                          levels, prescan, copy, crc) for the bench breakdown */
    int64_t* err_info /* nullable [4]: see above */) {
  StageClock clk{stage_ns, 0};
  size_t pos = 0;
  size_t n_pages = 0;
  int64_t lvl_total = 0;
  size_t values_used = 0, packed_used = 0, delta_used = 0;
  size_t runs = 0, minis = 0;
  bool has_dict = false;
  int64_t slots[23];
  // Failure-context tracking: the walk keeps err[] current (stage, page,
  // page byte offset) so every `return negative` below reports where it
  // died without threading the detail through dozens of return sites.
  int64_t err_local[4];
  int64_t* err = err_info ? err_info : err_local;
  err[0] = PTQ_STAGE_NONE; err[1] = 0; err[2] = 0; err[3] = 0;

  while (pos < src_len) {
    err[0] = PTQ_STAGE_HEADER;
    err[1] = static_cast<int64_t>(n_pages);
    err[2] = static_cast<int64_t>(pos);
    ssize_t hrc = ptq_parse_page_header(src + pos, src_len - pos, slots);
    if (hrc != 0) return -1;  // truncated-within-chunk IS corrupt here
    size_t hlen = static_cast<size_t>(slots[0]);
    int64_t psize = slots[3];
    if (psize < 0 || pos + hlen + static_cast<uint64_t>(psize) > src_len) return -1;
    int64_t usize = slots[2] == INT64_MIN ? 0 : slots[2];
    if (usize < 0) return -1;
    const uint8_t* payload = src + pos + hlen;
    size_t payload_len = static_cast<size_t>(psize);
    pos += hlen + payload_len;
    if (n_pages >= max_pages) return -2;
    if (validate_crc && slots[4] != INT64_MIN) {
      // CRC over the page payload EXACTLY as stored (V1: the compressed
      // block; V2: raw rep+def level streams + compressed values) — the
      // parquet-format contract, byte-for-byte what core/chunk._check_crc
      // computes on the staged path.
      err[0] = PTQ_STAGE_CRC;
      clk.start();
      uLong crc = crc32(0L, Z_NULL, 0);
      size_t off = 0;
      while (off < payload_len) {
        size_t take = payload_len - off;
        if (take > (1u << 30)) take = 1u << 30;  // uInt-safe chunks
        crc = crc32(crc, payload + off, static_cast<uInt>(take));
        off += take;
      }
      clk.stop(ST_CRC);
      if (static_cast<uint32_t>(crc) !=
          static_cast<uint32_t>(static_cast<int64_t>(slots[4])))
        return PTQ_E_CRC;
    }
    int64_t* P = pages + n_pages * PT_COLS;
    std::memset(P, 0, PT_COLS * sizeof(int64_t));

    int64_t ptype = slots[1];
    if (ptype == 2) {  // DICTIONARY_PAGE
      // Must be the FIRST page: later routes assume their values_out regions
      // are contiguous, and a mid-chunk dict page would interleave. The spec
      // puts it first; anything else takes the Python walk.
      if (has_dict || n_pages != 0 || slots[10] != 1) return -1;
      has_dict = true;
      const uint8_t* block = payload;
      size_t block_len = payload_len;
      if (codec != 0) {
        err[0] = PTQ_STAGE_DECOMPRESS;
        clk.start();
        int rc = decompress_page(codec, payload, payload_len, scratch,
                                 scratch_cap, static_cast<size_t>(usize));
        clk.stop(ST_DECOMPRESS);
        if (rc != 0) return rc;
      }
      err[0] = PTQ_STAGE_VALUES;
      if (codec != 0) {
        block = scratch;
        block_len = static_cast<size_t>(usize);
      }
      if (values_used + block_len > values_cap) return -5;
      clk.start();
      std::memcpy(values_out + values_used, block, block_len);
      clk.stop(ST_COPY);
      P[PC_KIND] = 1;
      P[PC_N] = slots[11] == INT64_MIN ? 0 : slots[11];  // dict num_values
      P[PC_ENC] = slots[12] == INT64_MIN ? 0 : slots[12];
      P[PC_VOFF] = static_cast<int64_t>(values_used);
      P[PC_VLEN] = static_cast<int64_t>(block_len);
      values_used += block_len;
      n_pages++;
      continue;
    }
    if (ptype == 1) {  // INDEX_PAGE: skipped (parity with the Python walk)
      P[PC_KIND] = 2;
      n_pages++;
      continue;
    }
    if (ptype != 0 && ptype != 3) return -1;

    // -- data page: levels ---------------------------------------------------
    int64_t n, enc;
    const uint8_t* vsrc;      // value stream start
    size_t vlen;              // value stream length
    int64_t non_null;
    if (ptype == 0) {  // DATA_PAGE (V1): block = levels + values, compressed whole
      if (slots[5] != 1) return -1;
      n = slots[6] == INT64_MIN ? 0 : slots[6];
      enc = slots[7] == INT64_MIN ? -1 : slots[7];
      if (n < 0) return -1;
      const uint8_t* block = payload;
      size_t block_len = payload_len;
      if (codec != 0) {
        // level-free PLAIN numeric pages decompress STRAIGHT into their
        // final values_out slot: no scratch bounce, no second multi-MB
        // memcpy (the PLAIN route below detects the in-place block)
        uint8_t* dst = scratch;
        size_t dcap = scratch_cap;
        if (enc == 0 && type_size > 0 && max_rep == 0 && max_def == 0 &&
            values_used + static_cast<uint64_t>(usize) <= values_cap) {
          dst = values_out + values_used;
          dcap = values_cap - values_used;
        }
        err[0] = PTQ_STAGE_DECOMPRESS;
        clk.start();
        int rc = decompress_page(codec, payload, payload_len, dst, dcap,
                                 static_cast<size_t>(usize));
        clk.stop(ST_DECOMPRESS);
        if (rc != 0) return rc;
        block = dst;
        block_len = static_cast<size_t>(usize);
      }
      size_t cur = 0;
      err[0] = PTQ_STAGE_LEVELS;
      if (lvl_total + n > expected_values) return -5;
      clk.start();
      if (max_rep > 0) {
        if (block_len < cur + 4) return -1;
        uint32_t sz;
        std::memcpy(&sz, block + cur, 4);
        if (cur + 4 + sz > block_len) return -1;
        ssize_t used = decode_levels16(block + cur + 4, sz, n, max_rep,
                                       rep_out + lvl_total, -1, nullptr);
        if (used < 0) return -1;
        cur += 4 + sz;
      }
      non_null = n;
      if (max_def > 0) {
        if (block_len < cur + 4) return -1;
        uint32_t sz;
        std::memcpy(&sz, block + cur, 4);
        if (cur + 4 + sz > block_len) return -1;
        int64_t eq = 0;
        ssize_t used = decode_levels16(block + cur + 4, sz, n, max_def,
                                       def_out + lvl_total, max_def, &eq);
        if (used < 0) return -1;
        cur += 4 + sz;
        non_null = eq;
      }
      clk.stop(ST_LEVELS);
      err[0] = PTQ_STAGE_VALUES;
      vsrc = block + cur;
      vlen = block_len - cur;
    } else {  // DATA_PAGE_V2: levels raw, values optionally compressed
      if (slots[14] != 1) return -1;
      n = slots[15] == INT64_MIN ? 0 : slots[15];
      enc = slots[18] == INT64_MIN ? -1 : slots[18];
      if (n < 0) return -1;
      int64_t def_len = slots[19] == INT64_MIN ? 0 : slots[19];
      int64_t rep_len = slots[20] == INT64_MIN ? 0 : slots[20];
      int64_t is_comp = slots[21];  // absent -> compressed (parity: None => true)
      if (def_len < 0 || rep_len < 0 ||
          static_cast<uint64_t>(def_len) + static_cast<uint64_t>(rep_len) >
              payload_len)
        return -1;
      err[0] = PTQ_STAGE_LEVELS;
      if (lvl_total + n > expected_values) return -5;
      clk.start();
      if (max_rep > 0) {
        if (decode_levels16(payload, static_cast<size_t>(rep_len), n, max_rep,
                            rep_out + lvl_total, -1, nullptr) < 0)
          return -1;
      }
      non_null = n;
      if (max_def > 0) {
        int64_t eq = 0;
        if (decode_levels16(payload + rep_len, static_cast<size_t>(def_len), n,
                            max_def, def_out + lvl_total, max_def, &eq) < 0)
          return -1;
        non_null = eq;
      }
      // FLAT columns only: the V2 header's num_nulls must agree with the
      // decoded levels (parity with decode_data_page_v2's cross-check; for
      // repeated columns foreign writers count nulls differently, so the
      // levels are the only trustworthy source there). A mismatch means the
      // header or the level stream is lying — corrupt, not unsupported.
      if (max_rep == 0 && max_def > 0 && slots[16] != INT64_MIN &&
          n - non_null != slots[16])
        return -1;
      clk.stop(ST_LEVELS);
      const uint8_t* vreg = payload + rep_len + def_len;
      size_t vreg_len = payload_len - static_cast<size_t>(rep_len + def_len);
      if (codec != 0 && (is_comp == INT64_MIN || is_comp != 0)) {
        int64_t vexpect = usize - rep_len - def_len;
        if (vexpect < 0) vexpect = 0;
        // V2 keeps levels outside the compressed region, so PLAIN numeric
        // values can always land directly in values_out (see V1 note)
        uint8_t* dst = scratch;
        size_t dcap = scratch_cap;
        if (enc == 0 && type_size > 0 &&
            values_used + static_cast<uint64_t>(vexpect) <= values_cap) {
          dst = values_out + values_used;
          dcap = values_cap - values_used;
        }
        err[0] = PTQ_STAGE_DECOMPRESS;
        clk.start();
        int rc = decompress_page(codec, vreg, vreg_len, dst, dcap,
                                 static_cast<size_t>(vexpect));
        clk.stop(ST_DECOMPRESS);
        if (rc != 0) return rc;
        vsrc = dst;
        vlen = static_cast<size_t>(vexpect);
      } else {
        vsrc = vreg;
        vlen = vreg_len;
      }
      err[0] = PTQ_STAGE_VALUES;
    }

    P[PC_KIND] = 0;
    P[PC_N] = n;
    P[PC_NONNULL] = non_null;
    P[PC_ENC] = enc;
    P[PC_LVLBASE] = lvl_total;
    lvl_total += n;

    // -- route the value stream ---------------------------------------------
    if (enc == 8 || enc == 2) {  // RLE_DICTIONARY / PLAIN_DICTIONARY
      if (!has_dict) return -1;
      if (non_null == 0) {
        P[PC_ROUTE] = 4;
        n_pages++;
        continue;
      }
      if (vlen < 1) return -1;
      int width = vsrc[0];
      if (width > 32) return -1;
      const uint8_t* stream = vsrc + 1;
      size_t stream_len = vlen - 1;
      // Inline prescan: clamp counts so the page contributes exactly
      // non_null outputs; copy bit-packed payloads (only) into packed_out so
      // batch bit offsets are global (mirrors prescan_hybrid's compaction +
      // _HybridBatch.add_page's clamping in one pass).
      const size_t vbytes = (width + 7) / 8;
      size_t spos = 0;
      int64_t produced = 0;
      size_t run0 = runs, pack0 = packed_used;
      err[0] = PTQ_STAGE_PRESCAN;
      clk.start();
      while (produced < non_null) {
        uint64_t header = 0;
        int shift = 0;
        for (;;) {
          if (spos >= stream_len || shift > 63) return -1;
          uint8_t b = stream[spos++];
          if (shift == 63 && (b & 0x7e)) return -1;
          header |= static_cast<uint64_t>(b & 0x7f) << shift;
          if (!(b & 0x80)) break;
          shift += 7;
        }
        if (runs >= max_runs) return -3;
        int64_t take;
        if (header & 1) {
          uint64_t groups = header >> 1;
          if (groups == 0 || groups > (1ull << 40)) return -1;
          uint64_t count = groups * 8;
          uint64_t nbytes = groups * static_cast<uint64_t>(width);
          if (spos + nbytes > stream_len) return -1;
          take = non_null - produced;
          if (static_cast<uint64_t>(take) > count) take = static_cast<int64_t>(count);
          if (packed_used + nbytes > packed_cap) return -5;
          std::memcpy(packed_out + packed_used, stream + spos, nbytes);
          h_is_rle[runs] = 0;
          h_counts[runs] = take;
          h_values[runs] = 0;
          h_byteoff[runs] = static_cast<int64_t>(packed_used);
          packed_used += nbytes;
          spos += nbytes;
        } else {
          uint64_t count = header >> 1;
          if (count == 0 || count > (1ull << 40) || spos + vbytes > stream_len)
            return -1;
          uint64_t v = 0;
          for (size_t i = 0; i < vbytes; i++)
            v |= static_cast<uint64_t>(stream[spos + i]) << (8 * i);
          if (width < 64 && v >= (1ull << width)) return -1;
          spos += vbytes;
          take = non_null - produced;
          if (static_cast<uint64_t>(take) > count) take = static_cast<int64_t>(count);
          h_is_rle[runs] = 1;
          h_counts[runs] = take;
          h_values[runs] = v;
          h_byteoff[runs] = 0;
        }
        runs++;
        produced += take;
      }
      clk.stop(ST_PRESCAN);
      P[PC_ROUTE] = 1;
      P[PC_RUNS] = static_cast<int64_t>(run0);
      P[PC_RUNE] = static_cast<int64_t>(runs);
      P[PC_PACKS] = static_cast<int64_t>(pack0);
      P[PC_PACKE] = static_cast<int64_t>(packed_used);
      P[PC_EXTRA] = width;
    } else if (enc == 5 && delta_nbits != 0) {  // DELTA_BINARY_PACKED
      uint64_t first = 0;
      int64_t total = 0, consumed = 0;
      size_t mini0 = minis;
      // prescan against max_minis - minis remaining slots
      err[0] = PTQ_STAGE_PRESCAN;
      clk.start();
      ssize_t m = ptq_prescan_delta_packed(
          vsrc, vlen, delta_nbits, non_null, d_widths + minis,
          d_bytestart + minis, d_outstart + minis, d_mins + minis,
          max_minis - minis, &first, &total, &consumed);
      clk.stop(ST_PRESCAN);
      if (m == -2) return -4;
      if (m < 0) return -1;
      err[0] = PTQ_STAGE_VALUES;
      // byte starts are relative to the page's stream: rebase into delta_out
      if (delta_used + static_cast<size_t>(consumed) > delta_cap) return -5;
      clk.start();
      std::memcpy(delta_out + delta_used, vsrc, static_cast<size_t>(consumed));
      clk.stop(ST_COPY);
      for (ssize_t i = 0; i < m; i++)
        d_bytestart[mini0 + i] += static_cast<int64_t>(delta_used);
      P[PC_ROUTE] = 2;
      P[PC_MINIS] = static_cast<int64_t>(mini0);
      P[PC_MINIE] = static_cast<int64_t>(mini0 + m);
      P[PC_DSTART] = static_cast<int64_t>(delta_used);
      P[PC_DCONS] = consumed;
      P[PC_EXTRA] = total;
      P[PC_DFIRST] = static_cast<int64_t>(first);
      delta_used += static_cast<size_t>(consumed);
      minis += static_cast<size_t>(m);
    } else if (enc == 0 && type_size > 0) {  // PLAIN numeric
      size_t need = static_cast<size_t>(non_null) * type_size;
      if (vlen < need) return -1;  // "plain payload too short"
      if (values_used + need > values_cap) return -5;
      if (vsrc != values_out + values_used) {  // direct decompress: in place
        clk.start();
        std::memcpy(values_out + values_used, vsrc, need);
        clk.stop(ST_COPY);
      }
      P[PC_ROUTE] = 3;
      P[PC_VOFF] = static_cast<int64_t>(values_used);
      P[PC_VLEN] = static_cast<int64_t>(need);
      values_used += need;
    } else if (enc == 9 && type_size == 4) {  // BYTE_STREAM_SPLIT, 4-byte
      // Ship the page's interleaved streams RAW (route 5): the transpose is
      // pure layout, and the device does it as a reshape+transpose for free
      // — the host never strides over the bytes at all. 8-byte BSS stays
      // host-side below (TPU x64 emulation cannot bitcast u8x8 lanes).
      size_t need = static_cast<size_t>(non_null) * type_size;
      if (vlen < need) return -1;
      if (values_used + need > values_cap) return -5;
      if (vsrc != values_out + values_used) {
        clk.start();
        std::memcpy(values_out + values_used, vsrc, need);
        clk.stop(ST_COPY);
      }
      P[PC_ROUTE] = 5;
      P[PC_VOFF] = static_cast<int64_t>(values_used);
      P[PC_VLEN] = static_cast<int64_t>(need);
      values_used += need;
    } else if (enc == 9 && type_size > 0) {  // BYTE_STREAM_SPLIT, 8-byte
      // De-interleave the byte streams back to PLAIN little-endian layout
      // in one strided pass; the page then rides the PLAIN device route
      // (the transform is pure layout, so doing it here keeps byte-identity
      // with the host decoder for free).
      size_t need = static_cast<size_t>(non_null) * type_size;
      if (vlen < need) return -1;
      if (values_used + need > values_cap) return -5;
      uint8_t* dstv = values_out + values_used;
      const size_t nn = static_cast<size_t>(non_null);
      clk.start();
      for (int b = 0; b < type_size; b++) {
        const uint8_t* sp = vsrc + static_cast<size_t>(b) * nn;
        for (size_t i = 0; i < nn; i++) dstv[i * type_size + b] = sp[i];
      }
      clk.stop(ST_COPY);
      P[PC_ROUTE] = 3;
      P[PC_VOFF] = static_cast<int64_t>(values_used);
      P[PC_VLEN] = static_cast<int64_t>(need);
      values_used += need;
    } else {  // anything else: stream bytes for the Python host decoder
      if (values_used + vlen > values_cap) return -5;
      clk.start();
      std::memcpy(values_out + values_used, vsrc, vlen);
      clk.stop(ST_COPY);
      P[PC_ROUTE] = 0;
      P[PC_VOFF] = static_cast<int64_t>(values_used);
      P[PC_VLEN] = static_cast<int64_t>(vlen);
      values_used += vlen;
    }
    n_pages++;
  }

  totals[0] = lvl_total;
  totals[1] = static_cast<int64_t>(values_used);
  totals[2] = static_cast<int64_t>(packed_used);
  totals[3] = static_cast<int64_t>(delta_used);
  totals[4] = static_cast<int64_t>(runs);
  totals[5] = static_cast<int64_t>(minis);
  totals[6] = has_dict ? 1 : 0;
  totals[7] = 0;
  return static_cast<ssize_t>(n_pages);
}

// ---------------------------------------------------------------------------
// Write-side encoders. Byte-identical to the NumPy reference encoders in
// ops/rle_hybrid.py / ops/delta.py (the roundtrip + conformance suites are
// the oracle); these exist because the encode loops were the write path's
// dominant cost (reference hot loops: hybrid_encoder.go:55-70,
// deltabp_encoder.go:58-115, chunk_writer.go:174-209).
// ---------------------------------------------------------------------------

namespace {

inline bool put_uvarint(uint8_t* out, size_t cap, size_t* pos, uint64_t v) {
  while (v >= 0x80) {
    if (*pos >= cap) return false;
    out[(*pos)++] = static_cast<uint8_t>(v | 0x80);
    v >>= 7;
  }
  if (*pos >= cap) return false;
  out[(*pos)++] = static_cast<uint8_t>(v);
  return true;
}

inline bool put_zigzag(uint8_t* out, size_t cap, size_t* pos, int64_t v) {
  uint64_t u = (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
  return put_uvarint(out, cap, pos, u);
}

struct BitWriter {
  uint8_t* out;
  size_t cap;
  size_t pos;
  unsigned __int128 acc;
  int nbits;
};

inline void bw_init(BitWriter* w, uint8_t* out, size_t cap, size_t pos) {
  w->out = out; w->cap = cap; w->pos = pos; w->acc = 0; w->nbits = 0;
}

inline bool bw_push(BitWriter* w, uint64_t v, int width) {
  w->acc |= static_cast<unsigned __int128>(v) << w->nbits;
  w->nbits += width;
  while (w->nbits >= 8) {
    if (w->pos >= w->cap) return false;
    w->out[w->pos++] = static_cast<uint8_t>(w->acc);
    w->acc >>= 8;
    w->nbits -= 8;
  }
  return true;
}

inline bool bw_flush(BitWriter* w) {
  if (w->nbits > 0) {
    if (w->pos >= w->cap) return false;
    w->out[w->pos++] = static_cast<uint8_t>(w->acc);
    w->acc = 0;
    w->nbits = 0;
  }
  return true;
}

// One bit-packed segment: header (groups<<1)|1 then LSB-first payload,
// zero-padding the final partial group (mirrors _emit_bitpacked). The
// element getter is size-generic so the fused encode walk packs uint16
// level streams and uint32 dictionary indices without first widening them
// to uint64 (the widening copy of a 1M-row index column was measurable).
static inline uint64_t he_get(const void* v, int es, int64_t i) {
  switch (es) {
    case 2: return static_cast<const uint16_t*>(v)[i];
    case 4: return static_cast<const uint32_t*>(v)[i];
    default: return static_cast<const uint64_t*>(v)[i];
  }
}

static bool emit_bitpacked_any(const void* v, int es, int64_t n, int width,
                               uint8_t* out, size_t cap, size_t* pos,
                               bool* bad_value) {
  if (n == 0) return true;
  int64_t padded = (n + 7) & ~7ll;
  if (!put_uvarint(out, cap, pos, ((static_cast<uint64_t>(padded) / 8) << 1) | 1))
    return false;
  if (width <= 16) {
    // fast lane for the common widths (levels and dictionary indices):
    // a full group of 8 values occupies exactly `width` bytes, and 8*16
    // bits fit one 128-bit accumulator — pack per GROUP with a single
    // bounds check and byte-store loop instead of per-value bit pushes
    size_t p = *pos;
    if (p + static_cast<size_t>((padded / 8)) * width > cap) return false;
    int64_t full = n & ~7ll;
    const uint64_t lim = 1ull << width;
    for (int64_t g = 0; g < full; g += 8) {
      unsigned __int128 acc = 0;
      uint64_t over = 0;
      for (int k = 0; k < 8; k++) {
        uint64_t x = he_get(v, es, g + k);
        over |= x;
        acc |= static_cast<unsigned __int128>(x) << (k * width);
      }
      if (over >= lim) { *bad_value = true; return false; }
      for (int b = 0; b < width; b++) {
        out[p++] = static_cast<uint8_t>(acc);
        acc >>= 8;
      }
    }
    if (full < n) {  // trailing partial group, zero-padded to 8
      unsigned __int128 acc = 0;
      for (int64_t i = full; i < n; i++) {
        uint64_t x = he_get(v, es, i);
        if (x >= lim) { *bad_value = true; return false; }
        acc |= static_cast<unsigned __int128>(x) << ((i - full) * width);
      }
      for (int b = 0; b < width; b++) {
        out[p++] = static_cast<uint8_t>(acc);
        acc >>= 8;
      }
    }
    *pos = p;
    return true;
  }
  BitWriter w;
  bw_init(&w, out, cap, *pos);
  for (int64_t i = 0; i < n; i++) {
    uint64_t x = he_get(v, es, i);
    if (width < 64 && (x >> width)) { *bad_value = true; return false; }
    if (!bw_push(&w, x, width)) return false;
  }
  for (int64_t i = n; i < padded; i++)
    if (!bw_push(&w, 0, width)) return false;
  if (!bw_flush(&w)) return false;
  *pos = w.pos;
  return true;
}

// Element-size-generic hybrid encode core — the ONE implementation behind
// ptq_hybrid_encode (es=8) and the fused encode walk (es=2/4), so the two
// cannot drift on bytes.
static ssize_t hybrid_encode_any(const void* vals, int es, int64_t n,
                                 int width, uint8_t* out, size_t out_cap) {
  if (width < 0 || width > 64 || n < 0) return -1;
  size_t pos = 0;
  if (n == 0) return 0;
  if (width == 0) {
    if (!put_uvarint(out, out_cap, &pos, static_cast<uint64_t>(n) << 1)) return -2;
    return static_cast<ssize_t>(pos);
  }
  const int vbytes = (width + 7) / 8;
  bool bad = false;
  int64_t i = 0;
  int64_t seg = 0;  // start of the pending bit-packed segment
  while (i < n) {
    int64_t j = i + 1;
    const uint64_t cur = he_get(vals, es, i);
    while (j < n && he_get(vals, es, j) == cur) j++;
    if (j - i >= 8) {
      // 8-align the RLE window so surrounding bit-packed segments stay
      // multiples of 8 values (mid-stream padding would shift the stream)
      int64_t rle_start = (i + 7) & ~7ll;
      int64_t rle_end = j & ~7ll;
      if (rle_end - rle_start >= 8) {
        if (rle_start > seg &&
            !emit_bitpacked_any(static_cast<const uint8_t*>(vals) + seg * es,
                                es, rle_start - seg, width, out, out_cap,
                                &pos, &bad))
          return bad ? -1 : -2;
        if (width < 64 && (cur >> width)) return -1;
        if (!put_uvarint(out, out_cap, &pos,
                         static_cast<uint64_t>(rle_end - rle_start) << 1))
          return -2;
        if (pos + vbytes > out_cap) return -2;
        for (int b = 0; b < vbytes; b++)
          out[pos++] = static_cast<uint8_t>(cur >> (8 * b));
        seg = rle_end;
      }
    }
    i = j;
  }
  if (seg < n &&
      !emit_bitpacked_any(static_cast<const uint8_t*>(vals) + seg * es, es,
                          n - seg, width, out, out_cap, &pos, &bad))
    return bad ? -1 : -2;
  return static_cast<ssize_t>(pos);
}

}  // namespace

// Hybrid RLE/bit-pack encode of uint64 values at `width` bits. 8-aligned
// stretches of >=8 identical values become RLE runs, everything else is
// bit-packed in groups of 8 (mirrors ops/rle_hybrid.py encode_hybrid
// byte-for-byte). Returns bytes written, -1 on a value that does not fit
// the width, -2 if out_cap is too small.
ssize_t ptq_hybrid_encode(const uint64_t* v, int64_t n, int width,
                          uint8_t* out, size_t out_cap) {
  return hybrid_encode_any(v, 8, n, width, out, out_cap);
}

// DELTA_BINARY_PACKED encode (mirrors ops/delta.py encode_delta
// byte-for-byte, including wrapping min-delta arithmetic and zero-width
// trailing miniblocks). vals is int32[n] or int64[n] by nbits. Returns
// bytes written, -1 bad args, -2 out_cap too small.
ssize_t ptq_delta_encode(const void* vals, int64_t n, int nbits,
                         int64_t block_size, int64_t mini_count,
                         uint8_t* out, size_t out_cap) {
  if (nbits != 32 && nbits != 64) return -1;
  // mini_count capped at 512 like every decoder (and the widths[] buffer)
  if (block_size <= 0 || mini_count <= 0 || mini_count > 512 ||
      block_size % mini_count)
    return -1;
  const int64_t mini_len = block_size / mini_count;
  if (mini_len % 8) return -1;
  const uint64_t mask = (nbits == 64) ? ~0ull : ((1ull << nbits) - 1);
  const int32_t* v32 = (nbits == 32) ? static_cast<const int32_t*>(vals) : nullptr;
  const int64_t* v64 = (nbits == 64) ? static_cast<const int64_t*>(vals) : nullptr;
  auto get = [&](int64_t i) -> uint64_t {
    return (v32 ? static_cast<uint64_t>(static_cast<uint32_t>(v32[i]))
                : static_cast<uint64_t>(v64[i])) & mask;
  };
  size_t pos = 0;
  if (!put_uvarint(out, out_cap, &pos, static_cast<uint64_t>(block_size))) return -2;
  if (!put_uvarint(out, out_cap, &pos, static_cast<uint64_t>(mini_count))) return -2;
  if (!put_uvarint(out, out_cap, &pos, static_cast<uint64_t>(n))) return -2;
  uint64_t first = n ? get(0) : 0;
  int64_t sfirst = static_cast<int64_t>(first);
  if (nbits < 64 && first >= (1ull << (nbits - 1)))
    sfirst = static_cast<int64_t>(first) - (1ll << nbits);
  if (!put_zigzag(out, out_cap, &pos, sfirst)) return -2;
  if (n <= 1) return static_cast<ssize_t>(pos);

  const int64_t n_deltas = n - 1;
  // per-block delta cache: one subtraction per element instead of re-reading
  // both neighbors in every one of the three scans below (min, width, pack)
  uint64_t dstack[4096];
  uint64_t* dheap = nullptr;
  uint64_t* dbuf = dstack;
  if (block_size > 4096) {
    dheap = static_cast<uint64_t*>(malloc(static_cast<size_t>(block_size) * 8));
    if (!dheap) return -2;
    dbuf = dheap;
  }
  for (int64_t bs = 0; bs < n_deltas; bs += block_size) {
    int64_t blen = n_deltas - bs < block_size ? n_deltas - bs : block_size;
    // one pass: deltas into the cache + signed min of the wrapping deltas
    int64_t min_s = 0;
    uint64_t dmin_u = 0;
    {
      bool have = false;
      uint64_t prev = get(bs);
      for (int64_t k = 0; k < blen; k++) {
        uint64_t cur = get(bs + k + 1);
        uint64_t d = (cur - prev) & mask;
        prev = cur;
        dbuf[k] = d;
        int64_t s = static_cast<int64_t>(d);
        if (nbits < 64 && d >= (1ull << (nbits - 1)))
          s = static_cast<int64_t>(d) - (1ll << nbits);
        if (!have || s < min_s) { have = true; min_s = s; dmin_u = d; }
      }
    }
    if (!put_zigzag(out, out_cap, &pos, min_s)) { free(dheap); return -2; }
    // per-miniblock widths, then payloads
    uint8_t widths[512];
    size_t wpos = pos;
    if (pos + static_cast<size_t>(mini_count) > out_cap) { free(dheap); return -2; }
    pos += static_cast<size_t>(mini_count);
    for (int64_t m = 0; m < mini_count; m++) {
      int64_t mstart = m * mini_len;
      int64_t mlen = blen - mstart;
      if (mlen <= 0) { widths[m] = 0; continue; }
      if (mlen > mini_len) mlen = mini_len;
      uint64_t mx = 0;
      for (int64_t k = 0; k < mlen; k++) {
        uint64_t adj = (dbuf[mstart + k] - dmin_u) & mask;
        if (adj > mx) mx = adj;
      }
      int w = 0;
      while (mx) { w++; mx >>= 1; }
      widths[m] = static_cast<uint8_t>(w);
      if (w == 0) continue;
      BitWriter bw;
      bw_init(&bw, out, out_cap, pos);
      for (int64_t k = 0; k < mini_len; k++) {
        uint64_t adj = 0;
        if (k < mlen) adj = (dbuf[mstart + k] - dmin_u) & mask;
        if (!bw_push(&bw, adj, w)) { free(dheap); return -2; }
      }
      if (!bw_flush(&bw)) { free(dheap); return -2; }
      pos = bw.pos;
    }
    for (int64_t m = 0; m < mini_count; m++) out[wpos + m] = widths[m];
  }
  free(dheap);
  return static_cast<ssize_t>(pos);
}

// Dictionary build over an (offsets, data) byte-array column: open-addressed
// FNV-1a hash, first-occurrence unique order (parity with the Python dict /
// CPython-ext builders). Fills indices[n] and firsts[<=max_uniques+1] (row
// of each unique's first occurrence). Returns the unique count, -2 when it
// exceeds max_uniques (dictionary encoding does not pay), -1 bad input /
// allocation failure.
ssize_t ptq_bytes_dict_indices(const char* data, size_t data_len,
                               const int64_t* offsets, int64_t n,
                               int64_t max_uniques, uint32_t* indices,
                               uint32_t* firsts) {
  if (n < 0 || max_uniques < 0) return -1;
  if (n == 0) return 0;
  // table sized for the unique cap, not n: a high-cardinality column bails
  // out early without a giant allocation
  size_t want = static_cast<size_t>(
      (max_uniques + 2) < n ? (max_uniques + 2) : n);
  size_t tsize = 64;
  while (tsize < want * 2) tsize <<= 1;
  uint32_t* table = static_cast<uint32_t*>(malloc(tsize * sizeof(uint32_t)));
  if (!table) return -1;
  std::memset(table, 0xff, tsize * sizeof(uint32_t));  // 0xffffffff = empty
  const size_t tmask = tsize - 1;
  int64_t uniques = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t off = offsets[i];
    int64_t len = offsets[i + 1] - off;
    if (off < 0 || len < 0 || static_cast<size_t>(off + len) > data_len) {
      free(table);
      return -1;
    }
    const uint8_t* p = reinterpret_cast<const uint8_t*>(data + off);
    uint64_t h = 1469598103934665603ull;
    for (int64_t b = 0; b < len; b++) h = (h ^ p[b]) * 1099511628211ull;
    size_t slot = static_cast<size_t>(h) & tmask;
    for (;;) {
      uint32_t uid = table[slot];
      if (uid == 0xffffffffu) {
        if (uniques >= max_uniques) {  // would exceed the cutoff: no dict
          free(table);
          return -2;
        }
        table[slot] = static_cast<uint32_t>(uniques);
        firsts[uniques] = static_cast<uint32_t>(i);
        indices[i] = static_cast<uint32_t>(uniques);
        uniques++;
        break;
      }
      int64_t fo = offsets[firsts[uid]];
      int64_t flen = offsets[firsts[uid] + 1] - fo;
      if (flen == len && std::memcmp(data + fo, data + off, len) == 0) {
        indices[i] = uid;
        break;
      }
      slot = (slot + 1) & tmask;
    }
  }
  free(table);
  return static_cast<ssize_t>(uniques);
}

// Lexicographic min/max over an (offsets, data) byte-array column.
// out[0]/out[1] = row index of min/max. Returns 0, -1 on bad input / n == 0.
ssize_t ptq_bytes_minmax(const char* data, size_t data_len,
                         const int64_t* offsets, int64_t n, int64_t* out) {
  if (n <= 0) return -1;
  if (offsets[0] < 0 || offsets[1] < offsets[0] ||
      static_cast<size_t>(offsets[1]) > data_len)
    return -1;  // row 0 is the running min/max base: validate it up front
  int64_t mn = 0, mx = 0;
  for (int64_t i = 1; i < n; i++) {
    int64_t io = offsets[i], il = offsets[i + 1] - io;
    if (io < 0 || il < 0 || static_cast<size_t>(io + il) > data_len) return -1;
    {
      int64_t mo = offsets[mn], ml = offsets[mn + 1] - mo;
      int64_t c = std::memcmp(data + io, data + mo, il < ml ? il : ml);
      if (c < 0 || (c == 0 && il < ml)) mn = i;
    }
    {
      int64_t mo = offsets[mx], ml = offsets[mx + 1] - mo;
      int64_t c = std::memcmp(data + io, data + mo, il < ml ? il : ml);
      if (c > 0 || (c == 0 && il > ml)) mx = i;
    }
  }
  out[0] = mn;
  out[1] = mx;
  return 0;
}

// Dictionary probe over numeric bit patterns (NaN payloads dedup by bits).
// elem_size selects uint32/uint64 elements so 32-bit columns probe their
// buffer in place. Same contract as ptq_bytes_dict_indices: fills indices[n]
// and firsts[<=max_uniques+1]; returns unique count, -2 over the cutoff
// (early exit — no O(n log n) sort for high-cardinality columns), -1 error.
ssize_t ptq_u64_dict_indices(const void* v_raw, int elem_size, int64_t n,
                             int64_t max_uniques, uint32_t* indices,
                             uint32_t* firsts) {
  if (n < 0 || max_uniques < 0) return -1;
  if (elem_size != 4 && elem_size != 8) return -1;
  if (n == 0) return 0;
  const uint32_t* v32 =
      elem_size == 4 ? static_cast<const uint32_t*>(v_raw) : nullptr;
  const uint64_t* v = elem_size == 8 ? static_cast<const uint64_t*>(v_raw) : nullptr;
  auto at = [&](int64_t i) -> uint64_t {
    return v ? v[i] : static_cast<uint64_t>(v32[i]);
  };
  size_t want = static_cast<size_t>(
      (max_uniques + 2) < n ? (max_uniques + 2) : n);
  size_t tsize = 64;
  while (tsize < want * 2) tsize <<= 1;
  uint32_t* table = static_cast<uint32_t*>(malloc(tsize * sizeof(uint32_t)));
  if (!table) return -1;
  std::memset(table, 0xff, tsize * sizeof(uint32_t));
  const size_t tmask = tsize - 1;
  int64_t uniques = 0;
  for (int64_t i = 0; i < n; i++) {
    uint64_t x = at(i);
    uint64_t h = x * 0x9e3779b97f4a7c15ull;
    h ^= h >> 29;
    size_t slot = static_cast<size_t>(h) & tmask;
    for (;;) {
      uint32_t uid = table[slot];
      if (uid == 0xffffffffu) {
        if (uniques >= max_uniques) {  // would exceed the cutoff: no dict
          free(table);
          return -2;
        }
        table[slot] = static_cast<uint32_t>(uniques);
        firsts[uniques] = static_cast<uint32_t>(i);
        indices[i] = static_cast<uint32_t>(uniques);
        uniques++;
        break;
      }
      if (at(firsts[uid]) == x) {
        indices[i] = uid;
        break;
      }
      slot = (slot + 1) & tmask;
    }
  }
  free(table);
  return static_cast<ssize_t>(uniques);
}

// ---------------------------------------------------------------------------
// ptq_chunk_encode: the fused whole-chunk ENCODE walk (the write-side
// inverse of ptq_chunk_prepare). Page split -> def-level hybrid pack ->
// value-stream encode -> block compression -> compact-Thrift page framing,
// all in one GIL-free call; every byte identical to the staged Python
// encoder (sink/encoder.py encode_chunk), which remains the fallback rung
// and the error-semantics oracle.
// ---------------------------------------------------------------------------

namespace {

// Minimal compact-Thrift writer for PageHeader framing (the write twin of
// ptq_parse_page_header). Field ids here are small and ascending, so the
// short-form field header (delta << 4 | wire) always applies.
struct ThriftW {
  uint8_t* out;
  size_t cap;
  size_t pos;
  int last_fid;
  bool ok;
};

inline void th_init(ThriftW* w, uint8_t* out, size_t cap, size_t pos) {
  w->out = out; w->cap = cap; w->pos = pos; w->last_fid = 0; w->ok = true;
}

inline void th_byte(ThriftW* w, uint8_t b) {
  if (w->pos >= w->cap) { w->ok = false; return; }
  w->out[w->pos++] = b;
}

inline void th_field(ThriftW* w, int fid, int wire) {
  th_byte(w, static_cast<uint8_t>(((fid - w->last_fid) << 4) | wire));
  w->last_fid = fid;
}

inline void th_i32(ThriftW* w, int fid, int64_t v) {
  th_field(w, fid, 0x05);  // CT_I32
  if (!w->ok) return;
  if (!put_zigzag(w->out, w->cap, &w->pos, v)) w->ok = false;
}

inline void th_bool(ThriftW* w, int fid, bool v) {
  th_field(w, fid, v ? 0x01 : 0x02);  // value rides the field header
}

inline void th_stop(ThriftW* w) { th_byte(w, 0x00); }

// Compress one raw block into dst. Returns compressed size, -1 unknown
// codec, -5 dst too small / deflate failure (retryable capacity).
ssize_t compress_block_enc(int codec, const uint8_t* raw, size_t raw_len,
                           uint8_t* dst, size_t dst_cap) {
  if (codec == 0) {
    if (raw_len > dst_cap) return -5;
    std::memcpy(dst, raw, raw_len);
    return static_cast<ssize_t>(raw_len);
  }
  if (codec == 1) {
    ssize_t n = ptq_snappy_compress(reinterpret_cast<const char*>(raw),
                                    raw_len, reinterpret_cast<char*>(dst),
                                    dst_cap);
    return n < 0 ? -5 : n;
  }
  if (codec == 2) {
    // the exact parameters CPython's zlib.compressobj(wbits=31) resolves
    // to (default level/memLevel/strategy); both link the same zlib, so
    // the stream — gzip header included — is byte-identical to _Gzip
    z_stream s;
    std::memset(&s, 0, sizeof(s));
    if (deflateInit2(&s, Z_DEFAULT_COMPRESSION, Z_DEFLATED, 31, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK)
      return -5;
    s.next_in = const_cast<Bytef*>(raw);
    s.avail_in = static_cast<uInt>(raw_len);
    s.next_out = dst;
    s.avail_out = static_cast<uInt>(dst_cap);
    int rc = deflate(&s, Z_FINISH);
    ssize_t n = static_cast<ssize_t>(s.total_out);
    deflateEnd(&s);
    return rc == Z_STREAM_END ? n : -5;
  }
  return -1;
}

// stage_ns slots for the encode walk
enum {
  EN_LEVELS = 0,
  EN_VALUES = 1,
  EN_COMPRESS = 2,
  EN_FRAME = 3,
  EN_CRC = 4,
};

}  // namespace

// Standalone gzip compress with the exact parameters the fused encode walk
// uses — exported so the Python side can PROBE byte-identity against
// zlib.compressobj(wbits=31) once at startup (a CPython linked against a
// different zlib build must keep GZIP on the staged encoder). Returns
// compressed size or -1.
ssize_t ptq_gzip_compress(const uint8_t* src, size_t src_len, uint8_t* dst,
                          size_t dst_cap) {
  ssize_t n = compress_block_enc(2, src, src_len, dst, dst_cap);
  return n < 0 ? -1 : n;
}

ssize_t ptq_chunk_encode(
    int route, const uint8_t* values, size_t values_len,
    const int64_t* ba_offsets, int64_t nv, int type_size, int dict_width,
    const uint8_t* dict_raw, size_t dict_raw_len, int64_t dict_num,
    const uint16_t* def_levels, int64_t num_entries, int max_def, int codec,
    int dpv, int with_crc, int64_t per_page, uint8_t* out, size_t out_cap,
    uint8_t* scratch, size_t scratch_cap, int64_t* pages, size_t max_pages,
    int64_t* totals, int64_t* stage_ns, int64_t* err_info) {
  StageClock clk{stage_ns, 0};
  int64_t page_idx = 0;
#define ENC_FAIL(code, stage_)                         \
  do {                                                 \
    if (err_info) {                                    \
      err_info[0] = (stage_);                          \
      err_info[1] = page_idx;                          \
      err_info[2] = 0;                                 \
      err_info[3] = 0;                                 \
    }                                                  \
    return (code);                                     \
  } while (0)

  if (route < 0 || route > 4 || (codec != 0 && codec != 1 && codec != 2) ||
      (dpv != 1 && dpv != 2) || per_page < 1 || num_entries < 0 || nv < 0 ||
      max_def < 0 || (max_def > 0 && def_levels == nullptr) ||
      (max_def == 0 && nv != num_entries))
    ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  if (route == 0 && (type_size < 1 || type_size > 4096))
    ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  if (route == 3 && type_size != 4 && type_size != 8)
    ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  if (route == 4 && type_size != 2)
    ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  if (route == 2 && (dict_width < 0 || dict_width > 32))
    ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  if (route == 1) {
    if (ba_offsets == nullptr || ba_offsets[0] != 0 ||
        static_cast<size_t>(ba_offsets[nv]) > values_len)
      ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  } else {
    size_t es = route == 2 ? 4 : static_cast<size_t>(type_size);
    if (static_cast<size_t>(nv) * es > values_len)
      ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  }

  // scratch splits into a raw-page half and a compressed half: the raw
  // block assembles first (levels + values), then compresses, then the
  // header (whose varints need the compressed size) frames into `out`.
  uint8_t* raw_buf = scratch;
  size_t raw_cap = scratch_cap / 2;
  uint8_t* comp_buf = scratch + raw_cap;
  size_t comp_cap = scratch_cap - raw_cap;

  size_t pos = 0;
  int64_t uncompressed_total = 0;
  int64_t dict_off = -1;
  const int def_width = level_bit_width(max_def);

  // -- leading dictionary page ----------------------------------------------
  if (route == 2 && dict_num > 0) {
    clk.start();
    ssize_t comp = compress_block_enc(codec, dict_raw, dict_raw_len,
                                      comp_buf, comp_cap);
    if (comp < 0) ENC_FAIL(comp == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                           PTQ_ENC_STAGE_COMPRESS);
    clk.stop(EN_COMPRESS);
    uint32_t crc = 0;
    if (with_crc) {
      crc = static_cast<uint32_t>(crc32(0, comp_buf, static_cast<uInt>(comp)));
      clk.stop(EN_CRC);
    }
    ThriftW w;
    th_init(&w, out, out_cap, pos);
    th_i32(&w, 1, 2);                                 // type = DICTIONARY_PAGE
    th_i32(&w, 2, static_cast<int64_t>(dict_raw_len));  // uncompressed size
    th_i32(&w, 3, comp);                              // compressed size
    if (with_crc) th_i32(&w, 4, static_cast<int32_t>(crc));
    th_field(&w, 7, 0x0C);                            // dictionary_page_header
    w.last_fid = 0;
    th_i32(&w, 1, dict_num);
    th_i32(&w, 2, 0);                                 // encoding = PLAIN
    th_bool(&w, 3, false);                            // is_sorted
    th_stop(&w);
    w.last_fid = 7;
    th_stop(&w);
    if (!w.ok || w.pos + static_cast<size_t>(comp) > out_cap)
      ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_FRAME);
    size_t hdr_len = w.pos - pos;
    std::memcpy(out + w.pos, comp_buf, static_cast<size_t>(comp));
    dict_off = static_cast<int64_t>(pos);
    pos = w.pos + static_cast<size_t>(comp);
    uncompressed_total +=
        static_cast<int64_t>(hdr_len) + static_cast<int64_t>(dict_raw_len);
    clk.stop(EN_FRAME);
    totals[5] = static_cast<int64_t>(hdr_len) + comp;
  } else {
    totals[5] = 0;
  }
  const int64_t data_off = static_cast<int64_t>(pos);

  // -- page split (mirrors _split_pages for flat columns) --------------------
  const int64_t n = num_entries;
  int64_t vpos = 0;  // non-null value cursor
  int64_t a = 0;
  bool first = true;
  while (first || a < n) {
    first = false;
    int64_t b = n;
    if (n > per_page) {
      b = a + per_page;
      if (b > n) b = n;
    }
    // per-page non-null count
    int64_t nn;
    if (max_def > 0) {
      clk.start();
      nn = 0;
      for (int64_t i = a; i < b; i++) nn += (def_levels[i] == max_def);
      clk.stop(EN_LEVELS);
    } else {
      nn = b - a;
    }
    if (vpos + nn > nv) ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);

    // -- assemble the raw block into raw_buf --------------------------------
    size_t raw_pos = 0;
    size_t def_block_len = 0;
    if (max_def > 0) {
      clk.start();
      if (dpv == 1) {
        if (raw_pos + 4 > raw_cap) ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_LEVELS);
        raw_pos += 4;  // back-patched length prefix
      }
      ssize_t ln = hybrid_encode_any(def_levels + a, 2, b - a, def_width,
                                     raw_buf + raw_pos, raw_cap - raw_pos);
      if (ln < 0) ENC_FAIL(ln == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                           PTQ_ENC_STAGE_LEVELS);
      def_block_len = static_cast<size_t>(ln);
      if (dpv == 1) {
        uint32_t l32 = static_cast<uint32_t>(def_block_len);
        raw_buf[raw_pos - 4] = static_cast<uint8_t>(l32);
        raw_buf[raw_pos - 3] = static_cast<uint8_t>(l32 >> 8);
        raw_buf[raw_pos - 2] = static_cast<uint8_t>(l32 >> 16);
        raw_buf[raw_pos - 1] = static_cast<uint8_t>(l32 >> 24);
        def_block_len += 4;  // v1 counts the prefix inside the block
      }
      raw_pos += static_cast<size_t>(ln);
      clk.stop(EN_LEVELS);
    }
    size_t values_start = raw_pos;
    clk.start();
    if (route == 0) {
      size_t nbytes = static_cast<size_t>(nn) * type_size;
      if (raw_pos + nbytes > raw_cap) ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_VALUES);
      std::memcpy(raw_buf + raw_pos, values + vpos * type_size, nbytes);
      raw_pos += nbytes;
    } else if (route == 1) {
      for (int64_t i = vpos; i < vpos + nn; i++) {
        int64_t off = ba_offsets[i];
        int64_t len = ba_offsets[i + 1] - off;
        if (len < 0 || off < 0 ||
            static_cast<size_t>(off + len) > values_len)
          ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_VALUES);
        if (raw_pos + 4 + static_cast<size_t>(len) > raw_cap)
          ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_VALUES);
        uint32_t l32 = static_cast<uint32_t>(len);
        raw_buf[raw_pos++] = static_cast<uint8_t>(l32);
        raw_buf[raw_pos++] = static_cast<uint8_t>(l32 >> 8);
        raw_buf[raw_pos++] = static_cast<uint8_t>(l32 >> 16);
        raw_buf[raw_pos++] = static_cast<uint8_t>(l32 >> 24);
        std::memcpy(raw_buf + raw_pos, values + off, static_cast<size_t>(len));
        raw_pos += static_cast<size_t>(len);
      }
    } else if (route == 2) {
      if (raw_pos + 1 > raw_cap) ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_VALUES);
      raw_buf[raw_pos++] = static_cast<uint8_t>(dict_width);
      ssize_t ln = hybrid_encode_any(
          reinterpret_cast<const uint32_t*>(values) + vpos, 4, nn, dict_width,
          raw_buf + raw_pos, raw_cap - raw_pos);
      if (ln < 0) ENC_FAIL(ln == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                           PTQ_ENC_STAGE_VALUES);
      raw_pos += static_cast<size_t>(ln);
    } else if (route == 4) {
      // BOOLEAN RLE: hybrid stream at width 1 behind a 4-byte LE length
      // prefix (the prefix is part of the VALUE encoding, so unlike def
      // levels it stays in BOTH page versions — ops/levels.py
      // encode_levels_v1 is the byte oracle)
      if (raw_pos + 4 > raw_cap) ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_VALUES);
      raw_pos += 4;  // back-patched length prefix
      ssize_t ln = hybrid_encode_any(
          reinterpret_cast<const uint16_t*>(values) + vpos, 2, nn, 1,
          raw_buf + raw_pos, raw_cap - raw_pos);
      if (ln < 0) ENC_FAIL(ln == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                           PTQ_ENC_STAGE_VALUES);
      uint32_t l32 = static_cast<uint32_t>(ln);
      raw_buf[raw_pos - 4] = static_cast<uint8_t>(l32);
      raw_buf[raw_pos - 3] = static_cast<uint8_t>(l32 >> 8);
      raw_buf[raw_pos - 2] = static_cast<uint8_t>(l32 >> 16);
      raw_buf[raw_pos - 1] = static_cast<uint8_t>(l32 >> 24);
      raw_pos += static_cast<size_t>(ln);
    } else {  // route 3: DELTA_BINARY_PACKED, one stream per page
      ssize_t ln = ptq_delta_encode(values + vpos * type_size, nn,
                                    type_size * 8, 128, 4,
                                    raw_buf + raw_pos, raw_cap - raw_pos);
      if (ln < 0) ENC_FAIL(ln == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                           PTQ_ENC_STAGE_VALUES);
      raw_pos += static_cast<size_t>(ln);
    }
    clk.stop(EN_VALUES);
    size_t values_raw_len = raw_pos - values_start;

    // -- compress ------------------------------------------------------------
    clk.start();
    ssize_t comp;
    size_t block_len;   // stored block size
    size_t unc_size;    // header's uncompressed_page_size
    if (dpv == 1) {
      comp = compress_block_enc(codec, raw_buf, raw_pos, comp_buf, comp_cap);
      if (comp < 0) ENC_FAIL(comp == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                             PTQ_ENC_STAGE_COMPRESS);
      block_len = static_cast<size_t>(comp);
      unc_size = raw_pos;
    } else {
      // v2: level stream stored RAW ahead of the compressed values block
      comp = compress_block_enc(codec, raw_buf + values_start, values_raw_len,
                                comp_buf, comp_cap);
      if (comp < 0) ENC_FAIL(comp == -1 ? PTQ_E_CORRUPT : PTQ_E_CAPACITY,
                             PTQ_ENC_STAGE_COMPRESS);
      block_len = def_block_len + static_cast<size_t>(comp);
      unc_size = def_block_len + values_raw_len;
    }
    clk.stop(EN_COMPRESS);
    uint32_t crc = 0;
    if (with_crc) {
      if (dpv == 1) {
        crc = static_cast<uint32_t>(
            crc32(0, comp_buf, static_cast<uInt>(comp)));
      } else {
        crc = static_cast<uint32_t>(
            crc32(0, raw_buf, static_cast<uInt>(def_block_len)));
        crc = static_cast<uint32_t>(
            crc32(crc, comp_buf, static_cast<uInt>(comp)));
      }
      clk.stop(EN_CRC);
    }

    // -- frame the PageHeader and copy the block -----------------------------
    if (page_idx >= static_cast<int64_t>(max_pages)) return PTQ_E_PAGES_FULL;
    int encoding =
        route == 2 ? 8 : (route == 3 ? 5 : (route == 4 ? 3 : 0));
    ThriftW w;
    th_init(&w, out, out_cap, pos);
    th_i32(&w, 1, dpv == 1 ? 0 : 3);                 // type
    th_i32(&w, 2, static_cast<int64_t>(unc_size));   // uncompressed size
    th_i32(&w, 3, static_cast<int64_t>(block_len));  // compressed size
    if (with_crc) th_i32(&w, 4, static_cast<int32_t>(crc));
    if (dpv == 1) {
      th_field(&w, 5, 0x0C);  // data_page_header
      w.last_fid = 0;
      th_i32(&w, 1, b - a);   // num_values (level entries)
      th_i32(&w, 2, encoding);
      th_i32(&w, 3, 3);       // definition_level_encoding = RLE
      th_i32(&w, 4, 3);       // repetition_level_encoding = RLE
      th_stop(&w);
      w.last_fid = 5;
    } else {
      th_field(&w, 8, 0x0C);  // data_page_header_v2
      w.last_fid = 0;
      th_i32(&w, 1, b - a);             // num_values
      th_i32(&w, 2, (b - a) - nn);      // num_nulls
      th_i32(&w, 3, b - a);             // num_rows (flat: = entries)
      th_i32(&w, 4, encoding);
      th_i32(&w, 5, static_cast<int64_t>(def_block_len));
      th_i32(&w, 6, 0);                 // repetition_levels_byte_length
      th_bool(&w, 7, true);             // is_compressed
      th_stop(&w);
      w.last_fid = 8;
    }
    th_stop(&w);
    if (!w.ok || w.pos + block_len > out_cap)
      ENC_FAIL(PTQ_E_CAPACITY, PTQ_ENC_STAGE_FRAME);
    size_t hdr_len = w.pos - pos;
    if (dpv == 1) {
      std::memcpy(out + w.pos, comp_buf, block_len);
    } else {
      std::memcpy(out + w.pos, raw_buf, def_block_len);
      std::memcpy(out + w.pos + def_block_len, comp_buf,
                  static_cast<size_t>(comp));
    }
    int64_t* row = pages + page_idx * 8;
    row[0] = static_cast<int64_t>(pos);
    row[1] = static_cast<int64_t>(hdr_len + block_len);
    row[2] = static_cast<int64_t>(hdr_len);
    row[3] = b - a;
    row[4] = nn;
    row[5] = static_cast<int64_t>(unc_size);
    row[6] = 0;
    row[7] = 0;
    pos = w.pos + block_len;
    uncompressed_total +=
        static_cast<int64_t>(hdr_len) + static_cast<int64_t>(unc_size);
    clk.stop(EN_FRAME);
    page_idx++;
    vpos += nn;
    a = b;
  }
  if (max_def == 0 && vpos != nv) ENC_FAIL(PTQ_E_CORRUPT, PTQ_ENC_STAGE_SPLIT);
  totals[0] = static_cast<int64_t>(pos);
  totals[1] = uncompressed_total;
  totals[2] = page_idx;
  totals[3] = dict_off;
  totals[4] = data_off;
  totals[6] = 0;
  totals[7] = 0;
#undef ENC_FAIL
  return static_cast<ssize_t>(page_idx);
}

}  // extern "C"

// Native host-side helpers for parquet_tpu.
//
// The TPU absorbs the bulk value decode (kernels/), but three host-side scalar
// walks remain on the critical path and are too branchy for NumPy:
//   1. snappy block (de)compression   (the reference links a Go snappy lib;
//      this implements the public snappy block format from its spec)
//   2. PLAIN byte_array offset scan   (data-dependent 4-byte length chain,
//      reference: type_bytearray.go:24-45)
//   3. hybrid RLE/bit-pack run-header prescan
//      (reference: hybrid_decoder.go:142-165; feeds the device run table)
//
// Exposed as a plain C ABI consumed via ctypes (utils/native.py). All
// functions validate sizes before writing and return -1 on corrupt input.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <sys/types.h>  // ssize_t

extern "C" {

// ---------------------------------------------------------------------------
// snappy block format
// ---------------------------------------------------------------------------

size_t ptq_snappy_max_compressed_length(size_t n) {
  // Worst case: all literals (header <= 5 bytes per element, one element) plus
  // copies that are only emitted when profitable (see emit rules), + varint.
  return 32 + n + n / 6;
}

ssize_t ptq_snappy_decompress(const char* src_c, size_t src_len,
                              char* dst, size_t dst_cap) {
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t pos = 0;
  uint64_t expect = 0;
  int shift = 0;
  // preamble: uncompressed length varint
  for (;;) {
    if (pos >= src_len || shift > 63) return -1;
    uint8_t b = src[pos++];
    expect |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  if (expect > dst_cap) return -1;
  size_t out = 0;
  while (pos < src_len) {
    uint8_t tag = src[pos++];
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      uint32_t len = tag >> 2;
      if (len >= 60) {
        uint32_t extra = len - 59;  // 1..4 length bytes
        if (pos + extra > src_len) return -1;
        len = 0;
        for (uint32_t i = 0; i < extra; i++) len |= static_cast<uint32_t>(src[pos + i]) << (8 * i);
        pos += extra;
      }
      uint64_t n = static_cast<uint64_t>(len) + 1;
      if (pos + n > src_len || out + n > expect) return -1;
      std::memcpy(dst + out, src + pos, n);
      out += n;
      pos += n;
    } else {
      uint32_t length, offset;
      if (kind == 1) {
        if (pos + 1 > src_len) return -1;
        length = ((tag >> 2) & 7) + 4;
        offset = (static_cast<uint32_t>(tag >> 5) << 8) | src[pos];
        pos += 1;
      } else if (kind == 2) {
        if (pos + 2 > src_len) return -1;
        length = (tag >> 2) + 1;
        offset = static_cast<uint32_t>(src[pos]) | (static_cast<uint32_t>(src[pos + 1]) << 8);
        pos += 2;
      } else {
        if (pos + 4 > src_len) return -1;
        length = (tag >> 2) + 1;
        offset = static_cast<uint32_t>(src[pos]) | (static_cast<uint32_t>(src[pos + 1]) << 8) |
                 (static_cast<uint32_t>(src[pos + 2]) << 16) | (static_cast<uint32_t>(src[pos + 3]) << 24);
        pos += 4;
      }
      if (offset == 0 || offset > out || out + length > expect) return -1;
      // overlapping copy must run forward byte-by-byte (RLE-style matches)
      const char* from = dst + out - offset;
      for (uint32_t i = 0; i < length; i++) dst[out + i] = from[i];
      out += length;
    }
  }
  return out == expect ? static_cast<ssize_t>(out) : -1;
}

static inline uint32_t snappy_hash(uint32_t v) {
  return (v * 0x1e35a7bdu) >> 18;  // 14-bit table
}

// Emits one literal element (callers never pass len >= 2^32). Returns false on
// insufficient space in dst.
static bool emit_literal(const uint8_t* src, size_t from, size_t len,
                         char* dst, size_t dst_cap, size_t* out) {
  if (len == 0) return true;
  if (*out + 5 + len > dst_cap) return false;
  size_t n = len - 1;
  if (n < 60) {
    dst[(*out)++] = static_cast<char>(n << 2);
  } else if (n < (1u << 8)) {
    dst[(*out)++] = static_cast<char>(60 << 2);
    dst[(*out)++] = static_cast<char>(n);
  } else if (n < (1u << 16)) {
    dst[(*out)++] = static_cast<char>(61 << 2);
    dst[(*out)++] = static_cast<char>(n);
    dst[(*out)++] = static_cast<char>(n >> 8);
  } else if (n < (1u << 24)) {
    dst[(*out)++] = static_cast<char>(62 << 2);
    dst[(*out)++] = static_cast<char>(n);
    dst[(*out)++] = static_cast<char>(n >> 8);
    dst[(*out)++] = static_cast<char>(n >> 16);
  } else {
    dst[(*out)++] = static_cast<char>(63 << 2);
    dst[(*out)++] = static_cast<char>(n);
    dst[(*out)++] = static_cast<char>(n >> 8);
    dst[(*out)++] = static_cast<char>(n >> 16);
    dst[(*out)++] = static_cast<char>(n >> 24);
  }
  std::memcpy(dst + *out, src + from, len);
  *out += len;
  return true;
}

static bool emit_copy(size_t offset, size_t len, char* dst, size_t dst_cap,
                      size_t* out) {
  while (len > 0) {
    size_t chunk = len > 64 ? 64 : len;
    // keep the final chunk >= 4 (canonical decoders may reject shorter copies)
    if (chunk == 64 && len - chunk > 0 && len - chunk < 4) chunk = 60;
    if (*out + 5 > dst_cap) return false;
    if (chunk >= 4 && chunk <= 11 && offset < 2048) {
      dst[(*out)++] = static_cast<char>(((offset >> 8) << 5) | ((chunk - 4) << 2) | 1);
      dst[(*out)++] = static_cast<char>(offset & 0xff);
    } else if (offset < (1u << 16)) {
      dst[(*out)++] = static_cast<char>(((chunk - 1) << 2) | 2);
      dst[(*out)++] = static_cast<char>(offset & 0xff);
      dst[(*out)++] = static_cast<char>(offset >> 8);
    } else {
      dst[(*out)++] = static_cast<char>(((chunk - 1) << 2) | 3);
      dst[(*out)++] = static_cast<char>(offset & 0xff);
      dst[(*out)++] = static_cast<char>((offset >> 8) & 0xff);
      dst[(*out)++] = static_cast<char>((offset >> 16) & 0xff);
      dst[(*out)++] = static_cast<char>((offset >> 24) & 0xff);
    }
    len -= chunk;
  }
  return true;
}

ssize_t ptq_snappy_compress(const char* src_c, size_t src_len,
                            char* dst, size_t dst_cap) {
  if (dst_cap < ptq_snappy_max_compressed_length(src_len)) return -1;
  const uint8_t* src = reinterpret_cast<const uint8_t*>(src_c);
  size_t out = 0;
  // preamble
  {
    uint64_t v = src_len;
    while (v >= 0x80) { dst[out++] = static_cast<char>(v | 0x80); v >>= 7; }
    dst[out++] = static_cast<char>(v);
  }
  if (src_len == 0) return static_cast<ssize_t>(out);
  constexpr size_t kTableSize = 1 << 14;
  static thread_local uint32_t table[kTableSize];
  std::memset(table, 0, sizeof(table));
  size_t lit_start = 0;
  size_t pos = 0;
  if (src_len >= 8) {
    const size_t limit = src_len - 4;
    while (pos < limit) {
      uint32_t cur;
      std::memcpy(&cur, src + pos, 4);
      uint32_t h = snappy_hash(cur);
      size_t cand = table[h];
      table[h] = static_cast<uint32_t>(pos);
      uint32_t cv;
      if (cand < pos && pos - cand < (1ull << 32) &&
          (std::memcpy(&cv, src + cand, 4), cv == cur)) {
        // extend match
        size_t len = 4;
        while (pos + len < src_len && src[cand + len] == src[pos + len]) len++;
        size_t offset = pos - cand;
        // Profitability: a far copy costs 5 bytes; only take it when it beats
        // the literal it replaces, which also keeps the advertised
        // max_compressed_length bound valid (no expanding elements).
        if (offset >= (1u << 16) && len < 8) {
          pos++;
          continue;
        }
        if (pos > lit_start &&
            !emit_literal(src, lit_start, pos - lit_start, dst, dst_cap, &out))
          return -1;
        if (!emit_copy(offset, len, dst, dst_cap, &out)) return -1;
        pos += len;
        lit_start = pos;
      } else {
        pos++;
      }
    }
  }
  if (lit_start < src_len &&
      !emit_literal(src, lit_start, src_len - lit_start, dst, dst_cap, &out))
    return -1;
  return static_cast<ssize_t>(out);
}

// ---------------------------------------------------------------------------
// PLAIN byte_array scan: 4-byte LE length + payload, repeated
// ---------------------------------------------------------------------------

// Fills offsets[0..num_values] (compacted) and copies payloads into data_out.
// Returns bytes consumed from src, or -1 on corrupt input / overflow.
ssize_t ptq_byte_array_gather(const char* src, size_t src_len, int64_t num_values,
                              int64_t* offsets, char* data_out, size_t data_cap) {
  size_t pos = 0;
  int64_t total = 0;
  offsets[0] = 0;
  for (int64_t i = 0; i < num_values; i++) {
    if (pos + 4 > src_len) return -1;
    uint32_t len;
    std::memcpy(&len, src + pos, 4);  // little-endian hosts only (x86/arm64)
    pos += 4;
    if (pos + len > src_len) return -1;
    if (static_cast<size_t>(total) + len > data_cap) return -1;
    std::memcpy(data_out + total, src + pos, len);
    pos += len;
    total += len;
    offsets[i + 1] = total;
  }
  return static_cast<ssize_t>(pos);
}

// ---------------------------------------------------------------------------
// hybrid RLE/bit-pack run-header prescan
// ---------------------------------------------------------------------------

// Outputs one row per run. bp_offsets are ABSOLUTE byte offsets into src
// (the caller uses src itself as the packed buffer). Returns the number of
// runs, or -1 on corrupt input, or -2 if max_runs is too small.
ssize_t ptq_prescan_hybrid(const uint8_t* src, size_t src_len, int64_t num_values,
                           int width, uint8_t* is_rle, int64_t* counts,
                           uint64_t* values, int64_t* bp_offsets,
                           size_t max_runs, int64_t* consumed) {
  if (width < 0 || width > 64) return -1;
  const size_t vbytes = (width + 7) / 8;
  size_t pos = 0;
  int64_t produced = 0;
  size_t runs = 0;
  while (produced < num_values) {
    uint64_t header = 0;
    int shift = 0;
    for (;;) {
      if (pos >= src_len || shift > 63) return -1;
      uint8_t b = src[pos++];
      header |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    if (runs >= max_runs) return -2;
    if (header & 1) {
      uint64_t groups = header >> 1;
      // overflow guards before any multiply (the Python fallback rejects these
      // via arbitrary-precision arithmetic; keep parity)
      if (groups == 0 || groups > (1ull << 40)) return -1;
      uint64_t count = groups * 8;
      uint64_t nbytes = groups * static_cast<uint64_t>(width);
      if (pos + nbytes > src_len) return -1;
      is_rle[runs] = 0;
      counts[runs] = static_cast<int64_t>(count);
      values[runs] = 0;
      bp_offsets[runs] = static_cast<int64_t>(pos);
      pos += nbytes;
      produced += static_cast<int64_t>(count);
    } else {
      uint64_t count = header >> 1;
      if (count == 0 || count > (1ull << 40) || pos + vbytes > src_len) return -1;
      uint64_t v = 0;
      for (size_t i = 0; i < vbytes; i++) v |= static_cast<uint64_t>(src[pos + i]) << (8 * i);
      if (width < 64 && v >= (1ull << width)) return -1;
      pos += vbytes;
      is_rle[runs] = 1;
      counts[runs] = static_cast<int64_t>(count);
      values[runs] = v;
      bp_offsets[runs] = 0;
      produced += static_cast<int64_t>(count);
    }
    runs++;
  }
  *consumed = static_cast<int64_t>(pos);
  return static_cast<ssize_t>(runs);
}

}  // extern "C"

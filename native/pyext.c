/* CPython extension for write-path hot loops that ctypes cannot reach
 * (they take Python object sequences, so a ctypes boundary would pay the
 * per-item conversion it exists to avoid).
 *
 * Built by native/Makefile into parquet_tpu/_native_ext.so; every caller
 * degrades to the pure-Python implementation when the module is absent.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

/* encode_items(seq) -> (flat_bytes, lengths_int64_le_bytes)
 *
 * One C pass over a sequence of str/bytes: str encodes UTF-8, bytes copies
 * verbatim. Raises TypeError on any other item type (callers fall back to
 * the general Python path).
 */
static PyObject *encode_items(PyObject *self, PyObject *arg) {
  PyObject *fast = PySequence_Fast(arg, "encode_items expects a sequence");
  if (fast == NULL) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject **items = PySequence_Fast_ITEMS(fast);

  PyObject *lengths = PyBytes_FromStringAndSize(NULL, n * 8);
  if (lengths == NULL) {
    Py_DECREF(fast);
    return NULL;
  }
  int64_t *lens = (int64_t *)PyBytes_AS_STRING(lengths);

  /* pass 1: sizes (PyUnicode_AsUTF8AndSize caches the UTF-8 form on the
   * unicode object, so pass 2 reuses it without re-encoding) */
  int64_t total = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = items[i];
    Py_ssize_t len;
    if (PyUnicode_Check(it)) {
      if (PyUnicode_AsUTF8AndSize(it, &len) == NULL) goto fail;
    } else if (PyBytes_Check(it)) {
      len = PyBytes_GET_SIZE(it);
    } else {
      PyErr_Format(PyExc_TypeError,
                   "encode_items: item %zd is %.80s, expected str or bytes", i,
                   Py_TYPE(it)->tp_name);
      goto fail;
    }
    lens[i] = (int64_t)len;
    total += (int64_t)len;
  }

  PyObject *flat = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
  if (flat == NULL) goto fail;
  char *dst = PyBytes_AS_STRING(flat);

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = items[i];
    const char *src;
    Py_ssize_t len;
    if (PyUnicode_Check(it)) {
      src = PyUnicode_AsUTF8AndSize(it, &len);
      if (src == NULL) {
        Py_DECREF(flat);
        goto fail;
      }
    } else {
      src = PyBytes_AS_STRING(it);
      len = PyBytes_GET_SIZE(it);
    }
    memcpy(dst, src, (size_t)len);
    dst += len;
  }

  Py_DECREF(fast);
  PyObject *out = PyTuple_Pack(2, flat, lengths);
  Py_DECREF(flat);
  Py_DECREF(lengths);
  return out;

fail:
  Py_DECREF(lengths);
  Py_DECREF(fast);
  return NULL;
}

/* dict_indices(list_of_bytes, max_uniques) -> (uniques_list, indices_u32_bytes)
 * or None when the unique count exceeds max_uniques.
 *
 * The write-side dictionary decision over byte values: one C pass with a
 * Python dict as the hash table (C-API calls, no interpreter dispatch).
 */
static PyObject *dict_indices(PyObject *self, PyObject *args) {
  PyObject *seq;
  Py_ssize_t max_uniques;
  if (!PyArg_ParseTuple(args, "On", &seq, &max_uniques)) return NULL;
  PyObject *fast = PySequence_Fast(seq, "dict_indices expects a sequence");
  if (fast == NULL) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject **items = PySequence_Fast_ITEMS(fast);

  PyObject *indices = PyBytes_FromStringAndSize(NULL, n * 4);
  PyObject *table = PyDict_New();
  PyObject *uniques = PyList_New(0);
  if (indices == NULL || table == NULL || uniques == NULL) goto fail;
  uint32_t *idx = (uint32_t *)PyBytes_AS_STRING(indices);

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = items[i];
    PyObject *found = PyDict_GetItemWithError(table, it); /* borrowed */
    if (found != NULL) {
      idx[i] = (uint32_t)PyLong_AsUnsignedLong(found);
      continue;
    }
    if (PyErr_Occurred()) goto fail; /* unhashable */
    Py_ssize_t next = PyList_GET_SIZE(uniques);
    if (next > max_uniques) {
      /* too many uniques: dictionary encoding does not pay */
      Py_DECREF(indices);
      Py_DECREF(table);
      Py_DECREF(uniques);
      Py_DECREF(fast);
      Py_RETURN_NONE;
    }
    PyObject *num = PyLong_FromSsize_t(next);
    if (num == NULL || PyDict_SetItem(table, it, num) < 0) {
      Py_XDECREF(num);
      goto fail;
    }
    Py_DECREF(num);
    if (PyList_Append(uniques, it) < 0) goto fail;
    idx[i] = (uint32_t)next;
  }

  Py_DECREF(table);
  Py_DECREF(fast);
  PyObject *out = PyTuple_Pack(2, uniques, indices);
  Py_DECREF(uniques);
  Py_DECREF(indices);
  return out;

fail:
  Py_XDECREF(indices);
  Py_XDECREF(table);
  Py_XDECREF(uniques);
  Py_DECREF(fast);
  return NULL;
}

static PyMethodDef methods[] = {
    {"encode_items", encode_items, METH_O,
     "encode_items(seq) -> (flat_bytes, int64le_lengths_bytes)"},
    {"dict_indices", dict_indices, METH_VARARGS,
     "dict_indices(seq, max_uniques) -> (uniques, u32le_indices_bytes) | None"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_native_ext",
                                       NULL, -1, methods};

PyMODINIT_FUNC PyInit__native_ext(void) { return PyModule_Create(&moduledef); }
